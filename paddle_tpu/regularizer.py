"""paddle.regularizer (reference python/paddle/regularizer.py): L1Decay/L2Decay.

Applied by the optimizer when a parameter carries `regularizer` (the reference
appends regularization ops in Optimizer.append_regularization_ops)."""
from __future__ import annotations

import jax.numpy as jnp


class WeightDecayRegularizer:
    def __call__(self, param):
        raise NotImplementedError


class L1Decay(WeightDecayRegularizer):
    def __init__(self, coeff=0.0):
        self._coeff = coeff

    @property
    def coeff(self):
        return self._coeff

    def grad_term(self, param_data):
        return self._coeff * jnp.sign(param_data)

    def __repr__(self):
        return f"L1Decay, coeff={self._coeff}"


class L2Decay(WeightDecayRegularizer):
    def __init__(self, coeff=0.0):
        self._coeff = coeff

    @property
    def coeff(self):
        return self._coeff

    def grad_term(self, param_data):
        return self._coeff * param_data

    def __repr__(self):
        return f"L2Decay, coeff={self._coeff}"
