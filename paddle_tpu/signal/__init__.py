"""paddle.signal parity (reference: python/paddle/signal.py — frame, overlap_add,
stft, istft over phi frame/overlap_add kernels + fft).

Implemented as gather/scatter-free jnp ops so XLA can fuse: ``frame`` is a strided
gather expressed with take, ``overlap_add`` a segment-sum via zero-padded reshape.
"""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.autograd.engine import apply
from paddle_tpu.tensor.tensor import Tensor

__all__ = ["frame", "overlap_add", "stft", "istft"]


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def _frame_impl(a, frame_length, hop_length, axis):
    if axis not in (-1, 0):
        raise ValueError("frame: axis must be 0 or -1")
    if axis == 0:
        a = jnp.moveaxis(a, 0, -1)
    n = a.shape[-1]
    if frame_length > n:
        raise ValueError(
            f"frame_length ({frame_length}) > signal length ({n})")
    num_frames = 1 + (n - frame_length) // hop_length
    starts = jnp.arange(num_frames) * hop_length
    idx = starts[:, None] + jnp.arange(frame_length)[None, :]
    out = a[..., idx]  # (..., num_frames, frame_length)
    out = jnp.swapaxes(out, -1, -2)  # (..., frame_length, num_frames)
    if axis == 0:
        out = jnp.moveaxis(out, (-2, -1), (1, 0))
    return out


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice a signal into overlapping frames (reference signal.py:frame)."""
    x = _t(x)
    return apply(
        "frame",
        lambda a: _frame_impl(a, int(frame_length), int(hop_length), int(axis)),
        x,
    )


def _overlap_add_impl(a, hop_length, axis):
    if axis not in (-1, 0):
        raise ValueError("overlap_add: axis must be 0 or -1")
    if axis == 0:
        # (frame_length, num_frames, ...) -> (..., frame_length, num_frames)
        a = jnp.moveaxis(a, (0, 1), (-2, -1))
    frame_length = a.shape[-2]
    num_frames = a.shape[-1]
    out_len = (num_frames - 1) * hop_length + frame_length
    # scatter-add each frame at offset i*hop: use a one-hot matmul so it maps to MXU
    # instead of serialized scatters.
    offsets = jnp.arange(num_frames) * hop_length  # (F,)
    pos = offsets[:, None] + jnp.arange(frame_length)[None, :]  # (F, L)
    onehot = (pos[..., None] == jnp.arange(out_len)).astype(a.dtype)  # (F, L, out)
    # a: (..., L, F) ; einsum over (F, L)
    out = jnp.einsum("...lf,flo->...o", a, onehot)
    if axis == 0:
        out = jnp.moveaxis(out, -1, 0)
    return out


def overlap_add(x, hop_length, axis=-1, name=None):
    """Reconstruct a signal from overlapping frames (reference signal.py:overlap_add)."""
    x = _t(x)
    return apply(
        "overlap_add",
        lambda a: _overlap_add_impl(a, int(hop_length), int(axis)),
        x,
    )


def stft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
         pad_mode="reflect", normalized=False, onesided=True, name=None):
    """Short-time Fourier transform (reference signal.py:stft).

    x: (batch?, signal_length) real or complex; returns (batch?, n_fft or
    n_fft//2+1, num_frames) complex.
    """
    x = _t(x)
    hop_length = int(hop_length) if hop_length is not None else n_fft // 4
    win_length = int(win_length) if win_length is not None else n_fft
    if window is not None:
        window = _t(window)

    def impl(a, w=None):
        complex_input = jnp.iscomplexobj(a)
        if w is None:
            w = jnp.ones((win_length,), a.real.dtype if complex_input else a.dtype)
        # center-pad window to n_fft
        if win_length < n_fft:
            lpad = (n_fft - win_length) // 2
            w = jnp.pad(w, (lpad, n_fft - win_length - lpad))
        if center:
            pad = n_fft // 2
            widths = [(0, 0)] * (a.ndim - 1) + [(pad, pad)]
            a = jnp.pad(a, widths, mode=pad_mode)
        frames = _frame_impl(a, n_fft, hop_length, -1)  # (..., n_fft, F)
        frames = frames * w[:, None]
        if onesided and not complex_input:
            spec = jnp.fft.rfft(frames, axis=-2)
        else:
            spec = jnp.fft.fft(frames, axis=-2)
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        return spec

    if window is not None:
        return apply("stft", impl, x, window)
    return apply("stft", impl, x)


def istft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
          normalized=False, onesided=True, length=None, return_complex=False,
          name=None):
    """Inverse STFT (reference signal.py:istft) with window-envelope normalization."""
    x = _t(x)
    hop_length = int(hop_length) if hop_length is not None else n_fft // 4
    win_length = int(win_length) if win_length is not None else n_fft
    if window is not None:
        window = _t(window)

    def impl(spec, w=None):
        if w is None:
            w = jnp.ones((win_length,), jnp.float32)
        if win_length < n_fft:
            lpad = (n_fft - win_length) // 2
            w = jnp.pad(w, (lpad, n_fft - win_length - lpad))
        if normalized:
            spec = spec * jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        if onesided:
            frames = jnp.fft.irfft(spec, n=n_fft, axis=-2)
        else:
            frames = jnp.fft.ifft(spec, axis=-2)
            if not return_complex:
                frames = frames.real
        frames = frames * w[:, None]
        sig = _overlap_add_impl(frames, hop_length, -1)
        # normalize by summed squared window envelope
        wsq = jnp.broadcast_to((w * w)[:, None], frames.shape[-2:])
        env = _overlap_add_impl(wsq, hop_length, -1)
        sig = sig / jnp.where(env > 1e-11, env, 1.0)
        if center:
            pad = n_fft // 2
            sig = sig[..., pad:sig.shape[-1] - pad]
        if length is not None:
            sig = sig[..., :length]
        return sig

    if window is not None:
        return apply("istft", impl, x, window)
    return apply("istft", impl, x)
