"""Decomp rule registry (reference python/paddle/decomposition/register.py)."""
_RULES = {}


def register_decomp(op_name):
    def wrapper(fn):
        _RULES[op_name] = fn
        return fn

    return wrapper


def get_decomp_rule(op_name):
    return _RULES.get(op_name)


def has_decomp(op_name):
    return op_name in _RULES
