"""Built-in decomposition rules (reference python/paddle/decomposition/rules.py):
big ops expressed in primitives.  Used by tests and custom compiler passes."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.autograd.engine import apply
from paddle_tpu.decomposition.register import get_decomp_rule, register_decomp
from paddle_tpu.tensor.tensor import Tensor


def decompose(op_name, *args, **kwargs):
    rule = get_decomp_rule(op_name)
    if rule is None:
        raise NotImplementedError(f"no decomposition registered for {op_name}")
    return rule(*args, **kwargs)


@register_decomp("softmax")
def _softmax(x, axis=-1):
    def f(a):
        m = jnp.max(a, axis, keepdims=True)
        e = jnp.exp(a - m)
        return e / jnp.sum(e, axis, keepdims=True)

    return apply("decomp_softmax", f, x)


@register_decomp("log_softmax")
def _log_softmax(x, axis=-1):
    def f(a):
        m = jnp.max(a, axis, keepdims=True)
        s = a - m
        return s - jnp.log(jnp.sum(jnp.exp(s), axis, keepdims=True))

    return apply("decomp_log_softmax", f, x)


@register_decomp("layer_norm")
def _layer_norm(x, weight=None, bias=None, epsilon=1e-5):
    def f(a, *wb):
        mean = a.mean(-1, keepdims=True)
        var = ((a - mean) ** 2).mean(-1, keepdims=True)
        out = (a - mean) / jnp.sqrt(var + epsilon)
        i = 0
        if weight is not None:
            out = out * wb[i]
            i += 1
        if bias is not None:
            out = out + wb[i]
        return out

    args = [x] + [t for t in (weight, bias) if t is not None]
    return apply("decomp_layer_norm", f, *args)


@register_decomp("dropout")
def _dropout(x, p=0.5, training=True):
    from paddle_tpu.nn.functional.common import dropout

    return dropout(x, p=p, training=training)


@register_decomp("gelu")
def _gelu(x, approximate=False):
    def f(a):
        if approximate:
            return 0.5 * a * (1 + jnp.tanh(jnp.sqrt(2 / jnp.pi) * (a + 0.044715 * a ** 3)))
        return 0.5 * a * (1 + jax.lax.erf(a / jnp.sqrt(2.0)))

    return apply("decomp_gelu", f, x)


@register_decomp("mean")
def _mean(x, axis=None, keepdim=False):
    def f(a):
        total = jnp.sum(a, axis, keepdims=keepdim)
        cnt = a.size if axis is None else a.shape[axis]
        return total / cnt

    return apply("decomp_mean", f, x)


@register_decomp("rsqrt")
def _rsqrt(x):
    return apply("decomp_rsqrt", lambda a: 1.0 / jnp.sqrt(a), x)


@register_decomp("pow")
def _pow(x, y):
    """Integer exponents via repeated squaring (exact, sign-correct);
    non-integer via exp(y·log|a|) with the nan domain the real op has —
    the reference rules.py pow decomposition's case split."""
    def static_int(a, n):
        if n == 0:
            return jnp.ones_like(a)
        result = jnp.ones_like(a)
        base, e = a, abs(n)
        while e:
            if e & 1:
                result = result * base
            base, e = base * base, e >> 1
        return result if n > 0 else 1.0 / result

    def traced(a, b):
        # sign-corrected |a|^b for integer-valued b; the real op's nan
        # domain (negative base, fractional exponent) otherwise
        mag = jnp.exp(b * jnp.log(jnp.abs(a)))
        odd = jnp.mod(b, 2.0) != 0.0
        signed = jnp.where((a < 0) & odd, -mag, mag)
        int_exp = jnp.floor(b) == b
        zero_base = jnp.where(b == 0.0, jnp.ones_like(a),
                              jnp.where(b > 0, jnp.zeros_like(a),
                                        jnp.full_like(a, jnp.inf)))
        res = jnp.where(int_exp, signed, jnp.exp(b * jnp.log(a)))
        return jnp.where(a == 0, zero_base, res)

    if isinstance(y, Tensor):
        return apply("decomp_pow", traced, x, y)
    if float(y) == int(float(y)):
        return apply("decomp_pow",
                     lambda a: static_int(a, int(float(y))), x)
    return apply("decomp_pow", lambda a: jnp.exp(float(y) * jnp.log(a)), x)


@register_decomp("sigmoid")
def _sigmoid(x):
    return apply("decomp_sigmoid", lambda a: 1.0 / (1.0 + jnp.exp(-a)), x)


@register_decomp("silu")
def _silu(x):
    return apply("decomp_silu", lambda a: a / (1.0 + jnp.exp(-a)), x)


@register_decomp("swiglu")
def _swiglu(x, y=None):
    def f(a, *rest):
        if rest:
            g, u = a, rest[0]
        else:
            g, u = jnp.split(a, 2, axis=-1)
        return (g / (1.0 + jnp.exp(-g))) * u

    args = [x] + ([y] if y is not None else [])
    return apply("decomp_swiglu", f, *args)


@register_decomp("relu6")
def _relu6(x):
    return apply("decomp_relu6",
                 lambda a: jnp.minimum(jnp.maximum(a, 0.0), 6.0), x)


@register_decomp("hardswish")
def _hardswish(x):
    return apply(
        "decomp_hardswish",
        lambda a: a * jnp.minimum(jnp.maximum(a + 3.0, 0.0), 6.0) / 6.0, x)


@register_decomp("softsign")
def _softsign(x):
    return apply("decomp_softsign", lambda a: a / (1.0 + jnp.abs(a)), x)


@register_decomp("rms_norm")
def _rms_norm(x, weight=None, epsilon=1e-6):
    def f(a, *w):
        ms = jnp.mean(jnp.square(a.astype(jnp.float32)), -1, keepdims=True)
        out = (a.astype(jnp.float32) / jnp.sqrt(ms + epsilon)).astype(a.dtype)
        return out * w[0] if w else out

    args = [x] + ([weight] if weight is not None else [])
    return apply("decomp_rms_norm", f, *args)


@register_decomp("batch_norm")
def _batch_norm(x, running_mean, running_var, weight=None, bias=None,
                epsilon=1e-5, data_format="NCHW"):
    """Inference-mode batch norm from primitives (reference rules.py
    batch_norm composite; training-mode statistics live in nn.BatchNorm)."""
    def f(a, mean, var, *wb):
        shape = [1, -1] + [1] * (a.ndim - 2) if data_format == "NCHW" \
            else [1] * (a.ndim - 1) + [-1]
        out = (a - mean.reshape(shape)) / jnp.sqrt(var.reshape(shape) + epsilon)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out

    args = [x, running_mean, running_var] + [
        t for t in (weight, bias) if t is not None]
    return apply("decomp_batch_norm", f, *args)


@register_decomp("instance_norm")
def _instance_norm(x, weight=None, bias=None, epsilon=1e-5):
    def f(a, *wb):
        axes = tuple(range(2, a.ndim))
        mean = a.mean(axes, keepdims=True)
        var = ((a - mean) ** 2).mean(axes, keepdims=True)
        out = (a - mean) / jnp.sqrt(var + epsilon)
        shape = [1, -1] + [1] * (a.ndim - 2)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out

    args = [x] + [t for t in (weight, bias) if t is not None]
    return apply("decomp_instance_norm", f, *args)


@register_decomp("group_norm")
def _group_norm(x, num_groups, weight=None, bias=None, epsilon=1e-5):
    def f(a, *wb):
        n, c = a.shape[0], a.shape[1]
        g = a.reshape((n, num_groups, c // num_groups) + a.shape[2:])
        axes = tuple(range(2, g.ndim))
        mean = g.mean(axes, keepdims=True)
        var = ((g - mean) ** 2).mean(axes, keepdims=True)
        out = ((g - mean) / jnp.sqrt(var + epsilon)).reshape(a.shape)
        shape = [1, -1] + [1] * (a.ndim - 2)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out

    args = [x] + [t for t in (weight, bias) if t is not None]
    return apply("decomp_group_norm", f, *args)


@register_decomp("bmm")
def _bmm(x, y):
    return apply("decomp_bmm",
                 lambda a, b: jnp.einsum("bij,bjk->bik", a, b), x, y)


@register_decomp("huber_loss")
def _huber_loss(x, label, delta=1.0):
    def f(a, t):
        d = a - t
        ad = jnp.abs(d)
        return jnp.where(ad <= delta, 0.5 * d * d,
                         delta * (ad - 0.5 * delta))

    return apply("decomp_huber_loss", f, x, label)


@register_decomp("squared_l2_norm")
def _squared_l2_norm(x):
    return apply("decomp_squared_l2_norm",
                 lambda a: jnp.sum(jnp.square(a)).reshape(1), x)


@register_decomp("stack")
def _stack(xs, axis=0):
    return apply("decomp_stack",
                 lambda *arrs: jnp.concatenate(
                     [jnp.expand_dims(a, axis) for a in arrs], axis), *xs)


@register_decomp("flatten")
def _flatten(x, start_axis=0, stop_axis=-1):
    def f(a):
        stop = stop_axis % a.ndim
        shape = (a.shape[:start_axis] + (-1,) + a.shape[stop + 1:])
        return a.reshape(shape)

    return apply("decomp_flatten", f, x)
