"""Built-in decomposition rules (reference python/paddle/decomposition/rules.py):
big ops expressed in primitives.  Used by tests and custom compiler passes."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.autograd.engine import apply
from paddle_tpu.decomposition.register import get_decomp_rule, register_decomp
from paddle_tpu.tensor.tensor import Tensor


def decompose(op_name, *args, **kwargs):
    rule = get_decomp_rule(op_name)
    if rule is None:
        raise NotImplementedError(f"no decomposition registered for {op_name}")
    return rule(*args, **kwargs)


@register_decomp("softmax")
def _softmax(x, axis=-1):
    def f(a):
        m = jnp.max(a, axis, keepdims=True)
        e = jnp.exp(a - m)
        return e / jnp.sum(e, axis, keepdims=True)

    return apply("decomp_softmax", f, x)


@register_decomp("log_softmax")
def _log_softmax(x, axis=-1):
    def f(a):
        m = jnp.max(a, axis, keepdims=True)
        s = a - m
        return s - jnp.log(jnp.sum(jnp.exp(s), axis, keepdims=True))

    return apply("decomp_log_softmax", f, x)


@register_decomp("layer_norm")
def _layer_norm(x, weight=None, bias=None, epsilon=1e-5):
    def f(a, *wb):
        mean = a.mean(-1, keepdims=True)
        var = ((a - mean) ** 2).mean(-1, keepdims=True)
        out = (a - mean) / jnp.sqrt(var + epsilon)
        i = 0
        if weight is not None:
            out = out * wb[i]
            i += 1
        if bias is not None:
            out = out + wb[i]
        return out

    args = [x] + [t for t in (weight, bias) if t is not None]
    return apply("decomp_layer_norm", f, *args)


@register_decomp("dropout")
def _dropout(x, p=0.5, training=True):
    from paddle_tpu.nn.functional.common import dropout

    return dropout(x, p=p, training=training)


@register_decomp("gelu")
def _gelu(x, approximate=False):
    def f(a):
        if approximate:
            return 0.5 * a * (1 + jnp.tanh(jnp.sqrt(2 / jnp.pi) * (a + 0.044715 * a ** 3)))
        return 0.5 * a * (1 + jax.lax.erf(a / jnp.sqrt(2.0)))

    return apply("decomp_gelu", f, x)


@register_decomp("mean")
def _mean(x, axis=None, keepdim=False):
    def f(a):
        total = jnp.sum(a, axis, keepdims=keepdim)
        cnt = a.size if axis is None else a.shape[axis]
        return total / cnt

    return apply("decomp_mean", f, x)
