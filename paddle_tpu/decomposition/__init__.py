"""paddle.decomposition (reference python/paddle/decomposition/): registry of
composite-op → primitive decompositions (§2.9).

On TPU the compiler (XLA) already receives primitives, so rules here serve
introspection/custom-lowering parity; `decompose` applies a rule eagerly."""
from paddle_tpu.decomposition.register import register_decomp, get_decomp_rule, has_decomp
from paddle_tpu.decomposition.decomp import decompose

__all__ = ['register_decomp', 'get_decomp_rule', 'has_decomp', 'decompose']
