"""Automatic training checkpoint/resume (reference
python/paddle/incubate/checkpoint/auto_checkpoint.py — train_epoch_range:624,
ExeTrainStatus, the hdfs-backed auto checkpointer).

TPU-native shape: ``train_epoch_range(max_epoch)`` is a generator that yields
the epochs still to run.  With ``PADDLE_CHECKPOINT_DIR`` set (the reference
uses PADDLE_RUNNING_ENV + fs checkpoint config), every completed epoch
persists the registered models/optimizers plus the epoch counter through
paddle.save with an atomic rename, and a relaunched process resumes from the
last completed epoch — the launcher kill-recover contract, epoch-granular.
"""
from __future__ import annotations

import json
import os

__all__ = ["train_epoch_range", "add_checkpoint_item", "reset"]

_STATE = {"items": {}, "dir": None}


def _ckpt_dir():
    return os.environ.get("PADDLE_CHECKPOINT_DIR") or _STATE["dir"]


def reset():
    _STATE["items"].clear()


def add_checkpoint_item(name, obj):
    """Register a model/optimizer (anything with state_dict/set_state_dict)
    to be saved each epoch and restored on resume."""
    if not hasattr(obj, "state_dict"):
        raise TypeError(f"{name}: checkpoint items need state_dict()")
    _STATE["items"][name] = obj
    return obj


def _save_epoch(path, epoch):
    import paddle_tpu as paddle

    os.makedirs(path, exist_ok=True)
    tmp = os.path.join(path, "_tmp.pdparams")
    blob = {name: obj.state_dict() for name, obj in _STATE["items"].items()}
    paddle.save(blob, tmp)
    os.replace(tmp, os.path.join(path, "items.pdparams"))
    meta_tmp = os.path.join(path, "_meta.json")
    with open(meta_tmp, "w") as f:
        json.dump({"epoch": epoch}, f)
    os.replace(meta_tmp, os.path.join(path, "meta.json"))


def _load_epoch(path):
    import paddle_tpu as paddle

    meta_p = os.path.join(path, "meta.json")
    if not os.path.exists(meta_p):
        return -1
    with open(meta_p) as f:
        epoch = int(json.load(f)["epoch"])
    items_p = os.path.join(path, "items.pdparams")
    if _STATE["items"] and os.path.exists(items_p):
        blob = paddle.load(items_p)
        for name, obj in _STATE["items"].items():
            if name in blob and hasattr(obj, "set_state_dict"):
                obj.set_state_dict(blob[name])
    return epoch


def train_epoch_range(max_epoch_num, save_checkpoint_inter=1, checkpoint_dir=None):
    """Yield the epochs still to be trained, checkpointing behind the scenes.

    for epoch in train_epoch_range(10):   # resumes mid-range after a crash
        train_one_epoch(...)
    """
    if checkpoint_dir is not None:
        _STATE["dir"] = checkpoint_dir
    path = _ckpt_dir()
    start = 0
    if path:
        start = _load_epoch(path) + 1
    for epoch in range(start, int(max_epoch_num)):
        yield epoch
        if path and (epoch % max(int(save_checkpoint_inter), 1) == 0
                     or epoch == max_epoch_num - 1):
            _save_epoch(path, epoch)
