"""Automatic training checkpoint/resume (reference
python/paddle/incubate/checkpoint/auto_checkpoint.py — train_epoch_range:624,
ExeTrainStatus, the hdfs-backed auto checkpointer).

TPU-native shape: ``train_epoch_range(max_epoch)`` is a generator that yields
the epochs still to run.  With ``PADDLE_CHECKPOINT_DIR`` set (the reference
uses PADDLE_RUNNING_ENV + fs checkpoint config), every completed epoch
persists the registered models/optimizers plus the epoch counter through
paddle.save with an atomic rename, and a relaunched process resumes from the
last completed epoch — the launcher kill-recover contract, epoch-granular.
"""
from __future__ import annotations

import json
import os

__all__ = ["train_epoch_range", "add_checkpoint_item", "reset"]

_STATE = {"items": {}, "dir": None}


def _ckpt_dir():
    return os.environ.get("PADDLE_CHECKPOINT_DIR") or _STATE["dir"]


def reset():
    _STATE["items"].clear()


def add_checkpoint_item(name, obj):
    """Register a model/optimizer (anything with state_dict/set_state_dict)
    to be saved each epoch and restored on resume."""
    if not hasattr(obj, "state_dict"):
        raise TypeError(f"{name}: checkpoint items need state_dict()")
    _STATE["items"][name] = obj
    return obj


def _save_epoch(path, epoch):
    import paddle_tpu as paddle

    os.makedirs(path, exist_ok=True)
    tmp = os.path.join(path, "_tmp.pdparams")
    blob = {name: obj.state_dict() for name, obj in _STATE["items"].items()}
    paddle.save(blob, tmp)
    os.replace(tmp, os.path.join(path, "items.pdparams"))
    meta_tmp = os.path.join(path, "_meta.json")
    with open(meta_tmp, "w") as f:
        json.dump({"epoch": epoch}, f)
    os.replace(meta_tmp, os.path.join(path, "meta.json"))


def _load_epoch(path):
    import paddle_tpu as paddle

    meta_p = os.path.join(path, "meta.json")
    if not os.path.exists(meta_p):
        return -1
    with open(meta_p) as f:
        epoch = int(json.load(f)["epoch"])
    items_p = os.path.join(path, "items.pdparams")
    if _STATE["items"] and os.path.exists(items_p):
        blob = paddle.load(items_p)
        for name, obj in _STATE["items"].items():
            if name in blob and hasattr(obj, "set_state_dict"):
                obj.set_state_dict(blob[name])
    return epoch


def train_epoch_range(max_epoch_num, save_checkpoint_inter=1,
                      checkpoint_dir=None, fs=None):
    """Yield the epochs still to be trained, checkpointing behind the scenes.

    for epoch in train_epoch_range(10):   # resumes mid-range after a crash
        train_one_epoch(...)

    ``fs`` (optional): a ``fleet.utils.fs`` client (LocalFS / HDFSClient —
    the reference's hdfs-backed auto checkpointer rides the same
    abstraction).  A client whose ``need_upload_download()`` is True treats
    ``checkpoint_dir`` as a REMOTE path: epochs are written to a local
    staging dir and uploaded atomically (delete + upload), and resume
    downloads the remote state first.
    """
    if checkpoint_dir is not None:
        _STATE["dir"] = checkpoint_dir
    path = _ckpt_dir()
    remote = fs is not None and fs.need_upload_download() and path
    stage = None
    if remote:
        import tempfile

        stage = tempfile.mkdtemp(prefix="auto_ckpt_stage_")
        # recover from a crash mid-swap: persist() renames the previous
        # checkpoint to <path>._old before moving the new one in; if only
        # the ._old survives, it IS the last complete checkpoint
        _old = f"{path}._old"
        if not fs.is_exist(path) and fs.is_exist(_old):
            fs.mv(_old, path)
        if fs.is_exist(path):
            fs.download(path, os.path.join(stage, "dl"))
            local_path = os.path.join(stage, "dl")
        else:
            local_path = os.path.join(stage, "dl")
            os.makedirs(local_path, exist_ok=True)
    else:
        local_path = path

    def persist(epoch):
        _save_epoch(local_path, epoch)
        if remote:
            # upload to a fresh temp name, then mv into place — a crash
            # between delete and upload must never strand the job with NO
            # remote checkpoint (the exact failure auto-checkpoint exists
            # to survive).  fs.mv is a metadata rename on HDFS.
            tmp = f"{path}._uploading_{epoch}"
            if fs.is_exist(tmp):
                fs.delete(tmp)
            fs.upload(local_path, tmp)
            old = f"{path}._old"
            if fs.is_exist(old):
                fs.delete(old)
            if fs.is_exist(path):
                fs.mv(path, old)
            fs.mv(tmp, path)
            if fs.is_exist(old):
                fs.delete(old)

    start = 0
    if local_path:
        start = _load_epoch(local_path) + 1
    try:
        for epoch in range(start, int(max_epoch_num)):
            yield epoch
            if local_path and (epoch % max(int(save_checkpoint_inter), 1) == 0
                               or epoch == max_epoch_num - 1):
                persist(epoch)
    finally:
        if stage is not None:
            import shutil

            shutil.rmtree(stage, ignore_errors=True)
