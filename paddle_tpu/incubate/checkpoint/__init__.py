"""paddle.incubate.checkpoint (reference python/paddle/incubate/checkpoint/)."""
from paddle_tpu.incubate.checkpoint import auto_checkpoint  # noqa: F401
