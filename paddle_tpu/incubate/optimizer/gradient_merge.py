"""GradientMergeOptimizer (reference python/paddle/incubate/optimizer/
gradient_merge.py:30 + distributed/passes/auto_parallel_gradient_merge.py).

Accumulate micro-batch gradients for ``k_steps`` steps, then apply the inner
optimizer once on the (optionally averaged) sum — the memory-free half of
large-batch training (recompute is the other half).

TPU-native: in the compiled train step the accumulator lives in the optimizer
state pytree and the "is this an update step" decision is a traced
``step % k == 0`` predicate select — one XLA program regardless of phase, no
control-flow graph rewrite (the reference implements this as a program pass
inserting conditional blocks).
"""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["GradientMergeOptimizer"]


class GradientMergeOptimizer:
    def __init__(self, inner_optimizer, k_steps=1, avg=True):
        if not (isinstance(k_steps, int) and k_steps > 0):
            raise ValueError("k_steps should be a positive integer")
        self.inner_optimizer = inner_optimizer
        self.k_steps = k_steps
        self.avg = bool(avg)
        self._acc = {}
        self._count = 0

    # -- facade: look like the wrapped optimizer -----------------------------
    def __getattr__(self, item):
        if item == "inner_optimizer":
            raise AttributeError(item)
        return getattr(self.inner_optimizer, item)

    # TrainStep assigns the traced step counter onto the optimizer it holds;
    # route it through to the inner optimizer the update math reads
    @property
    def _global_step(self):
        return self.inner_optimizer._global_step

    @_global_step.setter
    def _global_step(self, v):
        self.inner_optimizer._global_step = v

    def _set_k_steps(self, k_steps):
        self.k_steps = k_steps

    def _set_avg(self, avg):
        self.avg = avg

    # ----------------------------------------------------------------- eager
    def step(self):
        inner = self.inner_optimizer
        self._count += 1
        apply_now = self._count % self.k_steps == 0
        for p in inner._parameter_list or ():
            if p.grad is None:
                continue
            acc = self._acc.get(id(p))
            self._acc[id(p)] = (p.grad.data if acc is None
                                else acc + p.grad.data)
        if not apply_now:
            # grads consumed into the accumulator; no parameter update
            for p in inner._parameter_list or ():
                p.clear_grad() if hasattr(p, "clear_grad") else None
            return
        from paddle_tpu.tensor.tensor import Tensor

        for p in inner._parameter_list or ():
            acc = self._acc.pop(id(p), None)
            if acc is None:
                continue
            p._grad = Tensor(acc / self.k_steps if self.avg else acc)
        inner.step()

    def clear_grad(self, set_to_zero=True):
        self.inner_optimizer.clear_grad(set_to_zero)

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()

    # ------------------------------------------------------- compiled (jit)
    def functional_init_states(self, params):
        states = self.inner_optimizer.functional_init_states(params)
        states["gm_acc"] = {
            k: jnp.zeros(v.shape,
                         jnp.float32 if v.dtype == jnp.bfloat16 else v.dtype)
            for k, v in params.items()
        }
        return states

    def functional_update(self, params, grads, states, lr):
        inner = self.inner_optimizer
        k = self.k_steps
        step = jnp.asarray(inner._global_step)
        apply_now = (step % k) == 0  # traced predicate, not python control flow

        acc = states["gm_acc"]
        new_acc = {
            kk: (acc[kk] + g.astype(acc[kk].dtype) if g is not None else acc[kk])
            for kk, g in grads.items()
        }
        eff = {
            kk: (new_acc[kk] / k if self.avg else new_acc[kk])
            if grads.get(kk) is not None else None
            for kk in grads
        }
        # grad clip on the MERGED gradient at apply time (reference clips the
        # effective gradient once per merge window, not each micro-grad);
        # installed by TrainStep when a clip is configured with k_steps>1
        merged_clip = self.__dict__.get("_merged_clip")
        if merged_clip is not None:
            eff = merged_clip(eff)
        inner_states = {n: v for n, v in states.items() if n != "gm_acc"}
        # inner optimizer sees the merged step index (1, 2, ... per apply)
        prev = inner._global_step
        inner._global_step = step // k
        try:
            upd_params, upd_states = inner.functional_update(
                params, eff, inner_states, lr)
        finally:
            inner._global_step = prev

        sel = lambda a, b: jnp.where(apply_now, a, b)
        new_params = {kk: sel(upd_params[kk].astype(params[kk].dtype),
                              params[kk]) for kk in params}
        out_states = {
            n: {kk: sel(upd_states[n][kk], inner_states[n][kk])
                if upd_states[n][kk].dtype == inner_states[n][kk].dtype
                else upd_states[n][kk]
                for kk in inner_states[n]}
            for n in inner_states
        }
        out_states["gm_acc"] = {
            kk: sel(jnp.zeros_like(new_acc[kk]), new_acc[kk])
            for kk in new_acc
        }
        return new_params, out_states

    # state_dict passthrough with the merge window included: count AND the
    # partial accumulator (keyed by position in the parameter list), so a
    # checkpoint taken mid-window resumes with the exact partial sums instead
    # of silently under-weighting the next apply
    def state_dict(self):
        import numpy as np

        sd = self.inner_optimizer.state_dict()
        sd["gradient_merge_count"] = self._count
        acc = {}
        for i, p in enumerate(self.inner_optimizer._parameter_list or ()):
            v = self._acc.get(id(p))
            if v is not None:
                acc[str(i)] = np.asarray(v)
        sd["gradient_merge_acc"] = acc
        return sd

    def set_state_dict(self, sd):
        import jax.numpy as jnp

        self._count = int(sd.pop("gradient_merge_count", 0))
        acc = sd.pop("gradient_merge_acc", {})
        self._acc = {}
        plist = self.inner_optimizer._parameter_list or ()
        for i, p in enumerate(plist):
            v = acc.get(str(i))
            if v is not None:
                self._acc[id(p)] = jnp.asarray(v)
        self.inner_optimizer.set_state_dict(sd)
