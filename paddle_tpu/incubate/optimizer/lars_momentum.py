"""LARS momentum (reference python/paddle/incubate/optimizer/lars_momentum.py
LarsMomentumOptimizer over paddle/phi/kernels/gpu/lars_momentum_kernel.cu).

Layer-wise Adaptive Rate Scaling (You et al., 2017): each parameter's step is
scaled by trust = ||p|| / (||g|| + wd * ||p|| + eps), letting large-batch SGD
keep per-layer step sizes proportional to weight norms.

Update (matches the reference docstring exactly):
    local_lr = lr * lars_coeff * ||p|| / (||g|| + lars_weight_decay * ||p|| + eps)
    v        = mu * v + local_lr * (g + lars_weight_decay * p)
    p        = p - v

TPU-native: one fused jnp expression per parameter inside the compiled train
step — the reference's fused multi-tensor CUDA kernel is XLA's job here.
"""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.optimizer.optimizer import Optimizer

__all__ = ["LarsMomentumOptimizer"]


class LarsMomentumOptimizer(Optimizer):
    _accum_names = ("velocity",)

    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameter_list=None, parameters=None,
                 regularization=None, grad_clip=None, name=None,
                 exclude_from_weight_decay=None, epsilon=0.0,
                 multi_precision=False, rescale_grad=1.0):
        params = parameters if parameters is not None else parameter_list
        super().__init__(learning_rate, params, regularization, grad_clip,
                         name, multi_precision=multi_precision)
        self._momentum = float(momentum)
        self._lars_coeff = float(lars_coeff)
        self._lars_weight_decay = float(lars_weight_decay)
        self._epsilon = float(epsilon)
        self._rescale = float(rescale_grad)
        self._exclude = tuple(exclude_from_weight_decay or ())

    def _update(self, p, g, state, lr):
        g = (g * self._rescale).astype(jnp.float32)
        p32 = p.data.astype(jnp.float32)
        wd = self._lars_weight_decay
        pname = getattr(p, "name", "") or ""
        if any(tag in pname for tag in self._exclude):
            wd = 0.0
        # reference cpu/lars_momentum_kernel.cc:65 — LARS scaling only when
        # lars_weight_decay > 0 AND both norms are nonzero; plain momentum at
        # the base lr otherwise (zero-init params, excluded layers)
        if wd > 0:
            p_norm = jnp.linalg.norm(p32.reshape(-1))
            g_norm = jnp.linalg.norm(g.reshape(-1))
            local_lr = jnp.where(
                (p_norm > 0) & (g_norm > 0),
                lr * self._lars_coeff * p_norm
                / (g_norm + wd * p_norm + self._epsilon),
                lr,
            )
        else:
            local_lr = lr
        v = self._momentum * state["velocity"] + local_lr * (g + wd * p32)
        return p32 - v, {"velocity": v}
