"""paddle.incubate.optimizer (reference python/paddle/incubate/optimizer/)."""
from paddle_tpu.incubate.optimizer.distributed_fused_lamb import (
    DistributedFusedLamb,
)
from paddle_tpu.incubate.optimizer.gradient_merge import GradientMergeOptimizer
from paddle_tpu.incubate.optimizer.lars_momentum import LarsMomentumOptimizer
from paddle_tpu.incubate.optimizer.lookahead import LookAhead
from paddle_tpu.incubate.optimizer.modelaverage import ModelAverage

__all__ = [
    'DistributedFusedLamb', 'GradientMergeOptimizer', 'LarsMomentumOptimizer',
    'LookAhead', 'ModelAverage',
]
