"""paddle.incubate.optimizer (reference python/paddle/incubate/optimizer/)."""
from paddle_tpu.incubate.optimizer.lookahead import LookAhead
from paddle_tpu.incubate.optimizer.modelaverage import ModelAverage

__all__ = ['LookAhead', 'ModelAverage']
