"""DistributedFusedLamb (reference python/paddle/incubate/optimizer/
distributed_fused_lamb.py:115 over
paddle/fluid/operators/optimizers/distributed_fused_lamb_op.cu).

The reference flattens all params/grads/moments into a few fused buffers,
shards the optimizer math across ranks, allreduces the LAMB trust-ratio norms,
and keeps fp32 master params for fp16 training.

TPU-native inversion: the fused-buffer machinery IS the compiled train step —
XLA fuses the per-parameter LAMB updates, ZeRO sharding shards the state, and
GSPMD inserts the norm reductions.  What this class adds over plain ``Lamb``
is the reference's *semantic* surface: optional pre-update GLOBAL gradient
clipping folded into the step (``grad_clip`` restricted to
ClipGradByGlobalNorm, matching the reference assertion), master fp32 weights
(``multi_precision`` always on, as the fused kernel's master path), and
gradient accumulation (``gradient_accumulation_steps``) via the same merged
predicate used by GradientMergeOptimizer.
"""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.nn.clip import ClipGradByGlobalNorm
from paddle_tpu.optimizer.optimizers import Lamb

__all__ = ["DistributedFusedLamb"]


class DistributedFusedLamb(Lamb):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, clip_after_allreduce=True,
                 is_grad_scaled_by_nranks=True, alignment=128,
                 use_master_param_norm=True, gradient_accumulation_steps=1,
                 use_master_acc_grad=True, nproc_per_node=None,
                 use_hierarchical_allreduce=False, name=None):
        if grad_clip is not None and not isinstance(grad_clip,
                                                    ClipGradByGlobalNorm):
            raise TypeError(
                "Only ClipGradByGlobalNorm is supported in "
                "DistributedFusedLamb")
        super().__init__(
            learning_rate=learning_rate, lamb_weight_decay=lamb_weight_decay,
            beta1=beta1, beta2=beta2, epsilon=epsilon, parameters=parameters,
            grad_clip=grad_clip,
            exclude_from_weight_decay_fn=exclude_from_weight_decay_fn,
            multi_precision=True, name=name)
        self._multi_precision = True  # fused kernel always keeps fp32 masters
        self._use_master_param_norm = use_master_param_norm
        self._clip_after_allreduce = clip_after_allreduce
        self._is_grad_scaled_by_nranks = is_grad_scaled_by_nranks
        self._acc_steps = int(gradient_accumulation_steps)

    def functional_init_states(self, params):
        states = super().functional_init_states(params)
        if self._acc_steps > 1:
            states["acc_grad"] = {
                k: jnp.zeros(v.shape, jnp.float32)
                for k, v in params.items()
            }
        return states

    def functional_update(self, params, grads, states, lr):
        if self._acc_steps <= 1:
            return super().functional_update(params, grads, states, lr)
        k = self._acc_steps
        step = jnp.asarray(self._global_step)
        apply_now = (step % k) == 0
        acc = states["acc_grad"]
        new_acc = {
            kk: (acc[kk] + g.astype(jnp.float32) if g is not None else acc[kk])
            for kk, g in grads.items()
        }
        eff = {kk: (new_acc[kk] / k if grads.get(kk) is not None else None)
               for kk in grads}
        # global-norm clip on the MERGED gradient (reference clips at apply
        # time after accumulation); installed by TrainStep when acc_steps>1
        merged_clip = self.__dict__.get("_merged_clip")
        if merged_clip is not None:
            eff = merged_clip(eff)
        inner_states = {n: v for n, v in states.items() if n != "acc_grad"}
        prev = self._global_step
        self._global_step = step // k
        try:
            upd_params, upd_states = super().functional_update(
                params, eff, inner_states, lr)
        finally:
            self._global_step = prev
        sel = lambda a, b: jnp.where(apply_now, a, b)
        new_params = {kk: sel(upd_params[kk].astype(params[kk].dtype),
                              params[kk]) for kk in params}
        out_states = {
            n: {kk: sel(upd_states[n][kk], inner_states[n][kk])
                for kk in inner_states[n]}
            for n in inner_states
        }
        out_states["acc_grad"] = {
            kk: sel(jnp.zeros_like(new_acc[kk]), new_acc[kk])
            for kk in new_acc
        }
        return new_params, out_states
