"""ModelAverage (reference python/paddle/incubate/optimizer/modelaverage.py):
maintains running averages of parameters; apply()/restore() swap them in/out."""
from __future__ import annotations

from contextlib import contextmanager

import jax.numpy as jnp


class ModelAverage:
    def __init__(self, average_window_rate, parameters=None, min_average_window=10000,
                 max_average_window=10000, name=None):
        self.avg_rate = average_window_rate
        self.min_window = min_average_window
        self.max_window = max_average_window
        self._params = list(parameters or [])
        self._sum = [jnp.zeros_like(p.data) for p in self._params]
        self._num_accum = 0
        self._backup = None

    def step(self):
        for i, p in enumerate(self._params):
            self._sum[i] = self._sum[i] + p.data
        self._num_accum += 1
        window = max(self.min_window, min(self.max_window, int(self._num_accum * self.avg_rate) + 1))
        if self._num_accum > window:
            # restart accumulation from the current average so apply() stays valid
            avg = [s / self._num_accum for s in self._sum]
            self._sum = avg
            self._num_accum = 1

    def apply(self, executor=None, need_restore=True):
        """Swap averaged params in (context-manager style like the reference)."""

        @contextmanager
        def ctx():
            self._backup = [jnp.array(p.data) for p in self._params]
            n = max(self._num_accum, 1)
            for p, s in zip(self._params, self._sum):
                p._data = (s / n).astype(p.data.dtype)
            try:
                yield
            finally:
                if need_restore:
                    self.restore()

        return ctx()

    def restore(self, executor=None):
        if self._backup is not None:
            for p, b in zip(self._params, self._backup):
                p._data = b
            self._backup = None

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        self.step()
