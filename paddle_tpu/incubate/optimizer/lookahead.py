"""LookAhead optimizer (reference python/paddle/incubate/optimizer/lookahead.py):
slow weights updated every k steps toward the fast (inner) weights."""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.tensor.tensor import Tensor


class LookAhead:
    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        assert inner_optimizer is not None
        assert 0.0 <= alpha <= 1.0
        assert k >= 1 and isinstance(k, int)
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k
        self._global_step = 0
        self._slow_params = None

    @property
    def _parameter_list(self):
        return self.inner_optimizer._parameter_list

    def state_dict(self):
        sd = dict(self.inner_optimizer.state_dict())
        sd["@LOOKAHEAD_STEP"] = self._global_step
        if self._slow_params is not None:
            sd["@LOOKAHEAD_SLOW"] = [jnp.array(s) for s in self._slow_params]
        return sd

    def set_state_dict(self, sd):
        sd = dict(sd)
        self._global_step = sd.pop("@LOOKAHEAD_STEP", 0)
        slow = sd.pop("@LOOKAHEAD_SLOW", None)
        if slow is not None:
            self._slow_params = [jnp.asarray(s) for s in slow]
        self.inner_optimizer.set_state_dict(sd)

    def step(self):
        self.inner_optimizer.step()
        params = self.inner_optimizer._parameter_list
        if self._slow_params is None:
            self._slow_params = [jnp.array(p.data) for p in params]
        self._global_step += 1
        if self._global_step % self.k == 0:
            for p, slow in zip(params, self._slow_params):
                new_slow = slow + self.alpha * (p.data - slow)
                p._data = new_slow
            self._slow_params = [jnp.array(p.data) for p in params]

    def clear_grad(self, set_to_zero=True):
        self.inner_optimizer.clear_grad(set_to_zero)

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()
