"""paddle.incubate (reference python/paddle/incubate/__init__.py)."""
import jax.numpy as _jnp

from paddle_tpu.autograd.engine import apply as _apply
from paddle_tpu.incubate import asp  # noqa: F401
from paddle_tpu.incubate import autograd  # noqa: F401
from paddle_tpu.incubate import distributed  # noqa: F401
from paddle_tpu.incubate import nn  # noqa: F401
from paddle_tpu.incubate.optimizer import LookAhead, ModelAverage  # noqa: F401
from paddle_tpu.incubate import optimizer  # noqa: F401

# graph aliases (the pre-paddle.geometric API surface)
from paddle_tpu.geometric import (  # noqa: F401
    segment_max, segment_mean, segment_min, segment_sum,
)
from paddle_tpu.geometric import reindex_graph as graph_reindex  # noqa: F401
from paddle_tpu.geometric import sample_neighbors as graph_sample_neighbors  # noqa: F401


def graph_send_recv(x, src_index, dst_index, pool_type="sum", out_size=None, name=None):
    from paddle_tpu.geometric import send_u_recv

    return send_u_recv(x, src_index, dst_index, reduce_op=pool_type, out_size=out_size)


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes, sorted_eids=None,
                       return_eids=False, name=None):
    """Multi-hop sampling built on sample_neighbors (reference
    incubate/operators/graph_khop_sampler.py)."""
    import numpy as np

    from paddle_tpu.geometric import reindex_graph, sample_neighbors
    from paddle_tpu.tensor.tensor import Tensor

    nodes = input_nodes
    all_neighbors = []
    all_counts = []
    for size in sample_sizes:
        nbrs, counts = sample_neighbors(row, colptr, nodes, sample_size=size)
        all_neighbors.append(nbrs)
        all_counts.append(counts)
        nodes = Tensor(np.unique(np.concatenate([np.asarray(nodes.numpy()), nbrs.numpy()])))
    neighbors = Tensor(np.concatenate([n.numpy() for n in all_neighbors]))
    counts = Tensor(np.concatenate([c.numpy() for c in all_counts]))
    edge_src, edge_dst, sample_index = reindex_graph(input_nodes, neighbors, counts)
    return edge_src, edge_dst, sample_index, None


def softmax_mask_fuse(x, mask, name=None):
    """softmax(x + mask) fused (reference incubate/operators/softmax_mask_fuse.py)."""
    import jax

    return _apply("softmax_mask_fuse", lambda a, m: jax.nn.softmax(a + m, -1), x, mask)


def softmax_mask_fuse_upper_triangle(x):
    """softmax with causal (upper-triangle) mask fused (reference
    softmax_mask_fuse_upper_triangle.py)."""
    import jax

    def f(a):
        s = a.shape[-1]
        causal = _jnp.tril(_jnp.ones((a.shape[-2], s), bool))
        scores = _jnp.where(causal, a, _jnp.finfo(a.dtype).min)
        return jax.nn.softmax(scores, -1)

    return _apply("softmax_mask_fuse_ut", f, x)


def identity_loss(x, reduction="none"):
    """Mark a tensor as loss (IPU legacy; reference incubate/__init__.py)."""
    if reduction in ("mean", 1):
        return _apply("mean", _jnp.mean, x)
    if reduction in ("sum", 0):
        return _apply("sum", _jnp.sum, x)
    return x


__all__ = [
    'LookAhead', 'ModelAverage', 'softmax_mask_fuse_upper_triangle',
    'softmax_mask_fuse', 'graph_send_recv', 'graph_khop_sampler',
    'graph_sample_neighbors', 'graph_reindex', 'segment_sum', 'segment_mean',
    'segment_max', 'segment_min', 'identity_loss',
]
