"""paddle.incubate.autograd (reference python/paddle/incubate/autograd/__init__.py)."""
from paddle_tpu.incubate.autograd.functional import (
    Hessian, Jacobian, forward_grad, grad, jvp, vjp,
)
from paddle_tpu.incubate.autograd.primapi import disable_prim, enable_prim, prim_enabled

__all__ = ['vjp', 'jvp', 'Jacobian', 'Hessian', 'enable_prim', 'disable_prim',
           'forward_grad', 'grad']
