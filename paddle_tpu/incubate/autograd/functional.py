"""Functional autograd: jvp/vjp/Jacobian/Hessian (reference
python/paddle/incubate/autograd/functional.py).

TPU-native: these delegate to jax.jvp/jax.vjp/jax.jacobian on a jnp-level view
of the user function, so the whole Jacobian computation is one XLA program
(the reference builds per-row tape replays instead)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.tensor.tensor import Tensor


def _as_list(x):
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _jax_fn(func, nin):
    meta = {"single": True}

    def jfn(*arrays):
        ins = [Tensor(a) for a in arrays]
        out = func(*ins)
        meta["single"] = not isinstance(out, (list, tuple))
        outs = _as_list(out)
        return tuple(o.data if isinstance(o, Tensor) else jnp.asarray(o) for o in outs)

    jfn.meta = meta
    return jfn


def _wrap(outs, single):
    ts = [Tensor(o) for o in outs]
    return ts[0] if single and len(ts) == 1 else ts


def vjp(func, xs, v=None):
    """Returns (func(xs), vjp(v)) (reference functional.py vjp)."""
    xs_l = _as_list(xs)
    arrays = [x.data for x in xs_l]
    jfn = _jax_fn(func, len(arrays))
    out, pullback = jax.vjp(lambda *a: jfn(*a), *arrays)
    single_out = jfn.meta["single"]
    if v is None:
        cot = tuple(jnp.ones_like(o) for o in out)
    else:
        cot = tuple(t.data for t in _as_list(v))
    grads = pullback(cot)
    return _wrap(out, single_out), _wrap(grads, not isinstance(xs, (list, tuple)))


def jvp(func, xs, v=None):
    """Returns (func(xs), jvp(v)) (reference functional.py jvp)."""
    xs_l = _as_list(xs)
    arrays = [x.data for x in xs_l]
    jfn = _jax_fn(func, len(arrays))
    if v is None:
        tangents = tuple(jnp.ones_like(a) for a in arrays)
    else:
        tangents = tuple(t.data for t in _as_list(v))
    out, jv = jax.jvp(lambda *a: jfn(*a), tuple(arrays), tangents)
    single_out = jfn.meta["single"]
    return _wrap(out, single_out), _wrap(jv, single_out)


def forward_grad(outputs, inputs, grad_inputs=None):
    raise NotImplementedError(
        "forward_grad operates on the static prim program in the reference; "
        "use paddle.incubate.autograd.jvp for forward-mode derivatives."
    )


def grad(outputs, inputs, grad_outputs=None):
    from paddle_tpu.autograd.engine import grad as _grad

    return _grad(outputs, inputs, grad_outputs=grad_outputs, allow_unused=True)


class Jacobian:
    """Lazy Jacobian matrix (reference functional.py Jacobian): J[i, j] =
    d f_i / d x_j on flattened in/out; is_batched keeps the leading batch dim."""

    def __init__(self, func, xs, is_batched=False):
        self._func = func
        self._xs = _as_list(xs)
        self._is_batched = is_batched
        self._mat = None

    def _compute(self):
        if self._mat is not None:
            return self._mat
        arrays = [x.data for x in self._xs]
        jfn = _jax_fn(self._func, len(arrays))

        if not self._is_batched:
            def flat_fn(flat_in):
                parts = []
                off = 0
                for a in arrays:
                    parts.append(flat_in[off:off + a.size].reshape(a.shape))
                    off += a.size
                outs = jfn(*parts)
                return jnp.concatenate([o.reshape(-1) for o in outs])

            flat = jnp.concatenate([a.reshape(-1) for a in arrays])
            self._mat = jax.jacobian(flat_fn)(flat)
        else:
            # batched: func maps (B, n) -> (B, m); J is (B, m, n)
            def single_fn(flat_in):
                parts = []
                off = 0
                for a in arrays:
                    n = a.size // a.shape[0]
                    parts.append(flat_in[off:off + n].reshape(a.shape[1:]))
                    off += n
                outs = jfn(*[p[None] for p in parts])
                return jnp.concatenate([o.reshape(-1) for o in outs])

            per_sample = jnp.stack(
                [jnp.concatenate([a[i].reshape(-1) for a in arrays]) for i in range(arrays[0].shape[0])]
            )
            self._mat = jax.vmap(jax.jacobian(single_fn))(per_sample)
        return self._mat

    @property
    def shape(self):
        return list(self._compute().shape)

    def __getitem__(self, idx):
        return Tensor(self._compute()[idx])

    def numpy(self):
        import numpy as np

        return np.asarray(self._compute())


class Hessian(Jacobian):
    """Hessian of a scalar-output func (reference functional.py Hessian)."""

    def _compute(self):
        if self._mat is not None:
            return self._mat
        arrays = [x.data for x in self._xs]
        jfn = _jax_fn(self._func, len(arrays))

        if not self._is_batched:
            def flat_fn(flat_in):
                parts = []
                off = 0
                for a in arrays:
                    parts.append(flat_in[off:off + a.size].reshape(a.shape))
                    off += a.size
                outs = jfn(*parts)
                return outs[0].reshape(())

            flat = jnp.concatenate([a.reshape(-1) for a in arrays])
            self._mat = jax.hessian(flat_fn)(flat)
        else:
            def single_fn(flat_in):
                parts = []
                off = 0
                for a in arrays:
                    n = a.size // a.shape[0]
                    parts.append(flat_in[off:off + n].reshape(a.shape[1:]))
                    off += n
                outs = jfn(*[p[None] for p in parts])
                return outs[0].reshape(())

            per_sample = jnp.stack(
                [jnp.concatenate([a[i].reshape(-1) for a in arrays]) for i in range(arrays[0].shape[0])]
            )
            self._mat = jax.vmap(jax.hessian(single_fn))(per_sample)
        return self._mat
