"""Prim-mode switches (reference python/paddle/incubate/autograd/primapi.py).

The reference lowers big ops to primitives so its compiler (CINN) sees a small
op set; on TPU, XLA already consumes HLO primitives, so these are bookkeeping
flags kept for API parity (decomposition registry: paddle_tpu.decomposition)."""
_PRIM_ENABLED = False


def enable_prim():
    global _PRIM_ENABLED
    _PRIM_ENABLED = True


def disable_prim():
    global _PRIM_ENABLED
    _PRIM_ENABLED = False


def prim_enabled():
    return _PRIM_ENABLED
