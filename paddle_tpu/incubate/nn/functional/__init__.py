"""paddle.incubate.nn.functional (reference python/paddle/incubate/nn/functional/).

On TPU these "fused" ops are single jnp expressions handed to XLA whole — the
fusion the reference does with hand-written CUDA kernels
(paddle/phi/kernels/fusion/) falls out of the compiler here."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.autograd.engine import apply
from paddle_tpu.tensor.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))




def _ln_args(x, scale, bias):
    """Collect the optional scale/bias tensors for a last-axis LN apply() call."""
    args = [x]
    if scale is not None:
        args.append(_t(scale))
    if bias is not None:
        args.append(_t(bias))
    return args


def _ln_closure(has_scale, has_bias, eps):
    """Last-axis layer-norm as one jnp closure (signature: (a, [scale], [bias]))."""

    def ln(a, *wb):
        mean = a.mean(-1, keepdims=True)
        var = a.var(-1, keepdims=True)
        out = (a - mean) / jnp.sqrt(var + eps)
        i = 0
        if has_scale:
            out = out * wb[i]
            i += 1
        if has_bias:
            out = out + wb[i]
        return out

    return ln


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False, name=None):
    """reference incubate/nn/functional/fused_matmul_bias.py."""

    def f(a, b, *rest):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2)
        out = a @ b
        return out + rest[0] if rest else out

    args = [_t(x), _t(y)] + ([_t(bias)] if bias is not None else [])
    return apply("fused_matmul_bias", f, *args)


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    return fused_matmul_bias(x, weight, bias, False, transpose_weight)


def fused_linear_activation(x, y, bias, trans_x=False, trans_y=False, activation="gelu", name=None):
    out = fused_matmul_bias(x, y, bias, trans_x, trans_y)
    act = {"gelu": jax.nn.gelu, "relu": jax.nn.relu, "none": lambda v: v}[activation]
    return apply("fused_act", act, out)


def swiglu(x, y=None, name=None):
    """reference incubate/nn/functional/swiglu.py: silu(x) * y (y = second half
    of x when not given)."""

    if y is None:
        def f(a):
            a1, a2 = jnp.split(a, 2, axis=-1)
            return jax.nn.silu(a1) * a2

        return apply("swiglu", f, _t(x))
    return apply("swiglu", lambda a, b: jax.nn.silu(a) * b, _t(x), _t(y))


def fused_bias_act(x, bias=None, dequant_scales=None, shift=None, smooth=None,
                   act_method="gelu", compute_dtype="default", quant_scale=-1,
                   quant_round_type=0, quant_max_bound=0, quant_min_bound=0, name=None):
    """reference incubate/nn/functional/fused_bias_act.py (quant paths omitted:
    quantization on TPU flows through paddle.quantization fake-quant)."""
    acts = {"gelu": jax.nn.gelu, "relu": jax.nn.relu, "silu": jax.nn.silu,
            "swiglu": None, "geglu": None}
    if act_method in ("swiglu", "geglu"):
        inner = jax.nn.silu if act_method == "swiglu" else jax.nn.gelu

        def f(a, *rest):
            if rest:
                a = a + rest[0]
            a1, a2 = jnp.split(a, 2, axis=-1)
            return inner(a1) * a2
    else:
        act = acts[act_method]

        def f(a, *rest):
            if rest:
                a = a + rest[0]
            return act(a)

    args = [_t(x)] + ([_t(bias)] if bias is not None else [])
    return apply("fused_bias_act", f, *args)


def fused_layer_norm(x, norm_weight, norm_bias, epsilon, residual_alpha=1.0,
                     begin_norm_axis=1, bias=None, residual=None, quant_scale=-1,
                     quant_round_type=0, quant_max_bound=0, quant_min_bound=0, name=None):
    """reference incubate/nn/functional/fused_layer_norm.py: (x + bias +
    residual*alpha) → layernorm; returns (out, residual_out) when residual given."""

    def f(a, w, b, *rest):
        res_out = a
        i = 0
        if bias is not None:
            res_out = res_out + rest[i]
            i += 1
        if residual is not None:
            res_out = res_out + residual_alpha * rest[i]
            i += 1
        axes = tuple(range(begin_norm_axis, a.ndim))
        mean = res_out.mean(axes, keepdims=True)
        var = res_out.var(axes, keepdims=True)
        out = (res_out - mean) / jnp.sqrt(var + epsilon)
        if w is not None:
            out = out * w
        if b is not None:
            out = out + b
        return (out, res_out) if residual is not None else out

    args = [_t(x), _t(norm_weight) if norm_weight is not None else None,
            _t(norm_bias) if norm_bias is not None else None]
    extra = []
    if bias is not None:
        extra.append(_t(bias))
    if residual is not None:
        extra.append(_t(residual))
    return apply("fused_layer_norm", f, *(args + extra))


def fused_rms_norm(x, norm_weight, norm_bias, epsilon, begin_norm_axis,
                   bias=None, residual=None, quant_scale=-1, quant_round_type=0,
                   quant_max_bound=0, quant_min_bound=0, name=None):
    """reference incubate/nn/functional/fused_rms_norm.py."""

    def f(a, w, *rest):
        res_out = a
        i = 0
        if bias is not None:
            res_out = res_out + rest[i]
            i += 1
        if residual is not None:
            res_out = res_out + rest[i]
            i += 1
        axes = tuple(range(begin_norm_axis, a.ndim))
        ms = jnp.mean(jnp.square(res_out), axes, keepdims=True)
        out = res_out * jax.lax.rsqrt(ms + epsilon)
        if w is not None:
            out = out * w
        return (out, res_out) if residual is not None else out

    args = [_t(x), _t(norm_weight) if norm_weight is not None else None]
    extra = []
    if bias is not None:
        extra.append(_t(bias))
    if residual is not None:
        extra.append(_t(residual))
    out = apply("fused_rms_norm", f, *(args + extra))
    if norm_bias is not None:
        nb = _t(norm_bias)
        if residual is not None:
            return apply("add", jnp.add, out[0], nb), out[1]
        return apply("add", jnp.add, out, nb)
    return out


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      seed=None, name=None):
    """reference incubate/nn/functional/fused_dropout_add.py: dropout(x) + y."""
    from paddle_tpu.nn.functional.common import dropout

    return apply("add", jnp.add, dropout(_t(x), p=p, training=training, mode=mode), _t(y))


def fused_bias_dropout_residual_layer_norm(
    x, residual, bias=None, ln_scale=None, ln_bias=None, dropout_rate=0.5,
    ln_epsilon=1e-5, training=True, mode='upscale_in_train', name=None,
):
    """reference incubate/nn/functional/fused_transformer.py:
    layer_norm(residual + dropout(x + bias))."""
    from paddle_tpu.nn.functional.common import dropout

    h = _t(x)
    if bias is not None:
        h = apply("add", jnp.add, h, _t(bias))
    h = dropout(h, p=dropout_rate, training=training, mode=mode)
    h = apply("add", jnp.add, h, _t(residual))

    ln = _ln_closure(ln_scale is not None, ln_bias is not None, ln_epsilon)
    return apply("bias_dropout_residual_ln", ln, *_ln_args(h, ln_scale, ln_bias))


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True,
                                    time_major=False, rotary_emb_base=10000.0, name=None):
    """reference incubate/nn/functional/fused_rotary_position_embedding.py.

    q/k/v: (batch, seq, heads, head_dim).  Returns rotated (q, k, v) (None where
    input None)."""

    def rot(a, cos_t, sin_t):
        if use_neox_rotary_style:
            half = a.shape[-1] // 2
            a1, a2 = a[..., :half], a[..., half:]
            rotated = jnp.concatenate([-a2, a1], -1)
            return a * cos_t + rotated * sin_t
        a1 = a[..., 0::2]
        a2 = a[..., 1::2]
        rot_a = jnp.stack([-a2, a1], -1).reshape(a.shape)
        return a * cos_t + rot_a * sin_t

    def f(qa, *rest):
        seq_axis = 0 if time_major else 1
        seq_len = qa.shape[seq_axis]
        dim = qa.shape[-1]
        rest = list(rest)
        i = 0
        ka = rest[i] if k is not None else None
        i += k is not None
        va = rest[i] if v is not None else None
        i += v is not None
        if sin is not None:
            # the reference contract: outputs carry q's dtype (its docstring:
            # "has same shape and data type as q") — cast user tables up
            # front so the jnp fallback and the Pallas fast path agree
            sin_t = rest[i].astype(qa.dtype)
            cos_t = rest[i + 1].astype(qa.dtype)
            i += 2
        else:
            inv = 1.0 / (rotary_emb_base ** (jnp.arange(0, dim, 2, dtype=jnp.float32) / dim))
            t = jnp.arange(seq_len, dtype=jnp.float32)
            freqs = jnp.outer(t, inv)
            emb = jnp.concatenate([freqs, freqs], -1) if use_neox_rotary_style else jnp.repeat(freqs, 2, -1)
            sin_t = jnp.sin(emb).astype(qa.dtype)
            cos_t = jnp.cos(emb).astype(qa.dtype)
        if position_ids is not None:
            pid = rest[-1].astype(jnp.int32)
            sin_t = jnp.squeeze(sin_t)[pid]  # (b, s, d)
            cos_t = jnp.squeeze(cos_t)[pid]
            if time_major:  # layout (s, b, h, d)
                sin_t = jnp.swapaxes(sin_t, 0, 1)[:, :, None, :]
                cos_t = jnp.swapaxes(cos_t, 0, 1)[:, :, None, :]
            else:
                sin_t = sin_t[:, :, None, :]
                cos_t = cos_t[:, :, None, :]
        else:
            # TPU fast path for the common case (half-split style,
            # INTERNALLY-computed tables, q+k, batch-major, v unrotated):
            # one Pallas pass in the packed layout (ops/fused_rope.py)
            # instead of the 5+ XLA passes of the textbook chain.
            # User-PROVIDED sin/cos stay on the jnp path: the kernel's vjp
            # treats the tables as positional constants (zero cotangent),
            # which would silently kill gradients to trainable tables
            # (review r5)
            if (sin is None and use_neox_rotary_style and not time_major
                    and va is None and ka is not None and qa.ndim == 4):
                from paddle_tpu.ops import fused_rope as _frope

                bb, ll, nh, dd = qa.shape
                nkv = ka.shape[2]
                c2 = jnp.squeeze(cos_t)
                s2 = jnp.squeeze(sin_t)
                if (c2.shape == (ll, dd)
                        and _frope.available((bb, ll, nh * dd),
                                             (bb, ll, nkv * dd), nh, nkv)):
                    rq, rk = _frope.fused_rope(
                        qa.reshape(bb, ll, nh * dd),
                        ka.reshape(bb, ll, nkv * dd), c2, s2, nh, nkv)
                    return (rq.reshape(qa.shape), rk.reshape(ka.shape))
            sin_t = jnp.squeeze(sin_t).reshape(1, seq_len, 1, dim) if not time_major else jnp.squeeze(sin_t).reshape(seq_len, 1, 1, dim)
            cos_t = jnp.squeeze(cos_t).reshape(1, seq_len, 1, dim) if not time_major else jnp.squeeze(cos_t).reshape(seq_len, 1, 1, dim)
        outs = [rot(qa, cos_t, sin_t)]
        if ka is not None:
            outs.append(rot(ka, cos_t, sin_t))
        if va is not None:
            outs.append(rot(va, cos_t, sin_t))
        return tuple(outs)

    args = [_t(q)]
    if k is not None:
        args.append(_t(k))
    if v is not None:
        args.append(_t(v))
    if sin is not None:
        args += [_t(sin), _t(cos)]
    if position_ids is not None:
        args.append(_t(position_ids))
    outs = apply("fused_rope", f, *args)
    outs = list(outs) if isinstance(outs, (list, tuple)) else [outs]
    res = [outs.pop(0)]
    res.append(outs.pop(0) if k is not None else None)
    res.append(outs.pop(0) if v is not None else None)
    return tuple(res)


def fused_multi_head_attention(x, qkv_weight, linear_weight, pre_layer_norm=False,
                               pre_ln_scale=None, pre_ln_bias=None, ln_scale=None,
                               ln_bias=None, pre_ln_epsilon=1e-5, qkv_bias=None,
                               linear_bias=None, cache_kv=None, attn_mask=None,
                               dropout_rate=0.5, attn_dropout_rate=0.5, ln_epsilon=1e-5,
                               training=True, mode='upscale_in_train', ring_id=-1,
                               add_residual=True, num_heads=-1, transpose_qkv_wb=False,
                               name=None):
    """reference incubate/nn/functional/fused_transformer.py
    fused_multi_head_attention: full pre/post-LN MHA block in one op."""
    from paddle_tpu.nn.functional.common import dropout
    from paddle_tpu.tensor.random import default_generator

    attn_key = default_generator.next_key()

    def f(xa, qkvw, lw, *rest):
        names = []
        if qkv_bias is not None:
            names.append("qkvb")
        if linear_bias is not None:
            names.append("lb")
        if pre_ln_scale is not None:
            names.append("pls")
        if pre_ln_bias is not None:
            names.append("plb")
        if ln_scale is not None:
            names.append("lns")
        if ln_bias is not None:
            names.append("lnb")
        if attn_mask is not None:
            names.append("mask")
        r = dict(zip(names, rest))
        b, s, d = xa.shape
        h = xa
        if pre_layer_norm:
            mean = h.mean(-1, keepdims=True)
            var = h.var(-1, keepdims=True)
            h = (h - mean) / jnp.sqrt(var + pre_ln_epsilon)
            if "pls" in r:
                h = h * r["pls"]
            if "plb" in r:
                h = h + r["plb"]
        if transpose_qkv_wb:
            nh = num_heads
            qkv = h @ qkvw  # (b, s, 3d)
            if "qkvb" in r:
                qkv = qkv + r["qkvb"]
            qkv = qkv.reshape(b, s, 3, nh, d // nh)
        else:
            nh = qkvw.shape[1]
            hd = qkvw.shape[2]
            qkv = jnp.einsum("bsd,thkd->bsthk", h, qkvw)  # (b,s,3,nh,hd)
            if "qkvb" in r:
                qkv = qkv + r["qkvb"].reshape(1, 1, 3, nh, hd)
        q, kk, vv = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        scores = jnp.einsum("bshd,bthd->bhst", q, kk) / jnp.sqrt(q.shape[-1])
        if "mask" in r:
            scores = scores + r["mask"]
        att = jax.nn.softmax(scores, -1)
        if training and attn_dropout_rate > 0.0:
            keep = jax.random.bernoulli(attn_key, 1.0 - attn_dropout_rate, att.shape)
            att = jnp.where(keep, att / (1.0 - attn_dropout_rate), 0.0)
        ctx = jnp.einsum("bhst,bthd->bshd", att, vv).reshape(b, s, -1)
        out = ctx @ (lw.reshape(-1, lw.shape[-1]) if lw.ndim > 2 else lw)
        if "lb" in r:
            out = out + r["lb"]
        return out, xa

    args = [_t(x), _t(qkv_weight), _t(linear_weight)]
    for t in (qkv_bias, linear_bias, pre_ln_scale, pre_ln_bias, ln_scale, ln_bias, attn_mask):
        if t is not None:
            args.append(_t(t))
    out, residual = apply("fused_mha", f, *args)
    out = dropout(out, p=dropout_rate, training=training, mode=mode)
    if add_residual:
        out = apply("add", jnp.add, out, residual)
    if not pre_layer_norm:
        ln = _ln_closure(ln_scale is not None, ln_bias is not None, ln_epsilon)
        out = apply("post_ln", ln, *_ln_args(out, ln_scale, ln_bias))
    return out


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None, ln2_scale=None,
                      ln2_bias=None, dropout1_rate=0.5, dropout2_rate=0.5,
                      activation="relu", ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True, ring_id=-1,
                      mode='upscale_in_train', name=None):
    """reference fused_feedforward: LN → linear1 → act → dropout → linear2 →
    dropout → residual (+post-LN)."""
    from paddle_tpu.nn.functional.common import dropout

    act = {"relu": jax.nn.relu, "gelu": jax.nn.gelu}[activation]
    residual = _t(x)
    h = residual
    if pre_layer_norm:
        ln1 = _ln_closure(ln1_scale is not None, ln1_bias is not None, ln1_epsilon)
        h = apply("ffn_pre_ln", ln1, *_ln_args(h, ln1_scale, ln1_bias))

    def lin1(a, w, *bias):
        o = a @ w
        if bias:
            o = o + bias[0]
        return act(o)

    h = apply("ffn_lin1", lin1, h, _t(linear1_weight), *([_t(linear1_bias)] if linear1_bias is not None else []))
    h = dropout(h, p=dropout1_rate, training=training, mode=mode)

    def lin2(a, w, *bias):
        o = a @ w
        if bias:
            o = o + bias[0]
        return o

    h = apply("ffn_lin2", lin2, h, _t(linear2_weight), *([_t(linear2_bias)] if linear2_bias is not None else []))
    h = dropout(h, p=dropout2_rate, training=training, mode=mode)
    out = apply("add", jnp.add, h, residual)
    if not pre_layer_norm:
        ln2 = _ln_closure(ln2_scale is not None, ln2_bias is not None, ln2_epsilon)
        out = apply("ffn_post_ln", ln2, *_ln_args(out, ln2_scale, ln2_bias))
    return out


def fused_moe(x, gate_weight, ffn1_weight, ffn2_weight, ffn1_bias=None,
              ffn2_bias=None, quant_method="None", moe_topk=2, norm_topk_prob=True, name=None):
    """reference incubate/nn/functional/fused_moe.py: token → top-k experts →
    weighted combine, dense einsum formulation (MXU-friendly; EP sharding via
    paddle.incubate.distributed.models.moe.MoELayer)."""

    def f(xa, gw, w1, w2, *rest):
        b, s, d = xa.shape
        tokens = xa.reshape(-1, d)
        logits = tokens @ gw
        probs = jax.nn.softmax(logits, -1)
        topv, topi = jax.lax.top_k(probs, moe_topk)
        if norm_topk_prob:
            topv = topv / topv.sum(-1, keepdims=True)
        i = 0
        b1 = rest[i] if ffn1_bias is not None else None
        i += ffn1_bias is not None
        b2 = rest[i] if ffn2_bias is not None else None
        # dense dispatch: compute all experts (E small) — one big batched matmul
        h = jnp.einsum("td,edf->tef", tokens, w1)
        if b1 is not None:
            h = h + b1[None]
        h = jax.nn.gelu(h)
        o = jnp.einsum("tef,efd->ted", h, w2)
        if b2 is not None:
            o = o + b2[None]
        weight = jnp.zeros((tokens.shape[0], w1.shape[0]), xa.dtype)
        weight = weight.at[jnp.arange(tokens.shape[0])[:, None], topi].set(topv)
        out = jnp.einsum("ted,te->td", o, weight)
        return out.reshape(b, s, d)

    args = [_t(x), _t(gate_weight), _t(ffn1_weight), _t(ffn2_weight)]
    if ffn1_bias is not None:
        args.append(_t(ffn1_bias))
    if ffn2_bias is not None:
        args.append(_t(ffn2_bias))
    return apply("fused_moe", f, *args)


def masked_multihead_attention(x, cache_kv=None, bias=None, src_mask=None,
                               cum_offsets=None, sequence_lengths=None,
                               rotary_tensor=None, beam_cache_offset=None,
                               qkv_out_scale=None, out_shift=None,
                               out_smooth=None, seq_len=1, rotary_emb_dims=0,
                               use_neox_rotary_style=False,
                               compute_dtype="default", out_scale=-1,
                               quant_round_type=1, quant_max_bound=127.0,
                               quant_min_bound=-127.0):
    """Fused single-token decoding attention over a preallocated KV cache
    (reference: python/paddle/incubate/nn/functional/
    masked_multihead_attention.py over the phi fused kernel
    paddle/phi/kernels/fusion/gpu/masked_multihead_attention_kernel.cu).

    TPU-native: built on ops/decode_attention.py — static shapes, one
    compiled append-and-attend program; the cache layout
    ``[2, B, H, max_seq_len, D]`` is consumed directly (no per-step
    transpose).

    x [B, 3*H*D] — one decode step's packed qkv; cache_kv
    [2, B, H, Lmax, D]; bias [3*H*D] or [3, H, D]; src_mask
    [B, 1, 1, S] additive scores bias whose trailing length S fixes the
    timestep (S = cur_len + 1, the reference's convention) unless
    ``sequence_lengths [B(,1)]`` gives per-batch cache lengths.  Returns
    (out [B, H*D], cache_kv_out).

    Not supported on TPU (loud raise, no silent fallback): beam search
    offsets, cum_offsets, int8 quant in/out scales, and rotary_tensor —
    rope on TPU is applied in the model before the cache write
    (models/llama_decode.py), matching this framework's decode design.
    """
    for name, val in (("beam_cache_offset", beam_cache_offset),
                      ("cum_offsets", cum_offsets),
                      ("rotary_tensor", rotary_tensor),
                      ("qkv_out_scale", qkv_out_scale),
                      ("out_shift", out_shift), ("out_smooth", out_smooth)):
        if val is not None:
            raise NotImplementedError(
                f"masked_multihead_attention: {name} is not supported on "
                "TPU (beam/quant/fused-rope live outside the decode op "
                "here; apply rope in the model, see models/llama_decode.py)")
    if out_scale != -1:
        raise NotImplementedError(
            "masked_multihead_attention: int8 out_scale quantization is "
            "not supported on TPU")
    if cache_kv is None:
        raise ValueError("masked_multihead_attention requires cache_kv")
    if src_mask is None and sequence_lengths is None:
        raise ValueError(
            "masked_multihead_attention: need src_mask (its trailing dim "
            "fixes the timestep) or sequence_lengths")

    from paddle_tpu.ops.decode_attention import decode_attention

    def f(xa, cache, *rest):
        i = 0
        b_ = rest[i] if bias is not None else None
        i += bias is not None
        mask = rest[i] if src_mask is not None else None
        i += src_mask is not None
        seqlens = rest[i] if sequence_lengths is not None else None

        b, three_hd = xa.shape
        h = cache.shape[2]
        d = cache.shape[4]
        lmax = cache.shape[3]
        if three_hd != 3 * h * d:
            raise ValueError(
                f"masked_multihead_attention: x width {three_hd} != "
                f"3*H*D = {3 * h * d} from cache_kv {cache.shape}")
        if b_ is not None:
            xa = xa + b_.reshape(three_hd).astype(xa.dtype)
        q, k, v = jnp.split(xa.reshape(b, 3, h, d), 3, axis=1)
        q = q.reshape(b, 1, h, d)
        k = k.reshape(b, 1, h, d)
        v = v.reshape(b, 1, h, d)
        if seqlens is not None:
            lengths = seqlens.reshape(b).astype(jnp.int32)
        else:
            lengths = jnp.full((b,), mask.shape[-1] - 1, jnp.int32)
        attn_bias = None
        if mask is not None:
            # additive mask over [0, S); pad to Lmax (positions >= S are
            # causally dead anyway)
            s = mask.reshape(b, 1, 1, mask.shape[-1]).astype(jnp.float32)
            attn_bias = jnp.pad(s, ((0, 0), (0, 0), (0, 0),
                                    (0, lmax - mask.shape[-1])))
        out, kc, vc, _ = decode_attention(
            q, k, v, cache[0], cache[1], lengths, layout="bhld",
            attn_bias=attn_bias)
        return out.reshape(b, h * d), jnp.stack([kc, vc])

    args = [_t(x), _t(cache_kv)]
    if bias is not None:
        args.append(_t(bias))
    if src_mask is not None:
        args.append(_t(src_mask))
    if sequence_lengths is not None:
        args.append(_t(sequence_lengths))
    return apply("masked_multihead_attention", f, *args)


def memory_efficient_attention(query, key, value, attn_bias=None, p=0.0,
                               scale=None, training=True, name=None):
    """reference incubate/nn/memory_efficient_attention.py — on TPU the
    flash-attention pallas kernel IS the memory-efficient path."""
    from paddle_tpu.nn.functional.attention import scaled_dot_product_attention

    mask = attn_bias if not hasattr(attn_bias, "materialize") else attn_bias.materialize()
    return scaled_dot_product_attention(query, key, value, attn_mask=mask,
                                        dropout_p=p, training=training, scale=scale)
