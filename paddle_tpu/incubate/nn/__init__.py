"""paddle.incubate.nn (reference python/paddle/incubate/nn/__init__.py)."""
from paddle_tpu.incubate.nn import functional
from paddle_tpu.incubate.nn.layer import (
    FusedBiasDropoutResidualLayerNorm, FusedDropoutAdd, FusedFeedForward,
    FusedLinear, FusedMultiHeadAttention, FusedMultiTransformer,
    FusedTransformerEncoderLayer,
)

__all__ = [
    'FusedMultiHeadAttention', 'FusedFeedForward', 'FusedTransformerEncoderLayer',
    'FusedMultiTransformer', 'FusedLinear', 'FusedBiasDropoutResidualLayerNorm',
    'FusedDropoutAdd',
]
