"""Fused layers (reference python/paddle/incubate/nn/layer/fused_transformer.py,
fused_linear.py, fused_dropout_add.py)."""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.incubate.nn import functional as F
from paddle_tpu.nn.layer.layers import Layer
from paddle_tpu.nn import initializer as I


class FusedLinear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None,
                 transpose_weight=False, name=None):
        super().__init__()
        self.transpose_weight = transpose_weight
        w_shape = [out_features, in_features] if transpose_weight else [in_features, out_features]
        self.weight = self.create_parameter(w_shape, attr=weight_attr)
        self.bias = self.create_parameter([out_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.fused_linear(x, self.weight, self.bias, self.transpose_weight)


class FusedDropoutAdd(Layer):
    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.mode = mode

    def forward(self, x, y):
        return F.fused_dropout_add(x, y, p=self.p, training=self.training, mode=self.mode)


class FusedBiasDropoutResidualLayerNorm(Layer):
    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None, bias_attr=None,
                 epsilon=1e-5, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.dropout_rate = dropout_rate
        self._epsilon = epsilon
        self.linear_bias = self.create_parameter([embed_dim], is_bias=True)
        self.ln_scale = self.create_parameter([embed_dim], default_initializer=I.Constant(1.0))
        self.ln_bias = self.create_parameter([embed_dim], is_bias=True)

    def forward(self, x, residual):
        return F.fused_bias_dropout_residual_layer_norm(
            x, residual, bias=self.linear_bias, ln_scale=self.ln_scale,
            ln_bias=self.ln_bias, dropout_rate=self.dropout_rate,
            ln_epsilon=self._epsilon, training=self.training,
        )


class FusedMultiHeadAttention(Layer):
    """reference fused_transformer.py FusedMultiHeadAttention (qkv packed)."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5, attn_dropout_rate=0.5,
                 kdim=None, vdim=None, normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None, linear_weight_attr=None,
                 linear_bias_attr=None, pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5,
                 nranks=1, ring_id=-1, transpose_qkv_wb=False, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self._epsilon = epsilon
        self.transpose_qkv_wb = transpose_qkv_wb
        if transpose_qkv_wb:
            qkv_shape = [embed_dim, 3 * embed_dim]
        else:
            qkv_shape = [3, num_heads, self.head_dim, embed_dim]
        self.qkv_weight = self.create_parameter(qkv_shape, attr=qkv_weight_attr)
        self.qkv_bias = self.create_parameter(
            [3 * embed_dim] if transpose_qkv_wb else [3, num_heads, self.head_dim],
            attr=qkv_bias_attr, is_bias=True)
        self.linear_weight = self.create_parameter([embed_dim, embed_dim], attr=linear_weight_attr)
        self.linear_bias = self.create_parameter([embed_dim], attr=linear_bias_attr, is_bias=True)
        self.pre_ln_scale = self.create_parameter([embed_dim], default_initializer=I.Constant(1.0))
        self.pre_ln_bias = self.create_parameter([embed_dim], is_bias=True)
        self.ln_scale = self.create_parameter([embed_dim], default_initializer=I.Constant(1.0))
        self.ln_bias = self.create_parameter([embed_dim], is_bias=True)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        return F.fused_multi_head_attention(
            query, self.qkv_weight, self.linear_weight,
            pre_layer_norm=self.normalize_before,
            pre_ln_scale=self.pre_ln_scale, pre_ln_bias=self.pre_ln_bias,
            ln_scale=self.ln_scale, ln_bias=self.ln_bias,
            pre_ln_epsilon=self._epsilon, qkv_bias=self.qkv_bias,
            linear_bias=self.linear_bias, attn_mask=attn_mask,
            dropout_rate=self.dropout_rate, attn_dropout_rate=self.attn_dropout_rate,
            ln_epsilon=self._epsilon, training=self.training,
            num_heads=self.num_heads, transpose_qkv_wb=self.transpose_qkv_wb,
        )


class FusedFeedForward(Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1, epsilon=1e-5,
                 activation="relu", act_dropout_rate=None, normalize_before=False,
                 linear1_weight_attr=None, linear1_bias_attr=None,
                 linear2_weight_attr=None, linear2_bias_attr=None,
                 ln1_scale_attr=None, ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self._d_model = d_model
        self._dropout_rate = dropout_rate
        self._act_dropout_rate = dropout_rate if act_dropout_rate is None else act_dropout_rate
        self._activation = activation
        self._epsilon = epsilon
        self._normalize_before = normalize_before
        self.linear1_weight = self.create_parameter([d_model, dim_feedforward], attr=linear1_weight_attr)
        self.linear1_bias = self.create_parameter([dim_feedforward], attr=linear1_bias_attr, is_bias=True)
        self.linear2_weight = self.create_parameter([dim_feedforward, d_model], attr=linear2_weight_attr)
        self.linear2_bias = self.create_parameter([d_model], attr=linear2_bias_attr, is_bias=True)
        self.ln1_scale = self.create_parameter([d_model], default_initializer=I.Constant(1.0))
        self.ln1_bias = self.create_parameter([d_model], is_bias=True)
        self.ln2_scale = self.create_parameter([d_model], default_initializer=I.Constant(1.0))
        self.ln2_bias = self.create_parameter([d_model], is_bias=True)

    def forward(self, src, cache=None):
        return F.fused_feedforward(
            src, self.linear1_weight, self.linear2_weight,
            linear1_bias=self.linear1_bias, linear2_bias=self.linear2_bias,
            ln1_scale=self.ln1_scale, ln1_bias=self.ln1_bias,
            ln2_scale=self.ln2_scale, ln2_bias=self.ln2_bias,
            dropout1_rate=self._act_dropout_rate, dropout2_rate=self._dropout_rate,
            activation=self._activation, ln1_epsilon=self._epsilon,
            ln2_epsilon=self._epsilon, pre_layer_norm=self._normalize_before,
            training=self.training,
        )


class FusedTransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None, act_dropout_rate=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout_rate = dropout_rate if attn_dropout_rate is None else attn_dropout_rate
        act_dropout_rate = dropout_rate if act_dropout_rate is None else act_dropout_rate
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=attn_dropout_rate, normalize_before=normalize_before,
        )
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before,
        )

    def forward(self, src, src_mask=None, cache=None):
        out = self.fused_attn(src, attn_mask=src_mask)
        return self.ffn(out)


class FusedMultiTransformer(Layer):
    """reference fused_transformer.py FusedMultiTransformer: N decoder blocks with
    packed per-layer weight lists (inference-oriented)."""

    def __init__(self, embed_dim, num_heads, dim_feedforward, dropout_rate=0.0,
                 activation="gelu", normalize_before=True, num_layers=-1,
                 nranks=1, ring_id=-1, name=None, **kw):
        super().__init__()
        assert num_layers > 0, "num_layers must be given"
        self.layers = []
        for i in range(num_layers):
            blk = FusedTransformerEncoderLayer(
                embed_dim, num_heads, dim_feedforward, dropout_rate=dropout_rate,
                activation=activation, normalize_before=normalize_before,
            )
            self.add_sublayer(f"layer_{i}", blk)
            self.layers.append(blk)

    def forward(self, src, attn_mask=None, caches=None, **kw):
        h = src
        for blk in self.layers:
            h = blk(h, src_mask=attn_mask)
        return h
