from paddle_tpu.incubate.distributed.models import moe  # noqa: F401
