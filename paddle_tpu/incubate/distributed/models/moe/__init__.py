"""paddle.incubate.distributed.models.moe (reference __init__.py)."""
from paddle_tpu.incubate.distributed.models.moe.gate import (
    BaseGate, GShardGate, NaiveGate, SwitchGate,
)
from paddle_tpu.incubate.distributed.models.moe.moe_layer import MoELayer

__all__ = ['MoELayer', 'BaseGate', 'GShardGate', 'NaiveGate', 'SwitchGate']
