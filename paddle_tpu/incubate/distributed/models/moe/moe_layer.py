"""MoELayer (reference python/paddle/incubate/distributed/models/moe/moe_layer.py:263).

TPU-native dispatch: instead of the reference's global_scatter/global_gather CUDA
all-to-all kernels, tokens are routed with capacity-bucketed one-hot einsums (the
GShard/Mesh-TensorFlow formulation).  Under pjit with the expert axis sharded over
the moe_group mesh axis, XLA lowers the einsum pair to exactly the all-to-all the
reference does by hand — and overlaps it with expert compute."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.autograd.engine import apply
from paddle_tpu.incubate.distributed.models.moe.gate import (
    BaseGate, GShardGate, NaiveGate, SwitchGate,
)
from paddle_tpu.nn.layer.layers import Layer
from paddle_tpu.nn.layer.container import LayerList


class MoELayer(Layer):
    """``dispatch`` selects the single-chip routing formulation:

    - "dense" (default, the r3 path): every expert runs over every token,
      outputs scaled by the combine weight (zero for unrouted).  Simple,
      dropless, but top-k/E of the expert FLOPs are wasted — 4x at the
      bench's top-2-of-8.
    - "gather": GShard capacity dispatch.  Token-expert pairs are sorted
      by expert (stable argsort), each expert processes only its first
      ``capacity`` routed tokens gathered into a [E, c, d] bucket, and a
      scatter-add combines weighted expert outputs.  Pairs beyond
      capacity are DROPPED (the GShard paper's overflow semantics — the
      token keeps its other expert's contribution).  All shapes static;
      gather/scatter differentiate as scatter/gather.  c =
      ceil(capacity_factor * n * top_k / E), capacity_factor defaulting
      to the gate's (train, eval) factor pair selected by the layer's
      ``training`` flag (GShardGate.capacity: 1.2 train / 2.4 eval).
    """

    def __init__(self, d_model, experts, gate=None, moe_group=None, mp_group=None,
                 recompute_interval=0, recompute_ctx=None, dispatch="dense",
                 capacity_factor=None):
        super().__init__()
        self.d_model = d_model
        if isinstance(experts, (list, tuple)):
            experts = LayerList(experts)
        self.experts = experts
        self.num_expert = len(experts)
        self.moe_group = moe_group
        self.world_size = moe_group.nranks if moe_group is not None else 1
        if dispatch not in ("dense", "gather"):
            raise ValueError(f"unknown dispatch {dispatch!r}")
        self.dispatch = dispatch
        self.capacity_factor = capacity_factor

        if gate is None:
            gate = {"type": "gshard", "top_k": 2}
        if isinstance(gate, dict):
            self.top_k = gate.get("top_k", 2)
            gtype = gate.get("type", "gshard")
            if gtype == "naive" or gtype is None:
                gate = NaiveGate(d_model, self.num_expert, self.world_size, topk=self.top_k)
            elif gtype == "gshard":
                gate = GShardGate(d_model, self.num_expert, self.world_size,
                                  topk=self.top_k, group=moe_group)
            elif gtype == "switch":
                self.top_k = 1
                gate = SwitchGate(d_model, self.num_expert, self.world_size,
                                  topk=1, group=moe_group)
            else:
                raise AssertionError(f"unknown gate type {gtype}")
        else:
            self.top_k = getattr(gate, "top_k", 2)
        assert isinstance(gate, BaseGate)
        self.gate = gate

    def forward(self, inp):
        orig_shape = inp.shape
        d = orig_shape[-1]
        inp2 = inp.reshape([-1, d])
        value, gate_idx = self.gate(inp2)
        if self.dispatch == "gather":
            out = self._forward_gather(inp2, gate_idx, value)
            return out.reshape(orig_shape)

        # run every expert over every token's routed subset, gathered densely:
        # expert_in[e] = tokens routed to e (zeros elsewhere) via one-hot combine
        def build_masks(idx, val):
            # softmax over the selected top-k scores → convex combine weights
            # (reference moe_layer.py applies softmax to the naive gate's top-k)
            val = jax.nn.softmax(val, -1)
            oh = jax.nn.one_hot(idx.astype(jnp.int32), self.num_expert, dtype=val.dtype)  # (n, k, E)
            combine = jnp.einsum("nk,nke->ne", val, oh)  # (n, E) combine weights
            dispatch = (oh.sum(1) > 0).astype(val.dtype)  # (n, E)
            return dispatch, combine

        dispatch, combine = apply("moe_masks", build_masks, gate_idx, value)

        outs = []
        for e, expert in enumerate(self.experts):
            # dense formulation: every expert sees all tokens, output scaled by its
            # combine weight (zero for unrouted tokens) — static shapes for XLA
            expert_out = expert(inp2)
            outs.append(apply("mask_mul", jnp.multiply, expert_out,
                              apply("colc", lambda m, e=e: m[:, e:e + 1], combine)))
        total = outs[0]
        for o in outs[1:]:
            total = apply("add", jnp.add, total, o)
        return total.reshape(orig_shape)

    # ------------------------------------------------- GShard capacity dispatch
    def _capacity(self, n):
        import math

        factor = self.capacity_factor
        if factor is None:
            cap = getattr(self.gate, "capacity", None)
            if cap:
                # reference GShard semantics: capacity is a (train, eval)
                # factor pair — eval uses the larger factor (fewer drops)
                factor = cap[0] if self.training else cap[1]
            else:
                factor = 1.2
        c = int(math.ceil(factor * n * self.top_k / self.num_expert))
        return min(c, n * self.top_k)

    def _forward_gather(self, inp2, gate_idx, value):
        n = inp2.shape[0]
        k, E = self.top_k, self.num_expert
        c = self._capacity(int(n))

        def route(idx, val):
            # pair p = (token p//k, choice p%k); sort pairs by expert so each
            # expert's first c pairs claim its bucket slots (stable sort =
            # lower token index wins a contested slot, GShard's order)
            w = jax.nn.softmax(val, -1).reshape(-1)              # [n*k]
            flat_e = idx.reshape(-1).astype(jnp.int32)
            order = jnp.argsort(flat_e, stable=True).astype(jnp.int32)
            sorted_e = flat_e[order]
            start = jnp.searchsorted(sorted_e,
                                     jnp.arange(E, dtype=jnp.int32))
            pos = jnp.arange(n * k, dtype=jnp.int32) - \
                start[sorted_e].astype(jnp.int32)
            keep = pos < c
            # slot E*c is a scratch entry: dropped pairs write/read there
            slot = jnp.where(keep, sorted_e * c + pos, E * c)
            token = (order // k).astype(jnp.int32)
            # src[slot] = token feeding it; empty slots point at the zeros
            # row n appended to x
            src = jnp.full((E * c + 1,), n, jnp.int32).at[slot].set(
                jnp.where(keep, token, n))[:E * c]
            return src, slot, token, w[order]

        src, slot, token, w_sorted = apply("moe_route", route, gate_idx, value)

        def gather_in(x, src):
            xpad = jnp.concatenate([x, jnp.zeros((1, x.shape[1]), x.dtype)])
            return xpad[src]                                     # [E*c, d]

        xe = apply("moe_gather", gather_in, inp2, src)
        ye = []
        for e, expert in enumerate(self.experts):
            xe_e = apply("moe_bucket", lambda a, e=e: a[e * c:(e + 1) * c], xe)
            ye.append(expert(xe_e))

        def combine(token, slot, w_sorted, *outs):
            yflat = jnp.concatenate(list(outs) +
                                    [jnp.zeros((1, outs[0].shape[1]),
                                               outs[0].dtype)])
            contrib = yflat[slot] * w_sorted[:, None].astype(outs[0].dtype)
            return jnp.zeros((n, outs[0].shape[1]), outs[0].dtype
                             ).at[token].add(contrib)

        return apply("moe_combine", combine, token, slot, w_sorted, *ye)
