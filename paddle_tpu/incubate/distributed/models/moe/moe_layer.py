"""MoELayer (reference python/paddle/incubate/distributed/models/moe/moe_layer.py:263).

TPU-native dispatch: instead of the reference's global_scatter/global_gather CUDA
all-to-all kernels, tokens are routed with capacity-bucketed one-hot einsums (the
GShard/Mesh-TensorFlow formulation).  Under pjit with the expert axis sharded over
the moe_group mesh axis, XLA lowers the einsum pair to exactly the all-to-all the
reference does by hand — and overlaps it with expert compute."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.autograd.engine import apply
from paddle_tpu.incubate.distributed.models.moe.gate import (
    BaseGate, GShardGate, NaiveGate, SwitchGate,
)
from paddle_tpu.nn.layer.layers import Layer
from paddle_tpu.nn.layer.container import LayerList


class MoELayer(Layer):
    def __init__(self, d_model, experts, gate=None, moe_group=None, mp_group=None,
                 recompute_interval=0, recompute_ctx=None):
        super().__init__()
        self.d_model = d_model
        if isinstance(experts, (list, tuple)):
            experts = LayerList(experts)
        self.experts = experts
        self.num_expert = len(experts)
        self.moe_group = moe_group
        self.world_size = moe_group.nranks if moe_group is not None else 1

        if gate is None:
            gate = {"type": "gshard", "top_k": 2}
        if isinstance(gate, dict):
            self.top_k = gate.get("top_k", 2)
            gtype = gate.get("type", "gshard")
            if gtype == "naive" or gtype is None:
                gate = NaiveGate(d_model, self.num_expert, self.world_size, topk=self.top_k)
            elif gtype == "gshard":
                gate = GShardGate(d_model, self.num_expert, self.world_size,
                                  topk=self.top_k, group=moe_group)
            elif gtype == "switch":
                self.top_k = 1
                gate = SwitchGate(d_model, self.num_expert, self.world_size,
                                  topk=1, group=moe_group)
            else:
                raise AssertionError(f"unknown gate type {gtype}")
        else:
            self.top_k = getattr(gate, "top_k", 2)
        assert isinstance(gate, BaseGate)
        self.gate = gate

    def forward(self, inp):
        orig_shape = inp.shape
        d = orig_shape[-1]
        inp2 = inp.reshape([-1, d])
        value, gate_idx = self.gate(inp2)

        # run every expert over every token's routed subset, gathered densely:
        # expert_in[e] = tokens routed to e (zeros elsewhere) via one-hot combine
        def build_masks(idx, val):
            # softmax over the selected top-k scores → convex combine weights
            # (reference moe_layer.py applies softmax to the naive gate's top-k)
            val = jax.nn.softmax(val, -1)
            oh = jax.nn.one_hot(idx.astype(jnp.int32), self.num_expert, dtype=val.dtype)  # (n, k, E)
            combine = jnp.einsum("nk,nke->ne", val, oh)  # (n, E) combine weights
            dispatch = (oh.sum(1) > 0).astype(val.dtype)  # (n, E)
            return dispatch, combine

        dispatch, combine = apply("moe_masks", build_masks, gate_idx, value)

        outs = []
        for e, expert in enumerate(self.experts):
            # dense formulation: every expert sees all tokens, output scaled by its
            # combine weight (zero for unrouted tokens) — static shapes for XLA
            expert_out = expert(inp2)
            outs.append(apply("mask_mul", jnp.multiply, expert_out,
                              apply("colc", lambda m, e=e: m[:, e:e + 1], combine)))
        total = outs[0]
        for o in outs[1:]:
            total = apply("add", jnp.add, total, o)
        return total.reshape(orig_shape)
