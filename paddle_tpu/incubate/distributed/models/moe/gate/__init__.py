from paddle_tpu.incubate.distributed.models.moe.gate.base_gate import BaseGate
from paddle_tpu.incubate.distributed.models.moe.gate.naive_gate import NaiveGate
from paddle_tpu.incubate.distributed.models.moe.gate.gshard_gate import GShardGate
from paddle_tpu.incubate.distributed.models.moe.gate.switch_gate import SwitchGate

__all__ = ['BaseGate', 'NaiveGate', 'GShardGate', 'SwitchGate']
