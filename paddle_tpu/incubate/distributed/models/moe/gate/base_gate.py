"""BaseGate (reference python/paddle/incubate/distributed/models/moe/gate/base_gate.py)."""
from paddle_tpu.nn.layer.layers import Layer


class BaseGate(Layer):
    def __init__(self, num_expert, world_size):
        super().__init__()
        self.world_size = world_size
        self.num_expert = num_expert
        self.tot_expert = world_size * num_expert
        self.loss = None

    def forward(self, x):
        raise NotImplementedError("Base gate cannot be directly used for fwd")

    def set_loss(self, loss):
        self.loss = loss

    def get_loss(self, clear=True):
        loss = self.loss
        if clear:
            self.loss = None
        return loss
