"""SwitchGate (reference .../moe/gate/switch_gate.py): top-1 routing with
Switch-Transformer load-balancing loss."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.autograd.engine import apply
from paddle_tpu.incubate.distributed.models.moe.gate.naive_gate import NaiveGate


class SwitchGate(NaiveGate):
    def __init__(self, d_model, num_expert, world_size, topk=1,
                 switch_eps=0.1, capacity=(1.2, 2.4), group=None,
                 seed=None):
        assert topk == 1, "topk should be 1 in switch"
        super().__init__(d_model, num_expert, world_size, topk=1)
        self.switch_eps = switch_eps
        self.capacity = capacity
        self.group = group
        # Routing-noise seed: deterministic under paddle.seed() via the
        # process generator (tensor/random.py) instead of global np.random
        # state (tpu-lint PTL005 impurity — the old draw made every run's
        # routing irreproducible).  A per-forward counter is folded in so
        # each training step still gets fresh noise.
        if seed is None:
            from paddle_tpu.tensor.random import default_generator

            seed = int(np.asarray(
                jax.random.randint(default_generator.next_key(), (),
                                   0, 2**31 - 1)))
        self._seed = int(seed)
        self._route_calls = 0

    def forward(self, inp):
        score = self.gate(inp)

        def route(g, key_seed):
            if self.training:
                noise = jax.random.uniform(jax.random.key(key_seed), g.shape, g.dtype,
                                           minval=-self.switch_eps, maxval=self.switch_eps)
                g = g + noise
            probs = jax.nn.softmax(g, -1)
            top1_val, top1_idx = jax.lax.top_k(probs, 1)
            # switch load-balance loss
            c_e = jnp.zeros((self.tot_expert,), g.dtype).at[top1_idx[:, 0].astype(jnp.int32)].add(1.0) / g.shape[0]
            m_e = probs.mean(0)
            loss = jnp.sum(c_e * m_e) * self.tot_expert
            return top1_val, top1_idx.astype(jnp.int64), loss

        # fold the call counter into the base seed: fresh noise per step,
        # same sequence for the same paddle.seed()/constructor seed
        seed = self._seed + self._route_calls
        if self.training:
            self._route_calls += 1
        val, idx, loss = apply("switch_route", lambda g: route(g, seed), score)
        self.set_loss(loss)
        return val, idx
