"""NaiveGate (reference .../moe/gate/naive_gate.py): linear scorer + top-k."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.autograd.engine import apply
from paddle_tpu.incubate.distributed.models.moe.gate.base_gate import BaseGate
from paddle_tpu.nn.layer.common import Linear


class NaiveGate(BaseGate):
    def __init__(self, d_model, num_expert, world_size, topk=2):
        super().__init__(num_expert, world_size)
        self.gate = Linear(d_model, self.tot_expert)
        self.top_k = topk

    def forward(self, inp, return_all_scores=False):
        gate_score = self.gate(inp)

        def topk_fn(g):
            val, idx = jax.lax.top_k(g, self.top_k)
            return val, idx.astype(jnp.int64)

        gate_top_k_val, gate_top_k_idx = apply("gate_topk", topk_fn, gate_score)
        if return_all_scores:
            return gate_top_k_val, gate_top_k_idx, gate_score
        return gate_top_k_val, gate_top_k_idx
