"""GShardGate (reference .../moe/gate/gshard_gate.py): NaiveGate + capacity +
load-balance auxiliary loss, the GShard paper's gating."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.autograd.engine import apply
from paddle_tpu.incubate.distributed.models.moe.gate.naive_gate import NaiveGate


class GShardGate(NaiveGate):
    def __init__(self, d_model, num_expert, world_size, topk=2,
                 capacity=(1.2, 2.4), random_routing=True, group=None):
        assert topk == 2, "topk should be 2 in gshard"
        super().__init__(d_model, num_expert, world_size, topk=topk)
        self.capacity = capacity
        self.random_routing = random_routing
        self.group = group

    def forward(self, x):
        topk_val, topk_idx, gate_score = super().forward(x, return_all_scores=True)

        s = x.shape[0]
        top1_idx = topk_idx[:, 0] if hasattr(topk_idx, "__getitem__") else topk_idx

        def aux(g, t1):
            probs = jax.nn.softmax(g, -1)
            c_e = jnp.zeros((self.tot_expert,), g.dtype).at[t1.astype(jnp.int32)].add(1.0) / s
            m_e = probs.mean(0)
            return jnp.sum(c_e * m_e) * self.tot_expert

        self.set_loss(apply("gshard_aux", aux, gate_score, top1_idx))
        return topk_val, topk_idx
