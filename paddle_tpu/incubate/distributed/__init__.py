from paddle_tpu.incubate.distributed import models  # noqa: F401
