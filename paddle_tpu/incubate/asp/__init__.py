"""paddle.incubate.asp — 2:4 structured sparsity (reference
python/paddle/incubate/asp/): mask calculation + pruning + masked optimizer.

TPU note: the reference targets Ampere sparse tensor cores; on TPU the masks are
plain weight pruning (the MXU has no 2:4 path), kept for API/workflow parity."""
from paddle_tpu.incubate.asp.asp import (
    ASPHelper, calculate_density, decorate, prune_model, reset_excluded_layers,
    set_excluded_layers,
)
from paddle_tpu.incubate.asp.utils import (
    MaskAlgo, CheckMethod, check_mask_1d, check_mask_2d, check_sparsity,
    create_mask, get_mask_1d, get_mask_2d_best, get_mask_2d_greedy,
)

__all__ = [
    'calculate_density', 'decorate', 'prune_model', 'set_excluded_layers',
    'reset_excluded_layers',
]
