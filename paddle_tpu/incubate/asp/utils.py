"""n:m sparsity mask utilities (reference python/paddle/incubate/asp/utils.py)."""
from __future__ import annotations

import itertools
from enum import Enum

import numpy as np


class MaskAlgo(Enum):
    MASK_1D = 'get_mask_1d'
    MASK_2D_GREEDY = 'get_mask_2d_greedy'
    MASK_2D_BEST = 'get_mask_2d_best'


class CheckMethod(Enum):
    CHECK_1D = 'check_mask_1d'
    CHECK_2D = 'check_mask_2d'

    @staticmethod
    def get_checking_method(mask_algo):
        return CheckMethod.CHECK_1D if mask_algo == MaskAlgo.MASK_1D else CheckMethod.CHECK_2D


def calculate_density(x):
    x = np.asarray(x)
    return float(np.count_nonzero(x)) / x.size


def _reshape_1d(mat, m):
    pad = (m - mat.shape[1] % m) % m
    padded = np.pad(mat, ((0, 0), (0, pad)), 'constant')
    return padded.reshape(-1, m), padded.shape


def get_mask_1d(mat, n, m):
    """Keep n largest-|.| of every m consecutive elements (rows)."""
    mat2, padded_shape = _reshape_1d(np.asarray(mat), m)
    mask = np.zeros_like(mat2)
    order = np.argsort(np.abs(mat2), axis=1)[:, -n:]
    np.put_along_axis(mask, order, 1.0, axis=1)
    mask = mask.reshape(padded_shape)[: mat.shape[0], : mat.shape[1]]
    return mask


def check_mask_1d(mat, n, m):
    mat2, _ = _reshape_1d(np.asarray(mat), m)
    nnz = (mat2 != 0).sum(1)
    return bool((nnz <= n).all())


def get_mask_2d_greedy(mat, n, m):
    """Greedy m x m block selection (reference get_mask_2d_greedy)."""
    mat = np.asarray(mat)
    h, w = mat.shape
    pad_h, pad_w = (m - h % m) % m, (m - w % m) % m
    padded = np.pad(np.abs(mat), ((0, pad_h), (0, pad_w)), 'constant')
    mask = np.zeros_like(padded)
    for bi in range(0, padded.shape[0], m):
        for bj in range(0, padded.shape[1], m):
            block = padded[bi:bi + m, bj:bj + m]
            bmask = np.zeros_like(block)
            order = np.argsort(block.flatten())[::-1]
            row_cnt = np.zeros(m, int)
            col_cnt = np.zeros(m, int)
            for o in order:
                r, c = divmod(int(o), m)
                if row_cnt[r] < n and col_cnt[c] < n:
                    bmask[r, c] = 1.0
                    row_cnt[r] += 1
                    col_cnt[c] += 1
            mask[bi:bi + m, bj:bj + m] = bmask
    return mask[:h, :w]


def get_mask_2d_best(mat, n, m):
    return get_mask_2d_greedy(mat, n, m)


def check_mask_2d(mat, n, m):
    mat = np.asarray(mat)
    h, w = mat.shape
    pad_h, pad_w = (m - h % m) % m, (m - w % m) % m
    padded = np.pad(mat, ((0, pad_h), (0, pad_w)), 'constant')
    for bi in range(0, padded.shape[0], m):
        for bj in range(0, padded.shape[1], m):
            block = padded[bi:bi + m, bj:bj + m] != 0
            if (block.sum(0) > n).any() or (block.sum(1) > n).any():
                return False
    return True


def create_mask(tensor, func_name=MaskAlgo.MASK_1D, n=2, m=4):
    mat = np.asarray(tensor)
    shape = mat.shape
    if mat.ndim == 1:
        mat = mat.reshape(1, -1)
    elif mat.ndim > 2:
        mat = mat.reshape(shape[0], -1)
    fn = {MaskAlgo.MASK_1D: get_mask_1d, MaskAlgo.MASK_2D_GREEDY: get_mask_2d_greedy,
          MaskAlgo.MASK_2D_BEST: get_mask_2d_best}[func_name]
    mask = fn(mat, n, m)
    return mask.reshape(shape)


def check_sparsity(tensor, func_name=CheckMethod.CHECK_1D, n=2, m=4):
    mat = np.asarray(tensor)
    if mat.ndim == 1:
        mat = mat.reshape(1, -1)
    elif mat.ndim > 2:
        mat = mat.reshape(mat.shape[0], -1)
    fn = {CheckMethod.CHECK_1D: check_mask_1d, CheckMethod.CHECK_2D: check_mask_2d}[func_name]
    return fn(mat, n, m)
