"""ASP workflow (reference python/paddle/incubate/asp/asp.py): decorate the
optimizer so gradients respect the sparsity masks; prune_model computes masks."""
from __future__ import annotations

import numpy as np

from paddle_tpu.incubate.asp.utils import (
    CheckMethod, MaskAlgo, calculate_density, check_sparsity, create_mask,
)
from paddle_tpu.tensor.tensor import Tensor

_EXCLUDED_LAYERS = []


def set_excluded_layers(param_names, main_program=None):
    # one process-global exclusion list (eager mode has no program scoping)
    _EXCLUDED_LAYERS.clear()
    _EXCLUDED_LAYERS.extend(param_names)


def reset_excluded_layers(main_program=None):
    _EXCLUDED_LAYERS.clear()


class ASPHelper:
    MASK_APPENDDED_NAME = '_asp_mask'
    _masks = {}

    @classmethod
    def _is_supported_layer(cls, param_name):
        if any(e in param_name for e in _EXCLUDED_LAYERS):
            return False
        return ('w_' in param_name or 'weight' in param_name) and '_asp_mask' not in param_name

    @classmethod
    def prune_model(cls, model, n=2, m=4, mask_algo='mask_1d', with_mask=True):
        algo = {'mask_1d': MaskAlgo.MASK_1D, 'mask_2d_greedy': MaskAlgo.MASK_2D_GREEDY,
                'mask_2d_best': MaskAlgo.MASK_2D_BEST}[mask_algo]
        for name, param in model.named_parameters():
            # match exclusions against both the attribute path ("fc1.weight") and
            # the parameter's unique name ("linear_0.w_0"), like the reference
            full = f"{name}|{getattr(param, 'name', '')}"
            if not cls._is_supported_layer(full):
                continue
            if param.ndim < 2:
                continue
            arr = np.asarray(param.numpy())
            mask = create_mask(arr, func_name=algo, n=n, m=m)
            import jax.numpy as jnp

            param._data = jnp.asarray(arr * mask)
            param._asp_mask = jnp.asarray(mask, param.data.dtype)  # mask travels with the param
            cls._masks[name] = mask
        return cls._masks

    @classmethod
    def decorate(cls, optimizer):
        return OptimizerWithSparsityGuarantee(optimizer)


class OptimizerWithSparsityGuarantee:
    """After every step, re-applies the masks so pruned weights stay zero."""

    def __init__(self, optimizer):
        self._optimizer = optimizer

    def __getattr__(self, item):
        return getattr(self._optimizer, item)

    def step(self):
        self._optimizer.step()
        for p in self._optimizer._parameter_list:
            mask = getattr(p, '_asp_mask', None)
            if mask is not None:
                p._data = p.data * mask


def decorate(optimizer):
    return ASPHelper.decorate(optimizer)


def prune_model(model, n=2, m=4, mask_algo='mask_1d', with_mask=True):
    return ASPHelper.prune_model(model, n=n, m=m, mask_algo=mask_algo, with_mask=with_mask)
