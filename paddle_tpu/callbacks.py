"""paddle.callbacks namespace (python/paddle/callbacks.py parity)."""
from paddle_tpu.hapi.callbacks import (  # noqa: F401
    VisualDL,
    Callback, EarlyStopping, LRScheduler, ModelCheckpoint, ProgBarLogger,
)
