"""paddle.fft parity (reference: python/paddle/fft.py over phi fft kernels backed by
pocketfft/cuFFT — paddle/phi/kernels/funcs/fft.h).  On TPU the FFTs lower through
XLA's FFT HLO; every transform goes through the autograd tape so gradients work in
eager mode.
"""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.autograd.engine import apply
from paddle_tpu.tensor.tensor import Tensor

__all__ = [
    "fft", "ifft", "fft2", "ifft2", "fftn", "ifftn",
    "rfft", "irfft", "rfft2", "irfft2", "rfftn", "irfftn",
    "hfft", "ihfft", "hfft2", "ihfft2", "hfftn", "ihfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]

_NORMS = ("backward", "ortho", "forward")


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def _check_norm(norm):
    norm = norm or "backward"
    if norm not in _NORMS:
        raise ValueError(f"norm must be one of {_NORMS}, got {norm!r}")
    return norm


def _make1d(op_name, jnp_fn, real_input=False):
    def op(x, n=None, axis=-1, norm="backward", name=None):
        norm = _check_norm(norm)
        x = _t(x)

        def impl(a):
            if real_input and jnp.iscomplexobj(a):
                a = a.real
            return jnp_fn(a, n=n, axis=axis, norm=norm)

        return apply(op_name, impl, x)

    op.__name__ = op_name
    return op


def _make_nd(op_name, jnp_fn, default_axes=None, real_input=False):
    def op(x, s=None, axes=default_axes, norm="backward", name=None):
        norm = _check_norm(norm)
        x = _t(x)

        def impl(a):
            if real_input and jnp.iscomplexobj(a):
                a = a.real
            return jnp_fn(a, s=s, axes=axes, norm=norm)

        return apply(op_name, impl, x)

    op.__name__ = op_name
    return op


fft = _make1d("fft", jnp.fft.fft)
ifft = _make1d("ifft", jnp.fft.ifft)
rfft = _make1d("rfft", jnp.fft.rfft, real_input=True)
irfft = _make1d("irfft", jnp.fft.irfft)
hfft = _make1d("hfft", jnp.fft.hfft)
ihfft = _make1d("ihfft", jnp.fft.ihfft, real_input=True)

fft2 = _make_nd("fft2", jnp.fft.fft2, default_axes=(-2, -1))
ifft2 = _make_nd("ifft2", jnp.fft.ifft2, default_axes=(-2, -1))
rfft2 = _make_nd("rfft2", jnp.fft.rfft2, default_axes=(-2, -1), real_input=True)
irfft2 = _make_nd("irfft2", jnp.fft.irfft2, default_axes=(-2, -1))
fftn = _make_nd("fftn", jnp.fft.fftn)
ifftn = _make_nd("ifftn", jnp.fft.ifftn)
rfftn = _make_nd("rfftn", jnp.fft.rfftn, real_input=True)
irfftn = _make_nd("irfftn", jnp.fft.irfftn)


def _hfft_nd(op_name, fwd_nd, conj_ifft):
    """hfft2/hfftn and ihfft2/ihfftn are not in jnp.fft; build them from the
    identities hfftn(x) = irfftn-like real output of conj-symmetric input:
    hfft(x) = fft of hermitian signal → real; equivalently irfft(conj(x)) scaled.
    """

    def op(x, s=None, axes=(-2, -1), norm="backward", name=None):
        norm = _check_norm(norm)
        x = _t(x)

        def impl(a):
            if conj_ifft:
                # ihfftn: inverse of hfftn — rfftn of real input, conjugated
                if jnp.iscomplexobj(a):
                    a = a.real
                inv_norm = {"backward": "forward", "forward": "backward",
                            "ortho": "ortho"}[norm]
                return jnp.conj(jnp.fft.rfftn(a, s=s, axes=axes, norm=inv_norm))
            # hfftn: treat input as hermitian along the last axis
            inv_norm = {"backward": "forward", "forward": "backward",
                        "ortho": "ortho"}[norm]
            return jnp.fft.irfftn(jnp.conj(a), s=s, axes=axes, norm=inv_norm)

        return apply(op_name, impl, x)

    op.__name__ = op_name
    return op


hfft2 = _hfft_nd("hfft2", jnp.fft.fft2, conj_ifft=False)
ihfft2 = _hfft_nd("ihfft2", jnp.fft.ifft2, conj_ifft=True)


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    return _hfft_nd("hfftn", jnp.fft.fftn, conj_ifft=False)(
        x, s=s, axes=axes, norm=norm)


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    return _hfft_nd("ihfftn", jnp.fft.ifftn, conj_ifft=True)(
        x, s=s, axes=axes, norm=norm)


def fftfreq(n, d=1.0, dtype=None, name=None):
    out = jnp.fft.fftfreq(int(n), d=float(d))
    if dtype is not None:
        from paddle_tpu.core.dtype import convert_dtype

        out = out.astype(convert_dtype(dtype))
    return Tensor(out)


def rfftfreq(n, d=1.0, dtype=None, name=None):
    out = jnp.fft.rfftfreq(int(n), d=float(d))
    if dtype is not None:
        from paddle_tpu.core.dtype import convert_dtype

        out = out.astype(convert_dtype(dtype))
    return Tensor(out)


def fftshift(x, axes=None, name=None):
    x = _t(x)
    return apply("fftshift", lambda a: jnp.fft.fftshift(a, axes=axes), x)


def ifftshift(x, axes=None, name=None):
    x = _t(x)
    return apply("ifftshift", lambda a: jnp.fft.ifftshift(a, axes=axes), x)
