"""Unique name generation (reference: python/paddle/utils/unique_name.py over
python/paddle/base/unique_name.py — prefix counters with guard/switch)."""
from __future__ import annotations

import contextlib
import threading

__all__ = ["generate", "switch", "guard"]


class _Generator:
    def __init__(self):
        self._ids = {}
        self._lock = threading.Lock()

    def __call__(self, key: str) -> str:
        with self._lock:
            i = self._ids.get(key, 0)
            self._ids[key] = i + 1
        return f"{key}_{i}"


_generator = _Generator()


def generate(key: str) -> str:
    return _generator(key)


def switch(new_generator=None):
    global _generator
    old = _generator
    _generator = new_generator if new_generator is not None else _Generator()
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    if isinstance(new_generator, str):
        # paddle allows a string prefix guard
        gen = _Generator()
        prefix = new_generator

        class _Prefixed(_Generator):
            def __call__(self, key):
                return gen(prefix + key)

        new_generator = _Prefixed()
    old = switch(new_generator)
    try:
        yield
    finally:
        switch(old)
