"""Custom native-extension build helpers (reference:
python/paddle/utils/cpp_extension/ — CppExtension/CUDAExtension/setup/load used by
test/custom_op and test/cpp_extension).

TPU-native story: custom *device compute* belongs in Pallas (Python), so this module
covers the remaining native use case — building C++ host-side extensions (custom IO,
plugin-ABI devices, schedulers) with the in-image toolchain (g++).  pybind11 is not
available; extensions use the raw CPython C API or export a C ABI consumed via
ctypes (see paddle_tpu/native/).
"""
from __future__ import annotations

import os
import subprocess
import sysconfig as _pysysconfig
import tempfile

__all__ = ["CppExtension", "load", "get_build_directory"]


def get_build_directory() -> str:
    d = os.environ.get("PADDLE_TPU_EXTENSION_DIR",
                       os.path.join(tempfile.gettempdir(), "paddle_tpu_extensions"))
    os.makedirs(d, exist_ok=True)
    return d


class CppExtension:
    def __init__(self, sources, include_dirs=None, extra_compile_args=None,
                 extra_link_args=None, name=None):
        self.sources = list(sources)
        self.include_dirs = list(include_dirs or [])
        self.extra_compile_args = list(extra_compile_args or [])
        self.extra_link_args = list(extra_link_args or [])
        self.name = name


def load(name, sources, extra_include_paths=None, extra_cxx_cflags=None,
         extra_ldflags=None, build_directory=None, verbose=False):
    """Compile C++ sources into a shared library and return its path.

    Unlike the reference (which imports the resulting pybind11 module), the
    library is meant to be opened with ctypes/cffi; returns the .so path.
    """
    build_dir = build_directory or get_build_directory()
    out = os.path.join(build_dir, f"lib{name}.so")
    py_inc = _pysysconfig.get_paths()["include"]
    from paddle_tpu.sysconfig import get_include

    cmd = (
        ["g++", "-O2", "-fPIC", "-shared", "-std=c++17"]
        + [f"-I{p}" for p in [py_inc, get_include()] + list(extra_include_paths or [])]
        + list(extra_cxx_cflags or [])
        + list(sources)
        + ["-o", out]
        + list(extra_ldflags or [])
    )
    if verbose:
        print(" ".join(cmd))
    res = subprocess.run(cmd, capture_output=True, text=True)
    if res.returncode != 0:
        raise RuntimeError(f"extension build failed:\n{res.stderr}")
    return out
