"""DLPack interop (reference: python/paddle/utils/dlpack.py over
paddle/fluid/pybind/tensor.cc to_dlpack/from_dlpack; third_party/dlpack).

jax arrays implement the DLPack protocol natively, so this is a thin adapter that
keeps Paddle's API names.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["to_dlpack", "from_dlpack"]


def to_dlpack(x):
    from paddle_tpu.tensor.tensor import Tensor

    arr = x.data if isinstance(x, Tensor) else x
    return arr.__dlpack__()


def from_dlpack(capsule):
    from paddle_tpu.tensor.tensor import Tensor

    if isinstance(capsule, Tensor):
        capsule = capsule.data
    if hasattr(capsule, "__dlpack__"):
        arr = jnp.from_dlpack(capsule)
    else:  # legacy PyCapsule
        arr = jax.dlpack.from_dlpack(capsule)
    return Tensor(arr)
