"""Model/dataset download helpers (reference: python/paddle/utils/download.py).

This environment is zero-egress, so network fetches are gated: if the target file
already exists in the cache (pre-seeded) it is used; otherwise a clear error tells
the user to place the file manually.  md5 checking still works for local files.
"""
from __future__ import annotations

import hashlib
import os
import os.path as osp

__all__ = ["get_weights_path_from_url", "get_path_from_url"]

WEIGHTS_HOME = osp.expanduser("~/.cache/paddle_tpu/hapi/weights")
DATA_HOME = osp.expanduser("~/.cache/paddle_tpu/dataset")


def _md5check(fullname, md5sum=None) -> bool:
    if md5sum is None:
        return True
    md5 = hashlib.md5()
    with open(fullname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            md5.update(chunk)
    return md5.hexdigest() == md5sum


def get_path_from_url(url, root_dir, md5sum=None, check_exist=True):
    fname = osp.split(url)[-1]
    fullname = osp.join(root_dir, fname)
    if osp.exists(fullname) and (not check_exist or _md5check(fullname, md5sum)):
        return fullname
    raise RuntimeError(
        f"Cannot download '{url}': network access is disabled in this "
        f"environment. Place the file manually at '{fullname}'."
    )


def get_weights_path_from_url(url, md5sum=None):
    return get_path_from_url(url, WEIGHTS_HOME, md5sum)
