"""paddle.utils parity (reference: python/paddle/utils/).

Submodules: unique_name, download (gated — zero-egress), dlpack, cpp_extension
(native build helpers for the plugin ABI, §2.2 of SURVEY.md).
"""
from __future__ import annotations

import functools
import importlib
import warnings

from paddle_tpu.utils import dlpack, download, unique_name  # noqa: F401

__all__ = [
    "deprecated", "try_import", "require_version", "run_check",
    "unique_name", "download", "dlpack", "flatten", "pack_sequence_as", "map_structure",
]


def deprecated(update_to="", since="", reason="", level=1):
    """Decorator marking an API deprecated (reference:
    python/paddle/utils/deprecated.py)."""

    def decorator(func):
        msg = f"API '{func.__module__}.{func.__name__}' is deprecated"
        if since:
            msg += f" since {since}"
        if update_to:
            msg += f", use '{update_to}' instead"
        if reason:
            msg += f". Reason: {reason}"

        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            if level > 0:
                warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return func(*args, **kwargs)

        return wrapper

    return decorator


def try_import(module_name, err_msg=None):
    """Import an optional dependency with a clear error (reference:
    python/paddle/utils/lazy_import.py)."""
    try:
        return importlib.import_module(module_name)
    except ImportError:
        if err_msg is None:
            err_msg = (
                f"Optional dependency '{module_name}' is required for this API "
                f"but is not installed (installs are disabled in this environment)."
            )
        raise ImportError(err_msg)


def require_version(min_version, max_version=None):
    """Check the installed framework version is within range."""
    from paddle_tpu.version import full_version

    def _tuple(v):
        return tuple(int(x) for x in str(v).split(".")[:3])

    cur = _tuple(full_version)
    if _tuple(min_version) > cur:
        raise Exception(
            f"paddle_tpu>={min_version} required, found {full_version}")
    if max_version is not None and _tuple(max_version) < cur:
        raise Exception(
            f"paddle_tpu<={max_version} required, found {full_version}")
    return True


def run_check():
    """Sanity-check the install: run a small matmul on the default device and, if
    multiple devices exist, a psum across all of them (the analog of the
    reference's paddle.utils.install_check which runs a tiny train step and a
    2-GPU allreduce)."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle

    x = paddle.randn([4, 4])
    y = paddle.matmul(x, x)
    y.numpy()
    n = jax.device_count()
    if n > 1:
        arr = jnp.arange(float(n))
        out = jax.pmap(lambda v: jax.lax.psum(v, "i"), axis_name="i")(arr)
        assert float(out[0]) == float(arr.sum())
    print(
        f"paddle_tpu is installed successfully! "
        f"backend={jax.default_backend()}, devices={n}"
    )


# --- pytree helpers (reference: python/paddle/utils/layers_utils.py flatten etc.) ---

def flatten(nest):
    import jax

    return jax.tree_util.tree_leaves(nest)


def pack_sequence_as(structure, flat_sequence):
    import jax

    treedef = jax.tree_util.tree_structure(structure)
    return jax.tree_util.tree_unflatten(treedef, flat_sequence)


def map_structure(func, *structures):
    import jax

    return jax.tree_util.tree_map(func, *structures)
