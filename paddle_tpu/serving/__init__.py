"""Continuous-batching serving engine (Orca-style iteration-level
scheduling) over the compiled static-cache decode path, plus the
reliability layer around it: deadlines/cancellation, bounded-queue load
shedding (``EngineOverloaded``), poison-request quarantine, dispatch
retry with backoff, and the deterministic fault-injection harness
(``FaultPlan``) — and the fleet traffic layer above it: the
:class:`Replica` engine handle, the prefix-aware :class:`Router`, and
the stdlib asyncio streaming :class:`ServingServer`."""
from paddle_tpu.serving.engine import (
    EngineOverloaded, Request, ServingEngine,
)
from paddle_tpu.serving.faults import (
    FaultPlan, InjectedDispatchError, InjectedStreamCbError,
)
from paddle_tpu.serving.replica import Replica
from paddle_tpu.serving.router import Router
from paddle_tpu.serving.server import PRIORITY_CLASSES, ServingServer

__all__ = ["EngineOverloaded", "FaultPlan", "InjectedDispatchError",
           "InjectedStreamCbError", "PRIORITY_CLASSES", "Replica",
           "Request", "Router", "ServingEngine", "ServingServer"]
