"""Continuous-batching serving engine (Orca-style iteration-level
scheduling) over the compiled static-cache decode path."""
from paddle_tpu.serving.engine import Request, ServingEngine

__all__ = ["Request", "ServingEngine"]
