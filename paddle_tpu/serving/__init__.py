"""Continuous-batching serving engine (Orca-style iteration-level
scheduling) over the compiled static-cache decode path, plus the
reliability layer around it: deadlines/cancellation, bounded-queue load
shedding (``EngineOverloaded``), poison-request quarantine, dispatch
retry with backoff, and the deterministic fault-injection harness
(``FaultPlan``)."""
from paddle_tpu.serving.engine import (
    EngineOverloaded, Request, ServingEngine,
)
from paddle_tpu.serving.faults import (
    FaultPlan, InjectedDispatchError, InjectedStreamCbError,
)

__all__ = ["EngineOverloaded", "FaultPlan", "InjectedDispatchError",
           "InjectedStreamCbError", "Request", "ServingEngine"]
