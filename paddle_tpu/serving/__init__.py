"""Continuous-batching serving engine (Orca-style iteration-level
scheduling) over the compiled static-cache decode path, plus the
reliability layer around it: deadlines/cancellation, bounded-queue load
shedding (``EngineOverloaded``), poison-request quarantine, dispatch
retry with backoff, and the deterministic fault-injection harness
(``FaultPlan``) — the fleet traffic layer above it: the
:class:`Replica` engine handle, the prefix-aware :class:`Router`, and
the stdlib asyncio streaming :class:`ServingServer` — and the
disaggregated prefill/decode split (:class:`DisaggCoordinator` over
:class:`PrefillWorker`/:class:`DecodeWorker` with a paged-KV-block
:class:`KVTransport` handoff), which presents the same engine surface
so replicas and routers compose over it unchanged."""
from paddle_tpu.serving.disagg import (
    DecodeWorker, DisaggCoordinator, InProcessTransport, KVTransport,
    PickleTransport, PrefillWorker,
)
from paddle_tpu.serving.engine import (
    EngineOverloaded, Request, ServingEngine,
)
from paddle_tpu.serving.faults import (
    FaultPlan, InjectedDispatchError, InjectedStreamCbError,
)
from paddle_tpu.serving.replica import Replica
from paddle_tpu.serving.router import Router
from paddle_tpu.serving.server import PRIORITY_CLASSES, ServingServer

__all__ = ["DecodeWorker", "DisaggCoordinator", "EngineOverloaded",
           "FaultPlan", "InProcessTransport", "InjectedDispatchError",
           "InjectedStreamCbError", "KVTransport",
           "PRIORITY_CLASSES", "PickleTransport", "PrefillWorker",
           "Replica", "Request", "Router", "ServingEngine",
           "ServingServer"]
