"""Continuous-batching serving engine (Orca-style iteration-level
scheduling) over the compiled static-cache decode path, plus the
reliability layer around it: deadlines/cancellation, bounded-queue load
shedding (``EngineOverloaded``), poison-request quarantine, dispatch
retry with backoff, and the deterministic fault-injection harness
(``FaultPlan``) — the fleet traffic layer above it: the
:class:`Replica` engine handle, the prefix-aware :class:`Router`, and
the stdlib asyncio streaming :class:`ServingServer` — and the
disaggregated prefill/decode split (:class:`DisaggCoordinator` over
:class:`PrefillWorker`/:class:`DecodeWorker` with a paged-KV-block
:class:`KVTransport` handoff), which presents the same engine surface
so replicas and routers compose over it unchanged.  The multi-process
layer on top: :class:`SocketTransport` (serving/transport.py) carries
block chains over UDS/TCP, ``paddle_tpu.serving.worker`` runs one
worker per process, and :func:`launch` (serving/launch.py) turns a
declarative :class:`FleetConfig` into a running, drainable fleet."""
from paddle_tpu.serving.disagg import (
    DecodeWorker, DisaggCoordinator, InProcessTransport, KVTransport,
    PickleTransport, PrefillWorker,
)
from paddle_tpu.serving.engine import (
    EngineOverloaded, Request, ServingEngine,
)
from paddle_tpu.serving.faults import (
    FaultPlan, InjectedDispatchError, InjectedStreamCbError,
)
from paddle_tpu.serving.kv_cache import BlockStore
from paddle_tpu.serving.launch import (
    Fleet, FleetConfig, FleetCoordinator, launch,
)
from paddle_tpu.serving.replica import Replica
from paddle_tpu.serving.router import Router
from paddle_tpu.serving.server import PRIORITY_CLASSES, ServingServer
from paddle_tpu.serving.transport import SocketTransport

__all__ = ["BlockStore", "DecodeWorker", "DisaggCoordinator",
           "EngineOverloaded",
           "FaultPlan", "Fleet", "FleetConfig", "FleetCoordinator",
           "InProcessTransport", "InjectedDispatchError",
           "InjectedStreamCbError", "KVTransport",
           "PRIORITY_CLASSES", "PickleTransport", "PrefillWorker",
           "Replica", "Request", "Router", "ServingEngine",
           "ServingServer", "SocketTransport", "launch"]
