"""Declarative fleet deployment: one validated config -> a running
multi-process disaggregated serving fleet.

The config names everything a deployment varies — the P:D worker ratio,
engine geometry, decode mode (greedy/spec) and KV dtype, transport
scheme (UDS or TCP) and endpoints, router policy, platform/device
shape — and ``launch()`` turns it into processes: spawn each
``paddle_tpu.serving.worker`` with the config on disk, gate on every
worker's ``ready`` event (a worker that dies during bringup fails the
launch with its log tail, not a hang), and hand back a ``Fleet`` whose
``FleetCoordinator`` speaks the same Replica-shaped surface
(submit/step/run/drain/close/stats) as the in-process
``DisaggCoordinator`` — so the same config drives tier-1 tests, the
bench, soaks, and a real deployment.

Shutdown is graceful by default: ``drain`` commands let residents
finish, SIGTERM flips stragglers into their drain path, SIGKILL is the
deadline fallback.  Worker death mid-flight (crash or
``FaultPlan(worker_kill=...)``, which here SIGKILLs the actual process)
is recovered the same way the in-process coordinator does it: requests
still in prefill resubmit to a survivor; adopted decode streams
re-prefill their suffix (prompt + every emitted token) under a derived
attempt rid — the preemption-resume identity makes the continuation
byte-identical — and ``serving_worker_restarts_total`` /
``serving_orphan_reprefills_total`` count the recoveries.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time
from collections import deque

import numpy as np

from paddle_tpu.observability.flightrecorder import FlightRecorder
from paddle_tpu.observability.watchdog import DeadlockWatchdog

from .engine import EngineOverloaded, _backoff_sleep
from .metrics import DisaggMetrics
from .worker import FrameReader, pump_socket, send_msg

__all__ = ["FleetConfig", "Fleet", "FleetCoordinator", "launch"]

_LOG = logging.getLogger(__name__)

_PLATFORMS = ("cpu", "tpu")
_TRANSPORTS = ("uds", "tcp")
_ROUTER_POLICIES = ("least_backlog",)
_UDS_PATH_MAX = 107  # sun_path limit (Linux): bind() fails past this
_MAX_REPREFILLS = 8  # resume attempts per request before giving up


class FleetConfig:
    """Everything ``launch()`` needs, validated up front.  ``engine``
    is the geometry dict every worker's ``ServingEngine`` receives
    (batch_size/max_len/kv_block/...); ``prefill``/``decode`` are
    per-role overrides (decode owns ``mode``/``spec_k``/``kv_dtype``)."""

    def __init__(self, *, engine, model=None, n_prefill=1, n_decode=1,
                 prefill=None, decode=None, platform="cpu",
                 devices_per_worker=1, transport="uds",
                 host="127.0.0.1", base_port=0,
                 router_policy="least_backlog", workdir=None,
                 heartbeat_s=1.0, ready_timeout_s=120.0,
                 drain_timeout_s=30.0, restart_dead_workers=False,
                 adoption_timeout_s=20.0, watchdog_s=30.0,
                 name="fleet0"):
        self.engine = dict(engine)
        self.model = dict(model or {"kind": "llama", "preset": "tiny",
                                    "dtype": "float32", "seed": 0})
        self.n_prefill = int(n_prefill)
        self.n_decode = int(n_decode)
        self.prefill = dict(prefill or {})
        self.decode = dict(decode or {})
        self.platform = platform
        self.devices_per_worker = int(devices_per_worker)
        self.transport = transport
        self.host = host
        self.base_port = int(base_port)
        self.router_policy = router_policy
        self.workdir = workdir
        self.heartbeat_s = float(heartbeat_s)
        self.ready_timeout_s = float(ready_timeout_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self.restart_dead_workers = bool(restart_dead_workers)
        self.adoption_timeout_s = float(adoption_timeout_s)
        self.watchdog_s = float(watchdog_s)
        self.name = name

    # ---------------------------------------------------------- validation
    def validate(self):
        """Raise one aggregated ``ValueError`` naming every problem —
        a config rejected at validate() never spawned half a fleet."""
        errs = []
        if self.n_prefill < 1:
            errs.append(f"n_prefill must be >= 1 (got {self.n_prefill})")
        if self.n_decode < 1:
            errs.append(f"n_decode must be >= 1 (got {self.n_decode})")
        kvb = self.engine.get("kv_block")
        if not kvb:
            errs.append("engine.kv_block is required: the paged block "
                        "pool is the migration transfer unit")
        maxlen = self.engine.get("max_len")
        if not maxlen:
            errs.append("engine.max_len is required")
        if kvb and maxlen and maxlen % kvb:
            errs.append(f"engine.max_len ({maxlen}) must be a multiple "
                        f"of engine.kv_block ({kvb})")
        if not self.engine.get("batch_size"):
            errs.append("engine.batch_size is required")
        if self.platform not in _PLATFORMS:
            errs.append(f"platform must be one of {_PLATFORMS} "
                        f"(got {self.platform!r})")
        if self.transport not in _TRANSPORTS:
            errs.append(f"transport must be one of {_TRANSPORTS} "
                        f"(got {self.transport!r})")
        if self.transport == "tcp" and self.base_port <= 0:
            errs.append("tcp transport needs base_port > 0")
        if self.router_policy not in _ROUTER_POLICIES:
            errs.append(f"router_policy must be one of {_ROUTER_POLICIES} "
                        f"(got {self.router_policy!r})")
        if self.devices_per_worker < 1:
            errs.append("devices_per_worker must be >= 1")
        if self.heartbeat_s <= 0:
            errs.append("heartbeat_s must be > 0")
        if self.adoption_timeout_s <= 0:
            errs.append("adoption_timeout_s must be > 0")
        if self.watchdog_s < 0:
            errs.append("watchdog_s must be >= 0 (0 disables the "
                        "deadlock watchdog)")
        if self.model.get("kind", "llama") != "llama" or \
                self.model.get("preset", "tiny") != "tiny":
            errs.append(f"unsupported model spec {self.model!r} "
                        "(kind='llama', preset='tiny')")
        if self.decode.get("mode") == "spec" and \
                int(self.decode.get("spec_k", 0)) < 1:
            errs.append("decode.mode='spec' needs decode.spec_k >= 1")
        if self.transport == "uds" and self.workdir is not None:
            probe = os.path.join(self.workdir, "kv-decode99.sock")
            if len(probe) > _UDS_PATH_MAX:
                errs.append(
                    f"workdir {self.workdir!r} pushes UDS paths past the "
                    f"{_UDS_PATH_MAX}-char sun_path limit")
        if errs:
            raise ValueError("invalid FleetConfig: " + "; ".join(errs))
        return self

    # -------------------------------------------------------------- naming
    def worker_names(self):
        return ([f"prefill{i}" for i in range(self.n_prefill)]
                + [f"decode{i}" for i in range(self.n_decode)])

    def kv_endpoint(self, decode_name, workdir):
        if self.transport == "uds":
            return f"unix:{os.path.join(workdir, f'kv-{decode_name}.sock')}"
        idx = int(decode_name[len("decode"):])
        return f"tcp:{self.host}:{self.base_port + idx}"

    # ----------------------------------------------------------- serialize
    def to_dict(self):
        return {
            "name": self.name, "model": dict(self.model),
            "engine": dict(self.engine),
            "n_prefill": self.n_prefill, "n_decode": self.n_decode,
            "prefill": dict(self.prefill), "decode": dict(self.decode),
            "platform": self.platform,
            "devices_per_worker": self.devices_per_worker,
            "transport": self.transport, "host": self.host,
            "base_port": self.base_port,
            "router_policy": self.router_policy,
            "workdir": self.workdir,
            "heartbeat_s": self.heartbeat_s,
            "ready_timeout_s": self.ready_timeout_s,
            "drain_timeout_s": self.drain_timeout_s,
            "restart_dead_workers": self.restart_dead_workers,
            "adoption_timeout_s": self.adoption_timeout_s,
            "watchdog_s": self.watchdog_s,
        }

    @classmethod
    def from_dict(cls, d):
        return cls(**d)

    @classmethod
    def from_file(cls, path):
        with open(path) as f:
            return cls.from_dict(json.load(f))

    def save(self, path):
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=2, sort_keys=True)


class RemoteWorkerHandle:
    """The parent's view of one worker process: the Popen handle, the
    control socket, and an incremental reader that separates command
    replies from spontaneous events."""

    def __init__(self, name, role, proc, sock, log_path):
        self.name = name
        self.role = role
        self.proc = proc
        self.log_path = log_path
        self.sock = sock
        sock.setblocking(False)
        self._reader = FrameReader()
        self._events = deque()
        self._replies = {}
        self._next_req = 0
        self.ready_info = None
        self.last_hb = time.monotonic()
        self.drained = False
        self.dead = False
        self.recovered = False  # parent already ran death recovery

    def _pump(self):
        if self._reader.eof:
            return
        for msg in pump_socket(self.sock, self._reader):
            if "reply" in msg:
                self._replies[msg["reply"]] = msg
                continue
            ev = msg.get("ev")
            if ev == "hb":
                self.last_hb = time.monotonic()
                continue
            if ev == "ready":
                self.ready_info = msg
                continue
            if ev == "drained":
                self.drained = True
                continue
            self._events.append(msg)

    def poll_events(self):
        self._pump()
        out = list(self._events)
        self._events.clear()
        return out

    def request(self, msg, timeout=30.0):
        """Synchronous command round-trip; events arriving meanwhile are
        buffered for the next ``poll_events``."""
        req = self._next_req
        self._next_req += 1
        msg = dict(msg, req=req)
        self.sock.setblocking(True)
        try:
            send_msg(self.sock, msg)
        finally:
            self.sock.setblocking(False)
        deadline = time.monotonic() + timeout
        while req not in self._replies:
            if not self.alive():
                raise ConnectionError(
                    f"worker {self.name} died mid-request")
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"worker {self.name} did not answer {msg.get('cmd')!r} "
                    f"within {timeout:.0f}s")
            self._pump()
            if req not in self._replies:
                _backoff_sleep(0.002)
        return self._replies.pop(req)

    def alive(self):
        if self.dead:
            return False
        self._pump()
        if self.proc.poll() is not None or self._reader.eof:
            self.dead = True
            return False
        return True

    def kill(self):
        self.dead = True
        try:
            self.proc.kill()
        except OSError:
            pass

    def close_sock(self):
        try:
            self.sock.close()
        except OSError:
            pass

    def log_tail(self, n=30):
        try:
            with open(self.log_path, errors="replace") as f:
                return "".join(f.readlines()[-n:])
        except OSError:
            return "<no log>"


class FleetCoordinator:
    """Drives the remote fleet through the Replica-shaped surface: the
    parent routes submits, splices worker event streams onto the
    callers' Request objects, and recovers worker deaths.  Unlike
    ``DisaggCoordinator`` it owns no engine — recovery is pure rid
    bookkeeping: a dead decode worker's orphans resubmit as a suffix
    prefill of prompt + emitted tokens under a derived attempt rid, and
    the resumed stream forwards onto the root request."""

    def __init__(self, config, handles, registry=None, instrument=True,
                 faults=None):
        self._cfg = config
        self.name = config.name
        self._handles = {h.name: h for h in handles}
        self._m = (DisaggMetrics(registry, config.name)
                   if instrument else None)
        self._faults = faults
        self._users = {}       # wire rid -> root caller Request
        self._route = {}       # wire rid -> {"p","d","state","meta"}
        self._proxy = {}       # attempt rid -> root rid
        self._active = {}      # root rid -> live attempt rid
        self._attempt = {}
        self._finished = []
        self._rids = set()
        self._next_rid = 0
        self._step_idx = 0
        self._n_events = 0
        self._respawn_idx = 0
        # deadlock watchdog on the routing plane: requests outstanding
        # but no event/finish progress for watchdog_s means the parent
        # loop (or every worker at once) is wedged — dump all thread
        # stacks through a coordinator-owned flight recorder.  The
        # monitor thread is a daemon AND stopped/joined in close().
        self._last_progress_unix = 0.0
        self.recorder = None
        self._watchdog = None
        wd_s = float(getattr(config, "watchdog_s", 0.0) or 0.0)
        if wd_s > 0:
            self.recorder = FlightRecorder(policy=f"fleet:{config.name}")
            self._watchdog = DeadlockWatchdog(
                self._watchdog_probe, stall_after=wd_s,
                recorder=self.recorder, registry=registry,
                component=f"fleet:{config.name}").start()

    def _watchdog_probe(self):
        if not self._users:
            return None  # idle: nothing outstanding, nothing to stall
        return self._last_progress_unix or None

    # ----------------------------------------------------------- topology
    def _live(self, role):
        return [h for h in self._handles.values()
                if h.role == role and h.alive()]

    def _load(self, name, state):
        return sum(1 for r in self._route.values()
                   if r[state] == name and r["state"] != "done")

    # ------------------------------------------------------------- submit
    def submit(self, request):
        prefills = self._live("prefill")
        decodes = self._live("decode")
        if not prefills or not decodes:
            raise RuntimeError("fleet has no live prefill/decode worker")
        rid_given = request.rid is not None
        if rid_given and request.rid in self._rids:
            raise ValueError(f"rid {request.rid!r} already in use")
        rid = request.rid if rid_given else self._next_rid
        if not rid_given:
            request.rid = rid
            self._next_rid += 1
        elif isinstance(rid, int):
            self._next_rid = max(self._next_rid, rid + 1)
        p = min(prefills, key=lambda h: self._load(h.name, "p"))
        d = min(decodes, key=lambda h: self._load(h.name, "d"))
        self._send_submit(p, d, rid, request.prompt_ids,
                          request.max_new_tokens, request)
        self._rids.add(rid)
        request.t_submit = time.perf_counter()
        if request.deadline_ms is not None:
            request._t_deadline = request.t_submit \
                + request.deadline_ms / 1e3
        self._users[rid] = request
        self._last_progress_unix = time.time()
        return request

    def _send_submit(self, p, d, wire_rid, prompt, max_new, root):
        reply = p.request({
            "cmd": "submit", "rid": wire_rid,
            "prompt": [int(i) for i in np.asarray(prompt).ravel()],
            "max_new": int(max_new),
            "eos": (int(root.eos_token_id)
                    if root.eos_token_id is not None else None),
            "slo_class": root.slo_class,
            "priority": root.priority,
            "decode": d.name,
        })
        if not reply.get("ok"):
            if reply.get("etype") == "EngineOverloaded":
                root.status = "shed"
                raise EngineOverloaded(reply.get("error", "shed"))
            raise ValueError(reply.get("error", "submit rejected"))
        self._route[wire_rid] = {"p": p.name, "d": d.name,
                                 "state": "prefill"}

    # -------------------------------------------------------------- events
    def _finalize(self, rid, status):
        user = self._users.pop(rid, None)
        route = self._route.get(rid)
        if route is not None:
            route["state"] = "done"
        self._proxy.pop(rid, None)
        if user is None or user.done:
            return
        self._active.pop(getattr(user, "rid"), None)
        user.status = status
        user.done = True
        user.t_done = time.perf_counter()
        self._finished.append(user)

    def _emit(self, root, ids):
        root.output_ids.extend(int(i) for i in ids)
        if root.t_first is None:
            root.t_first = time.perf_counter()
        if root.stream_cb is not None:
            try:
                root.stream_cb(root, list(ids))
            except Exception as e:  # noqa: BLE001 — caller's bug, not ours
                if not root._cb_err_logged:
                    root._cb_err_logged = True
                    _LOG.warning("stream_cb for %r raised %s: %s",
                                 root.rid, type(e).__name__, e)

    def _on_event(self, h, msg):
        self._n_events += 1
        ev = msg["ev"]
        rid = msg.get("rid")
        root = self._users.get(rid) if rid is not None else None
        if ev == "first":
            if root is None or root.done:
                return 0
            self._emit(root, [msg["token"]])
            route = self._route.get(rid)
            if msg.get("final") or len(root.output_ids) >= \
                    root.max_new_tokens:
                self._finalize(rid, "done")
            elif root.eos_token_id is not None and \
                    int(msg["token"]) == int(root.eos_token_id):
                self._finalize(rid, "done")
            else:
                dh = self._handles.get(route["d"]) if route else None
                if dh is None or not dh.alive():
                    # The chain was shipped to a worker that died after the
                    # sender connected: a small chain fits in the kernel
                    # send buffer, so send() "succeeds" and no xfer_err
                    # ever fires.  Nobody will adopt it — resume as a
                    # suffix prefill on a live pair instead.
                    _LOG.warning("KV chain for %r handed to dead worker "
                                 "%s — re-prefilling", rid,
                                 route["d"] if route else "?")
                    if self._m is not None:
                        self._m.migration("aborted")
                    self._reprefill(rid)
                    return 1
                if route is not None:
                    route["state"] = "handoff"
                    route["handoff_t0"] = time.monotonic()
                if msg.get("nbytes") and self._m is not None:
                    self._m.transfer_bytes.inc(int(msg["nbytes"]))
            return 1
        if ev == "tokens":
            if root is None or root.done:
                return 0
            self._emit(root, msg["ids"])
            return len(msg["ids"])
        if ev == "adopted":
            route = self._route.get(rid)
            if route is not None:
                route["state"] = "decode"
            if self._m is not None:
                self._m.migration("ok")
            return 0
        if ev == "retired":
            self._finalize(rid, msg["status"])
            return 0
        if ev == "shadow_failed":
            self._finalize(rid, msg["status"])
            return 0
        if ev == "xfer_err":
            _LOG.warning("KV transfer for %r failed on %s: %s — "
                         "re-prefilling", rid, h.name, msg.get("error"))
            if self._m is not None:
                self._m.migration("aborted")
            self._reprefill(rid)
            return 0
        return 0

    # -------------------------------------------------------- worker death
    def kill_worker(self, name):
        """SIGKILL the named worker process (FaultPlan ``worker_kill``
        lands here): death detection + recovery happen on the next
        ``step``."""
        h = self._handles.get(name)
        if h is None or h.dead:
            return False
        _LOG.warning("killing fleet worker %s (pid %s)", name, h.proc.pid)
        h.kill()
        return True

    def _on_death(self, h):
        _LOG.warning("fleet worker %s died; recovering its requests "
                     "(log tail:\n%s)", h.name, h.log_tail(5))
        if self._cfg.restart_dead_workers:
            self._respawn(h)
        for rid, route in list(self._route.items()):
            if route["state"] == "done":
                continue
            if route["p"] == h.name and route["state"] == "prefill":
                self._reprefill(rid)
            elif route["d"] == h.name and route["state"] in ("handoff",
                                                             "decode"):
                self._reprefill(rid)

    def _respawn(self, h):
        try:
            nh = self._fleet.respawn(h.name)
        except Exception as e:  # noqa: BLE001 — respawn is best-effort
            _LOG.warning("respawn of %s failed: %s", h.name, e)
            return
        self._handles[h.name] = nh
        if self._m is not None:
            self._m.worker_restarts.inc()

    def _reprefill(self, rid):
        """Resume an orphaned request as a suffix prefill: prompt' =
        prompt + every emitted token, budget' = what remains, routed
        under a derived attempt rid to live workers.  No survivor that
        can host it -> clean terminal status, never a hang."""
        root = self._users.pop(rid, None)
        route = self._route.get(rid)
        if route is not None:
            route["state"] = "done"
        self._proxy.pop(rid, None)
        if root is None or root.done:
            return
        self._active.pop(root.rid, None)
        k = len(root.output_ids)
        remaining = root.max_new_tokens - k
        if remaining <= 0:
            root.status = "done"
            root.done = True
            root.t_done = time.perf_counter()
            self._finished.append(root)
            return
        prefills = self._live("prefill")
        decodes = self._live("decode")
        if not prefills or not decodes:
            root.status = "cancelled"
            root.done = True
            root.t_done = time.perf_counter()
            self._finished.append(root)
            return
        n = self._attempt.get(root.rid, 0) + 1
        self._attempt[root.rid] = n
        if n > _MAX_REPREFILLS:
            # A request that keeps losing its worker is shedding load the
            # fleet can't absorb — terminate it cleanly rather than storm
            # the prefill plane with resume attempts.
            _LOG.warning("request %r exhausted %d resume attempts; "
                         "cancelling", root.rid, _MAX_REPREFILLS)
            root.status = "cancelled"
            root.done = True
            root.t_done = time.perf_counter()
            self._finished.append(root)
            return
        arid = f"{root.rid}~r{n}"
        prompt = np.concatenate(
            [np.asarray(root.prompt_ids, dtype=np.int32).ravel(),
             np.asarray(root.output_ids, dtype=np.int32).ravel()])
        p = min(prefills, key=lambda h: self._load(h.name, "p"))
        d = min(decodes, key=lambda h: self._load(h.name, "d"))
        try:
            self._send_submit(p, d, arid, prompt, remaining, root)
        except (EngineOverloaded, ValueError, ConnectionError,
                TimeoutError) as e:
            _LOG.warning("re-prefill of %r failed (%s); retiring", rid, e)
            root.status = "cancelled"
            root.done = True
            root.t_done = time.perf_counter()
            self._finished.append(root)
            return
        self._rids.add(arid)
        self._users[arid] = root
        self._proxy[arid] = root.rid
        self._active[root.rid] = arid
        if self._m is not None:
            self._m.orphan_reprefills.inc()
        self._last_progress_unix = time.time()
        _LOG.info("re-prefilled orphan %r as %r (%d emitted, %d left)",
                  root.rid, arid, k, remaining)

    # ---------------------------------------------------------------- step
    def step(self):
        self._step_idx += 1
        before = self._n_events + len(self._finished)
        if self._faults is not None:
            for name in self._faults.worker_kills_due(self._step_idx):
                self.kill_worker(name)
        emitted = 0
        for h in list(self._handles.values()):
            if not h.alive():
                if not h.recovered:
                    h.recovered = True
                    for msg in h.poll_events():  # drain final events first
                        emitted += self._on_event(h, msg)
                    self._on_death(h)
                continue
            for msg in h.poll_events():
                emitted += self._on_event(h, msg)
        emitted += self._sweep_handoffs()
        if self._n_events + len(self._finished) != before:
            self._last_progress_unix = time.time()
        return emitted

    def _sweep_handoffs(self):
        """Re-prefill chains whose adoption ack never came.  The wire
        gives no delivery guarantee — a chain written into a dying
        worker's socket buffer 'sends' cleanly and then evaporates, and
        a respawn under the same name makes the target look healthy.
        The decode worker's ``adopted`` event is the real ack; a route
        stuck in handoff past the deadline lost its chain."""
        deadline = self._cfg.adoption_timeout_s
        moved = 0
        for rid, route in list(self._route.items()):
            if route["state"] != "handoff":
                continue
            t0 = route.get("handoff_t0")
            if t0 is None or time.monotonic() - t0 < deadline:
                continue
            _LOG.warning("KV chain for %r unadopted after %.0fs — "
                         "re-prefilling", rid, deadline)
            dh = self._handles.get(route["d"])
            if dh is not None and dh.alive():
                try:  # best-effort: free the chain if it did land
                    dh.request({"cmd": "cancel", "rid": rid}, timeout=5.0)
                except (OSError, TimeoutError, RuntimeError):
                    pass
            if self._m is not None:
                self._m.migration("aborted")
            self._reprefill(rid)
            moved += 1
        return moved

    @property
    def has_work(self):
        return bool(self._users)

    def run(self, stall_timeout=120.0):
        last_progress = time.monotonic()
        while self.has_work:
            before = self._n_events + len(self._finished)
            self.step()
            if self._n_events + len(self._finished) != before:
                last_progress = time.monotonic()
            elif time.monotonic() - last_progress > stall_timeout:
                raise RuntimeError(
                    f"fleet made no progress for {stall_timeout:.0f}s "
                    f"with {len(self._users)} request(s) outstanding")
            else:
                _backoff_sleep(0.003)
        return self._finished

    def drain(self):
        self.run()
        return {r.rid: r.status for r in self._finished}

    def cancel(self, rid):
        wire = self._active.get(rid, rid)
        route = self._route.get(wire)
        if route is None or route["state"] == "done":
            return False
        target = route["p"] if route["state"] == "prefill" else route["d"]
        h = self._handles.get(target)
        found = False
        if h is not None and h.alive():
            try:
                found = bool(h.request({"cmd": "cancel", "rid": wire},
                                       timeout=10.0).get("found"))
            except (ConnectionError, TimeoutError):
                pass
        self._finalize(wire, "cancelled")
        return found

    # ---------------------------------------------------------------- stats
    def stats(self):
        out = {"inflight": len(self._users),
               "finished": len(self._finished),
               "orphan_reprefills": sum(self._attempt.values()),
               "workers_dead": sum(1 for h in self._handles.values()
                                   if h.dead),
               "workers": {}}
        for h in self._handles.values():
            if not h.alive():
                out["workers"][h.name] = {"dead": True}
                continue
            try:
                out["workers"][h.name] = h.request(
                    {"cmd": "stats"}, timeout=30.0)["stats"]
            except (ConnectionError, TimeoutError):
                out["workers"][h.name] = {"dead": True}
        return out

    def queue_depth(self):
        return sum(1 for r in self._route.values()
                   if r["state"] in ("prefill", "handoff"))

    # ---------------------------------------------------------------- close
    def close(self, drain_timeout=None):
        if self._watchdog is not None:
            self._watchdog.stop()  # monitor thread joined before teardown
        timeout = (self._cfg.drain_timeout_s
                   if drain_timeout is None else drain_timeout)
        for h in self._handles.values():
            if h.alive():
                try:
                    h.request({"cmd": "close"}, timeout=5.0)
                except (ConnectionError, TimeoutError):
                    pass
        # grace: a closing worker drains its residents and exits on its
        # own; SIGTERM is for stragglers, SIGKILL for the truly stuck
        deadline = time.monotonic() + timeout
        pending = [h for h in self._handles.values()
                   if h.proc.poll() is None]
        while pending and time.monotonic() < deadline - timeout / 2:
            pending = [h for h in pending if h.proc.poll() is None]
            if pending:
                time.sleep(0.02)
        for h in pending:
            try:
                h.proc.terminate()
            except OSError:
                pass
        for h in self._handles.values():
            left = max(0.1, deadline - time.monotonic())
            try:
                h.proc.wait(timeout=left)
            except subprocess.TimeoutExpired:
                _LOG.warning("worker %s ignored SIGTERM; killing", h.name)
                h.kill()
                h.proc.wait(timeout=5.0)
            h.close_sock()
        for rid in list(self._users):
            self._finalize(rid, "cancelled")
        return {r.rid: r.status for r in self._finished}


class Fleet:
    """A running deployment: the config, the worker handles, and the
    coordinator.  Context-manager friendly; ``close()`` is the graceful
    drain."""

    def __init__(self, config, coordinator, handles, workdir,
                 own_workdir):
        self.config = config
        self.coordinator = coordinator
        self.handles = handles
        self.workdir = workdir
        self._own_workdir = own_workdir
        coordinator._fleet = self

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def respawn(self, name):
        """Spawn a replacement process for a dead worker under the same
        name (its control path and KV endpoint are reused)."""
        role = "prefill" if name.startswith("prefill") else "decode"
        idx = int(name[len(role):])
        # Unlink the corpse's socket paths before spawning: a SIGKILLed
        # worker's listeners can linger for a few ms and accept a connect
        # into their doomed backlog.  Once the names are gone, connects
        # fail fast until the replacement binds fresh inodes.
        for stale in (os.path.join(self.workdir, f"{name}.ctl"),
                      self.config.kv_endpoint(name, self.workdir)):
            if stale.startswith("unix:"):
                stale = stale[len("unix:"):]
            if os.path.sep in stale:
                try:
                    os.unlink(stale)
                except OSError:
                    pass
        proc, log_path = _spawn_worker(
            self.workdir, role, idx, platform=self.config.platform,
            devices_per_worker=self.config.devices_per_worker)
        handle = _connect_worker(self.config, name, role, proc, log_path,
                                 self.workdir)
        self.handles[name] = handle
        return handle

    def close(self):
        statuses = self.coordinator.close()
        if self._own_workdir:
            import shutil
            shutil.rmtree(self.workdir, ignore_errors=True)
        return statuses


def _tail(log_path, n=30):
    try:
        with open(log_path, errors="replace") as f:
            return "".join(f.readlines()[-n:])
    except OSError:
        return "<no log>"


def _spawn_worker(workdir, role, idx, platform="cpu",
                  devices_per_worker=1):
    log_path = os.path.join(workdir, f"{role}{idx}.log")
    env = dict(os.environ)
    # the platform/device shape must be pinned BEFORE the child's
    # imports can initialize a jax backend — env is the only channel
    # that beats `python -m`'s package import
    env["JAX_PLATFORMS"] = platform
    if platform == "cpu" and devices_per_worker > 1:
        env["JAX_NUM_CPU_DEVICES"] = str(devices_per_worker)
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    logf = open(log_path, "ab")
    proc = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.serving.worker",
         os.path.join(workdir, "fleet.json"), role, str(idx)],
        stdout=logf, stderr=subprocess.STDOUT, env=env, cwd=repo)
    logf.close()
    return proc, log_path


def _connect_worker(config, name, role, proc, log_path, workdir):
    """Connect to a spawned worker's control socket and wait for its
    ``ready`` event; raises with the worker's log tail on failure."""
    ctl_path = os.path.join(workdir, f"{name}.ctl")
    deadline = time.monotonic() + config.ready_timeout_s
    while True:
        sock = None
        while True:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"fleet worker {name} exited rc={proc.returncode} "
                    f"during bringup; log tail:\n" + _tail(log_path))
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            try:
                sock.connect(ctl_path)
                break
            except (FileNotFoundError, ConnectionRefusedError):
                sock.close()
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"fleet worker {name} never bound its control "
                        f"socket within {config.ready_timeout_s:.0f}s; "
                        f"log tail:\n" + _tail(log_path))
                time.sleep(0.05)
        handle = RemoteWorkerHandle(name, role, proc, sock, log_path)
        while handle.ready_info is None:
            if handle._reader.eof and proc.poll() is None:
                # Connected to a predecessor's dying listener (its socket
                # accepts for a few ms after SIGKILL) — the replacement
                # process is alive, so reconnect to its fresh socket.
                sock.close()
                handle = None
                break
            if not handle.alive():
                raise RuntimeError(
                    f"fleet worker {name} died before ready "
                    f"(rc={proc.returncode}); log tail:\n"
                    + handle.log_tail())
            if time.monotonic() > deadline:
                handle.kill()
                raise RuntimeError(
                    f"fleet worker {name} never sent ready within "
                    f"{config.ready_timeout_s:.0f}s; log tail:\n"
                    + handle.log_tail())
            handle._pump()
            time.sleep(0.02)
        if handle is not None:
            return handle


def launch(config, registry=None, instrument=True, faults=None):
    """Validate ``config``, spawn the fleet, gate on readiness, return a
    ``Fleet``.  Any bringup failure kills every spawned process and
    raises with the offender's log tail."""
    config.validate()
    own_workdir = config.workdir is None
    workdir = config.workdir or tempfile.mkdtemp(prefix="ptfleet-")
    os.makedirs(workdir, exist_ok=True)

    names = config.worker_names()
    cfg_blob = config.to_dict()
    cfg_blob["endpoints"] = {
        n: config.kv_endpoint(n, workdir)
        for n in names if n.startswith("decode")}
    cfg_blob["control"] = {
        n: os.path.join(workdir, f"{n}.ctl") for n in names}
    for pth in cfg_blob["control"].values():
        if len(pth) > _UDS_PATH_MAX:
            raise ValueError(
                f"control socket path {pth!r} exceeds the "
                f"{_UDS_PATH_MAX}-char sun_path limit")
    with open(os.path.join(workdir, "fleet.json"), "w") as f:
        json.dump(cfg_blob, f, indent=2, sort_keys=True)

    procs = []
    handles = {}
    try:
        for name in names:
            role = "prefill" if name.startswith("prefill") else "decode"
            idx = int(name[len(role):])
            proc, log_path = _spawn_worker(
                workdir, role, idx, platform=config.platform,
                devices_per_worker=config.devices_per_worker)
            procs.append((name, role, proc, log_path))
        for name, role, proc, log_path in procs:
            handles[name] = _connect_worker(config, name, role, proc,
                                            log_path, workdir)
    except Exception:
        for _, _, proc, _ in procs:
            try:
                proc.kill()
                proc.wait(timeout=5.0)
            except (OSError, subprocess.TimeoutExpired):
                pass
        if own_workdir:
            import shutil
            shutil.rmtree(workdir, ignore_errors=True)
        raise

    coord = FleetCoordinator(config, handles.values(), registry=registry,
                             instrument=instrument, faults=faults)
    return Fleet(config, coord, handles, workdir, own_workdir)
