"""Declarative registry of the static axes that define a serving program.

Every knob that changes the *traced program* rather than its runtime
inputs — which attention kernel runs, how the KV cache and the decode
weights are stored, whether the row-parallel TP reduction is segmented —
is declared exactly once, here, as a :class:`StaticAxis` row of
:data:`PROGRAM_AXES`.  The frozen :class:`ProgramKey` dataclass carries
one value per axis and is the single static argument threaded through
the four ``models/llama_decode.py`` serving impls, ``serving/engine.py``,
and ``serving/sharding.py``'s TP program cache key.  Adding a new static
knob means adding one axis row and one field — not editing N
``static_argnames`` lists and M hand-built cache-key tuples.

tpu-lint's PTL014 (program-cache-key completeness) reads
:data:`PROGRAM_AXES` as the source of truth: a program-cache key that
hand-threads a *subset* of these axis names instead of carrying a
``program_key`` is an incomplete key and is flagged.

``ProgramKey`` is hashable and comparison-stable, so it is directly
usable as a jit ``static_argnames`` value and as a dict-key component:
two engines configured identically share compiled programs; any
differing axis forks the cache entry.
"""

from __future__ import annotations

import dataclasses

__all__ = ["StaticAxis", "PROGRAM_AXES", "ProgramKey"]


@dataclasses.dataclass(frozen=True)
class StaticAxis:
    """One static program axis: name, default, validation, and intent.

    ``values`` is the closed enum of allowed settings when ``kind`` is
    ``"enum"``; ``kind="segments"`` instead accepts ``None`` (off) or an
    ``int >= 2`` (the number of per-layer reduction segments);
    ``kind="depth"`` accepts ``None`` (off) or an ``int >= 1`` (a draft
    depth — the number of speculative candidate tokens per round).
    """

    name: str
    default: object
    doc: str
    values: tuple = ()
    kind: str = "enum"

    def validate(self, value):
        if self.kind == "enum":
            if value not in self.values:
                allowed = ", ".join(repr(v) for v in self.values)
                raise ValueError(
                    f"ProgramKey: unknown {self.name} {value!r}; expected "
                    f"one of ({allowed}).  {self.doc}")
            return value
        if self.kind == "segments":
            if value is None:
                return None
            if isinstance(value, bool) or not isinstance(value, int) or value < 2:
                raise ValueError(
                    f"ProgramKey: {self.name} must be None (off) or an "
                    f"int >= 2 (segments per row-parallel reduction), got "
                    f"{value!r}.  {self.doc}")
            return value
        if self.kind == "depth":
            if value is None:
                return None
            if isinstance(value, bool) or not isinstance(value, int) or value < 1:
                raise ValueError(
                    f"ProgramKey: {self.name} must be None (off) or an "
                    f"int >= 1 (draft tokens per speculative round), got "
                    f"{value!r}.  {self.doc}")
            return value
        raise AssertionError(f"unknown StaticAxis kind {self.kind!r}")


#: THE registry.  One row per static knob; every consumer (the serving
#: impls' ``program_key`` static, the engine's constructor kwargs, the TP
#: program-cache key, bench_sweep axes, PTL014) derives from this tuple.
PROGRAM_AXES = (
    StaticAxis(
        "attn_impl", None,
        "decode-time cache-read attention: None/'reference' = XLA flash "
        "loop, 'pallas' = fused VMEM-resident kernel with reference "
        "fallback when unsupported.",
        values=(None, "reference", "pallas")),
    StaticAxis(
        "prefill_impl", None,
        "chunked-prefill attention + KV append: None/'reference' = flash "
        "loop plus separate quantize-on-append scatter, 'pallas' = one "
        "fused kernel (attention + in-kernel append) with reference "
        "fallback when unsupported.",
        values=(None, "reference", "pallas")),
    StaticAxis(
        "kv_dtype", None,
        "KV cache storage override: None keeps the model dtype, 'int8' "
        "selects the quantized cache (f16 absmax scale leaf).",
        values=(None, "int8")),
    StaticAxis(
        "weight_dtype", None,
        "decode matmul weight storage: None keeps the checkpoint dtype, "
        "'int8' selects per-output-channel symmetric quantization.",
        values=(None, "int8")),
    StaticAxis(
        "tp_overlap", None,
        "segment the row-parallel (wo/down) matmul + psum along the "
        "output-feature axis so per-segment collectives can overlap "
        "trailing compute; byte-identical math, different schedule.",
        kind="segments"),
    StaticAxis(
        "draft_source", None,
        "speculative draft generator: None = not speculating (greedy), "
        "'prompt_lookup' = n-gram continuation mined from the slot's "
        "token history, 'draft_model' = a resident shrunk-llama draft "
        "model decoding k candidates through its own compiled program.",
        values=(None, "prompt_lookup", "draft_model")),
    StaticAxis(
        "spec_depth", None,
        "draft tokens verified per speculative round (the k in the "
        "[B, k+1] verify forward); each depth is its own compiled "
        "program, so the adaptive-k ladder pre-warms one entry per rung.",
        kind="depth"),
    StaticAxis(
        "spec_tree", None,
        "tree-structured candidates: None = linear draft chain, 'top2' = "
        "top-2 branch at the first draft position verified in the same "
        "batched forward through a tree attention mask (draft_model + "
        "dense caches only).",
        values=(None, "top2")),
)

_AXES_BY_NAME = {ax.name: ax for ax in PROGRAM_AXES}


@dataclasses.dataclass(frozen=True)
class ProgramKey:
    """One frozen, hashable value per :data:`PROGRAM_AXES` row.

    Field order and names mirror the registry; ``__post_init__`` runs each
    axis's validator so an invalid knob fails loudly at construction —
    never as an opaque trace error inside the first compiled step.
    """

    attn_impl: object = None
    prefill_impl: object = None
    kv_dtype: object = None
    weight_dtype: object = None
    tp_overlap: object = None
    draft_source: object = None
    spec_depth: object = None
    spec_tree: object = None

    def __post_init__(self):
        for ax in PROGRAM_AXES:
            ax.validate(getattr(self, ax.name))

    def axes(self):
        """(name, value) pairs in registry order — for logs and metrics."""
        return tuple((ax.name, getattr(self, ax.name)) for ax in PROGRAM_AXES)

    def replace(self, **kw):
        """A copy with some axes swapped (re-validated)."""
        return dataclasses.replace(self, **kw)


# The registry and the dataclass must stay in lockstep: one field per axis.
_PK_FIELDS = tuple(f.name for f in dataclasses.fields(ProgramKey))
if _PK_FIELDS != tuple(ax.name for ax in PROGRAM_AXES):  # pragma: no cover
    raise AssertionError(
        f"ProgramKey fields {_PK_FIELDS} out of sync with PROGRAM_AXES "
        f"{tuple(ax.name for ax in PROGRAM_AXES)}")
