"""Continuous-batching serving engine on the ragged decode path.

The compiled decode step (models/llama_decode.py) already supports ragged
per-batch lengths and rewind, but a run-to-completion batch leaves finished
slots idling while the longest request drags the step.  This engine closes
that gap with Orca-style *iteration-level scheduling* — the technique behind
vLLM-class serving throughput — under the TPU constraint that every device
program keeps ONE static compiled shape:

* The device runs a fixed-batch-B step; a host-side scheduler retires
  finished slots (EOS / max-new-tokens) and admits queued requests into
  them *between* compiled steps.
* Admission prefills the incoming prompt against fresh [1, bucket] mini
  caches — cost proportional to the PROMPT, not B×bucket — and inserts
  the rows into the batch cache at the freed slot: the ragged cache's
  per-slot reset.  Retired slots stay parked via
  ``ops.decode_attention.masked_lengths``: their write offset is lmax so
  every decode-step cache write DROPS — recycling needs no reshape,
  copy-out, or recompile.  Prompts are right-padded to a small set of
  power-of-two buckets, bounding the compile count; the slot's first
  token is picked from the logit at its own last prompt column (pad
  columns are causally invisible to it).
* Decode runs either mode behind one ``ServingEngine.step()``: greedy
  (``sync_every`` tokens per dispatch via an inner lax.scan) or model-free
  prompt-lookup speculative drafting (serving_spec_step — the same
  _verify_and_emit verify/rewind machinery as the compiled while-loop, so
  speculation composes with mixed-length slots and emits exactly the
  verify forward's greedy picks; agreement with the 1-token-step program
  holds up to floating-point near-ties between the two program shapes).
* ``policy="gang"`` disables mid-run admission (a batch is admitted only
  when every slot is free and runs to completion) — the sequential
  baseline for the bench A/B, sharing the exact same compiled programs so
  the measured win is pure scheduling.

The per-slot state the scheduler owns host-side: token history, a length
mirror of the device cache, and the speculative rewind offset (folded into
the length mirror as ``+ j + 1`` per accepted round).
"""
from __future__ import annotations

import time
import warnings
from collections import deque

import numpy as np

import jax.numpy as jnp

from paddle_tpu.models.llama_decode import (
    _decode_params_of, serving_decode_steps, serving_prefill_slot,
    serving_spec_step,
)
from paddle_tpu.ops.decode_attention import init_kv_cache, masked_lengths

# the serving step/prefill programs donate their cache buffers (in-place
# update on TPU instead of a full-cache copy per dispatch); CPU has no
# donation support and warns per program — harmless here, silence it
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable")

__all__ = ["Request", "ServingEngine"]


class Request:
    """One generation request.

    ``prompt_ids``: 1-D int token ids.  ``eos_token_id`` retires the slot
    when emitted (the EOS itself is kept in ``output_ids``).  ``stream_cb``
    (optional ``cb(request, new_ids)``) fires per emission batch — the
    streaming hook; with an engine ``detokenizer`` the accumulated text is
    kept current in ``.text``.  Timing (perf_counter): ``t_submit`` /
    ``t_first`` (first token) / ``t_done``.
    """

    def __init__(self, prompt_ids, max_new_tokens, eos_token_id=None,
                 stream_cb=None, rid=None):
        self.prompt_ids = np.asarray(prompt_ids, np.int32).reshape(-1)
        if self.prompt_ids.size == 0:
            raise ValueError("Request: empty prompt")
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens < 1:
            raise ValueError("Request: max_new_tokens must be >= 1")
        self.eos_token_id = eos_token_id
        self.stream_cb = stream_cb
        self.rid = rid
        self.output_ids = []
        self.text = ""
        self.done = False
        self.t_submit = None
        self.t_first = None
        self.t_done = None

    @property
    def latency(self):
        """submit -> completion seconds (None until done)."""
        if self.t_done is None or self.t_submit is None:
            return None
        return self.t_done - self.t_submit


class ServingEngine:
    """Fixed-batch continuous-batching engine over one causal LM.

    ``mode``: "greedy" or "spec" (model-free prompt-lookup speculative
    drafting, lossless — per-slot outputs byte-identical to greedy).
    ``sync_every``: greedy tokens decoded per host dispatch (inner scan);
    retirement/admission latency is bounded by it.  ``policy``:
    "continuous" (admit into any free slot between steps) or "gang"
    (run-to-completion baseline).  ``prompt_buckets``: padded prefill
    widths (default: powers of two up to ``max_len``).
    ``detokenizer``: optional ``ids -> str`` for streamed ``.text``.
    """

    def __init__(self, model, batch_size=8, max_len=2048, mode="greedy",
                 spec_k=8, sync_every=1, policy="continuous",
                 prompt_buckets=None, detokenizer=None):
        if mode not in ("greedy", "spec"):
            raise ValueError(f"unknown mode {mode!r}")
        if policy not in ("continuous", "gang"):
            raise ValueError(f"unknown policy {policy!r}")
        self._B = int(batch_size)
        self._lmax = int(max_len)
        self._mode = mode
        self._spec_k = int(spec_k)
        self._sync = max(1, int(sync_every))
        self._policy = policy
        self._detok = detokenizer
        self._params, self._cfg = _decode_params_of(model, self._lmax)
        nh, nkv, hd, eps = self._cfg
        dtype = self._params["embed"].dtype
        self._caches = [init_kv_cache(self._B, self._lmax, nkv, hd, dtype)
                        for _ in self._params["layers"]]
        if prompt_buckets is None:
            prompt_buckets = []
            b = 16
            while b < self._lmax:
                prompt_buckets.append(b)
                b *= 2
        self._buckets = sorted(int(b) for b in prompt_buckets)
        if not self._buckets or self._buckets[-1] > self._lmax:
            raise ValueError("prompt_buckets must be non-empty and <= max_len")
        # host mirrors of per-slot device state
        self._len = np.zeros((self._B,), np.int32)
        self._cur = np.zeros((self._B,), np.int32)
        self._reqs = [None] * self._B
        if mode == "spec":
            self._hist = jnp.zeros((self._B, self._lmax), jnp.int32)
            self._hist_len = jnp.zeros((self._B,), jnp.int32)
        else:
            self._hist = self._hist_len = None
        self._queue = deque()
        self._finished = []
        self._next_rid = 0

    # ------------------------------------------------------------- scheduling
    @property
    def has_work(self):
        return bool(self._queue) or any(r is not None for r in self._reqs)

    def _headroom(self):
        # greedy may overshoot a retiring slot by < sync_every cache rows;
        # spec's verify forward writes spec_k+1 rows before the rewind
        return self._spec_k + 1 if self._mode == "spec" else self._sync

    def submit(self, request):
        p = int(request.prompt_ids.size)
        bucket = next((b for b in self._buckets if b >= p), None)
        if bucket is None:
            raise ValueError(
                f"prompt length {p} exceeds the largest prompt bucket "
                f"{self._buckets[-1]}")
        need = p + request.max_new_tokens + self._headroom()
        if need > self._lmax:
            raise ValueError(
                f"request needs {need} cache rows (prompt {p} + "
                f"max_new {request.max_new_tokens} + headroom "
                f"{self._headroom()}) > max_len {self._lmax}")
        request._bucket = bucket
        if request.rid is None:
            request.rid = self._next_rid
        self._next_rid += 1
        request.t_submit = time.perf_counter()
        self._queue.append(request)
        return request

    def _admit(self):
        free = [i for i in range(self._B) if self._reqs[i] is None]
        if not free or not self._queue:
            return
        if self._policy == "gang" and len(free) < self._B:
            return  # run-to-completion: wait for the whole batch to drain
        while free and self._queue:
            r = self._queue.popleft()
            slot = free.pop(0)
            self._reqs[slot] = r
            p = r.prompt_ids.size
            tokens = np.zeros((1, r._bucket), np.int32)
            tokens[0, :p] = r.prompt_ids
            first, self._caches, hist, hist_len = serving_prefill_slot(
                self._params, self._cfg, jnp.asarray(tokens),
                jnp.asarray(np.array([p], np.int32)), self._caches,
                jnp.asarray(slot, jnp.int32),
                hist=self._hist, hist_len=self._hist_len,
                with_hist=self._mode == "spec")
            if self._mode == "spec":
                self._hist, self._hist_len = hist, hist_len
            self._len[slot] = p
            first = int(np.asarray(first)[0])
            self._cur[slot] = first
            self._emit(slot, [first])

    def _emit(self, slot, toks):
        """Append emitted tokens to the slot's request, truncating at EOS /
        max_new_tokens; retires the slot when the request completes.
        Returns the number of tokens actually consumed."""
        r = self._reqs[slot]
        took = 0
        for t in toks:
            if r.done:
                break
            r.output_ids.append(int(t))
            took += 1
            if r.t_first is None:
                r.t_first = time.perf_counter()
            if len(r.output_ids) >= r.max_new_tokens or (
                    r.eos_token_id is not None
                    and int(t) == int(r.eos_token_id)):
                r.done = True
        if took:
            if self._detok is not None:
                r.text = self._detok(list(r.output_ids))
            if r.stream_cb is not None:
                r.stream_cb(r, r.output_ids[-took:])
        if r.done:
            r.t_done = time.perf_counter()
            self._reqs[slot] = None
            self._finished.append(r)
        return took

    # ------------------------------------------------------------ step / run
    def step(self):
        """One scheduler iteration: retire/admit, then one compiled decode
        dispatch over every live slot.  Returns tokens emitted."""
        self._admit()
        live = [i for i in range(self._B) if self._reqs[i] is not None]
        if not live:
            return 0
        active = np.array([r is not None for r in self._reqs])
        dev_len = masked_lengths(jnp.asarray(self._len), jnp.asarray(active),
                                 self._lmax)
        emitted = 0
        if self._mode == "greedy":
            toks, self._caches = serving_decode_steps(
                self._params, self._cfg, jnp.asarray(self._cur),
                self._caches, dev_len, n_steps=self._sync)
            toks = np.asarray(toks)
            for i in live:
                emitted += self._emit(i, toks[i].tolist())
                self._len[i] += self._sync
                self._cur[i] = toks[i, -1]
        else:
            blk, j, cur, self._caches, self._hist, self._hist_len = \
                serving_spec_step(
                    self._params, self._cfg, jnp.asarray(self._cur),
                    self._caches, dev_len, self._hist, self._hist_len,
                    jnp.asarray(active), spec_k=self._spec_k)
            blk, j, cur = np.asarray(blk), np.asarray(j), np.asarray(cur)
            for i in live:
                emitted += self._emit(i, blk[i, :int(j[i]) + 1].tolist())
                self._len[i] += int(j[i]) + 1
                self._cur[i] = cur[i]
        return emitted

    def run(self):
        """Drive ``step()`` until the queue and every slot drain; returns
        the finished requests in completion order."""
        while self.has_work:
            self.step()
        return self._finished
