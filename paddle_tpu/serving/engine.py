"""Continuous-batching serving engine on the ragged decode path.

The compiled decode step (models/llama_decode.py) already supports ragged
per-batch lengths and rewind, but a run-to-completion batch leaves finished
slots idling while the longest request drags the step.  This engine closes
that gap with Orca-style *iteration-level scheduling* — the technique behind
vLLM-class serving throughput — under the TPU constraint that every device
program keeps ONE static compiled shape:

* The device runs a fixed-batch-B step; a host-side scheduler retires
  finished slots (EOS / max-new-tokens) and admits queued requests into
  them *between* compiled steps.
* **Chunked prefill with budgeted interleaving** (``prefill_chunk``,
  default 256; ``prefill_budget`` chunks per scheduler step).  Admission
  is INCREMENTAL: an admitted request enters a ``prefilling`` state and
  its prompt is processed in fixed ``[1, P]`` chunks
  (``serving_prefill_chunk``) written straight into the slot's rows of
  the batch cache at a device-carried offset — ONE compiled program for
  every prompt length (short/tail chunks are length-masked, zero
  retraces in steady state), and each scheduler step spends at most
  ``prefill_budget`` chunks before dispatching the decode step, so a
  long prompt never stalls resident decode for its full prefill
  (Sarathi-style stall-free admission; the TPOT spike the monolithic
  path takes at admission is bounded by the budget).  The final chunk's
  program also returns the first sampled token — it stays device-
  resident and feeds the slot's first decode dispatch without a host
  round-trip; the host copy is synced at the next drain.
  ``prefill_chunk=None`` falls back to the bitwise-compatible monolithic
  path: the whole prompt against fresh [1, bucket] mini caches — cost
  proportional to the PROMPT, not B×bucket — inserted into the batch
  cache at the freed slot (one compiled program per power-of-two
  bucket).  Either way retired slots stay parked via
  ``ops.decode_attention.masked_lengths``: their write offset is lmax so
  every decode-step cache write DROPS — recycling needs no reshape,
  copy-out, or recompile.  Prompts validate against the bucket set in
  both modes (buckets bound the admissible prompt length and label the
  per-bucket prefill counter); the slot's first token is picked from the
  logit at its own last prompt column (pad columns are causally
  invisible to it).
* Decode runs either mode behind one ``ServingEngine.step()``: greedy
  (``sync_every`` tokens per dispatch via an inner lax.scan) or model-free
  prompt-lookup speculative drafting (serving_spec_step — the same
  _verify_and_emit verify/rewind machinery as the compiled while-loop, so
  speculation composes with mixed-length slots and emits exactly the
  verify forward's greedy picks; agreement with the 1-token-step program
  holds up to floating-point near-ties between the two program shapes).
* ``policy="gang"`` disables mid-run admission (a batch is admitted only
  when every slot is free and runs to completion) — the sequential
  baseline for the bench A/B, sharing the exact same compiled programs so
  the measured win is pure scheduling.
* **Pipelined (double-buffered) dispatch** (``pipeline=True``, default):
  step N+1 depends only on device-resident state — the carried ``cur``
  tokens, caches, and lengths — so the engine dispatches it BEFORE
  syncing step N's tokens to the host.  Host-side emit/detokenize/
  stream-callback work and admission bookkeeping then overlap device
  compute; the drain-side block is measured by
  ``serving_pipeline_stall_seconds`` and the outstanding dispatch by the
  ``serving_inflight_steps`` gauge.  The ONE device→host sync per
  iteration goes through ``_host_fetch`` (the sanctioned sync point the
  tpu-lint PTL004 rule recognizes).  Correctness invariant: retirement
  and admission take effect ONE STEP LATE — a step dispatched before the
  scheduler discovers a slot finished still computes that slot, but the
  stale step is byte-harmless: ``masked_lengths`` gives a freed slot an
  offset of ``lmax`` at the NEXT dispatch so its writes drop, re-admission
  prefills are dispatched after the stale step in device program order so
  they overwrite its rows, rows past a new prompt's length are invisible
  to decode_attention's position masking, and the drain discards tokens
  whose slot no longer holds the same Request object.  The extra
  inflight dispatch is why ``_headroom`` doubles under pipelining.
  ``pipeline=False`` restores the fully synchronous loop (the A/B
  baseline) — token streams are byte-identical either way (tested).

* **Paged KV cache** (``kv_block=``): the dense per-slot ``[B, Lmax]``
  cache rows become a global block pool indirected through per-slot
  block tables (serving/kv_cache.py has the allocator; the constructor
  docstring has the knob semantics).  Admission switches to total-live-
  token budgeting, identical prompt prefixes are adopted from a radix
  cache instead of re-prefilled, and refcount-0 cached blocks are
  evicted LRU-first under pressure — all host bookkeeping over the same
  compiled-program discipline (fixed shapes, zero retraces).

The per-slot state the scheduler owns host-side: token history, a length
mirror of the device cache, and the speculative rewind offset (folded into
the length mirror as ``+ j + 1`` per accepted round).  Decode-side cache
reads are length-adaptive: ``decode_chunk`` is forwarded to the chunked
online-softmax path in ops/decode_attention.py, so per-step HBM traffic
tracks the longest LIVE context instead of ``max_len``.
"""
from __future__ import annotations

import bisect
import contextlib
import dataclasses
import logging
import threading
import time
import warnings
from collections import OrderedDict, deque

import numpy as np

import jax.numpy as jnp

from paddle_tpu.models.llama_decode import (
    _canon_weight_dtype, _decode_params_of, quantize_decode_weights,
    serving_decode_steps, serving_prefill_chunk, serving_prefill_slot,
    serving_spec_draft_step, serving_spec_step,
)
from paddle_tpu.observability.flightrecorder import (
    FlightRecorder, RequestTrace,
)
from paddle_tpu.observability.slo import SLOTracker
from paddle_tpu.observability.watchdog import DeadlockWatchdog
from paddle_tpu.ops.decode_attention import _canon_kv_dtype
from paddle_tpu.serving.faults import InjectedDispatchError
from paddle_tpu.serving.kv_cache import (
    BlockStore, KVCacheManager, KVPoolExhausted, PagedKVCacheManager,
)
from paddle_tpu.serving.metrics import EngineMetrics

# the serving step/prefill programs donate their cache buffers (in-place
# update on TPU instead of a full-cache copy per dispatch); CPU has no
# donation support and warns per program — harmless here, silence it
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable")

__all__ = ["AcceptWindow", "EngineOverloaded", "KVPoolExhausted",
           "Request", "ServingEngine", "SpecConfig"]

_NULL_CTX = contextlib.nullcontext()

_LOG = logging.getLogger(__name__)

# the transient device-error class the bounded dispatch retry targets
# (runtime/compile-service hiccups surface as XlaRuntimeError); the
# injected twin from serving/faults.py rides the same path so the retry
# machinery is provable without a flaky device
try:
    from jax.errors import JaxRuntimeError as _XLA_ERROR
except ImportError:  # pragma: no cover — older jax spellings
    try:
        from jaxlib.xla_extension import XlaRuntimeError as _XLA_ERROR
    except ImportError:
        class _XLA_ERROR(Exception):
            pass
_RETRYABLE = (_XLA_ERROR, InjectedDispatchError)


class EngineOverloaded(RuntimeError):
    """``submit()`` rejected the request: the bounded admission queue
    (``max_pending``) is full.  Load shedding at the front door — the
    caller owns the backoff/reroute decision; the engine's resident work
    is never displaced."""


def _backoff_sleep(seconds):
    """The engine's sanctioned blocking wait: the exponential backoff
    between dispatch retry attempts.  Funneled through this one name for
    the same reason ``_host_fetch`` exists — the tpu-lint PTL008 rule
    keeps flagging raw ``time.sleep`` added inside step-dispatch loops
    without false-positiving on the bounded retry's deliberate backoff."""
    if seconds > 0:
        time.sleep(seconds)


def _host_fetch(*arrays):
    """The engine's sanctioned device→host sync point: materialize device
    arrays as numpy, blocking until their producing dispatches complete.
    Every OTHER engine/device interaction is an async dispatch — funneling
    the blocking reads through this one name is what lets the tpu-lint
    PTL004 rule keep flagging raw ``np.asarray`` added inside step loops
    without false-positiving on the pipelined drain."""
    return [np.asarray(a) for a in arrays]


# warn-once latch for the SpecConfig draft-model fallback (satellite
# contract: asking for model drafting without a model degrades to
# prompt-lookup LOUDLY, but only once per process — a fleet of workers
# constructing engines in a loop must not spam the log)
_SPEC_FALLBACK_WARNED = False


def _warn_spec_fallback():
    global _SPEC_FALLBACK_WARNED
    if _SPEC_FALLBACK_WARNED:
        return
    _SPEC_FALLBACK_WARNED = True
    warnings.warn(
        "SpecConfig(source='draft_model') with no draft_model supplied — "
        "falling back to prompt-lookup drafting (this warning fires once "
        "per process)", RuntimeWarning, stacklevel=3)


@dataclasses.dataclass(frozen=True)
class SpecConfig:
    """THE validated speculative-decoding config: every drafting knob in
    one frozen value, checked loudly at construction instead of free-form
    kwargs failing deep inside the first compiled dispatch.

    ``source``: ``"prompt_lookup"`` (model-free n-gram mining from the
    slot's token history) or ``"draft_model"`` (a resident shrunk-llama
    draft model decoding ``spec_k`` candidates through its own compiled
    program).  ``draft_model``: the draft ``LlamaForCausalLM`` — required
    for model drafting; ``source="draft_model"`` WITHOUT one falls back
    to prompt-lookup with a once-per-process RuntimeWarning (the engine
    must keep serving when a deployment forgets to ship draft weights).
    ``spec_k``: draft tokens per verify round (``None`` inherits the
    engine's ``spec_k`` kwarg); under the adaptive policy this is the
    depth CEILING.  ``adaptive_window``: ``None`` = fixed k; an int >= 1
    sizes the per-slot sliding window of verify rounds whose accept rate
    drives the adaptive-k ladder (hard slots degrade toward ``k_min``
    instead of paying dead verify lanes).  ``k_min``: the adaptive
    floor.  ``tree``: ``None`` or ``"top2"`` — top-2 branching at the
    first draft position, verified in the same batched forward through a
    tree attention mask (draft-model source + dense caches only)."""

    source: str = "prompt_lookup"
    draft_model: object = None
    spec_k: object = None
    adaptive_window: object = None
    k_min: int = 1
    tree: object = None

    def __post_init__(self):
        if self.source not in ("prompt_lookup", "draft_model"):
            raise ValueError(
                f"SpecConfig: unknown source {self.source!r} — expected "
                "'prompt_lookup' or 'draft_model'")
        if self.spec_k is not None and (
                isinstance(self.spec_k, bool)
                or not isinstance(self.spec_k, int) or self.spec_k < 1):
            raise ValueError(
                f"SpecConfig: spec_k must be None (inherit the engine "
                f"knob) or an int >= 1, got {self.spec_k!r}")
        if self.adaptive_window is not None and (
                isinstance(self.adaptive_window, bool)
                or not isinstance(self.adaptive_window, int)
                or self.adaptive_window < 1):
            raise ValueError(
                f"SpecConfig: adaptive_window must be None (fixed k) or "
                f"an int >= 1 (verify rounds in the accept-rate window), "
                f"got {self.adaptive_window!r}")
        if isinstance(self.k_min, bool) or not isinstance(self.k_min, int) \
                or self.k_min < 1:
            raise ValueError(
                f"SpecConfig: k_min must be an int >= 1, got "
                f"{self.k_min!r}")
        if self.spec_k is not None and self.k_min > self.spec_k:
            raise ValueError(
                f"SpecConfig: k_min ({self.k_min}) exceeds spec_k "
                f"({self.spec_k})")
        if self.tree not in (None, "top2"):
            raise ValueError(
                f"SpecConfig: unknown tree {self.tree!r} — expected None "
                "(linear chain) or 'top2'")
        if self.tree is not None and self.source != "draft_model":
            raise ValueError(
                "SpecConfig: tree='top2' branches on the draft model's "
                "top-2 — it requires source='draft_model'")

    def replace(self, **kw):
        return dataclasses.replace(self, **kw)


class AcceptWindow:
    """Sliding window of (drafted, accepted) verify rounds — the accept
    rate that drives one slot's adaptive-k rung.  ``rate()`` is
    ``sum(accepted) / sum(drafted)`` over the last ``window`` rounds, or
    ``None`` while empty (a fresh slot holds its rung until evidence
    arrives).  Pure host arithmetic; one instance per slot."""

    def __init__(self, window):
        self.window = int(window)
        if self.window < 1:
            raise ValueError(
                f"AcceptWindow: window must be >= 1, got {window!r}")
        self._q = deque(maxlen=self.window)

    def push(self, drafted, accepted):
        if drafted < 0 or accepted < 0 or accepted > drafted:
            raise ValueError(
                f"AcceptWindow: need 0 <= accepted <= drafted, got "
                f"accepted={accepted} drafted={drafted}")
        self._q.append((int(drafted), int(accepted)))

    def rate(self):
        drafted = sum(d for d, _ in self._q)
        if not drafted:
            return None
        return sum(a for _, a in self._q) / drafted

    def reset(self):
        self._q.clear()

    def __len__(self):
        return len(self._q)


class Request:
    """One generation request.

    ``prompt_ids``: 1-D int token ids.  ``eos_token_id`` retires the slot
    when emitted (the EOS itself is kept in ``output_ids``).  ``stream_cb``
    (optional ``cb(request, new_ids)``) fires per emission batch — the
    streaming hook; with an engine ``detokenizer`` the accumulated text is
    kept current in ``.text``.  A raising ``stream_cb`` never kills the
    scheduler: the error is counted (``serving_stream_cb_errors_total``,
    labeled by exception type) and logged once per request, and decoding
    continues.  ``deadline_ms`` (optional) bounds submit -> completion:
    when it expires the request is retired wherever it is — queued,
    mid-prefill, or mid-decode — with whatever tokens it has.  Timing
    (perf_counter): ``t_submit`` / ``t_first`` (first token) /
    ``t_done``, with derived ``ttft`` / ``tpot`` / ``latency`` properties
    (None until available).

    ``status`` is the terminal-status state machine every front-end
    consumer reads: ``None`` while pending/in-flight, then exactly one of
    ``"done"`` (EOS / max_new_tokens), ``"timed_out"`` (deadline_ms),
    ``"cancelled"`` (host ``cancel()``/``close()``), ``"poisoned"``
    (non-finite logits quarantine) or ``"shed"`` (rejected at submit by
    the bounded admission queue).  ``done`` is True for every terminal
    status except ``"shed"`` (a shed request never entered the engine).

    ``slo_class`` names the request's traffic class for the engine's SLO
    tracker (observability/slo.py; ``None`` = the tracker's default,
    ``"interactive"``).  Classes must stay low-cardinality — they label
    the attainment/burn-rate gauges.  ``timeline()`` returns the
    engine-recorded lifecycle transitions (``queued`` → ``prefilling``
    per chunk → ``decoding`` → terminal status) as a list of ``{"t",
    "phase", ...}`` dicts on the ``perf_counter`` clock — empty until
    the request is submitted.

    ``priority`` (int, default 0, higher wins) orders admission and —
    on paged engines — arms preemption: when a strictly higher-priority
    request is queued and cannot be admitted, the engine parks the
    lowest-priority resident slot (its emitted tokens survive on the
    request; its KV chain survives EVICTABLE in the radix map) and
    re-queues it.  The resumed request re-adopts its own prefix, so a
    preemption round-trip costs one suffix prefill, not a recompute.
    ``preempts`` counts how many times this request was parked.
    All-default-priority traffic never preempts and admits in exact
    FIFO order — byte-identical to the pre-priority engine.
    """

    def __init__(self, prompt_ids, max_new_tokens, eos_token_id=None,
                 stream_cb=None, rid=None, deadline_ms=None,
                 slo_class=None, priority=0):
        self.prompt_ids = np.asarray(prompt_ids, np.int32).reshape(-1)
        if self.prompt_ids.size == 0:
            raise ValueError("Request: empty prompt")
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens < 1:
            raise ValueError("Request: max_new_tokens must be >= 1")
        self.eos_token_id = eos_token_id
        self.stream_cb = stream_cb
        self.rid = rid
        self.deadline_ms = (float(deadline_ms)
                            if deadline_ms is not None else None)
        if self.deadline_ms is not None and self.deadline_ms < 0:
            raise ValueError("Request: deadline_ms must be >= 0")
        self.slo_class = None if slo_class is None else str(slo_class)
        self.priority = int(priority)
        self.preempts = 0
        self._adm_ids = None      # tokens the last chunked admission prefilled
        self.output_ids = []
        self.text = ""
        self.done = False
        self.status = None
        self.t_submit = None
        self.t_first = None
        self.t_done = None
        self._t_deadline = None   # stamped at submit()
        self._trace = None        # RequestTrace, attached at submit()
        self._cb_err_logged = False

    def timeline(self):
        """Lifecycle transitions the engine recorded for this request
        (class docstring); ``[]`` before ``submit()``."""
        tr = self._trace
        return [] if tr is None else tr.as_dicts()

    @property
    def latency(self):
        """submit -> completion seconds (None until done)."""
        if self.t_done is None or self.t_submit is None:
            return None
        return self.t_done - self.t_submit

    @property
    def ttft(self):
        """Time to first token: submit -> first emission seconds (None
        until the first token lands)."""
        if self.t_first is None or self.t_submit is None:
            return None
        return self.t_first - self.t_submit

    @property
    def tpot(self):
        """Time per output token AFTER the first: (t_done - t_first) /
        max(1, n_out - 1) seconds (None until done) — the steady-state
        decode rate, with the prefill-dominated first token excluded."""
        if self.t_done is None or self.t_first is None:
            return None
        return (self.t_done - self.t_first) / max(1, len(self.output_ids) - 1)


class ServingEngine:
    """Fixed-batch continuous-batching engine over one causal LM.

    ``mode``: "greedy" or "spec" (model-free prompt-lookup speculative
    drafting, lossless — per-slot outputs byte-identical to greedy).
    ``sync_every``: greedy tokens decoded per host dispatch (inner scan);
    retirement/admission latency is bounded by it.  ``policy``:
    "continuous" (admit into any free slot between steps) or "gang"
    (run-to-completion baseline).  ``prompt_buckets``: padded prefill
    widths (default: powers of two up to ``max_len``).
    ``detokenizer``: optional ``ids -> str`` for streamed ``.text``.
    ``pipeline``: double-buffer the decode loop — dispatch step N+1 before
    syncing step N's tokens (module docstring has the one-step-late
    retirement invariant); ``False`` is the synchronous A/B baseline with
    byte-identical token streams.  ``decode_chunk``: KV chunk size for the
    length-adaptive cache read (ops/decode_attention.py); ``None`` reads
    the full ``[B, max_len]`` cache every step.  The default (256) falls
    back to the full read automatically when ``max_len <= 256``.
    ``prefill_chunk``: prompt tokens per chunked-prefill dispatch (one
    compiled program for every prompt length; ``None`` restores the
    monolithic per-bucket prefill — token streams byte-identical when
    both sides resolve to the same attention read, which the default
    ``decode_chunk`` does for every bucket <= 256).  ``prefill_budget``:
    max prefill chunks dispatched per scheduler step before the decode
    step goes out — bounds how long resident decode can stall on an
    admission (both knobs tuned via ``bench_sweep.py prefill_chunk``).
    ``kv_block``: paged KV cache — the per-layer cache becomes a global
    ``[num_blocks, kv_block, Hkv, D]`` pool indirected through per-slot
    block tables (serving/kv_cache.PagedKVCacheManager), with
    ``max_live_tokens`` (default ``batch_size * max_len``) sizing the
    pool: admission budgets total live TOKENS instead of slots, defers
    the queue head when the pool can't cover a request's worst case, and
    radix prefix hits adopt already-cached blocks so chunked prefill
    runs only the unmatched suffix.  Requires ``prefill_chunk``; forces
    ``decode_chunk = kv_block`` (the paged read IS the chunked loop).
    Token streams are byte-identical to the dense engine at f32
    (tested), and the block tables are traced operands — zero retraces
    across appends, prefix hits and evictions.
    ``host_tier_bytes`` / ``host_tier``: tiered KV cache — LRU eviction
    DEMOTES registered prefix chains into a byte-budgeted host-RAM
    ``BlockStore`` (a budget builds a private store; ``host_tier=``
    shares a caller-built one) instead of destroying them, and
    admission restores the host continuation of a prompt via a
    ``kv_transfer`` scatter (a device_put — cheaper than re-prefilling
    any prefix past ``host_tier_min_blocks`` blocks, the crossover
    knob).  Demotion copies are staged off the step path and
    materialized between scheduler steps; restores run at admission,
    never inside the dispatch loop; restored streams are byte-identical
    to never-evicted runs and the block tables still only change
    VALUES — zero retraces across a demote→restore wave.  Requires
    ``kv_block``.
    ``kv_dtype``: KV cache STORAGE dtype (``None`` = the model dtype).
    ``"int8"`` quantizes the cache — symmetric absmax over the head dim,
    one float16 scale per (position, head) row in a parallel pytree leaf
    riding the same donated-cache plumbing — quantized on append inside
    the cache scatter and dequantized inside the chunked attention read,
    so KV HBM traffic drops to ~0.53× of bf16 (~0.27× of f32).  Works
    with dense AND paged geometries (the scale pool shares the block
    tables — prefix reuse stays keyed on token ids) and with ``mesh``
    (scales head-sharded like the data).  Greedy streams can drift from
    the float engine within a small bounded rate (quantization error can
    flip near-tied argmaxes — the tested drift budget); every
    NON-quantized invariant (parking, poison quarantine, prefix
    adoption/accounting, pipeline drain identity, paged-vs-dense and
    TP-vs-single-device parity WITHIN q8) stays byte-identical.
    ``mesh``: a ``jax.sharding.Mesh`` to tensor-parallel the compiled
    hot path across (``None`` = single-device, bitwise the pre-mesh
    engine).  Params are shard-placed once at construction under the
    llama TP rules and the KV cache shards along heads
    (serving/sharding.py); every host-facing operand stays replicated,
    so the scheduler, pipeline, and chunked prefill above this line run
    unchanged.  ``tp_axis`` names the mesh axis to shard along (default
    ``"mp"``); the attention and KV head counts must divide its size.

    Reliability layer (a strict no-op on the clean path — with no
    deadlines, no faults and ``max_pending=None`` the token streams,
    program identities and sync structure are unchanged):
    ``max_pending`` bounds the admission queue — a ``submit()`` that
    would push it past the bound raises ``EngineOverloaded`` (status
    ``"shed"``, counted in ``serving_requests_shed_total``) instead of
    growing an unbounded backlog.  ``retry_attempts`` /
    ``retry_backoff``: the decode dispatch and the drain-side fetch are
    wrapped in a bounded retry (exponential backoff through the
    sanctioned ``_backoff_sleep``) against transient
    ``XlaRuntimeError``-class failures; exhaustion re-raises.  Per-slot
    non-finite logits (the jitted ``ok`` flag riding every step's
    outputs through the SAME ``_host_fetch`` — no extra sync) quarantine
    the slot's request with status ``"poisoned"``; cohabiting slots are
    untouched (per-row attention isolation, tested byte-identical).
    ``faults``: a serving/faults.FaultPlan injecting deterministic
    dispatch errors / NaN payloads / slow steps / stream_cb crashes
    through test-only seams.  ``cancel(rid)`` and per-request
    ``deadline_ms`` retire work anywhere in its lifecycle via the same
    write-drop parking retirement the scheduler already uses — no
    recompile, no retrace.

    Request-lifecycle observability (host-side bookkeeping on the
    existing sync structure — zero new device syncs, and token outputs
    are byte-identical recorder-on vs recorder-off, tested):
    ``recorder`` is the always-on flight recorder — ``True`` (default)
    builds a :class:`~paddle_tpu.observability.flightrecorder.
    FlightRecorder` with defaults, ``False`` disables recording, or pass
    a configured instance (capacity / ``dump_dir`` for anomaly dumps).
    A ``timed_out``/``poisoned`` retirement or a retry exhaustion
    auto-dumps the last events and bumps
    ``flight_recorder_dumps_total{reason}``.  Every request also gets a
    rid-keyed lifecycle trace behind ``Request.timeline()``, aggregated
    into the ``serving_queue/prefill/decode_seconds`` phase histograms
    at retirement.  ``slo``: per-class SLO objectives — ``None`` uses
    :data:`~paddle_tpu.observability.slo.DEFAULT_OBJECTIVES`, or pass an
    iterable of ``SLObjective`` / a ready ``SLOTracker``; retirements
    feed the windowed ``serving_slo_attainment`` / ``_burn_rate``
    gauges by ``Request(slo_class=...)``.  ``debug_sources()`` plugs
    ``/debug/requests``, ``/debug/flightrecorder`` and ``/debug/slo``
    into a ``MetricsExporter``.
    """

    def __init__(self, model, batch_size=8, max_len=2048, mode="greedy",
                 spec_k=8, sync_every=1, policy="continuous",
                 prompt_buckets=None, detokenizer=None, registry=None,
                 instrument=True, pipeline=True, decode_chunk=256,
                 prefill_chunk=256, prefill_budget=2, kv_block=None,
                 max_live_tokens=None, kv_dtype=None, mesh=None,
                 tp_axis="mp", max_pending=None, retry_attempts=3,
                 retry_backoff=0.05, faults=None, recorder=True,
                 slo=None, attn_impl=None, weight_dtype=None,
                 prefill_impl=None, tp_overlap=None,
                 prefill_only=False, on_prefilled=None, watchdog=None,
                 host_tier_bytes=None, host_tier=None,
                 host_tier_min_blocks=1, spec=None):
        if mode not in ("greedy", "spec"):
            raise ValueError(f"unknown mode {mode!r}")
        if policy not in ("continuous", "gang"):
            raise ValueError(f"unknown policy {policy!r}")
        # ONE validated config for every drafting knob (SpecConfig): the
        # engine's legacy ``spec_k`` kwarg survives as the default depth,
        # everything else — draft source, draft model, adaptive window,
        # tree mode — routes through ``spec=``.  Asking for model
        # drafting without a model degrades to prompt-lookup with a
        # once-per-process warning; every other inconsistency is a loud
        # ValueError here, never a trace error inside the first dispatch.
        if spec is not None and mode != "spec":
            raise ValueError(
                "spec= carries speculative-drafting knobs — construct "
                f"the engine with mode='spec' (got mode={mode!r})")
        if mode == "spec":
            if spec is None:
                spec = SpecConfig()
            elif isinstance(spec, dict):
                spec = SpecConfig(**spec)
            elif not isinstance(spec, SpecConfig):
                raise ValueError(
                    f"spec= must be a SpecConfig or a kwargs dict, got "
                    f"{type(spec).__name__}")
            if spec.spec_k is None:
                spec = spec.replace(spec_k=int(spec_k))
            if spec.source == "draft_model" and spec.draft_model is None:
                _warn_spec_fallback()
                spec = spec.replace(source="prompt_lookup", tree=None)
            if spec.tree is not None and kv_block is not None:
                raise ValueError(
                    "spec tree='top2' requires dense caches (kv_block="
                    "None): the accepted-branch row repair scatters into "
                    "dense per-slot cache rows")
            spec_k = spec.spec_k
        else:
            spec = None
        self._spec = spec
        self._dspec = spec is not None and spec.source == "draft_model"
        # prefill/decode disaggregation seams (serving/disagg.py).  A
        # prefill-only engine owns admission + chunked prefill and NEVER
        # dispatches a decode program: every request carries max_new=1
        # (the first token is the prefill's own pick), pipelining is
        # forced off so the synchronous first-token flush retires each
        # slot before any decode dispatch could include it, and the
        # paged admission budget shrinks to the prompt's own blocks.
        # ``on_prefilled(request, slot, first)`` fires after the finite
        # check + radix registration and BEFORE the slot is released —
        # the window where the block chain is still mapped and
        # exportable.
        if prefill_only:
            if kv_block is None:
                raise ValueError(
                    "prefill_only requires paged KV (kv_block=): the "
                    "block chain is the migration transfer unit")
            if mode != "greedy":
                raise ValueError(
                    "prefill_only engines never decode — spec drafting "
                    "belongs to the decode worker")
            pipeline = False
        elif on_prefilled is not None:
            raise ValueError(
                "on_prefilled is the prefill_only completion hook — "
                "construct the engine with prefill_only=True")
        self._prefill_only = bool(prefill_only)
        self._on_prefilled = on_prefilled
        if mesh is not None and tp_axis not in mesh.axis_names:
            raise ValueError(
                f"mesh has no axis {tp_axis!r} (axes: {mesh.axis_names})")
        mesh_devices = int(mesh.shape[tp_axis]) if mesh is not None else 1
        # observability: purely host-side counters/gauges/histograms/spans
        # keyed by policy (paddle_tpu/observability).  ``registry=None``
        # feeds the process-wide registry; benches pass private registries
        # for isolated readings.  ``instrument=False`` removes every metric
        # touch — token outputs are byte-identical either way (tested).
        self._m = (EngineMetrics(registry, policy, int(batch_size),
                                  mesh_devices=mesh_devices)
                   if instrument else None)
        # request-scoped observability: the flight-recorder event ring,
        # rid-keyed lifecycle traces (Request.timeline() / /debug/requests)
        # and the sliding-window SLO tracker fed at retirement — all host
        # bookkeeping riding the existing drain, never a device value
        if recorder is True:
            recorder = FlightRecorder(policy=policy)
        elif recorder is False:
            recorder = None
        self._fr = recorder
        if self._fr is not None and self._fr.on_dump is None \
                and self._m is not None:
            self._fr.on_dump = self._m.recorder_dump
        if isinstance(slo, SLOTracker):
            self._slo = slo
        else:
            self._slo = SLOTracker(
                objectives=slo, policy=policy,
                registry=self._m.registry if self._m is not None else None)
        self._traces = OrderedDict()   # rid -> RequestTrace, newest last
        self._trace_cap = 1024
        self._trace_lock = threading.Lock()
        # runtime deadlock watchdog (observability/watchdog.py):
        # ``watchdog=<seconds>`` arms a daemon thread that dumps every
        # thread's stack through the flight recorder when the step loop
        # goes stale past the threshold WITH work outstanding.  The
        # probe reads `_last_step_unix` (stamped 0 until the first
        # step), so it stays quiet through construction and idle.
        self._last_step_unix = 0.0
        self._watchdog = None
        if watchdog:
            self._watchdog = DeadlockWatchdog(
                self._watchdog_probe, stall_after=float(watchdog),
                recorder=self._fr,
                registry=self._m.registry if self._m is not None else None,
                component=policy).start()
        self._B = int(batch_size)
        self._lmax = int(max_len)
        self._mode = mode
        self._spec_k = int(spec_k)
        self._sync = max(1, int(sync_every))
        self._policy = policy
        self._detok = detokenizer
        self._pipeline = bool(pipeline)
        self._chunk = int(decode_chunk) if decode_chunk else None
        # a chunk wider than the cache would only pad — clamp so small
        # max_len engines don't pay a [1, 256] forward per tiny prompt
        self._pchunk = (min(int(prefill_chunk), self._lmax)
                        if prefill_chunk else None)
        if self._pchunk is not None and self._pchunk < 1:
            raise ValueError("prefill_chunk must be >= 1 or None")
        self._pbudget = max(1, int(prefill_budget))
        if self._dspec and self._pchunk is None:
            raise ValueError(
                "SpecConfig(source='draft_model') requires chunked "
                "prefill (prefill_chunk=): the draft model's prompt KV "
                "is built by per-chunk draft prefill dispatches riding "
                "the admission path")
        # paged KV geometry: ``kv_block`` switches the cache to a global
        # block pool + per-slot block tables with radix prefix reuse, and
        # admission to total-live-TOKEN budgeting (``max_live_tokens``).
        # The paged read IS the chunked attention loop (one gather per
        # chunk), so decode_chunk is forced to the block size; chunked
        # prefill is required (the monolithic mini-cache path has no slot
        # rows to insert into a pool), and the block/chunk sizes must
        # divide one another so a prefix hit's suffix chunks start on the
        # same chunk boundaries a miss would prefill — the byte-identity
        # condition across hit/miss admission.
        self._paged = kv_block is not None
        if self._paged:
            kv_block = int(kv_block)
            if self._pchunk is None:
                raise ValueError(
                    "paged KV (kv_block=) requires chunked prefill "
                    "(prefill_chunk=)")
            if self._pchunk % kv_block and kv_block % self._pchunk:
                raise ValueError(
                    f"prefill_chunk ({self._pchunk}) and kv_block "
                    f"({kv_block}) must divide one another (prefix hits "
                    "must land on prefill-chunk boundaries)")
            self._chunk = kv_block
        elif max_live_tokens is not None:
            raise ValueError("max_live_tokens requires kv_block (paged KV)")
        self._params, self._cfg = _decode_params_of(model, self._lmax)
        nh, nkv, hd, eps = self._cfg
        # kv_dtype: cache STORAGE dtype override.  None keeps the model
        # dtype (bitwise the pre-quantization engine — kv_dtype simply
        # never enters the program identity as a non-None static).
        # "int8" switches every cache leaf to a quantized (data, scale)
        # pair: quantize-on-append, dequant inside the chunked read
        # (ops/decode_attention.py) — ~0.53× the KV bytes of bf16.
        # Validated against the supported set here, at construction, so a
        # typo fails loudly instead of deep inside the first dispatch.
        self._kv_dtype = (_canon_kv_dtype(kv_dtype, "ServingEngine")
                          if kv_dtype is not None else None)
        self._q8 = self._kv_dtype == "int8"
        self._kvq = "int8" if self._q8 else "off"
        # attn_impl: cache-READ implementation.  None/"reference" keeps the
        # chunked lax.while_loop (bitwise the pre-kernel engine — like
        # kv_dtype=None it never enters the program identity as non-None);
        # "pallas" routes decode_attention through the fused Pallas kernel
        # (ops/paged_attention_pallas.py) — gather + dequant + online
        # softmax in one VMEM residency, interpret mode off-TPU.
        if attn_impl not in (None, "reference", "pallas"):
            raise ValueError(
                f"ServingEngine: unknown attn_impl {attn_impl!r} — "
                "supported: None (reference), 'reference', 'pallas' "
                "(fused kernel, falls back per-call when the geometry "
                "is unsupported)")
        self._attn_impl = attn_impl
        self._attn_label = "fused" if attn_impl == "pallas" else "reference"
        # prefill_impl: chunked-prefill implementation.  None/"reference"
        # keeps the dense fold + scatter append; "pallas" fuses the
        # causal-masked chunk attention WITH the (quantize-on-)append into
        # one kernel (ops/prefill_attention_pallas.py), falling back
        # per-call when the chunk geometry is unsupported.
        if prefill_impl not in (None, "reference", "pallas"):
            raise ValueError(
                f"ServingEngine: unknown prefill_impl {prefill_impl!r} — "
                "supported: None (reference), 'reference', 'pallas' "
                "(fused prefill+append kernel, falls back per-call when "
                "the chunk geometry is unsupported)")
        self._prefill_impl = prefill_impl
        self._prefill_label = ("fused" if prefill_impl == "pallas"
                               else "reference")
        # tp_overlap: split the row-parallel projections (wo/down) into N
        # output-feature segments so each segment's psum can overlap the
        # next segment's matmul.  None/0 keeps the single fused matmul;
        # int >= 2 is the segment count (byte-identical outputs — the
        # per-element dot products are unchanged, only issue order moves).
        if tp_overlap is not None:
            if isinstance(tp_overlap, bool) or not isinstance(
                    tp_overlap, int) or tp_overlap < 2:
                raise ValueError(
                    f"ServingEngine: tp_overlap must be None or an int "
                    f">= 2 (segment count), got {tp_overlap!r}")
        self._tp_overlap = tp_overlap
        # weight_dtype: decode matmul WEIGHT storage.  "int8" swaps the
        # seven projection weights for symmetric per-output-channel
        # quantized copies with f16 scales (quantize_decode_weights) —
        # dequant-in-matmul keeps the host-facing API unchanged.
        self._weight_dtype = _canon_weight_dtype(weight_dtype,
                                                 "ServingEngine")
        self._w8 = self._weight_dtype == "int8"
        self._wq_label = "int8" if self._w8 else "off"
        if self._w8:
            # quantize AFTER the model cache handed us its pytree (a fresh
            # dict — the cache entry itself is never mutated) and BEFORE
            # any mesh placement so the int8 leaves shard directly
            self._params = quantize_decode_weights(
                self._params, self._weight_dtype)
        # resident draft model (SpecConfig source="draft_model"): its
        # decode pytree lives alongside the target's and rides the same
        # weight-quantization / mesh-placement path.  Paged engines share
        # ONE block pool across both tenants — draft layer l reads/writes
        # target layer l's pool arrays through its own block tables — so
        # the geometries that alias (kv heads, head dim, dtype, layer
        # count <= target's) are validated here, loudly.
        self._dparams = self._dcfg = None
        self._dcaches = None
        if self._dspec:
            self._dparams, self._dcfg = _decode_params_of(
                spec.draft_model, self._lmax)
            dnh, dnkv, dhd, _ = self._dcfg
            if int(self._dparams["embed"].shape[0]) \
                    != int(self._params["embed"].shape[0]):
                raise ValueError(
                    f"draft model vocab "
                    f"{int(self._dparams['embed'].shape[0])} != target "
                    f"vocab {int(self._params['embed'].shape[0])} — the "
                    "verify forward compares token ids, so the vocabs "
                    "must match")
            if self._paged:
                if len(self._dparams["layers"]) > len(
                        self._params["layers"]):
                    raise ValueError(
                        f"paged draft sharing: draft layer count "
                        f"{len(self._dparams['layers'])} exceeds target "
                        f"{len(self._params['layers'])} (draft layer l "
                        "rides target layer l's pool array)")
                if (dnkv, dhd) != (nkv, hd):
                    raise ValueError(
                        f"paged draft sharing: draft KV geometry "
                        f"(kv_heads={dnkv}, head_dim={dhd}) != target "
                        f"({nkv}, {hd}) — blocks are model-agnostic "
                        "bytes only when the per-row shapes match; use "
                        "a dense engine for mismatched drafters")
                if self._dparams["embed"].dtype \
                        != self._params["embed"].dtype:
                    raise ValueError(
                        f"paged draft sharing: draft dtype "
                        f"{self._dparams['embed'].dtype} != target "
                        f"{self._params['embed'].dtype}")
            if self._w8:
                self._dparams = quantize_decode_weights(
                    self._dparams, self._weight_dtype)
        # the declarative program identity: every static kernel/precision
        # knob flows through this ONE frozen registry value — the four
        # serving impls, the TP program cache and the jit static axes all
        # consume it instead of hand-threaded per-impl keyword lists
        # (serving/program_key.py re-validates each axis on construction)
        from paddle_tpu.serving.program_key import ProgramKey
        self._pk = ProgramKey(
            attn_impl=self._attn_impl, prefill_impl=self._prefill_impl,
            kv_dtype=self._kv_dtype, weight_dtype=self._weight_dtype,
            tp_overlap=self._tp_overlap,
            draft_source=spec.source if spec is not None else None,
            spec_depth=self._spec_k if spec is not None else None,
            spec_tree=spec.tree if spec is not None else None)
        # adaptive draft length: per-slot AcceptWindows drive a rung on a
        # power-of-two ladder [k_min .. spec_k]; the batch runs ONE
        # program per round at min(live slots' rungs), moving one rung
        # per round (each depth is its own compiled program — the ladder
        # is what bounds how many the warm set holds)
        if spec is not None and spec.adaptive_window is not None:
            rungs = {spec.k_min, self._spec_k}
            r = 1
            while r < self._spec_k:
                if r > spec.k_min:
                    rungs.add(r)
                r *= 2
            self._k_rungs = sorted(rungs)
            self._awin = [AcceptWindow(spec.adaptive_window)
                          for _ in range(self._B)]
        else:
            self._k_rungs = [self._spec_k]
            self._awin = None
        self._k_cur = self._k_rungs[-1]
        self._k_want = [len(self._k_rungs) - 1] * self._B
        dtype = (self._kv_dtype if self._kv_dtype is not None
                 else self._params["embed"].dtype)
        # mesh=None: single-device engine, module-level jitted programs,
        # byte-identical to every prior release.  mesh set: params are
        # shard-placed ONCE here under the llama TP rules, the KV cache is
        # head-sharded, and the four entry points dispatch through the
        # process-wide cached TP programs (serving/sharding.py).  Host
        # scheduler state (cur/lengths/queues) stays replicated either way.
        self._tp = None
        self._tp_spec = None   # adaptive-k ladder: {rung k: TPPrograms}
        cache_sharding = None
        scale_sharding = None
        if mesh is not None:
            from paddle_tpu.serving.sharding import (
                shard_decode_params, serving_tp_programs)
            n = mesh_devices
            if nkv % n or nh % n:
                raise ValueError(
                    f"heads not shardable {n}-way along {tp_axis!r}: "
                    f"num_attention_heads={nh}, num_key_value_heads={nkv} "
                    f"(the KV cache shards along heads)")
            self._params, pspecs = shard_decode_params(
                self._params, mesh, axis=tp_axis)
            dspecs = None
            if self._dspec:
                dnh, dnkv, _, _ = self._dcfg
                if dnkv % n or dnh % n:
                    raise ValueError(
                        f"draft heads not shardable {n}-way along "
                        f"{tp_axis!r}: num_attention_heads={dnh}, "
                        f"num_key_value_heads={dnkv} (the draft KV "
                        "shards along heads like the target's)")
                self._dparams, dspecs = shard_decode_params(
                    self._dparams, mesh, axis=tp_axis)
            d_layers = (len(self._dparams["layers"]) if self._dspec
                        else 0)
            self._tp = serving_tp_programs(
                mesh, tp_axis, self._cfg, pspecs,
                len(self._params["layers"]), sync_every=self._sync,
                spec_k=self._spec_k, with_hist=mode == "spec",
                chunk_size=self._chunk, paged=self._paged,
                program_key=self._pk, dcfg=self._dcfg,
                dparam_specs=dspecs, d_layers=d_layers)
            if mode == "spec":
                # one compiled spec program per ladder rung (a depth IS
                # a program shape); the top rung is the base TPPrograms
                self._tp_spec = {self._spec_k: self._tp}
                for k in self._k_rungs[:-1]:
                    self._tp_spec[k] = serving_tp_programs(
                        mesh, tp_axis, self._cfg, pspecs,
                        len(self._params["layers"]),
                        sync_every=self._sync, spec_k=k,
                        with_hist=True, chunk_size=self._chunk,
                        paged=self._paged,
                        program_key=self._pk.replace(spec_depth=k),
                        dcfg=self._dcfg, dparam_specs=dspecs,
                        d_layers=d_layers)
            cache_sharding = self._tp.cache_sharding
            scale_sharding = self._tp.scale_sharding
        # host KV tier: evictions demote into a byte-budgeted host-RAM
        # BlockStore and admission restores from it (a device_put, not a
        # suffix prefill).  ``host_tier=`` shares a caller-built store;
        # ``host_tier_bytes=`` builds a private one.
        if host_tier is not None and host_tier_bytes is not None:
            raise ValueError(
                "pass host_tier= (a BlockStore) OR host_tier_bytes= (a "
                "budget for a private one), not both")
        host_store = host_tier
        if host_store is None and host_tier_bytes:
            if not self._paged:
                raise ValueError(
                    "host_tier_bytes requires paged KV (kv_block=): only "
                    "a block pool has demotable prefix chains")
            host_store = BlockStore(int(host_tier_bytes), block=kv_block)
        if host_store is not None and not self._paged:
            raise ValueError(
                "host_tier requires paged KV (kv_block=): only a block "
                "pool has demotable prefix chains")
        self._host_min_blocks = max(1, int(host_tier_min_blocks))
        self._restore_s = []   # per-admission restore wall times (bench)
        if self._paged:
            # a resident draft model is a second pool tenant: its chains
            # grow in lockstep with the target's, so the default pool
            # doubles (an explicit max_live_tokens is the caller's
            # sizing decision and is respected as-is)
            self._kv = PagedKVCacheManager(
                len(self._params["layers"]), self._B, self._lmax, nkv, hd,
                dtype, block=kv_block,
                max_live_tokens=(int(max_live_tokens) if max_live_tokens
                                 else (2 if self._dspec else 1)
                                 * self._B * self._lmax),
                sharding=cache_sharding, on_event=self._kv_event,
                scale_sharding=scale_sharding, host_store=host_store)
        else:
            self._kv = KVCacheManager(
                len(self._params["layers"]), self._B, self._lmax, nkv, hd,
                dtype, sharding=cache_sharding,
                scale_sharding=scale_sharding)
            if self._dspec:
                # dense draft tenancy: a SEPARATE per-draft-layer cache
                # list (dense rows are slot-indexed — cohabitation in the
                # target's arrays would clobber it), same storage dtype
                # rules and head sharding as the target's
                from paddle_tpu.ops.decode_attention import init_kv_cache
                from paddle_tpu.serving.kv_cache import _place_caches
                _, dnkv, dhd, _ = self._dcfg
                ddtype = (self._kv_dtype if self._kv_dtype is not None
                          else self._dparams["embed"].dtype)
                self._dcaches = [
                    init_kv_cache(self._B, self._lmax, dnkv, dhd, ddtype)
                    for _ in range(len(self._dparams["layers"]))]
                if cache_sharding is not None:
                    self._dcaches = _place_caches(
                        self._dcaches, cache_sharding, scale_sharding)
        if self._m is not None:
            self._m.set_kv_quant(self._kvq)
            self._m.set_decode_kernel(self._attn_label)
            self._m.set_prefill_kernel(self._prefill_label)
            self._m.set_tp_overlap(self._tp_overlap or 0)
            self._m.set_weight_quant(self._wq_label)
            if spec is not None:
                self._m.set_spec_source(spec.source)
                self._m.spec_draft_k.set(self._spec_k)
            if self._q8:
                # analytic per-context-token KV traffic at int8: 1 data
                # byte per (head, dim) element + 2 f16 scale bytes per
                # (position, head) row, both k and v, every layer
                n_layers = len(self._params["layers"])
                self._m.hbm_gb_per_tok_q8.set(
                    n_layers * 2 * nkv * (hd + 2) / 1e9)
            if self._w8:
                # analytic per-decode-token WEIGHT traffic at int8: every
                # projection element is read once per token — 1 byte of
                # data plus 2 f16 scale bytes per output channel (global
                # .size, placement-independent)
                wbytes = sum(
                    lp[n].size + 2 * lp[n + "_scale"].size
                    for lp in self._params["layers"]
                    for n in ("wq", "wk", "wv", "wo", "gate", "up", "down"))
                self._m.hbm_gb_per_tok_w8.set(wbytes / 1e9)
        # paged decode-time row growth is capped per slot by the token
        # budget reserved at admission (prompt + max_new + headroom,
        # clamped to lmax) — the mirror _spend/_dispatch draw ensure_rows
        # against
        self._need_rows = np.zeros((self._B,), np.int64)
        if prompt_buckets is None:
            prompt_buckets = []
            b = 16
            while b < self._lmax:
                prompt_buckets.append(b)
                b *= 2
        self._buckets = [int(b) for b in prompt_buckets]
        if not self._buckets or self._buckets[-1] > self._lmax:
            raise ValueError("prompt_buckets must be non-empty and <= max_len")
        if any(b2 <= b1 for b1, b2 in zip(self._buckets, self._buckets[1:])):
            raise ValueError(
                "prompt_buckets must be sorted strictly ascending (submit "
                f"bisects over them), got {self._buckets}")
        # host mirror of the carried next-token per slot; lengths and the
        # slot -> request table live on the cache manager
        self._cur = np.zeros((self._B,), np.int32)
        if mode == "spec":
            self._hist = jnp.zeros((self._B, self._lmax), jnp.int32)
            self._hist_len = jnp.zeros((self._B,), jnp.int32)
        else:
            self._hist = self._hist_len = None
        self._queue = deque()
        self._finished = []
        self._next_rid = 0
        self._rids = set()
        # pipelined-dispatch state: the one outstanding (dispatched, not yet
        # drained) step, the device-resident carries feeding the NEXT
        # dispatch without a host round-trip, and the slots admitted since
        # the last dispatch (whose cur/length live host-side until mixed in)
        self._inflight = None
        self._dev_cur = None
        self._dev_len = None
        self._adm_pending = set()
        # chunked-prefill state: per-slot prefill progress (insertion order
        # = admission order, the budget-spend order), the device-resident
        # first token of slots whose final chunk is dispatched but whose
        # host copy has not been drained yet, the (slot, request, first)
        # triples awaiting host emission, and the was-a-prefill-running
        # flag feeding the decode-interference histogram
        self._pf = {}
        self._dev_first = {}
        self._pending_firsts = []
        self._adm_wave = False
        self._t_lastdrain = None
        # reliability state: the bounded admission queue, the dispatch
        # retry policy, the fault-injection plan (None in production) and
        # the scheduler-step index the plan keys its injections to
        self._max_pending = (int(max_pending)
                             if max_pending is not None else None)
        if self._max_pending is not None and self._max_pending < 0:
            raise ValueError("max_pending must be >= 0 or None")
        self._retry_attempts = max(1, int(retry_attempts))
        self._retry_backoff = float(retry_backoff)
        self._faults = faults
        self._step_idx = -1
        # fleet-facing host counters, maintained UNCONDITIONALLY (a
        # router reads them through stats() even on instrument=False
        # engines): paged prompt/reuse token totals (the fleet hit-rate
        # ratio) and the preemption park/resume tallies
        self._n_prompt_tokens = 0
        self._n_reuse_tokens = 0
        self._n_preempted = 0
        self._n_resume_suffix = 0
        self._n_resume_total = 0
        self._n_host_reuse_tokens = 0

    # ------------------------------------------------------------- scheduling
    @property
    def has_work(self):
        return (bool(self._queue) or self._kv.any_live()
                or self._inflight is not None)

    def _watchdog_probe(self):
        """Watchdog progress probe: last step time while work is
        outstanding, None when idle (an idle engine is not stalled)."""
        t = self._last_step_unix
        if not t or not self.has_work:
            return None
        return t

    def _headroom(self):
        # greedy may overshoot a retiring slot by < sync_every cache rows;
        # spec's verify forward writes spec_k+1 rows before the rewind
        # (+1 more under tree mode: the branch token appends at L+k+1)
        if self._mode == "spec":
            per = self._spec_k + (
                2 if self._spec is not None and self._spec.tree else 1)
        else:
            per = self._sync
        # a pipelined engine discovers retirement one drain late, so one
        # extra full dispatch of cache writes can land past the emission
        # point before the slot's offset is masked to lmax
        return 2 * per if self._pipeline else per

    def submit(self, request):
        if self._prefill_only and request.max_new_tokens != 1:
            raise ValueError(
                "prefill-only engine: requests carry max_new_tokens=1 "
                "(the prefill's own first token) — decode belongs to a "
                f"decode worker, got max_new={request.max_new_tokens}")
        p = int(request.prompt_ids.size)
        i = bisect.bisect_left(self._buckets, p)
        if i == len(self._buckets):
            raise ValueError(
                f"prompt length {p} exceeds the largest prompt bucket "
                f"{self._buckets[-1]}")
        bucket = self._buckets[i]
        need = p + request.max_new_tokens + self._headroom()
        if need > self._lmax:
            raise ValueError(
                f"request needs {need} cache rows (prompt {p} + "
                f"max_new {request.max_new_tokens} + headroom "
                f"{self._headroom()}) > max_len {self._lmax}")
        request._bucket = bucket
        # load shedding AFTER validation (a malformed request stays a
        # ValueError) but BEFORE rid assignment (a shed request never
        # consumes engine state): bounding what's QUEUED — resident slots
        # are capacity already paid for — keeps worst-case queue wait
        # proportional to max_pending, the backpressure contract
        if self._max_pending is not None \
                and len(self._queue) >= self._max_pending:
            request.status = "shed"
            if self._m is not None:
                self._m.terminal("shed")
            if self._fr is not None:
                self._fr.record("shed", step=self._step_idx,
                                rid=request.rid,
                                queued=len(self._queue))
            raise EngineOverloaded(
                f"admission queue full ({len(self._queue)} pending >= "
                f"max_pending={self._max_pending}); request shed")
        if request.rid is None:
            # the engine assigns (and only then advances) the auto rid
            request.rid = self._next_rid
            self._next_rid += 1
        else:
            # a caller-provided rid must never collide with one already
            # handed out, nor silently alias a FUTURE auto rid: reject the
            # former, bump the auto counter past the latter
            if request.rid in self._rids:
                raise ValueError(
                    f"rid {request.rid!r} is already in use by another "
                    "request on this engine")
            if isinstance(request.rid, int):
                self._next_rid = max(self._next_rid, request.rid + 1)
        self._rids.add(request.rid)
        request.t_submit = time.perf_counter()
        if request.deadline_ms is not None:
            request._t_deadline = request.t_submit \
                + request.deadline_ms / 1e3
        # lifecycle trace: born "queued"; bounded rid-keyed index so
        # /debug/requests can show recent timelines without unbounded
        # growth (the Request itself keeps its own trace alive regardless).
        # recorder=False switches off ALL request-scoped recording —
        # timelines included
        if self._fr is not None:
            tr = RequestTrace(request.rid)
            request._trace = tr
            with self._trace_lock:
                self._traces[request.rid] = tr
                while len(self._traces) > self._trace_cap:
                    self._traces.popitem(last=False)
            tr.mark("queued")
            self._fr.record("submit", step=self._step_idx, rid=request.rid,
                            prompt_len=p, slo_class=request.slo_class)
        self._queue.append(request)
        if self._m is not None:
            self._m.queue_depth.set(len(self._queue))
        return request

    def _decodable(self, i):
        """Slot ``i`` holds a live request that finished prefilling — the
        population the decode dispatch runs over.  Slots mid-prefill stay
        parked (masked_lengths) until their final chunk is dispatched."""
        return self._kv.reqs[i] is not None and i not in self._pf

    # --------------------------------------------------- priority preemption
    @staticmethod
    def _admission_ids(r):
        """The token sequence a (re-)admission must prefill: the prompt,
        plus — for a request resuming after preemption — every token it
        already emitted.  The emitted tokens' KV rows must exist before
        decode continues, and the LAST emitted token's forward is exactly
        what produces the next one, so re-admitting this sequence through
        the ordinary chunked-prefill path continues the greedy stream
        byte-identically."""
        if not r.output_ids:
            return r.prompt_ids
        return np.concatenate(
            [r.prompt_ids, np.asarray(r.output_ids, np.int32)])

    def _preempt_slot(self, slot):
        """Park ``slot``'s request mid-decode.  The tokens whose KV rows
        are verified written — the prompt plus every emitted token but
        the last (the last token's row is written by the NEXT dispatch,
        which the park cancels) — are registered into the radix map, so
        ``release`` parks that chain EVICTABLE instead of freeing it and
        the resume admission re-adopts it for the cost of one suffix
        prefill.  An inflight pipelined dispatch for this slot is
        harmless by the same one-step-late invariant retirement rides:
        its writes land only in blocks PAST the registered chain (freed,
        and overwritten in device program order if reallocated) and its
        drained tokens fail the request-identity check."""
        r = self._kv.reqs[slot]
        cached = self._admission_ids(r)[:-1]
        self._kv.register_prefix(slot, cached)
        self._kv.release(slot)
        self._forget_slot(slot)
        r.preempts += 1
        r._adm_ids = None
        self._n_preempted += 1
        if r._trace is not None:
            r._trace.mark("preempted", slot=slot)
        if self._fr is not None:
            self._fr.record("preempt", step=self._step_idx, rid=r.rid,
                            slot=slot, cached_tokens=int(cached.size),
                            n_out=len(r.output_ids))
        self._queue.appendleft(r)
        if self._m is not None:
            self._m.preempted.inc()
            self._m.queue_depth.set(len(self._queue))
            self._m.slots_occupied.set(self._kv.occupied())
            self._m.live_tokens.set(self._kv.live_tokens())

    def _maybe_preempt(self):
        """Park low-priority resident work when a strictly higher-priority
        waiter is blocked (no free slot, or the block pool cannot cover
        its worst case).  Victims go lowest priority first; within a
        class the most recently submitted loses (old work keeps
        finishing).  Paged continuous engines only — and a strict no-op
        while every queued priority <= every resident priority, which is
        what keeps all-default traffic byte-identical."""
        if not self._paged or self._policy != "continuous" \
                or not self._queue:
            return
        top = max(self._queue, key=lambda q: q.priority)
        for _ in range(self._B):
            victims = [
                (i, self._kv.reqs[i]) for i in range(self._B)
                if self._kv.reqs[i] is not None and i not in self._pf
                and self._kv.reqs[i].t_first is not None
                and self._kv.reqs[i].priority < top.priority]
            if not victims:
                return
            # is the head actually blocked?  mirror the admission math
            # (worst-case rows minus the radix match, chunk-aligned)
            tok = self._admission_ids(top)
            C, P = self._kv.block, self._pchunk
            p = int(tok.size)
            rem = max(1, top.max_new_tokens - len(top.output_ids))
            need = min(self._lmax, p + rem + self._headroom())
            off0, shared = self._kv.match_prefix(tok)
            if P > C:
                off0 = (off0 // P) * P
                shared = shared[:off0 // C]
            budget = -(-need // C) - len(shared)
            if self._kv.free_slots() and self._kv.can_reserve(budget):
                return   # admissible as-is — nothing to displace
            slot, _ = min(victims,
                          key=lambda sr: (sr[1].priority, -sr[1].t_submit))
            self._preempt_slot(slot)

    # -------------------------------------------------- request lifecycle
    # terminal statuses beyond "done": every path below retires through
    # the SAME write-drop parking the scheduler already uses (the slot's
    # masked offset goes to lmax at the next dispatch, its stale pipelined
    # tokens fail the request-identity drain check) — no recompile, no
    # retrace, and the freed slot re-admits immediately.

    def _on_terminal(self, r, status, slot=None):
        """Request-scoped observability fanout, once per terminal
        transition: the timeline's terminal mark, the flight-recorder
        ``retire`` event, the lifecycle phase histograms and the SLO
        window — plus the anomaly auto-dump for ``timed_out`` /
        ``poisoned`` (retry exhaustion dumps from ``_retry``).  Pure host
        bookkeeping; the scheduling state machine is untouched."""
        tr = r._trace
        if tr is not None:
            if slot is not None:
                tr.mark(status, slot=slot)
            else:
                tr.mark(status)
        if self._fr is not None:
            self._fr.record("retire", step=self._step_idx, rid=r.rid,
                            slot=slot, status=status,
                            n_out=len(r.output_ids))
            if status in ("timed_out", "poisoned"):
                self._fr.auto_dump(status)
        if self._m is not None and tr is not None:
            self._m.observe_phases(tr.durations())
        if self._slo is not None:
            self._slo.observe(r)

    def _terminal_queued(self, r, status):
        """Retire a request that never reached a slot (still queued)."""
        r.status = status
        r.done = True
        r.t_done = time.perf_counter()
        self._finished.append(r)
        if self._m is not None:
            self._m.terminal(status)
        self._on_terminal(r, status)

    def _forget_slot(self, slot):
        """Drop every piece of per-slot scheduler state that outlives the
        slot's request: chunked-prefill progress, the device-resident
        first token, monolithic-admission membership and not-yet-drained
        first-token records.  Records already riding an inflight dispatch
        need no scrub — the drain's identity check discards them."""
        self._pf.pop(slot, None)
        self._dev_first.pop(slot, None)
        self._adm_pending.discard(slot)
        self._pending_firsts = [t for t in self._pending_firsts
                                if t[0] != slot]

    def _retire(self, slot, status):
        """Retire ``slot``'s request with a non-``done`` terminal status
        (timed_out / cancelled / poisoned), keeping whatever tokens it
        already emitted as its partial output."""
        r = self._kv.reqs[slot]
        r.status = status
        r.done = True
        r.t_done = time.perf_counter()
        self._kv.release(slot)
        self._forget_slot(slot)
        self._finished.append(r)
        if self._m is not None:
            self._m.terminal(status)
            self._m.slots_occupied.set(self._kv.occupied())
        self._on_terminal(r, status, slot=slot)

    def cancel(self, rid):
        """Host-side cancellation: retire ``rid`` wherever it is —
        queued, mid-prefill (``_pf``) or mid-decode-flight (stale
        pipelined tokens are discarded by the drain's identity check).
        Partial outputs stay on the request (status ``"cancelled"``).
        Returns True if the request was found live, False otherwise
        (already finished, shed, or unknown)."""
        for r in self._queue:
            if r.rid == rid:
                self._queue.remove(r)
                if self._fr is not None:
                    self._fr.record("cancel", step=self._step_idx, rid=rid)
                self._terminal_queued(r, "cancelled")
                if self._m is not None:
                    self._m.queue_depth.set(len(self._queue))
                return True
        for slot, r in enumerate(self._kv.reqs):
            if r is not None and r.rid == rid:
                if self._fr is not None:
                    self._fr.record("cancel", step=self._step_idx, rid=rid,
                                    slot=slot)
                self._retire(slot, "cancelled")
                return True
        return False

    def _expire_deadlines(self):
        """Retire every request whose ``deadline_ms`` has passed — queued
        requests never reach a slot; resident ones (mid-prefill or
        decoding) free their slot for re-admission this same step."""
        now = time.perf_counter()
        expired = [r for r in self._queue
                   if r._t_deadline is not None and now >= r._t_deadline]
        for r in expired:
            self._queue.remove(r)
            self._terminal_queued(r, "timed_out")
        if expired and self._m is not None:
            self._m.queue_depth.set(len(self._queue))
        for slot, r in enumerate(self._kv.reqs):
            if r is not None and r._t_deadline is not None \
                    and now >= r._t_deadline:
                self._retire(slot, "timed_out")

    # ------------------------------------------------- faults and retries
    def _inject_nan(self, slot):
        """Fault seam (FaultPlan poison): overwrite the slot's first
        cached key row (layer 0, position 0 — attended by every later
        query of the slot) with NaN, eagerly between compiled steps.
        Functional ``.at[].set`` touches only that row, so cohabiting
        slots' cache bytes are untouched — the quarantine's
        byte-identity guarantee rests on per-row attention isolation.
        Paged engines poison the slot's FIRST MAPPED BLOCK instead (the
        pool has no per-slot rows); the seam is test-only and the paged
        fault tests use distinct prompts, so the poisoned block is never
        a shared prefix block.

        int8 caches can't hold a NaN in the data leaf — the poison lands
        in the SCALE leaf instead (same row indices minus the trailing
        ``D`` axis): a NaN scale dequantizes the row to NaN, which
        reaches the logits exactly like a NaN float row."""
        k, v = self._kv.caches[0]

        def poison(leaf, *idx):
            if isinstance(leaf, tuple):
                return (leaf[0], leaf[1].at[idx].set(jnp.nan))
            return leaf.at[idx].set(jnp.nan)

        if self._paged:
            b = int(self._kv.block_tables[slot, 0])
            if b >= self._kv.num_blocks:
                return   # no rows mapped yet (unreachable: _apply_poison
                         # already defers slots with no chunk dispatched)
            self._kv.caches[0] = (poison(k, b, 0), v)
            return
        self._kv.caches[0] = (poison(k, slot, 0), v)

    def _apply_poison(self):
        """Inject every due NaN payload from the fault plan.  Injection
        waits until the slot has at least one cache row written (a
        mid-prefill slot at offset 0 would have its poison overwritten by
        its own first chunk)."""
        f = self._faults
        if f is None or not f.poison:
            return
        for slot, r in enumerate(self._kv.reqs):
            if r is None or not f.poison_due(r.rid, self._step_idx):
                continue
            st = self._pf.get(slot)
            if st is not None and st["off"] == 0:
                continue   # no rows written yet — defer to a later step
            self._inject_nan(slot)
            f.mark_poisoned(r.rid)
            if self._fr is not None:
                self._fr.record("poison", step=self._step_idx, rid=r.rid,
                                slot=slot)

    def _apply_host_corrupt(self):
        """Inject every due ``FaultPlan(host_tier_corrupt=...)`` payload:
        damage the host-tier entries along a token chain (or every entry)
        so the NEXT restore exercises the validation + suffix-prefill
        fallback path.  No-op without a host tier — the plan's damage
        lands on stored bytes only, never the device pool."""
        f = self._faults
        if (f is None or not f.host_tier_corrupt or not self._paged
                or self._kv.host_tier is None):
            return
        for tokens, mode in f.host_corrupts_due(self._step_idx):
            n = self._kv.corrupt_host(tokens, mode=mode)
            if self._fr is not None:
                self._fr.record("host_corrupt", step=self._step_idx,
                                mode=mode, entries=n)

    def _fault_point(self, kind, attempt):
        if self._faults is not None:
            self._faults.maybe_dispatch_error(kind, self._step_idx,
                                              attempt)

    def _retry(self, fn, what):
        """Bounded dispatch/drain retry: run ``fn(attempt)`` up to
        ``retry_attempts`` times against transient
        ``XlaRuntimeError``-class failures, backing off exponentially
        through the sanctioned ``_backoff_sleep``; exhaustion re-raises
        the last error.  ``fn`` must be side-effect-free until it
        returns (the engine's fault points raise BEFORE the real
        dispatch), so a retried attempt re-issues an identical program
        and the run's outputs stay byte-identical to an unfaulted one."""
        delay = self._retry_backoff
        for attempt in range(self._retry_attempts):
            try:
                return fn(attempt)
            except _RETRYABLE as e:
                if attempt + 1 >= self._retry_attempts:
                    # exhaustion: the engine is about to surface a device
                    # error to the caller — snapshot the path that led here
                    if self._fr is not None:
                        self._fr.record(
                            "retry", step=self._step_idx, what=what,
                            attempt=attempt + 1, error=type(e).__name__,
                            exhausted=True)
                        self._fr.auto_dump("retry_exhausted")
                    raise
                if self._m is not None:
                    self._m.dispatch_retries.inc()
                if self._fr is not None:
                    self._fr.record("retry", step=self._step_idx,
                                    what=what, attempt=attempt + 1,
                                    error=type(e).__name__)
                _LOG.warning(
                    "serving %s failed (%s: %s) — retrying "
                    "(attempt %d/%d) after %.3fs backoff",
                    what, type(e).__name__, e, attempt + 1,
                    self._retry_attempts - 1, delay)
                _backoff_sleep(delay)
                delay *= 2

    def _fetch(self, kind, *arrays):
        """``_host_fetch`` behind the bounded retry + fault seam: the
        drain-side twin of the dispatch retry (re-fetching the same
        device futures is idempotent)."""
        def go(attempt):
            self._fault_point(kind, attempt)
            return _host_fetch(*arrays)
        return self._retry(go, kind)

    # --------------------------------------------------- program dispatch
    # the four compiled entry points behind ONE seam: mesh=None dispatches
    # the module-level single-device jits (bitwise the pre-mesh engine);
    # a mesh dispatches the cached TP programs (serving/sharding.py —
    # statics baked in at construction).  Both take and return replicated
    # host-facing operands, so every caller is placement-oblivious.
    def _kv_event(self, kind, **info):
        """PagedKVCacheManager event hook: mirror allocator + host-tier
        activity (``block_alloc`` / ``block_free`` / ``demote`` /
        ``restore`` / ``host_evict`` / ``host_error``) into the flight
        recorder and keep the block-pool and host-tier gauges current.
        Host bookkeeping only — the hook never touches a device value."""
        if self._fr is not None:
            self._fr.record(kind, step=self._step_idx, **info)
        if self._m is not None:
            draft_used = self._kv.draft_blocks_used()
            self._m.set_kv_blocks(
                self._kv.blocks_used() - draft_used, draft_used,
                self._kv.free_count())
            host = getattr(self._kv, "host_tier", None)
            if host is not None:
                self._m.kv_host_blocks.set(host.n_blocks)
                self._m.kv_host_bytes.set(host.total_bytes)
                if kind == "demote":
                    self._m.tier_demotions.inc(info.get("n_blocks", 1))
                elif kind == "restore":
                    self._m.tier_restores.inc(info.get("n_blocks", 1))
                elif kind == "host_error":
                    self._m.host_tier_errors.inc()

    def _tables(self):
        """The block-table operand for one dispatch: the host mirror
        shipped as a fixed-shape ``[B, W]`` traced array (never a Python
        list — tpu-lint PTL010 polices the difference)."""
        return self._kv.device_tables()

    def _call_decode(self, cur, dev_len):
        if self._tp is not None:
            if self._paged:
                return self._tp.decode_steps(self._params, cur,
                                             self._kv.caches, dev_len,
                                             self._tables())
            return self._tp.decode_steps(self._params, cur,
                                         self._kv.caches, dev_len)
        return serving_decode_steps(
            self._params, self._cfg, cur, self._kv.caches, dev_len,
            n_steps=self._sync, chunk_size=self._chunk,
            block_tables=self._tables() if self._paged else None,
            program_key=self._pk)

    def _call_spec(self, cur, dev_len, active, k=None):
        """One speculative round at draft depth ``k`` (``None`` = the
        configured ceiling).  Returns the SAME 8-tuple for both draft
        sources — (emitted, j, cur', new_len, ok, caches, hist,
        hist_len) — so the two call sites stay source-oblivious: the
        draft-model path stashes its dense draft caches as engine state
        and passes the (unused) history straight through."""
        k = self._spec_k if k is None else k
        pk = (self._pk if k == self._spec_k
              else self._pk.replace(spec_depth=k))
        if self._dspec:
            if self._tp is not None:
                tp = self._tp_spec[k]
                if self._paged:
                    out = tp.spec_draft_step(
                        self._params, self._dparams, cur, self._kv.caches,
                        dev_len, active, self._tables(),
                        self._kv.device_draft_tables())
                else:
                    out = tp.spec_draft_step(
                        self._params, self._dparams, cur, self._kv.caches,
                        self._dcaches, dev_len, active)
            else:
                out = serving_spec_draft_step(
                    self._params, self._dparams, self._cfg, self._dcfg,
                    cur, self._kv.caches,
                    None if self._paged else self._dcaches, dev_len,
                    active, spec_k=k, chunk_size=self._chunk,
                    block_tables=self._tables() if self._paged else None,
                    draft_tables=(self._kv.device_draft_tables()
                                  if self._paged else None),
                    program_key=pk)
            emitted, j, cur2, new_len, ok, caches, dc = out
            if not self._paged:
                self._dcaches = list(dc)
            return (emitted, j, cur2, new_len, ok, caches, self._hist,
                    self._hist_len)
        if self._tp is not None:
            tp = self._tp_spec[k]
            if self._paged:
                return tp.spec_step(self._params, cur,
                                    self._kv.caches, dev_len,
                                    self._hist, self._hist_len,
                                    active, self._tables())
            return tp.spec_step(self._params, cur, self._kv.caches,
                                dev_len, self._hist, self._hist_len,
                                active)
        return serving_spec_step(
            self._params, self._cfg, cur, self._kv.caches, dev_len,
            self._hist, self._hist_len, active, spec_k=k,
            chunk_size=self._chunk,
            block_tables=self._tables() if self._paged else None,
            program_key=pk)

    def _call_prefill_slot(self, tokens, prompt_len, slot):
        if self._tp is not None:
            return self._tp.prefill_slot(self._params, tokens, prompt_len,
                                         self._kv.caches, slot,
                                         self._hist, self._hist_len)
        return serving_prefill_slot(
            self._params, self._cfg, tokens, prompt_len, self._kv.caches,
            slot, hist=self._hist, hist_len=self._hist_len,
            with_hist=self._mode == "spec", chunk_size=self._chunk,
            program_key=self._pk)

    def _call_prefill_chunk(self, tokens, offset, prompt_len, slot):
        if self._tp is not None:
            if self._paged:
                return self._tp.prefill_chunk(self._params, tokens, offset,
                                              prompt_len, self._kv.caches,
                                              slot, self._hist,
                                              self._hist_len,
                                              self._tables())
            return self._tp.prefill_chunk(self._params, tokens, offset,
                                          prompt_len, self._kv.caches,
                                          slot, self._hist, self._hist_len)
        return serving_prefill_chunk(
            self._params, self._cfg, tokens, offset, prompt_len,
            self._kv.caches, slot, hist=self._hist,
            hist_len=self._hist_len, with_hist=self._mode == "spec",
            chunk_size=self._chunk,
            block_tables=self._tables() if self._paged else None,
            program_key=self._pk)

    def _call_draft_prefill_chunk(self, chunk, off, plen, slot):
        """One DRAFT-model prefill chunk: fills the draft tenant's KV for
        the prompt rows the draft decode scan will attend.  Paged engines
        run it over the shared pool's first ``d`` layer arrays through
        the draft block tables (the target's pool list is re-assembled
        around the returned layers — serving_prefill_chunk donates its
        cache operand); dense engines write the separate ``_dcaches``.
        The chunk's first-token/finite outputs are dropped: draft KV is
        advisory (a bad draft row costs accept rate, never output
        bytes)."""
        d = len(self._dparams["layers"])
        if self._tp is not None:
            if self._paged:
                _, _, new_dc, _, _ = self._tp.draft_prefill_chunk(
                    self._dparams, jnp.asarray(chunk),
                    jnp.asarray(off, jnp.int32), plen,
                    self._kv.caches[:d], jnp.asarray(slot, jnp.int32),
                    self._kv.device_draft_tables())
            else:
                _, _, new_dc, _, _ = self._tp.draft_prefill_chunk(
                    self._dparams, jnp.asarray(chunk),
                    jnp.asarray(off, jnp.int32), plen,
                    self._dcaches, jnp.asarray(slot, jnp.int32))
        else:
            _, _, new_dc, _, _ = serving_prefill_chunk(
                self._dparams, self._dcfg, jnp.asarray(chunk),
                jnp.asarray(off, jnp.int32), plen,
                self._kv.caches[:d] if self._paged else self._dcaches,
                jnp.asarray(slot, jnp.int32), with_hist=False,
                chunk_size=self._chunk,
                block_tables=(self._kv.device_draft_tables()
                              if self._paged else None),
                program_key=self._pk)
        if self._paged:
            self._kv.caches = list(new_dc) + self._kv.caches[d:]
        else:
            self._dcaches = list(new_dc)

    # ------------------------------------------------ adaptive draft depth
    def _reset_spec_slot(self, slot):
        """Fresh request in ``slot``: restart its accept-rate window and
        return its desired rung to the ceiling (a new prompt's
        draftability is unknown — start at full depth, degrade on
        evidence)."""
        if self._awin is not None:
            self._awin[slot].reset()
            self._k_want[slot] = len(self._k_rungs) - 1

    def _adapt_k(self, rounds, k):
        """Feed one drained verify round into the adaptive-k policy:
        per-slot windows absorb (k drafted, j accepted), hysteresis
        moves each slot's desired rung (>= 80% of the window accepted:
        one rung deeper; <= 40%: one rung shallower).  Host arithmetic
        only — the chosen batch depth is read at the NEXT dispatch."""
        if self._awin is None:
            return
        for slot, j in rounds:
            w = self._awin[slot]
            w.push(k, j)
            r = w.rate()
            if r is None or len(w) < w.window:
                continue
            if r >= 0.8 and self._k_want[slot] < len(self._k_rungs) - 1:
                self._k_want[slot] += 1
            elif r <= 0.4 and self._k_want[slot] > 0:
                self._k_want[slot] -= 1

    def _next_k(self, live):
        """The batch depth for the NEXT spec dispatch: the most
        conservative live slot's desired rung (one program serves the
        whole batch — a deep k wastes dead verify lanes on every hard
        slot), approached ONE rung per round so a retiring pessimist
        never yanks the batch straight to the ceiling."""
        if self._awin is None or not live:
            return self._k_cur
        want = min(self._k_want[i] for i in live)
        cur = self._k_rungs.index(self._k_cur)
        nxt = cur + (1 if want > cur else -1 if want < cur else 0)
        self._k_cur = self._k_rungs[nxt]
        if self._m is not None:
            self._m.spec_draft_k.set(self._k_cur)
        return self._k_cur

    def _admit(self):
        free = self._kv.free_slots()
        if not free or not self._queue:
            return
        if self._policy == "gang" and len(free) < self._B:
            return  # run-to-completion: wait for the whole batch to drain
        if self._pchunk is not None:
            self._admit_chunked(free)
            return
        self._adm_wave = True
        m = self._m
        pending = []
        while free and self._queue:
            r = self._queue.popleft()
            slot = free.pop(0)
            self._kv.assign(slot, r)
            self._reset_spec_slot(slot)
            p = r.prompt_ids.size
            if r._trace is not None:
                r._trace.mark("prefilling", slot=slot)
            if self._fr is not None:
                self._fr.record("admit", step=self._step_idx, rid=r.rid,
                                slot=slot, bucket=r._bucket)
            if m is not None:
                m.admitted.inc()
                m.prefill(r._bucket)
                m.queue_wait.observe(time.perf_counter() - r.t_submit)
            tokens = np.zeros((1, r._bucket), np.int32)
            tokens[0, :p] = r.prompt_ids
            with m.span_prefill if m is not None else _NULL_CTX:
                first, okf, self._kv.caches, hist, hist_len = \
                    self._call_prefill_slot(
                        jnp.asarray(tokens),
                        jnp.asarray(np.array([p], np.int32)),
                        jnp.asarray(slot, jnp.int32))
            if self._mode == "spec":
                self._hist, self._hist_len = hist, hist_len
            self._kv.lengths[slot] = p
            self._adm_pending.add(slot)
            pending.append((slot, first, okf))
        # every prefill in the wave is dispatched (async) above; block ONCE
        # here for all their first tokens (+ finite flags) — one host sync
        # per _admit, not one per admitted request
        vals = _host_fetch(*(x for _, f, o in pending for x in (f, o)))
        for n, (slot, _, _) in enumerate(pending):
            fv, ov = vals[2 * n], vals[2 * n + 1]
            if not bool(ov[0]):
                self._retire(slot, "poisoned")
                continue
            first = int(fv[0])
            self._cur[slot] = first
            self._emit(slot, [first])
        if m is not None:
            m.queue_depth.set(len(self._queue))
            m.slots_occupied.set(self._kv.occupied())

    def _admit_chunked(self, free):
        """Chunked admission: assign freed slots and queue each prompt for
        incremental chunk dispatch (``_spend_prefill``).  Nothing here
        touches the device, so admission itself never stalls the loop —
        the prompt work is spread over the following scheduler steps under
        ``prefill_budget``.

        Paged engines budget TOKENS, not slots: admission reserves the
        request's worst-case block count (prompt + max_new + headroom,
        clamped to max_len, minus any radix-matched prefix) and DEFERS the
        queue head when the pool can't cover it — FIFO, so a smaller later
        request never starves the head.  A prefix hit adopts the matched
        blocks and starts prefill at the suffix offset; when the prefill
        chunk is wider than the kv block the match is aligned DOWN to a
        chunk boundary so the suffix decomposes into the exact same
        compiled chunks a miss would run (byte-identity across hit/miss)."""
        m = self._m
        P = self._pchunk
        while free and self._queue:
            # priority-aware head: the highest-priority waiter admits
            # first.  max() is stable, so all-default traffic keeps the
            # exact FIFO order (and bytes) of the pre-priority engine;
            # the paged defer below still BREAKS, so held-back capacity
            # protects the head's class instead of leaking to smaller
            # later requests.  ``tok`` is the (re-)admission sequence —
            # for a preemption resume it includes every emitted token,
            # so the radix match re-adopts the parked chain and prefill
            # runs only the suffix.
            r = max(self._queue, key=lambda q: q.priority)
            tok = self._admission_ids(r)
            off0, shared, budget, need, host_tok = 0, [], 0, 0, 0
            doff0, dshared, dbudget = 0, [], 0
            if self._paged:
                C = self._kv.block
                p = int(tok.size)
                rem = max(1, r.max_new_tokens - len(r.output_ids))
                need = min(self._lmax, p + rem + self._headroom())
                if self._prefill_only:
                    # no decode ever writes past the prompt here: the
                    # chain budget is exactly the prompt's own blocks,
                    # which is the capacity win admission throughput
                    # rides on a dedicated prefill worker
                    need = p
                off0, shared = self._kv.match_prefix(tok)
                # restore-on-adopt: when the device radix breaks before
                # the match cap, rehydrate the host tier's continuation
                # (a device_put of stored rows, cheaper than suffix
                # prefill past ~1 block) and re-run the ordinary radix
                # match — restored blocks park exactly like a released
                # chain, so admission below is tier-oblivious
                host = self._kv.host_tier
                off_dev = off0
                if (host is not None and host.n_blocks
                        and len(shared) < (p - 1) // C):
                    t0 = time.perf_counter()
                    got = self._kv.restore_from_host(
                        tok, rid=r.rid, min_blocks=self._host_min_blocks)
                    if got:
                        self._restore_s.append(time.perf_counter() - t0)
                        if m is not None:
                            m.tier_restore_seconds.observe(
                                self._restore_s[-1])
                        off0, shared = self._kv.match_prefix(tok)
                if P > C:
                    off0 = (off0 // P) * P
                    shared = shared[:off0 // C]
                host_tok = max(0, off0 - min(off_dev, off0))
                budget = -(-need // C) - len(shared)
                if self._dspec:
                    # draft tenancy: the draft chain needs the same block
                    # count (shared pool, own tables/namespace), reserved
                    # up front so a mid-stream OOM can't strand a slot
                    # with target KV but no draft KV
                    doff0, dshared = self._kv.match_draft_prefix(tok)
                    if P > C:
                        doff0 = (doff0 // P) * P
                        dshared = dshared[:doff0 // C]
                    dbudget = -(-need // C) - len(dshared)
                if not self._kv.can_reserve(budget + dbudget):
                    if self._fr is not None:
                        self._fr.record("admit_defer", step=self._step_idx,
                                        rid=r.rid,
                                        need_blocks=budget + dbudget)
                    break
            self._queue.remove(r)
            slot = free.pop(0)
            self._kv.assign(slot, r)
            self._reset_spec_slot(slot)
            p = int(tok.size)
            if self._paged:
                self._kv.adopt_prefix(slot, shared)
                if self._dspec:
                    self._kv.adopt_draft_prefix(slot, dshared)
                self._kv.reserve(slot, budget + dbudget)
                self._need_rows[slot] = need
                r._adm_ids = tok
                self._n_prompt_tokens += p
                self._n_reuse_tokens += off0
                self._n_host_reuse_tokens += host_tok
            if r._trace is not None:
                r._trace.mark("prefilling", slot=slot)
            if self._fr is not None:
                self._fr.record("admit", step=self._step_idx, rid=r.rid,
                                slot=slot, bucket=r._bucket)
            if r.preempts:
                # preemption resume: the adopted chain covers [0, off0) —
                # the suffix is the whole recompute cost
                self._n_resume_suffix += p - off0
                self._n_resume_total += p
                if self._fr is not None:
                    self._fr.record("resume", step=self._step_idx,
                                    rid=r.rid, slot=slot,
                                    suffix_tokens=p - off0, total_tokens=p)
                if m is not None:
                    m.preempt_resume_tokens.inc(p - off0)
            padded = np.zeros((-(-p // P) * P,), np.int32)
            padded[:p] = tok
            if off0:
                # prefix hit: the adopted blocks already hold rows
                # [0, off0) — prefill starts at the suffix offset
                if self._fr is not None:
                    self._fr.record("prefix_hit", step=self._step_idx,
                                    rid=r.rid, slot=slot, tokens=off0,
                                    host_tokens=host_tok)
                if m is not None:
                    m.prefix_reuse_tokens.inc(off0)
                    if off0 > host_tok:
                        m.prefix_hit("device")
                    if host_tok:
                        m.prefix_hit("host")
                if self._mode == "spec":
                    # the skipped chunks would have written hist rows
                    # [0, off0); rebuild the slot's whole prompt row
                    # eagerly.  Draft quality only — emission is always
                    # the verify forward's own greedy picks (lossless),
                    # so output bytes never depend on hist contents
                    row = np.zeros((self._lmax,), np.int32)
                    w = min(padded.size, self._lmax)
                    row[:w] = padded[:w]
                    self._hist = self._hist.at[slot].set(jnp.asarray(row))
            # device-ready prompt length, built here (outside the chunk
            # dispatch loop) so _spend_prefill stays sync-free
            self._pf[slot] = {"req": r, "tok": padded, "p": p, "off": off0,
                              "doff": doff0, "first": None, "okf": None,
                              "plen": jnp.asarray(np.array([p], np.int32))}
            if m is not None:
                m.admitted.inc()
                m.prefill(r._bucket)
                if self._paged:
                    m.prompt_tokens.inc(p)
                m.queue_wait.observe(time.perf_counter() - r.t_submit)
        if m is not None:
            m.queue_depth.set(len(self._queue))
            m.slots_occupied.set(self._kv.occupied())
            m.live_tokens.set(self._kv.live_tokens())

    # ---------------------------------------------- disaggregated adoption
    # the decode-worker half of a prefill/decode split (serving/disagg.py):
    # a request whose prefill ran on ANOTHER engine enters here with its
    # first token and its exported block chain, bypassing _admit/_pf
    # entirely.  From the next decode dispatch on, the slot is
    # indistinguishable from a locally prefilled one — same cur / length /
    # block-table VALUES, no new shapes — which is both the byte-identity
    # and the zero-retrace argument for migration.

    def can_adopt(self, request):
        """Whether ``adopt_prefilled`` would succeed right now: a free
        slot plus pool capacity for the imported chain AND the decode
        growth budget.  The coordinator gates on this BEFORE paying for
        a transfer — a deferred migration costs nothing."""
        if not self._paged or self._policy != "continuous" \
                or self._prefill_only:
            return False
        if not self._kv.free_slots():
            return False
        p = int(request.prompt_ids.size)
        rem = max(1, request.max_new_tokens - len(request.output_ids))
        need = min(self._lmax, p + rem + self._headroom())
        return self._kv.can_reserve(
            -(-need // self._kv.block) * (2 if self._dspec else 1))

    def adoption_viable(self, request):
        """The static half of ``can_adopt``: could this request EVER fit
        this engine (prompt bucket exists, worst-case rows within
        ``max_len``)?  The coordinator sheds statically-impossible
        requests at submit time — a ``can_adopt`` False only ever means
        *defer and retry*, never *abort*."""
        p = int(request.prompt_ids.size)
        if bisect.bisect_left(self._buckets, p) == len(self._buckets):
            return False
        return p + request.max_new_tokens + self._headroom() <= self._lmax

    def adopt_prefilled(self, request, first, leaves):
        """Admit ``request`` with its prefill already done elsewhere:
        import the transfer ``leaves`` into fresh pool blocks, splice
        them under a free slot's table row, and seed the decode carry
        (cur = ``first``, length = prompt) exactly where a local prefill
        would have left it.  The request must already hold its first
        token — the coordinator emits it at migration start, so TTFT
        rides the handoff, never the adoption.  Raises on capacity
        (callers gate on ``can_adopt``); a failed import rolls its
        blocks back (kv_cache.import_chain).  Returns the slot."""
        if not self._paged or self._policy != "continuous":
            raise ValueError(
                "adopt_prefilled requires a paged continuous engine "
                "(the block pool IS the migration transfer unit)")
        if self._prefill_only:
            raise ValueError("a prefill-only engine cannot adopt decode "
                             "work")
        if not request.output_ids:
            raise ValueError("adopt_prefilled: the request must already "
                             "hold its migrated first token")
        free = self._kv.free_slots()
        if not free:
            raise EngineOverloaded("no free slot to adopt into")
        tok = request.prompt_ids
        p = int(tok.size)
        i = bisect.bisect_left(self._buckets, p)
        if i == len(self._buckets):
            raise ValueError(
                f"prompt length {p} exceeds the largest prompt bucket "
                f"{self._buckets[-1]}")
        request._bucket = self._buckets[i]
        rem = max(1, request.max_new_tokens - len(request.output_ids))
        need = min(self._lmax, p + rem + self._headroom())
        # rid bookkeeping mirrors submit(): the coordinator's rid is
        # kept, so flight-recorder events correlate across both workers
        if request.rid is None:
            request.rid = self._next_rid
            self._next_rid += 1
        else:
            if request.rid in self._rids:
                raise ValueError(
                    f"rid {request.rid!r} is already in use by another "
                    "request on this engine")
            if isinstance(request.rid, int):
                self._next_rid = max(self._next_rid, request.rid + 1)
        self._rids.add(request.rid)
        if request.t_submit is None:
            request.t_submit = time.perf_counter()
        if request.deadline_ms is not None \
                and request._t_deadline is None:
            request._t_deadline = request.t_submit \
                + request.deadline_ms / 1e3
        slot = free[0]
        blocks = self._kv.import_chain(leaves)  # all-or-nothing
        self._kv.assign(slot, request)
        self._reset_spec_slot(slot)
        self._kv.splice_chain(slot, blocks)
        resv = -(-need // self._kv.block) - len(blocks)
        doff0, dshared = 0, []
        if self._dspec:
            # the transfer carries only TARGET KV (the draft's is cheap
            # to rebuild and model-specific); the draft chain starts from
            # whatever its own radix namespace already holds
            C = self._kv.block
            doff0, dshared = self._kv.match_draft_prefix(tok)
            P = self._pchunk
            if P > C:
                doff0 = (doff0 // P) * P
                dshared = dshared[:doff0 // C]
            resv += -(-need // C) - len(dshared)
            self._kv.adopt_draft_prefix(slot, dshared)
        self._kv.reserve(slot, resv)
        self._need_rows[slot] = need
        self._kv.lengths[slot] = p
        request._adm_ids = tok
        self._n_prompt_tokens += p
        self._cur[slot] = int(first)
        self._adm_pending.add(slot)
        if self._mode == "spec":
            # rebuild the draft-history row the final prefill chunk
            # would have written: prompt at [0, p), first at p, frontier
            # p + 1.  Draft quality only — emission is always the verify
            # forward's own picks, so output bytes never depend on it
            row = np.zeros((self._lmax,), np.int32)
            w = min(p, self._lmax)
            row[:w] = tok[:w]
            if p < self._lmax:
                row[p] = int(first)
            self._hist = self._hist.at[slot].set(jnp.asarray(row))
            self._hist_len = self._hist_len.at[slot].set(p + 1)
        if self._dspec:
            # rebuild the draft model's prompt KV locally, off the step
            # path (adoption is already a slow-path handoff): chunked
            # draft prefill over the suffix the draft radix didn't cover
            P = self._pchunk
            padded = np.zeros((-(-p // P) * P,), np.int32)
            padded[:p] = tok
            plen = jnp.asarray(np.array([p], np.int32))
            off = doff0
            while off < p:
                self._kv.ensure_draft_rows(slot, min(off + P, p))
                self._call_draft_prefill_chunk(
                    padded[off:off + P][None, :], off, plen, slot)
                off += P
            self._kv.register_draft_prefix(slot, tok)
        # the imported chain is as good as a local prefill's (its finite
        # check passed before export): publish it so later identical
        # prompts on THIS worker reuse it — prefix reuse survives
        # migration
        self._kv.register_prefix(slot, tok)
        if self._fr is not None:
            tr = RequestTrace(request.rid)
            request._trace = tr
            with self._trace_lock:
                self._traces[request.rid] = tr
                while len(self._traces) > self._trace_cap:
                    self._traces.popitem(last=False)
            tr.mark("decoding", slot=slot)
        if self._m is not None:
            self._m.admitted.inc()
            self._m.prompt_tokens.inc(p)
            self._m.prefix_hit("fleet")
            self._m.slots_occupied.set(self._kv.occupied())
            self._m.live_tokens.set(self._kv.live_tokens())
        return slot

    def _spend_prefill(self):
        """Dispatch up to ``prefill_budget`` prompt chunks across the
        slots mid-prefill, admission order first (the earliest admission
        reaches its first token soonest).  Every chunk dispatch is async
        and feeds off device-resident state (the carried caches / hist /
        write offset) — the loop never syncs, the tpu-lint PTL004 rule
        polices that.  A slot whose FINAL chunk went out leaves the
        prefilling state: it joins the very next decode dispatch with its
        device-resident first token, and the host copy is emitted at the
        next drain.  Returns the number of chunks dispatched."""
        if not self._pf:
            return 0
        m = self._m
        P = self._pchunk
        budget = self._pbudget
        spent = 0
        for slot in list(self._pf):
            if not budget:
                break
            st = self._pf[slot]
            while budget:
                if st["off"] < st["p"]:
                    k = st["off"] // P
                    if st["req"]._trace is not None:
                        st["req"]._trace.mark("prefilling", chunk=k,
                                              slot=slot)
                    if self._fr is not None:
                        self._fr.record("prefill_chunk",
                                        step=self._step_idx,
                                        rid=st["req"].rid, slot=slot,
                                        chunk=k)
                    if self._paged:
                        # map the chunk's REAL rows before its writes
                        # dispatch (pad columns past the prompt drop on
                        # the sentinel); draws down the reservation made
                        # at admission
                        self._kv.ensure_rows(
                            slot, min(st["off"] + P, st["p"]))
                    chunk = st["tok"][st["off"]:st["off"] + P][None, :]
                    with m.span_prefill if m is not None else _NULL_CTX:
                        first, okf, self._kv.caches, hist, hist_len = \
                            self._call_prefill_chunk(
                                jnp.asarray(chunk),
                                jnp.asarray(st["off"], jnp.int32),
                                st["plen"],
                                jnp.asarray(slot, jnp.int32))
                    if self._mode == "spec":
                        self._hist, self._hist_len = hist, hist_len
                    st["off"] += P
                    if m is not None:
                        m.prefill_chunks.inc()
                    if st["off"] >= st["p"]:
                        # only the FINAL chunk's finite flag is meaningful
                        # (its query attends the whole prefix) — it rides
                        # with the first token and is checked at emission
                        st["first"], st["okf"] = first, okf
                if self._dspec and st["doff"] < st["p"]:
                    # the draft model's prompt KV rides the same budget
                    # unit: one target chunk + one draft chunk per spend
                    # (the draft forward is a fraction of the target's
                    # cost).  Its cursor is independent — a target-side
                    # radix hit skips chunks the draft may still need
                    if self._paged:
                        self._kv.ensure_draft_rows(
                            slot, min(st["doff"] + P, st["p"]))
                    dchunk = st["tok"][st["doff"]:st["doff"] + P][None, :]
                    with m.span_prefill if m is not None else _NULL_CTX:
                        self._call_draft_prefill_chunk(
                            dchunk, st["doff"], st["plen"], slot)
                    st["doff"] += P
                budget -= 1
                spent += 1
                if st["off"] >= st["p"] and (
                        not self._dspec or st["doff"] >= st["p"]):
                    del self._pf[slot]
                    self._kv.lengths[slot] = st["p"]
                    self._dev_first[slot] = st["first"]
                    self._pending_firsts.append(
                        (slot, st["req"], st["first"], st["okf"]))
                    break
        if m is not None:
            m.prefill_backlog.set(sum(
                -(-max(0, st["p"] - st["off"]) // P)
                for st in self._pf.values()))
        return spent

    def _flush_firsts(self):
        """Synchronous-mode first-token drain: block ONCE on the wave of
        pending final chunks and emit (``pipeline=True`` instead rides
        them on the next inflight record, fetched with its tokens)."""
        if not self._pending_firsts:
            return 0
        pend, self._pending_firsts = self._pending_firsts, []
        vals = self._fetch(
            "drain", *(x for _, _, f, o in pend for x in (f, o)))
        emitted = 0
        for n, (slot, r, _, _) in enumerate(pend):
            fv, ov = vals[2 * n], vals[2 * n + 1]
            self._cur[slot] = int(fv[0])
            self._dev_first.pop(slot, None)
            if self._kv.reqs[slot] is not r:
                continue
            if not bool(ov[0]):
                self._retire(slot, "poisoned")
                continue
            if self._paged:
                # publish the prefix only now that the finite check passed
                # (registering at dispatch could publish poisoned blocks a
                # later radix hit would silently adopt); before _emit,
                # which may release the slot.  The ADMISSION ids, not the
                # prompt — a preemption resume's chain also covers the
                # tokens it re-prefilled
                self._kv.register_prefix(slot, r._adm_ids)
                if self._dspec:
                    self._kv.register_draft_prefix(slot, r._adm_ids)
            if self._on_prefilled is not None:
                # disagg handoff: the chain is registered and still
                # mapped — the coordinator exports it here; _emit
                # (max_new=1) then retires the slot on the normal path
                self._on_prefilled(r, slot, int(fv[0]))
            emitted += self._emit(slot, [int(fv[0])])
        return emitted

    def _emit(self, slot, toks):
        """Append emitted tokens to the slot's request, truncating at EOS /
        max_new_tokens; retires the slot when the request completes.
        Returns the number of tokens actually consumed."""
        r = self._kv.reqs[slot]
        m = self._m
        took = 0
        for t in toks:
            if r.done:
                break
            r.output_ids.append(int(t))
            took += 1
            if r.t_first is None:
                r.t_first = time.perf_counter()
                if m is not None:
                    m.ttft.observe(r.t_first - r.t_submit)
                if r._trace is not None:
                    r._trace.mark("decoding", slot=slot)
            if len(r.output_ids) >= r.max_new_tokens or (
                    r.eos_token_id is not None
                    and int(t) == int(r.eos_token_id)):
                r.done = True
        if took:
            if m is not None:
                m.emitted.inc(took)
            if self._detok is not None:
                r.text = self._detok(list(r.output_ids))
            if r.stream_cb is not None:
                try:
                    if self._faults is not None:
                        self._faults.maybe_crash_stream_cb(self._step_idx)
                    r.stream_cb(r, r.output_ids[-took:])
                except Exception as e:
                    # a crashing user callback must not kill the scheduler
                    # loop mid-batch (every other live slot would lose its
                    # in-flight block): count the drop by exception type,
                    # log once per request, and keep decoding
                    if m is not None:
                        m.stream_cb_error(type(e).__name__)
                    if not r._cb_err_logged:
                        r._cb_err_logged = True
                        _LOG.warning(
                            "stream_cb for request %r raised %s: %s — "
                            "further errors from this request are "
                            "counted but not logged", r.rid,
                            type(e).__name__, e)
        if r.done:
            r.status = "done"
            r.t_done = time.perf_counter()
            self._kv.release(slot)
            self._finished.append(r)
            if m is not None:
                m.retired.inc()
                m.e2e.observe(r.t_done - r.t_submit)
                m.tpot.observe(r.tpot)
                m.slots_occupied.set(self._kv.occupied())
            self._on_terminal(r, "done", slot=slot)
        return took

    # ------------------------------------------------------------ step / run
    def step(self):
        """One scheduler iteration: retire/admit, then one compiled decode
        dispatch over every live slot.  Returns tokens emitted."""
        self._last_step_unix = time.time()
        m = self._m
        if m is None:
            return self._step_impl()
        m.steps.inc()
        m.last_step_time.set(self._last_step_unix)
        with m.span_step:
            return self._step_impl()

    def _step_impl(self):
        self._step_idx += 1
        if self._faults is not None:
            stalled = self._faults.maybe_slow_step(self._step_idx)
            if stalled and self._fr is not None:
                self._fr.record("stall", step=self._step_idx,
                                seconds=stalled, injected=True)
        self._expire_deadlines()
        self._apply_poison()
        self._apply_host_corrupt()
        self._maybe_preempt()
        self._adm_wave = False
        self._admit()
        spent = self._spend_prefill()
        # decode-interference flag for this iteration: a monolithic prefill
        # wave ran, chunks were spent, or a prefill is still in progress
        adm_active = self._adm_wave or spent > 0 or bool(self._pf)
        if not self._pipeline:
            self._adm_pending.clear()
            out = self._step_sync(adm_active)
        else:
            # the double buffer: stash the record of the PREVIOUS
            # iteration's dispatch, issue the next dispatch, and only then
            # drain the stash — step N+1 is outstanding on the device while
            # step N's tokens are synced and its emit/retire bookkeeping
            # runs.  When _dispatch has nothing to issue (e.g. every slot
            # retired at the last drain) the stashed record is still
            # drained, so run() terminates.
            prev, self._inflight = self._inflight, None
            self._dispatch(adm_active)
            out = self._drain(prev)
        if self._paged:
            # materialize staged demotions BETWEEN steps: the eviction-time
            # gathers have long since finished behind the drained dispatch,
            # so this copies host<-device buffers without stalling the loop
            self._kv.pump_host_tier()
        return out

    def _observe_interference(self, adm_active, per_slot_tokens):
        """Feed ``serving_tpot_during_admission_seconds``: the per-token
        interval between this decode drain and the previous one, observed
        only while admission work (monolithic wave or chunked backlog) was
        in flight — the series the chunked-prefill A/B reads its
        TPOT-p95-during-admission from."""
        now = time.perf_counter()
        if self._m is not None:
            self._m.live_tokens.set(self._kv.live_tokens())
            if adm_active and self._t_lastdrain is not None:
                self._m.tpot_admission.observe(
                    (now - self._t_lastdrain) / max(1.0, per_slot_tokens))
        self._t_lastdrain = now

    def _ensure_decode_rows(self, live):
        """Paged: grow every live slot's block chain to cover the rows
        this decode dispatch may write — the host length mirror plus
        headroom (the mirror lags the device by at most one inflight
        dispatch, which headroom doubles to cover), capped by the token
        budget reserved at admission.  Must run BEFORE the dispatch reads
        the table operand; a no-op once the chain reaches the cap."""
        if not self._paged:
            return
        for i in live:
            upto = min(int(self._need_rows[i]),
                       int(self._kv.lengths[i]) + self._headroom())
            self._kv.ensure_rows(i, upto)
            if self._dspec:
                # the draft chain writes the same rows this round (its
                # append rides the identical dev_lengths), so it grows in
                # lockstep from the admission-time draft reservation
                self._kv.ensure_draft_rows(i, upto)

    # ------------------------------------------------- synchronous baseline
    def _step_sync(self, adm_active=False):
        """``pipeline=False``: dispatch one step and block on its tokens in
        the same iteration — the A/B baseline the pipelined loop is
        byte-identical to."""
        m = self._m
        emitted = self._flush_firsts()
        live = [i for i in range(self._B) if self._decodable(i)]
        if not live:
            return emitted
        if self._prefill_only:
            raise RuntimeError(
                "prefill-only engine reached a decode dispatch — a "
                "resident request survived its first-token flush")
        self._ensure_decode_rows(live)
        active = np.array([self._decodable(i) for i in range(self._B)])
        dev_len = self._kv.device_lengths(active)
        if self._fr is not None:
            self._fr.record("dispatch", step=self._step_idx,
                            mode=self._mode, n_live=len(live),
                            kv_quant=self._kvq,
                            attn_impl=self._attn_label,
                            prefill_impl=self._prefill_label,
                            weight_dtype=self._wq_label)
        if self._mode == "greedy":
            def go(attempt):
                self._fault_point("dispatch", attempt)
                return self._call_decode(jnp.asarray(self._cur), dev_len)
            with m.span_decode if m is not None else _NULL_CTX:
                toks, okd, self._kv.caches = self._retry(
                    go, "decode dispatch")
                toks, okd = self._fetch("drain", toks, okd)
            if self._fr is not None:
                self._fr.record("drain", step=self._step_idx,
                                mode="greedy", n_live=len(live))
            self._observe_interference(adm_active, self._sync)
            for i in live:
                if not bool(okd[i]):
                    self._retire(i, "poisoned")
                    continue
                emitted += self._emit(i, toks[i].tolist())
                self._kv.lengths[i] += self._sync
                self._cur[i] = toks[i, -1]
        else:
            k = self._next_k(live)
            if self._fr is not None:
                self._fr.record("draft", step=self._step_idx,
                                source=self._spec.source, k=k,
                                n_live=len(live))

            def go(attempt):
                self._fault_point("dispatch", attempt)
                return self._call_spec(jnp.asarray(self._cur), dev_len,
                                       jnp.asarray(active), k)
            with m.span_spec if m is not None else _NULL_CTX:
                blk, j, cur, _, oks, self._kv.caches, self._hist, \
                    self._hist_len = self._retry(go, "spec dispatch")
                blk, j, cur, oks = self._fetch("drain", blk, j, cur, oks)
            if self._fr is not None:
                self._fr.record("drain", step=self._step_idx, mode="spec",
                                n_live=len(live))
            accepted = 0
            rounds = []
            for i in live:
                if not bool(oks[i]):
                    self._retire(i, "poisoned")
                    continue
                emitted += self._emit(i, blk[i, :int(j[i]) + 1].tolist())
                self._kv.lengths[i] += int(j[i]) + 1
                self._cur[i] = cur[i]
                accepted += int(j[i])
                rounds.append((i, int(j[i])))
            if self._fr is not None:
                self._fr.record("verify", step=self._step_idx, k=k,
                                drafted=k * len(rounds), accepted=accepted)
                self._fr.record("rewind", step=self._step_idx,
                                tokens=k * len(rounds) - accepted)
            self._adapt_k(rounds, k)
            self._observe_interference(
                adm_active, 1.0 + accepted / len(live))
            if m is not None:
                # per verify round each live slot drafts k and accepts
                # j of them (the +1 bonus token is the verify forward's own
                # pick, not a draft)
                m.spec_round(k * len(live), accepted)
        return emitted

    # --------------------------------------------------- pipelined dispatch
    def _dispatch(self, adm_active=False):
        """Dispatch the next decode step WITHOUT waiting for the previous
        one (still undrained — ``_step_impl`` holds its record).  The
        step's inputs are all device-resident: the carried ``cur`` tokens /
        lengths of the previous dispatch (still futures — the device
        executes in program order) plus the caches; slots admitted since
        the last dispatch mix their host-known first token and prompt
        length into the carry.  A slot whose FINAL prefill chunk was just
        dispatched joins with its DEVICE-resident first token
        (``_dev_first`` — still a future) and host-known prompt length;
        its first token rides this record and is emitted at its drain."""
        live = [i for i in range(self._B) if self._decodable(i)]
        if not live:
            return
        self._ensure_decode_rows(live)
        m = self._m
        if self._fr is not None:
            self._fr.record("dispatch", step=self._step_idx,
                            mode=self._mode, n_live=len(live),
                            pipelined=True, kv_quant=self._kvq,
                            attn_impl=self._attn_label,
                            prefill_impl=self._prefill_label,
                            weight_dtype=self._wq_label)
        active = np.array([self._decodable(i) for i in range(self._B)])
        host_len = self._kv.device_lengths(active)
        use_host = ~active
        use_host[list(self._adm_pending)] = True
        # freshly prefilled slots: length is host-known (the prompt length,
        # stamped at the final chunk) but cur is a device future
        use_host_len = use_host.copy()
        use_host_len[list(self._dev_first)] = True
        if self._dev_cur is None:
            cur = jnp.asarray(self._cur)
        else:
            cur = jnp.where(jnp.asarray(use_host), jnp.asarray(self._cur),
                            self._dev_cur)
        for s, f in self._dev_first.items():
            cur = cur.at[s].set(f[0])
        self._dev_first.clear()
        firsts, self._pending_firsts = self._pending_firsts, []
        if self._mode == "greedy":
            # greedy lengths are host-derivable: every live slot advances
            # exactly sync_every per dispatch, so the mirror (bumped below)
            # IS the device value and needs no device carry
            def go(attempt):
                self._fault_point("dispatch", attempt)
                return self._call_decode(cur, host_len)
            with m.span_decode if m is not None else _NULL_CTX:
                toks, okd, self._kv.caches = self._retry(
                    go, "decode dispatch")
            self._dev_cur = toks[:, -1]
            for i in live:
                self._kv.lengths[i] += self._sync
            self._inflight = {"kind": "greedy", "toks": toks, "ok": okd,
                              "reqs": list(self._kv.reqs), "live": live,
                              "firsts": firsts, "adm": adm_active}
        else:
            if self._dev_len is None:
                dev_len = host_len
            else:
                # spec lengths advance by the DEVICE-known j+1, so the
                # carry comes back from serving_spec_step; host values are
                # authoritative only for just-admitted / just-prefilled
                # (prompt length) and freed (masked to lmax) slots
                dev_len = jnp.where(jnp.asarray(use_host_len), host_len,
                                    self._dev_len)

            k = self._next_k(live)
            if self._fr is not None:
                self._fr.record("draft", step=self._step_idx,
                                source=self._spec.source, k=k,
                                n_live=len(live))

            def go(attempt):
                self._fault_point("dispatch", attempt)
                return self._call_spec(cur, dev_len, jnp.asarray(active),
                                       k)
            with m.span_spec if m is not None else _NULL_CTX:
                blk, j, cur2, new_len, oks, self._kv.caches, self._hist, \
                    self._hist_len = self._retry(go, "spec dispatch")
            self._dev_cur, self._dev_len = cur2, new_len
            self._inflight = {"kind": "spec", "blk": blk, "j": j,
                              "ok": oks, "k": k,
                              "reqs": list(self._kv.reqs), "live": live,
                              "firsts": firsts, "adm": adm_active}
        self._adm_pending.clear()
        if m is not None:
            m.inflight.set(1)

    def _drain(self, rec):
        """Sync the PREVIOUS iteration's dispatch (handed over by
        ``_step_impl`` after the next one is already issued) and run the
        host-side emit / retire bookkeeping for it.  A slot whose Request
        object changed since that dispatch (retired, or
        retired-and-readmitted) gets its stale tokens discarded — the
        host-visible half of the one-step-late retirement invariant."""
        if rec is None:
            return 0
        m = self._m
        # the freshly issued dispatch (if any) stays outstanding through
        # this drain — that overlap is the point; the gauge must not claim
        # the pipe is empty just because THIS record got synced
        still_inflight = 1 if self._inflight is not None else 0
        firsts = rec.get("firsts", [])
        t0 = time.perf_counter()
        emitted = 0
        fo = [x for _, _, f, o in firsts for x in (f, o)]
        if rec["kind"] == "greedy":
            vals = self._fetch("drain", rec["toks"], rec["ok"], *fo)
            toks, okd, fvals = vals[0], vals[1], vals[2:]
            stall = time.perf_counter() - t0
            if m is not None:
                m.pipeline_stall.observe(stall)
                m.inflight.set(still_inflight)
            if self._fr is not None:
                self._fr.record("stall", step=self._step_idx, seconds=stall)
                self._fr.record("drain", step=self._step_idx, mode="greedy",
                                n_live=len(rec["live"]), pipelined=True)
            self._observe_interference(rec.get("adm", False), self._sync)
            # the first tokens ride the record they were dispatched before
            # (program order: final prefill chunk, then this decode step) —
            # emit them ahead of the slot's decode block
            for n, (slot, r, _, _) in enumerate(firsts):
                if self._kv.reqs[slot] is not r:
                    continue
                fv, ov = fvals[2 * n], fvals[2 * n + 1]
                if not bool(ov[0]):
                    self._retire(slot, "poisoned")
                    continue
                if self._paged:
                    # post-finite-check, pre-_emit (which may release):
                    # same registration rule as _flush_firsts
                    self._kv.register_prefix(slot, r._adm_ids)
                self._cur[slot] = int(fv[0])
                emitted += self._emit(slot, [int(fv[0])])
            for i in rec["live"]:
                if self._kv.reqs[i] is not rec["reqs"][i]:
                    continue
                if not bool(okd[i]):
                    self._retire(i, "poisoned")
                    continue
                emitted += self._emit(i, toks[i].tolist())
                self._cur[i] = toks[i, -1]
        else:
            vals = self._fetch("drain", rec["blk"], rec["j"], rec["ok"],
                               *fo)
            blk, j, okd, fvals = vals[0], vals[1], vals[2], vals[3:]
            stall = time.perf_counter() - t0
            if m is not None:
                m.pipeline_stall.observe(stall)
                m.inflight.set(still_inflight)
            if self._fr is not None:
                self._fr.record("stall", step=self._step_idx, seconds=stall)
                self._fr.record("drain", step=self._step_idx, mode="spec",
                                n_live=len(rec["live"]), pipelined=True)
            for n, (slot, r, _, _) in enumerate(firsts):
                if self._kv.reqs[slot] is not r:
                    continue
                fv, ov = fvals[2 * n], fvals[2 * n + 1]
                if not bool(ov[0]):
                    self._retire(slot, "poisoned")
                    continue
                if self._paged:
                    # post-finite-check, pre-_emit (which may release):
                    # same registration rule as _flush_firsts
                    self._kv.register_prefix(slot, r._adm_ids)
                    if self._dspec:
                        self._kv.register_draft_prefix(slot, r._adm_ids)
                self._cur[slot] = int(fv[0])
                emitted += self._emit(slot, [int(fv[0])])
            k = rec.get("k", self._spec_k)
            accepted = 0
            drained = 0
            rounds = []
            for i in rec["live"]:
                if self._kv.reqs[i] is not rec["reqs"][i]:
                    continue
                if not bool(okd[i]):
                    self._retire(i, "poisoned")
                    continue
                drained += 1
                emitted += self._emit(i, blk[i, :int(j[i]) + 1].tolist())
                self._kv.lengths[i] += int(j[i]) + 1
                accepted += int(j[i])
                rounds.append((i, int(j[i])))
            if self._fr is not None:
                self._fr.record("verify", step=self._step_idx, k=k,
                                drafted=k * drained, accepted=accepted)
                self._fr.record("rewind", step=self._step_idx,
                                tokens=k * drained - accepted)
            self._adapt_k(rounds, k)
            self._observe_interference(
                rec.get("adm", False), 1.0 + accepted / max(1, drained))
            if m is not None and drained:
                m.spec_round(k * drained, accepted)
        return emitted

    def run(self):
        """Drive ``step()`` until the queue and every slot drain; returns
        the finished requests in completion order."""
        while self.has_work:
            self.step()
        return self._finished

    def drain(self):
        """Run the engine to quiescence, then return ``{rid: terminal
        status}`` over every request it finished — the graceful-shutdown
        half of ``close()`` (all outstanding work completes; deadlines
        and faults still apply while draining)."""
        self.run()
        return {r.rid: r.status for r in self._finished}

    def close(self):
        """Abort outstanding work cleanly.  The inflight pipelined
        dispatch (if any) is drained first — its tokens still emit, so
        every in-flight request keeps its partial output — then every
        queued and resident request is retired with terminal status
        ``"cancelled"``.  Returns ``{rid: terminal status}`` over every
        request the engine ever finished.  Idempotent: a second call
        finds nothing to cancel and returns the same map."""
        if self._watchdog is not None:
            self._watchdog.stop()
        if self._inflight is not None:
            prev, self._inflight = self._inflight, None
            self._drain(prev)
        while self._queue:
            self._terminal_queued(self._queue.popleft(), "cancelled")
        for slot in range(self._B):
            if self._kv.reqs[slot] is not None:
                self._retire(slot, "cancelled")
        if self._m is not None:
            self._m.queue_depth.set(len(self._queue))
        return {r.rid: r.status for r in self._finished}

    # ------------------------------------------------- fleet introspection
    # the surface serving/replica.py programs against: pure host reads
    # (no device work, no allocation) a router can poll every route
    @property
    def kv_block(self):
        """Paged KV block size in tokens (None on dense engines) — the
        chunk width router-side prefix mirrors must key on."""
        return self._kv.block if self._paged else None

    def queue_depth(self):
        """Requests waiting for a slot (the admission backlog)."""
        return len(self._queue)

    def prefix_lookup(self, tokens):
        """Longest cached prefix (in tokens) this engine holds for
        ``tokens`` across BOTH tiers — the device radix match plus its
        contiguous host-tier continuation (a restore at admission makes
        those tokens just as reusable) — the router's cache-aware
        placement probe.  Pure probe: no LRU heat on either tier.  0 on
        dense engines."""
        if not self._paged:
            return 0
        tok = np.asarray(tokens, np.int32).reshape(-1)
        matched, _ = self._kv.match_prefix(tok, touch=False)
        if self._kv.host_tier is not None:
            matched += self._kv.host_match(tok, matched)
        return int(matched)

    def stats(self):
        """JSON-ready scheduling snapshot for replica handles/routers:
        backlog, occupancy, and the cumulative paged prompt/reuse and
        preemption token tallies.  Maintained unconditionally, so
        ``instrument=False`` engines report them too."""
        return {
            "queue_depth": len(self._queue),
            "slots_occupied": self._kv.occupied(),
            "slots_total": self._B,
            "prefill_slots": len(self._pf),
            "inflight": 1 if self._inflight is not None else 0,
            "live_tokens": int(self._kv.live_tokens()),
            "prompt_tokens": self._n_prompt_tokens,
            "prefix_reuse_tokens": self._n_reuse_tokens,
            "host_reuse_tokens": self._n_host_reuse_tokens,
            "preempted": self._n_preempted,
            "preempt_resume_suffix_tokens": self._n_resume_suffix,
            "preempt_resume_total_tokens": self._n_resume_total,
        }

    @property
    def kv_manager(self):
        """The engine's KV cache manager — the paged block-pool surface
        ``serving/disagg.py`` exports/imports block chains through
        (``block_chain`` / ``export_chain``)."""
        return self._kv

    # ------------------------------------------------- debug introspection
    @property
    def recorder(self):
        """The engine's ``FlightRecorder`` (None when ``recorder=False``)."""
        return self._fr

    @property
    def slo_tracker(self):
        """The engine's ``SLOTracker``."""
        return self._slo

    def requests_snapshot(self, last=64):
        """JSON-ready view of the most recent request timelines (newest
        ``last`` of the rid-keyed trace cache, including still-live
        requests).  Thread-safe: copies under the trace lock, so a scrape
        thread can call it mid-``step()``."""
        with self._trace_lock:
            traces = list(self._traces.values())[-int(last):]
        return {
            "n_tracked": len(traces),
            "requests": [{"rid": t.rid, "phase": t.phase,
                          "timeline": t.as_dicts()} for t in traces],
        }

    def recorder_snapshot(self, last=256):
        """JSON-ready flight-recorder view (plus the fault plan, when one
        is configured, so a postmortem reader sees the injected schedule
        next to the events it caused)."""
        if self._fr is None:
            return {"enabled": False}
        snap = self._fr.snapshot(last=last)
        snap["enabled"] = True
        if self._faults is not None:
            snap["fault_plan"] = self._faults.snapshot()
        return snap

    def slo_snapshot(self):
        """JSON-ready windowed SLO attainment / burn-rate view."""
        return self._slo.snapshot()

    def debug_sources(self):
        """``{name: callable}`` map for ``MetricsExporter`` — wires the
        engine's ``/debug/requests``, ``/debug/flightrecorder`` and
        ``/debug/slo`` endpoints in one call::

            MetricsExporter(debug_sources=engine.debug_sources()).start()
        """
        return {"requests": self.requests_snapshot,
                "flightrecorder": self.recorder_snapshot,
                "slo": self.slo_snapshot}
