"""Continuous-batching serving engine on the ragged decode path.

The compiled decode step (models/llama_decode.py) already supports ragged
per-batch lengths and rewind, but a run-to-completion batch leaves finished
slots idling while the longest request drags the step.  This engine closes
that gap with Orca-style *iteration-level scheduling* — the technique behind
vLLM-class serving throughput — under the TPU constraint that every device
program keeps ONE static compiled shape:

* The device runs a fixed-batch-B step; a host-side scheduler retires
  finished slots (EOS / max-new-tokens) and admits queued requests into
  them *between* compiled steps.
* Admission prefills the incoming prompt against fresh [1, bucket] mini
  caches — cost proportional to the PROMPT, not B×bucket — and inserts
  the rows into the batch cache at the freed slot: the ragged cache's
  per-slot reset.  Retired slots stay parked via
  ``ops.decode_attention.masked_lengths``: their write offset is lmax so
  every decode-step cache write DROPS — recycling needs no reshape,
  copy-out, or recompile.  Prompts are right-padded to a small set of
  power-of-two buckets, bounding the compile count; the slot's first
  token is picked from the logit at its own last prompt column (pad
  columns are causally invisible to it).
* Decode runs either mode behind one ``ServingEngine.step()``: greedy
  (``sync_every`` tokens per dispatch via an inner lax.scan) or model-free
  prompt-lookup speculative drafting (serving_spec_step — the same
  _verify_and_emit verify/rewind machinery as the compiled while-loop, so
  speculation composes with mixed-length slots and emits exactly the
  verify forward's greedy picks; agreement with the 1-token-step program
  holds up to floating-point near-ties between the two program shapes).
* ``policy="gang"`` disables mid-run admission (a batch is admitted only
  when every slot is free and runs to completion) — the sequential
  baseline for the bench A/B, sharing the exact same compiled programs so
  the measured win is pure scheduling.
* **Pipelined (double-buffered) dispatch** (``pipeline=True``, default):
  step N+1 depends only on device-resident state — the carried ``cur``
  tokens, caches, and lengths — so the engine dispatches it BEFORE
  syncing step N's tokens to the host.  Host-side emit/detokenize/
  stream-callback work and admission bookkeeping then overlap device
  compute; the drain-side block is measured by
  ``serving_pipeline_stall_seconds`` and the outstanding dispatch by the
  ``serving_inflight_steps`` gauge.  The ONE device→host sync per
  iteration goes through ``_host_fetch`` (the sanctioned sync point the
  tpu-lint PTL004 rule recognizes).  Correctness invariant: retirement
  and admission take effect ONE STEP LATE — a step dispatched before the
  scheduler discovers a slot finished still computes that slot, but the
  stale step is byte-harmless: ``masked_lengths`` gives a freed slot an
  offset of ``lmax`` at the NEXT dispatch so its writes drop, re-admission
  prefills are dispatched after the stale step in device program order so
  they overwrite its rows, rows past a new prompt's length are invisible
  to decode_attention's position masking, and the drain discards tokens
  whose slot no longer holds the same Request object.  The extra
  inflight dispatch is why ``_headroom`` doubles under pipelining.
  ``pipeline=False`` restores the fully synchronous loop (the A/B
  baseline) — token streams are byte-identical either way (tested).

The per-slot state the scheduler owns host-side: token history, a length
mirror of the device cache, and the speculative rewind offset (folded into
the length mirror as ``+ j + 1`` per accepted round).  Decode-side cache
reads are length-adaptive: ``decode_chunk`` is forwarded to the chunked
online-softmax path in ops/decode_attention.py, so per-step HBM traffic
tracks the longest LIVE context instead of ``max_len``.
"""
from __future__ import annotations

import contextlib
import time
import warnings
from collections import deque

import numpy as np

import jax.numpy as jnp

from paddle_tpu.models.llama_decode import (
    _decode_params_of, serving_decode_steps, serving_prefill_slot,
    serving_spec_step,
)
from paddle_tpu.observability.metrics import get_registry
from paddle_tpu.observability.trace import span
from paddle_tpu.ops.decode_attention import init_kv_cache, masked_lengths

# the serving step/prefill programs donate their cache buffers (in-place
# update on TPU instead of a full-cache copy per dispatch); CPU has no
# donation support and warns per program — harmless here, silence it
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable")

__all__ = ["Request", "ServingEngine"]

_NULL_CTX = contextlib.nullcontext()


def _host_fetch(*arrays):
    """The engine's sanctioned device→host sync point: materialize device
    arrays as numpy, blocking until their producing dispatches complete.
    Every OTHER engine/device interaction is an async dispatch — funneling
    the blocking reads through this one name is what lets the tpu-lint
    PTL004 rule keep flagging raw ``np.asarray`` added inside step loops
    without false-positiving on the pipelined drain."""
    return [np.asarray(a) for a in arrays]


class _EngineMetrics:
    """Pre-bound metric children for one engine (observability subsystem).

    The series live in ``registry`` (default: the process-wide one) keyed by
    a ``policy`` label, so a continuous engine and its gang baseline stay
    separable in one scrape.  All instrumentation is host-side bookkeeping —
    the compiled device programs are untouched, which is what keeps the
    instrumented engine's token outputs byte-identical to an uninstrumented
    run (tested: tests/test_observability.py).
    """

    def __init__(self, registry, policy, batch_size):
        reg = registry if registry is not None else get_registry()
        self.registry = reg
        L = ("policy",)
        lbl = {"policy": policy}
        self.queue_depth = reg.gauge(
            "serving_queue_depth", "requests waiting for a slot",
            L).labels(**lbl)
        self.slots_occupied = reg.gauge(
            "serving_slots_occupied", "batch slots holding a live request",
            L).labels(**lbl)
        self.slots_total = reg.gauge(
            "serving_slots_total", "engine batch size", L).labels(**lbl)
        self.slots_total.set(batch_size)
        self.admitted = reg.counter(
            "serving_requests_admitted_total",
            "requests admitted into a slot", L).labels(**lbl)
        self.retired = reg.counter(
            "serving_requests_retired_total",
            "requests completed (EOS or max_new_tokens)", L).labels(**lbl)
        self.emitted = reg.counter(
            "serving_tokens_emitted_total",
            "tokens delivered to requests", L).labels(**lbl)
        self.steps = reg.counter(
            "serving_steps_total", "scheduler iterations", L).labels(**lbl)
        self._prefills = reg.counter(
            "serving_prefill_total", "slot prefills by prompt bucket",
            ("policy", "bucket"))
        self._policy = policy
        self.queue_wait = reg.histogram(
            "serving_queue_wait_seconds",
            "submit -> slot admission", L).labels(**lbl)
        self.ttft = reg.histogram(
            "serving_ttft_seconds", "submit -> first token", L).labels(**lbl)
        self.tpot = reg.histogram(
            "serving_tpot_seconds",
            "mean per-token time after the first", L).labels(**lbl)
        self.e2e = reg.histogram(
            "serving_e2e_seconds", "submit -> completion", L).labels(**lbl)
        self.stream_cb_errors = reg.counter(
            "serving_stream_cb_errors_total",
            "stream_cb exceptions swallowed by the scheduler",
            L).labels(**lbl)
        self.spec_drafted = reg.counter(
            "serving_spec_drafted_total",
            "draft tokens proposed by prompt-lookup", L).labels(**lbl)
        self.spec_accepted = reg.counter(
            "serving_spec_accepted_total",
            "draft tokens accepted by the verify forward", L).labels(**lbl)
        self.spec_accept_rate = reg.gauge(
            "serving_spec_accept_rate",
            "cumulative accepted/drafted ratio", L).labels(**lbl)
        self.pipeline_stall = reg.histogram(
            "serving_pipeline_stall_seconds",
            "drain-side block waiting on the inflight dispatch",
            L).labels(**lbl)
        self.inflight = reg.gauge(
            "serving_inflight_steps",
            "device steps dispatched but not yet drained", L).labels(**lbl)
        self.span_step = span("serving.step", registry=reg)
        self.span_prefill = span("serving.prefill", registry=reg)
        self.span_decode = span("serving.decode", registry=reg)
        self.span_spec = span("serving.spec_step", registry=reg)

    def prefill(self, bucket):
        self._prefills.labels(policy=self._policy, bucket=bucket).inc()

    def spec_round(self, drafted, accepted):
        self.spec_drafted.inc(drafted)
        self.spec_accepted.inc(accepted)
        total = self.spec_drafted.value
        if total:
            self.spec_accept_rate.set(self.spec_accepted.value / total)


class Request:
    """One generation request.

    ``prompt_ids``: 1-D int token ids.  ``eos_token_id`` retires the slot
    when emitted (the EOS itself is kept in ``output_ids``).  ``stream_cb``
    (optional ``cb(request, new_ids)``) fires per emission batch — the
    streaming hook; with an engine ``detokenizer`` the accumulated text is
    kept current in ``.text``.  A raising ``stream_cb`` never kills the
    scheduler: the error is counted (``serving_stream_cb_errors_total``)
    and decoding continues.  Timing (perf_counter): ``t_submit`` /
    ``t_first`` (first token) / ``t_done``, with derived ``ttft`` /
    ``tpot`` / ``latency`` properties (None until available).
    """

    def __init__(self, prompt_ids, max_new_tokens, eos_token_id=None,
                 stream_cb=None, rid=None):
        self.prompt_ids = np.asarray(prompt_ids, np.int32).reshape(-1)
        if self.prompt_ids.size == 0:
            raise ValueError("Request: empty prompt")
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens < 1:
            raise ValueError("Request: max_new_tokens must be >= 1")
        self.eos_token_id = eos_token_id
        self.stream_cb = stream_cb
        self.rid = rid
        self.output_ids = []
        self.text = ""
        self.done = False
        self.t_submit = None
        self.t_first = None
        self.t_done = None

    @property
    def latency(self):
        """submit -> completion seconds (None until done)."""
        if self.t_done is None or self.t_submit is None:
            return None
        return self.t_done - self.t_submit

    @property
    def ttft(self):
        """Time to first token: submit -> first emission seconds (None
        until the first token lands)."""
        if self.t_first is None or self.t_submit is None:
            return None
        return self.t_first - self.t_submit

    @property
    def tpot(self):
        """Time per output token AFTER the first: (t_done - t_first) /
        max(1, n_out - 1) seconds (None until done) — the steady-state
        decode rate, with the prefill-dominated first token excluded."""
        if self.t_done is None or self.t_first is None:
            return None
        return (self.t_done - self.t_first) / max(1, len(self.output_ids) - 1)


class ServingEngine:
    """Fixed-batch continuous-batching engine over one causal LM.

    ``mode``: "greedy" or "spec" (model-free prompt-lookup speculative
    drafting, lossless — per-slot outputs byte-identical to greedy).
    ``sync_every``: greedy tokens decoded per host dispatch (inner scan);
    retirement/admission latency is bounded by it.  ``policy``:
    "continuous" (admit into any free slot between steps) or "gang"
    (run-to-completion baseline).  ``prompt_buckets``: padded prefill
    widths (default: powers of two up to ``max_len``).
    ``detokenizer``: optional ``ids -> str`` for streamed ``.text``.
    ``pipeline``: double-buffer the decode loop — dispatch step N+1 before
    syncing step N's tokens (module docstring has the one-step-late
    retirement invariant); ``False`` is the synchronous A/B baseline with
    byte-identical token streams.  ``decode_chunk``: KV chunk size for the
    length-adaptive cache read (ops/decode_attention.py); ``None`` reads
    the full ``[B, max_len]`` cache every step.  The default (256) falls
    back to the full read automatically when ``max_len <= 256``.
    """

    def __init__(self, model, batch_size=8, max_len=2048, mode="greedy",
                 spec_k=8, sync_every=1, policy="continuous",
                 prompt_buckets=None, detokenizer=None, registry=None,
                 instrument=True, pipeline=True, decode_chunk=256):
        if mode not in ("greedy", "spec"):
            raise ValueError(f"unknown mode {mode!r}")
        if policy not in ("continuous", "gang"):
            raise ValueError(f"unknown policy {policy!r}")
        # observability: purely host-side counters/gauges/histograms/spans
        # keyed by policy (paddle_tpu/observability).  ``registry=None``
        # feeds the process-wide registry; benches pass private registries
        # for isolated readings.  ``instrument=False`` removes every metric
        # touch — token outputs are byte-identical either way (tested).
        self._m = (_EngineMetrics(registry, policy, int(batch_size))
                   if instrument else None)
        self._B = int(batch_size)
        self._lmax = int(max_len)
        self._mode = mode
        self._spec_k = int(spec_k)
        self._sync = max(1, int(sync_every))
        self._policy = policy
        self._detok = detokenizer
        self._pipeline = bool(pipeline)
        self._chunk = int(decode_chunk) if decode_chunk else None
        self._params, self._cfg = _decode_params_of(model, self._lmax)
        nh, nkv, hd, eps = self._cfg
        dtype = self._params["embed"].dtype
        self._caches = [init_kv_cache(self._B, self._lmax, nkv, hd, dtype)
                        for _ in self._params["layers"]]
        if prompt_buckets is None:
            prompt_buckets = []
            b = 16
            while b < self._lmax:
                prompt_buckets.append(b)
                b *= 2
        self._buckets = sorted(int(b) for b in prompt_buckets)
        if not self._buckets or self._buckets[-1] > self._lmax:
            raise ValueError("prompt_buckets must be non-empty and <= max_len")
        # host mirrors of per-slot device state
        self._len = np.zeros((self._B,), np.int32)
        self._cur = np.zeros((self._B,), np.int32)
        self._reqs = [None] * self._B
        if mode == "spec":
            self._hist = jnp.zeros((self._B, self._lmax), jnp.int32)
            self._hist_len = jnp.zeros((self._B,), jnp.int32)
        else:
            self._hist = self._hist_len = None
        self._queue = deque()
        self._finished = []
        self._next_rid = 0
        # pipelined-dispatch state: the one outstanding (dispatched, not yet
        # drained) step, the device-resident carries feeding the NEXT
        # dispatch without a host round-trip, and the slots admitted since
        # the last dispatch (whose cur/length live host-side until mixed in)
        self._inflight = None
        self._dev_cur = None
        self._dev_len = None
        self._adm_pending = set()

    # ------------------------------------------------------------- scheduling
    @property
    def has_work(self):
        return (bool(self._queue) or any(r is not None for r in self._reqs)
                or self._inflight is not None)

    def _headroom(self):
        # greedy may overshoot a retiring slot by < sync_every cache rows;
        # spec's verify forward writes spec_k+1 rows before the rewind
        per = self._spec_k + 1 if self._mode == "spec" else self._sync
        # a pipelined engine discovers retirement one drain late, so one
        # extra full dispatch of cache writes can land past the emission
        # point before the slot's offset is masked to lmax
        return 2 * per if self._pipeline else per

    def submit(self, request):
        p = int(request.prompt_ids.size)
        bucket = next((b for b in self._buckets if b >= p), None)
        if bucket is None:
            raise ValueError(
                f"prompt length {p} exceeds the largest prompt bucket "
                f"{self._buckets[-1]}")
        need = p + request.max_new_tokens + self._headroom()
        if need > self._lmax:
            raise ValueError(
                f"request needs {need} cache rows (prompt {p} + "
                f"max_new {request.max_new_tokens} + headroom "
                f"{self._headroom()}) > max_len {self._lmax}")
        request._bucket = bucket
        if request.rid is None:
            request.rid = self._next_rid
        self._next_rid += 1
        request.t_submit = time.perf_counter()
        self._queue.append(request)
        if self._m is not None:
            self._m.queue_depth.set(len(self._queue))
        return request

    def _admit(self):
        free = [i for i in range(self._B) if self._reqs[i] is None]
        if not free or not self._queue:
            return
        if self._policy == "gang" and len(free) < self._B:
            return  # run-to-completion: wait for the whole batch to drain
        m = self._m
        pending = []
        while free and self._queue:
            r = self._queue.popleft()
            slot = free.pop(0)
            self._reqs[slot] = r
            p = r.prompt_ids.size
            if m is not None:
                m.admitted.inc()
                m.prefill(r._bucket)
                m.queue_wait.observe(time.perf_counter() - r.t_submit)
            tokens = np.zeros((1, r._bucket), np.int32)
            tokens[0, :p] = r.prompt_ids
            with m.span_prefill if m is not None else _NULL_CTX:
                first, self._caches, hist, hist_len = serving_prefill_slot(
                    self._params, self._cfg, jnp.asarray(tokens),
                    jnp.asarray(np.array([p], np.int32)), self._caches,
                    jnp.asarray(slot, jnp.int32),
                    hist=self._hist, hist_len=self._hist_len,
                    with_hist=self._mode == "spec",
                    chunk_size=self._chunk)
            if self._mode == "spec":
                self._hist, self._hist_len = hist, hist_len
            self._len[slot] = p
            self._adm_pending.add(slot)
            pending.append((slot, first))
        # every prefill in the wave is dispatched (async) above; block ONCE
        # here for all their first tokens — one host sync per _admit, not
        # one per admitted request
        firsts = _host_fetch(*(f for _, f in pending))
        for (slot, _), fv in zip(pending, firsts):
            first = int(fv[0])
            self._cur[slot] = first
            self._emit(slot, [first])
        if m is not None:
            m.queue_depth.set(len(self._queue))
            m.slots_occupied.set(
                sum(r is not None for r in self._reqs))

    def _emit(self, slot, toks):
        """Append emitted tokens to the slot's request, truncating at EOS /
        max_new_tokens; retires the slot when the request completes.
        Returns the number of tokens actually consumed."""
        r = self._reqs[slot]
        m = self._m
        took = 0
        for t in toks:
            if r.done:
                break
            r.output_ids.append(int(t))
            took += 1
            if r.t_first is None:
                r.t_first = time.perf_counter()
                if m is not None:
                    m.ttft.observe(r.t_first - r.t_submit)
            if len(r.output_ids) >= r.max_new_tokens or (
                    r.eos_token_id is not None
                    and int(t) == int(r.eos_token_id)):
                r.done = True
        if took:
            if m is not None:
                m.emitted.inc(took)
            if self._detok is not None:
                r.text = self._detok(list(r.output_ids))
            if r.stream_cb is not None:
                try:
                    r.stream_cb(r, r.output_ids[-took:])
                except Exception:
                    # a crashing user callback must not kill the scheduler
                    # loop mid-batch (every other live slot would lose its
                    # in-flight block): count the drop and keep decoding
                    if m is not None:
                        m.stream_cb_errors.inc()
        if r.done:
            r.t_done = time.perf_counter()
            self._reqs[slot] = None
            self._finished.append(r)
            if m is not None:
                m.retired.inc()
                m.e2e.observe(r.t_done - r.t_submit)
                m.tpot.observe(r.tpot)
                m.slots_occupied.set(
                    sum(q is not None for q in self._reqs))
        return took

    # ------------------------------------------------------------ step / run
    def step(self):
        """One scheduler iteration: retire/admit, then one compiled decode
        dispatch over every live slot.  Returns tokens emitted."""
        m = self._m
        if m is None:
            return self._step_impl()
        m.steps.inc()
        with m.span_step:
            return self._step_impl()

    def _step_impl(self):
        self._admit()
        if not self._pipeline:
            self._adm_pending.clear()
            return self._step_sync()
        # the double buffer: stash the record of the PREVIOUS iteration's
        # dispatch, issue the next dispatch, and only then drain the stash —
        # step N+1 is outstanding on the device while step N's tokens are
        # synced and its emit/retire bookkeeping runs.  When _dispatch has
        # nothing to issue (e.g. every slot retired at the last drain) the
        # stashed record is still drained, so run() terminates.
        prev, self._inflight = self._inflight, None
        self._dispatch()
        return self._drain(prev)

    # ------------------------------------------------- synchronous baseline
    def _step_sync(self):
        """``pipeline=False``: dispatch one step and block on its tokens in
        the same iteration — the A/B baseline the pipelined loop is
        byte-identical to."""
        m = self._m
        live = [i for i in range(self._B) if self._reqs[i] is not None]
        if not live:
            return 0
        active = np.array([r is not None for r in self._reqs])
        dev_len = masked_lengths(jnp.asarray(self._len), jnp.asarray(active),
                                 self._lmax)
        emitted = 0
        if self._mode == "greedy":
            with m.span_decode if m is not None else _NULL_CTX:
                toks, self._caches = serving_decode_steps(
                    self._params, self._cfg, jnp.asarray(self._cur),
                    self._caches, dev_len, n_steps=self._sync,
                    chunk_size=self._chunk)
                (toks,) = _host_fetch(toks)
            for i in live:
                emitted += self._emit(i, toks[i].tolist())
                self._len[i] += self._sync
                self._cur[i] = toks[i, -1]
        else:
            with m.span_spec if m is not None else _NULL_CTX:
                blk, j, cur, _, self._caches, self._hist, self._hist_len = \
                    serving_spec_step(
                        self._params, self._cfg, jnp.asarray(self._cur),
                        self._caches, dev_len, self._hist, self._hist_len,
                        jnp.asarray(active), spec_k=self._spec_k,
                        chunk_size=self._chunk)
                blk, j, cur = _host_fetch(blk, j, cur)
            accepted = 0
            for i in live:
                emitted += self._emit(i, blk[i, :int(j[i]) + 1].tolist())
                self._len[i] += int(j[i]) + 1
                self._cur[i] = cur[i]
                accepted += int(j[i])
            if m is not None:
                # per verify round each live slot drafts spec_k and accepts
                # j of them (the +1 bonus token is the verify forward's own
                # pick, not a draft)
                m.spec_round(self._spec_k * len(live), accepted)
        return emitted

    # --------------------------------------------------- pipelined dispatch
    def _dispatch(self):
        """Dispatch the next decode step WITHOUT waiting for the previous
        one (still undrained — ``_step_impl`` holds its record).  The
        step's inputs are all device-resident: the carried ``cur`` tokens /
        lengths of the previous dispatch (still futures — the device
        executes in program order) plus the caches; slots admitted since
        the last dispatch mix their host-known first token and prompt
        length into the carry."""
        live = [i for i in range(self._B) if self._reqs[i] is not None]
        if not live:
            return
        m = self._m
        active = np.array([r is not None for r in self._reqs])
        host_len = masked_lengths(jnp.asarray(self._len),
                                  jnp.asarray(active), self._lmax)
        use_host = ~active
        use_host[list(self._adm_pending)] = True
        if self._dev_cur is None:
            cur = jnp.asarray(self._cur)
        else:
            cur = jnp.where(jnp.asarray(use_host), jnp.asarray(self._cur),
                            self._dev_cur)
        if self._mode == "greedy":
            # greedy lengths are host-derivable: every live slot advances
            # exactly sync_every per dispatch, so the mirror (bumped below)
            # IS the device value and needs no device carry
            with m.span_decode if m is not None else _NULL_CTX:
                toks, self._caches = serving_decode_steps(
                    self._params, self._cfg, cur, self._caches, host_len,
                    n_steps=self._sync, chunk_size=self._chunk)
            self._dev_cur = toks[:, -1]
            for i in live:
                self._len[i] += self._sync
            self._inflight = {"kind": "greedy", "toks": toks,
                              "reqs": list(self._reqs), "live": live}
        else:
            if self._dev_len is None:
                dev_len = host_len
            else:
                # spec lengths advance by the DEVICE-known j+1, so the
                # carry comes back from serving_spec_step; host values are
                # authoritative only for just-admitted (prompt length) and
                # freed (masked to lmax) slots
                dev_len = jnp.where(jnp.asarray(use_host), host_len,
                                    self._dev_len)
            with m.span_spec if m is not None else _NULL_CTX:
                blk, j, cur2, new_len, self._caches, self._hist, \
                    self._hist_len = serving_spec_step(
                        self._params, self._cfg, cur, self._caches,
                        dev_len, self._hist, self._hist_len,
                        jnp.asarray(active), spec_k=self._spec_k,
                        chunk_size=self._chunk)
            self._dev_cur, self._dev_len = cur2, new_len
            self._inflight = {"kind": "spec", "blk": blk, "j": j,
                              "reqs": list(self._reqs), "live": live}
        self._adm_pending.clear()
        if m is not None:
            m.inflight.set(1)

    def _drain(self, rec):
        """Sync the PREVIOUS iteration's dispatch (handed over by
        ``_step_impl`` after the next one is already issued) and run the
        host-side emit / retire bookkeeping for it.  A slot whose Request
        object changed since that dispatch (retired, or
        retired-and-readmitted) gets its stale tokens discarded — the
        host-visible half of the one-step-late retirement invariant."""
        if rec is None:
            return 0
        m = self._m
        # the freshly issued dispatch (if any) stays outstanding through
        # this drain — that overlap is the point; the gauge must not claim
        # the pipe is empty just because THIS record got synced
        still_inflight = 1 if self._inflight is not None else 0
        t0 = time.perf_counter()
        emitted = 0
        if rec["kind"] == "greedy":
            (toks,) = _host_fetch(rec["toks"])
            if m is not None:
                m.pipeline_stall.observe(time.perf_counter() - t0)
                m.inflight.set(still_inflight)
            for i in rec["live"]:
                if self._reqs[i] is not rec["reqs"][i]:
                    continue
                emitted += self._emit(i, toks[i].tolist())
                self._cur[i] = toks[i, -1]
        else:
            blk, j = _host_fetch(rec["blk"], rec["j"])
            if m is not None:
                m.pipeline_stall.observe(time.perf_counter() - t0)
                m.inflight.set(still_inflight)
            accepted = 0
            drained = 0
            for i in rec["live"]:
                if self._reqs[i] is not rec["reqs"][i]:
                    continue
                drained += 1
                emitted += self._emit(i, blk[i, :int(j[i]) + 1].tolist())
                self._len[i] += int(j[i]) + 1
                accepted += int(j[i])
            if m is not None and drained:
                m.spec_round(self._spec_k * drained, accepted)
        return emitted

    def run(self):
        """Drive ``step()`` until the queue and every slot drain; returns
        the finished requests in completion order."""
        while self.has_work:
            self.step()
        return self._finished
