"""KV-cache + slot state machine for the serving engine.

Extracted from serving/engine.py so placement policies (tensor-parallel
head sharding today, paged block tables next — ROADMAP item 2) plug in
underneath the scheduler without re-threading it.  The manager owns the
three per-slot facts the engine's scheduling logic reads and the device
programs consume:

* ``caches`` — the per-layer ``(k, v)`` pytrees, ``[B, Lmax, Hkv, D]``
  each, preallocated once (ops.decode_attention.init_kv_cache) and
  thereafter only REBOUND by the engine to each dispatch's donated
  outputs.  With ``sharding`` set (a ``NamedSharding`` over the head
  axis — serving/sharding.kv_cache_pspec) the zeros are placed sharded
  at construction, so every later donated output inherits the layout and
  no per-step resharding ever happens.
* ``lengths`` — the host int32 mirror of each slot's device write offset
  (prompt + emitted so far).  The engine bumps it as dispatches go out;
  ``device_lengths`` masks it through
  ops.decode_attention.masked_lengths, which parks every dead slot at
  ``max_len`` so its cache writes DROP — retirement needs no reshape,
  copy-out, or recompile (the write-drop parking invariant).
* ``reqs`` — slot -> live Request (None = free).  Slot allocation is
  lowest-free-first; the engine compares stored Request objects by
  identity at drain time to discard stale pipelined tokens, so the
  manager never recycles state, only the slot index.

``PagedKVCacheManager`` swaps the dense per-slot rows for a global block
pool (ops.decode_attention.init_kv_pool) indirected through per-slot
block tables — the paged geometry of ROADMAP item 2:

* blocks are REFCOUNTED: a radix map keyed on token-id chunks lets
  multiple slots map the same physical prefix blocks (decode only
  appends PAST the shared prefix, so copy-on-write is unnecessary).
  The key is TOKEN IDS, never cache bytes — so prefix reuse is
  storage-dtype-agnostic: an int8 (data, scale) pool shares blocks by
  the same table ids, one block id covering both leaves;
* refcount-0 blocks that still back a cached prefix stay resident as
  EVICTABLE until the allocator needs them (LRU-first subtree
  eviction), so an identical prompt admitted later skips its prefill;
* the table rows are host int32 mirrors shipped to the device as
  TRACED operands — growing a slot's chain or remapping it to shared
  blocks changes values, never shapes: zero retraces.

``BlockStore`` is the HOST-RAM tier below the pool (ROADMAP item 2,
the Mooncake/SGLang hierarchical-cache shape): when the allocator
reclaims a registered EVICTABLE chain, the chain's block data is
*demoted* — gathered off-pool (``export_chain``, an async device
dispatch staged at eviction time) and materialized into the store by
the engine's between-steps pump — instead of destroyed.  Store entries
are keyed by CONTENT (the nested chunk-key spelling of the full token
prefix, not device block ids), so a chain whose ancestors still live
on-device and a chain demoted whole are both matchable.  At admission
``restore_from_host`` rehydrates the host continuation of a prompt
into freshly allocated, EVICTABLE-registered blocks — one functional
``.at[ids].set`` per pool leaf through the sanctioned ``kv_transfer``
seam — so the ordinary radix match then adopts them: a restore is a
device_put, never a suffix prefill, and tables change values, never
shapes (zero retraces across a demote→restore wave, tested).

Everything here is host-side bookkeeping plus ONE eager masking op;
nothing dispatches a compiled step — that stays the engine's job.
"""
from __future__ import annotations

import zlib

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops.decode_attention import (init_kv_cache, init_kv_pool,
                                             masked_lengths)

__all__ = ["BlockStore", "KVCacheManager", "PagedKVCacheManager",
           "KVPoolExhausted", "chunk_keys"]


def chunk_keys(tokens, block):
    """Content keys for every FULL ``block``-sized chunk of ``tokens``:
    the nested ``(parent_key, chunk)`` spelling — structurally the whole
    token prefix up to and including each chunk, hashable, with shared
    structure between a chain and its extensions.  Keying the host tier
    by content (instead of device block ids) is what lets a chain whose
    ancestors still live on-device match its demoted continuation."""
    keys, key = [], None
    block = int(block)
    for k in range(len(tokens) // block):
        chunk = tuple(int(t) for t in tokens[k * block:(k + 1) * block])
        key = (key, chunk)
        keys.append(key)
    return keys


def _kv_transfer(leaves):
    """Materialize staged demotion leaves on the host: block on the
    eviction-time device gathers (dispatched long before — device
    program order already ran them ahead of any subsequent pool write)
    and return numpy copies.  This is the device→host half of the tier
    boundary and the tpu-lint-sanctioned transfer seam (PTL017): it is
    called ONLY from ``pump_host_tier`` between scheduler steps, never
    inside a dispatch loop."""
    def fetch(x):
        if isinstance(x, tuple):
            return tuple(fetch(e) for e in x)
        # np.asarray of a jax buffer can alias it read-only — the store
        # owns its bytes (and the corruption seam mutates them), so copy
        return np.array(x)
    return [(fetch(k), fetch(v)) for k, v in leaves]


def kv_transfer(caches, ids, leaves):
    """Scatter host-tier block data back into the pool: one functional
    ``.at[ids].set`` per leaf (a device_put of values into an existing
    buffer — shapes, shardings and programs are untouched, which is the
    zero-retrace argument for restore-on-adopt).  The host→device half
    of the tier boundary and the other sanctioned transfer seam
    (tpu-lint PTL017): called only from ``restore_from_host``, which
    the engine runs at admission — between steps, off the dispatch
    loop."""
    ids = jnp.asarray(np.asarray(ids, np.int32))

    def put(pool, leaf):
        if isinstance(pool, tuple):
            return tuple(put(p, x) for p, x in zip(pool, leaf))
        return pool.at[ids].set(jnp.asarray(leaf).astype(pool.dtype))
    return [(put(kc, lk), put(vc, lv))
            for (kc, vc), (lk, lv) in zip(caches, leaves)]


def _leaf_nbytes(leaf):
    if isinstance(leaf, tuple):
        return sum(_leaf_nbytes(x) for x in leaf)
    return int(leaf.nbytes)


def _leaf_crc(leaf, crc=0):
    if isinstance(leaf, tuple):
        for x in leaf:
            crc = _leaf_crc(x, crc)
        return crc
    return zlib.crc32(np.ascontiguousarray(leaf).tobytes(), crc)


def _leaf_spec_of(leaf):
    if isinstance(leaf, tuple):
        return tuple(_leaf_spec_of(x) for x in leaf)
    return (tuple(leaf.shape), str(leaf.dtype))


class BlockStore:
    """Host-RAM demotion tier for evicted prefix chains.

    A radix map over CONTENT keys (``chunk_keys``) holding one KV
    block's per-layer ``(k, v)`` leaves per entry — ``[C, Hkv, D]``
    data plus ``[C, Hkv]`` scales on int8 pools, numpy, off-pool — under
    its own LRU + byte budget:

    * ``put`` inserts one block's leaves; when the budget overflows, the
      least-recently-used entry AND its registered descendants are
      evicted first (a child is only matchable through its parent, so a
      subtree orphaned by its parent's eviction would be dead weight).
      An entry bigger than the whole budget is rejected.
    * ``fetch`` validates the entry against the pool's expected leaf
      structure AND the CRC recorded at insert; a mismatch (truncated /
      garbled chain — ``FaultPlan(host_tier_corrupt=...)``) drops the
      entry's subtree, counts ``stats["errors"]`` and returns None, so
      wrong bytes are NEVER spliced into a pool — the caller falls back
      to suffix prefill.
    * ``has`` is a pure probe (no LRU touch): routers may ask often.

    Host bookkeeping only; the device halves of demotion/restore live in
    the manager's ``_kv_transfer``/``kv_transfer`` seams.  One store may
    be shared by several managers (engines) as long as their block sizes
    agree — content keys carry the token bytes, so cross-engine hits are
    exactly as safe as same-engine ones.
    """

    def __init__(self, max_bytes, block):
        self.max_bytes = int(max_bytes)
        self.block = int(block)
        if self.max_bytes < 0:
            raise ValueError("BlockStore max_bytes must be >= 0")
        if self.block <= 0:
            raise ValueError("BlockStore block must be > 0")
        self._data = {}     # key -> per-layer [(k, v)] numpy leaves
        self._nbytes = {}   # key -> payload bytes
        self._crc = {}      # key -> crc32 at insert
        self._kids = {}     # parent key -> set(child keys)
        self._lru = {}      # key -> tick
        self._tick = 0
        self.total_bytes = 0
        self.stats = {"demoted": 0, "restored": 0, "evicted": 0,
                      "rejected": 0, "errors": 0}

    @property
    def n_blocks(self):
        return len(self._data)

    def __contains__(self, key):
        return key in self._data

    def has(self, key):
        """Pure presence probe — no LRU touch (probing must not make an
        entry look hot; only a restore-bound ``fetch`` does)."""
        return key in self._data

    @staticmethod
    def key_digest(key):
        """Short stable hex digest of a content key for events/logs (the
        nested key itself spells the whole token prefix)."""
        return format(zlib.crc32(repr(key).encode()), "08x")

    def nbytes_of(self, key):
        return self._nbytes.get(key, 0)

    # ------------------------------------------------------------ mutation
    def _drop_subtree(self, key, stat):
        """Remove ``key`` and every registered descendant; returns the
        dropped keys.  ``stat`` names the stats counter to charge."""
        dropped, stack = [], [key]
        while stack:
            k = stack.pop()
            stack.extend(self._kids.pop(k, ()))
            if k not in self._data:
                continue
            del self._data[k]
            self.total_bytes -= self._nbytes.pop(k)
            self._crc.pop(k, None)
            self._lru.pop(k, None)
            kids = self._kids.get(k[0])
            if kids is not None:
                kids.discard(k)
            dropped.append(k)
            self.stats[stat] += 1
        return dropped

    def put(self, key, leaves):
        """Insert one block's per-layer leaves under content ``key``.
        Returns ``(stored, evicted_keys)``: LRU entries (with subtrees)
        evicted to make room, or ``stored=False`` when the entry alone
        exceeds the budget (counted ``rejected``).  Re-inserting a
        present key refreshes its LRU tick and payload."""
        nb = sum(_leaf_nbytes(k) + _leaf_nbytes(v) for k, v in leaves)
        evicted = []
        if nb > self.max_bytes:
            self.stats["rejected"] += 1
            return False, evicted
        if key in self._data:
            self.total_bytes -= self._nbytes[key]
        while self.total_bytes + nb > self.max_bytes:
            victim = min(self._lru, key=self._lru.get)
            evicted.extend(self._drop_subtree(victim, "evicted"))
        self._data[key] = leaves
        self._nbytes[key] = nb
        self._crc[key] = _leaf_crc(tuple(leaves))
        self._kids.setdefault(key[0], set()).add(key)
        self.total_bytes += nb
        self._tick += 1
        self._lru[key] = self._tick
        self.stats["demoted"] += 1
        return True, evicted

    def fetch(self, key, spec=None):
        """The entry's leaves, validated — or None (absent, or corrupt:
        structure/shape/dtype mismatch against ``spec`` or a CRC
        mismatch; the bad entry's subtree is dropped and ``errors``
        counted, so a broken chain can never splice wrong bytes)."""
        entry = self._data.get(key)
        if entry is None:
            return None
        ok = True
        if spec is not None:
            ok = (len(entry) == len(spec)
                  and all(_leaf_spec_of(k) == sk and _leaf_spec_of(v) == sv
                          for (k, v), (sk, sv) in zip(entry, spec)))
        if ok:
            ok = _leaf_crc(tuple(entry)) == self._crc.get(key)
        if not ok:
            self._drop_subtree(key, "errors")
            return None
        self._tick += 1
        self._lru[key] = self._tick
        self.stats["restored"] += 1
        return entry

    # --------------------------------------------------------- fault seam
    def corrupt(self, key=None, mode="truncate"):
        """Test-only damage seam (``FaultPlan.host_tier_corrupt``):
        truncate (drop the last cached row of every leaf — a structural
        length mismatch ``fetch`` catches against the pool spec) or
        garble (flip payload bytes in place, leaving the insert-time CRC
        stale) the entry at ``key``, or every entry when ``key`` is
        None.  Returns the number of entries damaged."""
        if mode not in ("truncate", "garble"):
            raise ValueError(f"unknown corruption mode {mode!r}")
        keys = [key] if key is not None else list(self._data)
        n = 0
        for k in keys:
            entry = self._data.get(k)
            if entry is None:
                continue
            if mode == "truncate":
                def cut(leaf):
                    if isinstance(leaf, tuple):
                        return tuple(cut(x) for x in leaf)
                    return leaf[:-1]
                self._data[k] = [(cut(kk), cut(vv)) for kk, vv in entry]
            else:
                def garble(leaf):
                    if isinstance(leaf, tuple):
                        return (garble(leaf[0]),) + tuple(leaf[1:])
                    out = np.array(leaf)
                    raw = out.reshape(-1).view(np.uint8)
                    raw[: min(8, raw.size)] ^= 0xFF
                    return out
                kk, vv = entry[0]
                entry[0] = (garble(kk), vv)
            n += 1
        return n

    # ------------------------------------------------------- introspection
    def snapshot(self):
        """JSON-ready occupancy/stats view for debug endpoints."""
        return {"max_bytes": self.max_bytes, "block": self.block,
                "n_blocks": self.n_blocks,
                "total_bytes": self.total_bytes,
                "stats": dict(self.stats)}


def _place_caches(caches, sharding, scale_sharding):
    """Shard-place freshly allocated caches.  A float cache leaf is one
    array; an int8 cache leaf is a ``(data, scale)`` pair whose scale
    array has no trailing ``D`` axis, so it takes its OWN head-sharded
    spec (serving/sharding.kv_scale_pspec) rather than the data spec."""
    def put(leaf):
        if isinstance(leaf, tuple):
            return (jax.device_put(leaf[0], sharding),
                    jax.device_put(leaf[1], scale_sharding
                                   if scale_sharding is not None
                                   else sharding))
        return jax.device_put(leaf, sharding)
    return [(put(k), put(v)) for k, v in caches]


class KVPoolExhausted(RuntimeError):
    """A block allocation could not be satisfied even after evicting
    every refcount-0 cached block.  The engine treats this as
    back-pressure (defer the admission, shed on queue overflow) — never
    a crash mid-stream, because admission reserves a request's worst-case
    block budget up front."""


class KVCacheManager:
    """Slot allocator + KV-cache owner for one fixed-batch engine."""

    def __init__(self, n_layers, batch_size, max_len, num_kv_heads,
                 head_dim, dtype, sharding=None, scale_sharding=None):
        self.batch_size = int(batch_size)
        self.max_len = int(max_len)
        caches = [init_kv_cache(self.batch_size, self.max_len,
                                num_kv_heads, head_dim, dtype)
                  for _ in range(n_layers)]
        if sharding is not None:
            caches = _place_caches(caches, sharding, scale_sharding)
        self.caches = caches
        self.sharding = sharding
        # host mirrors of per-slot device state
        self.lengths = np.zeros((self.batch_size,), np.int32)
        self.reqs = [None] * self.batch_size

    # ------------------------------------------------------------- slots
    def free_slots(self):
        """Free slot indices, lowest first (the admission fill order)."""
        return [i for i in range(self.batch_size) if self.reqs[i] is None]

    def occupied(self):
        """Count of slots holding a live request."""
        return sum(r is not None for r in self.reqs)

    def any_live(self):
        return any(r is not None for r in self.reqs)

    def live_tokens(self):
        """Total context tokens held by live slots (capacity-utilisation
        numerator: dense strands ``B*Lmax - live_tokens`` cache rows)."""
        return int(sum(int(self.lengths[i])
                       for i in range(self.batch_size)
                       if self.reqs[i] is not None))

    def assign(self, slot, request):
        """Bind ``request`` to ``slot`` (admission).  Assigning over a
        live slot raises: the old occupant's cache rows would be silently
        orphaned and its retirement would then double-free the slot."""
        if self.reqs[slot] is not None:
            raise ValueError(
                f"slot {slot} already holds request "
                f"{getattr(self.reqs[slot], 'rid', None)!r} — release it "
                "before assigning (double-assign orphans the occupant)")
        self.reqs[slot] = request

    def release(self, slot):
        """Free ``slot`` (retirement).  The cache rows are NOT touched:
        ``device_lengths`` parks the slot at ``max_len`` so subsequent
        writes drop, and the next occupant's prefill overwrites them.
        Releasing a free slot raises: a silent double-free lets two
        admissions claim the same slot from ``free_slots``."""
        if self.reqs[slot] is None:
            raise ValueError(
                f"slot {slot} is already free — double-release corrupts "
                "the slot free list")
        self.reqs[slot] = None

    # ------------------------------------------------------------ device
    def device_lengths(self, active):
        """The device lengths operand for one dispatch: the host mirror
        with every non-``active`` slot masked to ``max_len`` (write-drop
        parking)."""
        return masked_lengths(jnp.asarray(self.lengths),
                              jnp.asarray(active), self.max_len)


class PagedKVCacheManager(KVCacheManager):
    """Block allocator + radix prefix cache over a paged KV pool.

    Same slot interface as the dense manager (the engine's scheduler is
    geometry-blind) plus the block machinery:

    * ``caches`` — per-layer ``(k, v)`` POOL pairs ``[N, C, Hkv, D]``
      where ``N = max_live_tokens // C``.  Concurrency is budgeted in
      TOKENS, not slots: the engine may run far more slots than
      ``N*C / Lmax`` dense equivalents as long as live contexts fit.
    * ``block_tables`` — host int32 ``[B, W]`` (``W = Lmax / C``) mirror
      of each slot's logical-chunk -> physical-block chain; unmapped
      entries hold the sentinel ``N`` so device writes there DROP (the
      paged continuation of the write-drop parking invariant).
    * refcounts / radix map / LRU — see the module docstring.

    Every block is in exactly one of three states: on the free list,
    LIVE (refcount > 0), or EVICTABLE (refcount 0 but still registered
    as a cached prefix, tracked LRU).  ``refcnt[child] <= refcnt[parent]``
    holds along every registered chain because prefixes are adopted and
    released whole — which is what makes subtree eviction safe.
    """

    def __init__(self, n_layers, batch_size, max_len, num_kv_heads,
                 head_dim, dtype, block, max_live_tokens, sharding=None,
                 on_event=None, scale_sharding=None, host_store=None):
        self.batch_size = int(batch_size)
        self.max_len = int(max_len)
        self.block = int(block)
        if self.block <= 0 or self.max_len % self.block:
            raise ValueError(
                f"kv block ({block}) must divide max_len ({max_len}): the "
                "paged read is the chunked loop and a partial tail block "
                "would break the clamped-tail masking")
        self.width = self.max_len // self.block
        self.num_blocks = int(max_live_tokens) // self.block
        if self.num_blocks < self.width:
            raise ValueError(
                f"max_live_tokens ({max_live_tokens}) must cover at least "
                f"one full-length request ({max_len} tokens): a smaller "
                "pool could never admit a valid submit() and would defer "
                "it forever")
        caches = [init_kv_pool(self.num_blocks, self.block, num_kv_heads,
                               head_dim, dtype) for _ in range(n_layers)]
        if sharding is not None:
            caches = _place_caches(caches, sharding, scale_sharding)
        self.caches = caches
        self.sharding = sharding
        self.lengths = np.zeros((self.batch_size,), np.int32)
        self.reqs = [None] * self.batch_size
        # ---- block state (host-side; sentinel num_blocks = unmapped)
        self.block_tables = np.full((self.batch_size, self.width),
                                    self.num_blocks, np.int32)
        self.refcnt = np.zeros((self.num_blocks,), np.int32)
        self._free = list(range(self.num_blocks - 1, -1, -1))  # pop() -> 0
        self._mapped = [0] * self.batch_size       # chunks mapped per slot
        self._resv_left = [0] * self.batch_size    # reserved, unallocated
        # ---- draft tenancy: a SECOND chain per slot for the resident
        # draft model's KV.  Blocks are model-agnostic bytes, so draft
        # chains draw from the same free list / refcounts / allocator —
        # the manager only keeps the chains (and the radix namespace,
        # below) apart.  Draft blocks are freed OUTRIGHT at refcount 0
        # (never LRU-parked, never host-demoted): draft KV is the small
        # model's — cheap to recompute — and parking it would displace
        # target prefixes from the LRU and the host tier.
        self.draft_tables = np.full((self.batch_size, self.width),
                                    self.num_blocks, np.int32)
        self._dmapped = [0] * self.batch_size      # draft chunks per slot
        self._draft_blocks = set()                 # live draft block ids
        # ---- radix prefix map (root parent id = -1)
        self._node = {}     # (parent_block, chunk tokens) -> block id
        self._key_of = {}   # registered block id -> its key
        self._kids = {}     # parent block id -> set(registered child ids)
        self._lru = {}      # evictable block id -> release tick
        self._tick = 0
        self._on_event = on_event
        # ---- host tier (BlockStore): eviction demotes instead of
        # destroying; staged (keys, device leaves) pairs wait here for
        # the engine's between-steps pump to materialize them
        if host_store is not None and host_store.block != self.block:
            raise ValueError(
                f"host tier block size ({host_store.block}) must match "
                f"the pool block size ({self.block}): content keys are "
                "chunked at the block width")
        self._host = host_store
        self._pending_demote = []

    def _emit(self, kind, **info):
        if self._on_event is not None:
            self._on_event(kind, **info)

    def _check_block(self, b):
        if not 0 <= b < self.num_blocks:
            raise ValueError(
                f"block index {b} out of range [0, {self.num_blocks})")

    # ---------------------------------------------------------- accounting
    def free_count(self):
        return len(self._free)

    def evictable_count(self):
        return len(self._lru)

    def blocks_used(self):
        """Blocks that are live OR holding an evictable cached prefix."""
        return self.num_blocks - len(self._free)

    def draft_blocks_used(self):
        """LIVE draft-chain blocks.  Draft blocks are freed outright at
        refcount 0 (see ``__init__``), so this returns to 0 once every
        spec request drains — the ``serving_kv_blocks_used{model=draft}``
        accounting invariant."""
        return len(self._draft_blocks)

    def outstanding(self):
        """Blocks promised to admitted slots but not yet allocated."""
        return sum(self._resv_left)

    def can_reserve(self, n_blocks):
        """Whether ``n_blocks`` NEW allocations can be promised without
        starving any slot's existing reservation.  Evictable blocks count
        as available — the allocator reclaims them on demand."""
        return n_blocks <= (len(self._free) + len(self._lru)
                            - self.outstanding())

    def reserve(self, slot, n_blocks):
        """Record ``slot``'s remaining worst-case block budget (admission
        time, after shared prefix chunks are subtracted).  ``ensure_rows``
        draws it down; ``release`` clears it."""
        self._resv_left[slot] = int(n_blocks)

    # ---------------------------------------------------------- allocator
    def _content_key(self, b):
        """The host-tier content key of registered block ``b``: its chunk
        path from the radix root, spelled as ``chunk_keys`` nests it."""
        parts = []
        while b != -1:
            parent, chunk = self._key_of[b]
            parts.append(chunk)
            b = parent
        key = None
        for chunk in reversed(parts):
            key = (key, chunk)
        return key

    def _evict_subtree(self, root):
        """Reclaim evictable ``root`` and every registered descendant
        (all refcount-0 by the chain invariant) back to the free list.

        With a host tier attached this is DEMOTION, not destruction: the
        subtree's block data is gathered off-pool here (``export_chain``
        — an async device dispatch; program order runs it before any
        later write to the freed blocks) and staged with its content
        keys for ``pump_host_tier`` to materialize between steps.
        Nothing blocks on the step path."""
        parent = self._key_of[root][0]
        self._kids.get(parent, set()).discard(root)
        demote = self._host is not None
        stack = [(root, self._content_key(root) if demote else None)]
        n, order, keys = 0, [], []
        while stack:
            b, ck = stack.pop()
            if self.refcnt[b] != 0:
                raise RuntimeError(
                    f"prefix chain invariant broken: evicting block {b} "
                    f"with refcount {int(self.refcnt[b])}")
            for kid in self._kids.pop(b, ()):
                stack.append(
                    (kid, (ck, self._key_of[kid][1]) if demote else None))
            if demote and not self._host.has(ck):
                order.append(b)
                keys.append(ck)
            self._node.pop(self._key_of.pop(b), None)
            self._lru.pop(b, None)
            self._free.append(b)
            n += 1
            self._emit("block_free", block=int(b), evicted=True)
        if order:
            self._pending_demote.append((keys, self.export_chain(order)))
        return n

    def alloc_block(self):
        """One free block (refcount 1), evicting the least-recently-
        released cached prefix subtree if the free list is dry.  Raises
        ``KVPoolExhausted`` when every block is live."""
        if not self._free:
            if not self._lru:
                raise KVPoolExhausted(
                    f"kv pool exhausted: all {self.num_blocks} blocks of "
                    f"{self.block} tokens are live")
            self._evict_subtree(min(self._lru, key=self._lru.get))
        b = self._free.pop()
        self.refcnt[b] = 1
        self._emit("block_alloc", block=int(b))
        return b

    def free_block(self, b):
        """Drop one reference.  At refcount 0 a registered block parks as
        EVICTABLE (its cached prefix stays matchable); an unregistered one
        returns to the free list.  Underflow and OOB raise — a silent
        double-free would let two slots claim the same physical block."""
        b = int(b)
        self._check_block(b)
        if self.refcnt[b] <= 0:
            raise ValueError(
                f"refcount underflow: block {b} is already free "
                "(double-free corrupts the pool)")
        self.refcnt[b] -= 1
        if self.refcnt[b] == 0:
            if b in self._draft_blocks:
                # draft policy: unregister from the draft radix namespace
                # and free outright — never LRU-park, never demote
                key = self._key_of.pop(b, None)
                if key is not None:
                    self._node.pop(key, None)
                    self._kids.get(key[0], set()).discard(b)
                    self._kids.pop(b, None)
                self._draft_blocks.discard(b)
                self._free.append(b)
            elif b in self._key_of:
                self._tick += 1
                self._lru[b] = self._tick
            else:
                self._free.append(b)
            self._emit("block_free", block=b, evicted=False)

    def ensure_rows(self, slot, upto):
        """Grow ``slot``'s chain to cover logical rows ``[0, upto)``
        (called before every dispatch that may write those rows).  Rows
        past ``max_len`` are silently capped — the device drops those
        writes anyway (parking invariant)."""
        need = min(-(-int(upto) // self.block), self.width)
        while self._mapped[slot] < need:
            b = self.alloc_block()
            self.block_tables[slot, self._mapped[slot]] = b
            self._mapped[slot] += 1
            if self._resv_left[slot] > 0:
                self._resv_left[slot] -= 1
        return self._mapped[slot]

    def ensure_draft_rows(self, slot, upto):
        """Grow ``slot``'s DRAFT chain to cover logical rows
        ``[0, upto)`` — the draft-model twin of ``ensure_rows``, drawing
        the same free list and the same admission reservation (a spec
        engine reserves both chains' worst case up front)."""
        need = min(-(-int(upto) // self.block), self.width)
        while self._dmapped[slot] < need:
            b = self.alloc_block()
            self._draft_blocks.add(b)
            self.draft_tables[slot, self._dmapped[slot]] = b
            self._dmapped[slot] += 1
            if self._resv_left[slot] > 0:
                self._resv_left[slot] -= 1
        return self._dmapped[slot]

    # ------------------------------------------------------- prefix reuse
    def match_prefix(self, tokens, touch=True):
        """Longest cached prefix of ``tokens`` -> (matched_tokens, blocks).

        Only FULL blocks are shareable, and the match is capped at
        ``((p-1)//C)*C`` so at least one suffix token always prefills —
        the suffix forward is what produces the first-token logits.

        A match is a HIT: with ``touch`` (the admission default) every
        matched block still parked EVICTABLE gets a fresh LRU tick, so a
        hot shared prefix cannot be reclaimed ahead of a cold one just
        because nobody released it recently (before this fix only
        ``release`` moved the LRU clock).  Pure probes — a router asking
        every replica, ``prefix_lookup`` — pass ``touch=False`` so
        asking does not fake heat."""
        cap = max(0, (len(tokens) - 1) // self.block)
        parent, out = -1, []
        for k in range(cap):
            chunk = tuple(int(t) for t in
                          tokens[k * self.block:(k + 1) * self.block])
            b = self._node.get((parent, chunk))
            if b is None:
                break
            if touch and b in self._lru:
                self._tick += 1
                self._lru[b] = self._tick
            out.append(b)
            parent = b
        return len(out) * self.block, out

    def adopt_prefix(self, slot, blocks):
        """Map shared prefix ``blocks`` at the head of fresh ``slot``'s
        chain (admission after a radix hit): refcounts bump and evictable
        blocks return to LIVE.  Decode never writes below the adopted
        span, so no copy-on-write is needed."""
        if self._mapped[slot]:
            raise ValueError(
                f"adopt_prefix: slot {slot} already maps "
                f"{self._mapped[slot]} blocks")
        for w, b in enumerate(blocks):
            b = int(b)
            self._check_block(b)
            self.refcnt[b] += 1
            if self.refcnt[b] == 1:
                self._lru.pop(b, None)
            self.block_tables[slot, w] = b
        self._mapped[slot] = len(blocks)

    def register_prefix(self, slot, tokens):
        """Publish ``slot``'s full-block prefix chain into the radix map.

        Called at FIRST-TOKEN EMISSION (after the prefill's finite check
        passed), never at dispatch — registering earlier could publish
        NaN-poisoned blocks that a later hit would silently adopt.  First
        writer wins per chunk key; on a collision (two identical prompts
        prefilled concurrently) the rest of our chain stays private —
        mixing blocks across chains would break the refcount ordering
        that makes subtree eviction safe."""
        parent = -1
        n_full = min(len(tokens) // self.block, self._mapped[slot])
        for k in range(n_full):
            chunk = tuple(int(t) for t in
                          tokens[k * self.block:(k + 1) * self.block])
            key = (parent, chunk)
            b = int(self.block_tables[slot, k])
            cur = self._node.get(key)
            if cur is None:
                self._node[key] = b
                self._key_of[b] = key
                self._kids.setdefault(parent, set()).add(b)
                parent = b
            elif cur == b:          # adopted shared block: walk through
                parent = b
            else:                   # lost the race: keep the rest private
                break

    # ------------------------------------------------ draft radix namespace
    # The draft model's prefix chains live in the SAME radix structures
    # (_node/_key_of/_kids) under a salted root chunk, so two concurrent
    # requests with an identical prompt share one draft prefix chain the
    # same way they share the target's — while a draft chunk can never
    # collide with (or be adopted as) a target chunk, and its host-tier
    # content key is salted by construction.  Unlike target chunks, draft
    # chunks are only shareable while some slot still references them:
    # refcount 0 frees a draft block outright (see ``free_block``).

    _DRAFT_SALT = "__draft__"

    def _draft_chunk(self, tokens, k):
        chunk = tuple(int(t) for t in
                      tokens[k * self.block:(k + 1) * self.block])
        return ((self._DRAFT_SALT,) + chunk) if k == 0 else chunk

    def match_draft_prefix(self, tokens, touch=True):
        """Longest LIVE draft-namespace prefix of ``tokens`` ->
        (matched_tokens, blocks) — ``match_prefix`` over the salted
        namespace (``touch`` kept for interface symmetry; draft blocks
        never sit in the LRU, so there is no heat to fake)."""
        cap = max(0, (len(tokens) - 1) // self.block)
        parent, out = -1, []
        for k in range(cap):
            b = self._node.get((parent, self._draft_chunk(tokens, k)))
            if b is None:
                break
            out.append(b)
            parent = b
        return len(out) * self.block, out

    def register_draft_prefix(self, slot, tokens):
        """Publish ``slot``'s full-block DRAFT chain into the salted
        namespace — ``register_prefix``'s first-writer-wins walk over
        ``draft_tables``."""
        parent = -1
        n_full = min(len(tokens) // self.block, self._dmapped[slot])
        for k in range(n_full):
            key = (parent, self._draft_chunk(tokens, k))
            b = int(self.draft_tables[slot, k])
            cur = self._node.get(key)
            if cur is None:
                self._node[key] = b
                self._key_of[b] = key
                self._kids.setdefault(parent, set()).add(b)
                parent = b
            elif cur == b:
                parent = b
            else:
                break

    def adopt_draft_prefix(self, slot, blocks):
        """Map shared draft ``blocks`` at the head of ``slot``'s fresh
        draft chain (admission after a ``match_draft_prefix`` hit) —
        refcounts bump exactly like ``adopt_prefix``."""
        if self._dmapped[slot]:
            raise ValueError(
                f"adopt_draft_prefix: slot {slot} already maps "
                f"{self._dmapped[slot]} draft blocks")
        for w, b in enumerate(blocks):
            b = int(b)
            self._check_block(b)
            self.refcnt[b] += 1
            self.draft_tables[slot, w] = b
        self._dmapped[slot] = len(blocks)

    # ---------------------------------------------------------- host tier
    @property
    def host_tier(self):
        """The attached ``BlockStore`` demotion target (None = eviction
        destroys, the pre-tier behavior)."""
        return self._host

    def _block_spec(self):
        """Expected per-block leaf structure for host-tier validation:
        per-layer ``(k, v)`` of ``(shape, dtype)`` descriptors over ONE
        block's rows (tuple-nested on int8 pools)."""
        def spec(leaf):
            if isinstance(leaf, tuple):
                return tuple(spec(x) for x in leaf)
            return (tuple(leaf.shape[1:]), str(leaf.dtype))
        return [(spec(k), spec(v)) for k, v in self.caches]

    def host_match(self, tokens, matched_tokens):
        """Host-tier tokens CONTINUING a device match of
        ``matched_tokens``: contiguous chunks present in the store from
        the device break onward, capped like ``match_prefix`` so at
        least one suffix token always prefills.  Pure probe — no store
        LRU touch."""
        if self._host is None:
            return 0
        cap = max(0, (len(tokens) - 1) // self.block)
        k0 = int(matched_tokens) // self.block
        n = 0
        for k, key in enumerate(chunk_keys(tokens[:cap * self.block],
                                           self.block)):
            if k < k0:
                continue
            if not self._host.has(key):
                break
            n += 1
        return n * self.block

    def restore_from_host(self, tokens, rid=None, min_blocks=1):
        """Rehydrate the host-tier continuation of ``tokens`` into
        freshly allocated, EVICTABLE-registered blocks; returns blocks
        restored.  The caller (admission) simply re-runs
        ``match_prefix`` afterwards and adopts through the ordinary
        radix path — restored blocks enter the exact state a released
        registered chain parks in (refcount 0, fresh LRU tick), so no
        new invariants exist.

        Chains shorter than ``min_blocks`` are left to suffix prefill
        (the restore-vs-reprefill crossover knob).  Validation failures
        (a corrupted store entry) stop the walk at the bad chunk, emit
        ``host_error`` and leave earlier restored blocks in place —
        wrong bytes are never spliced.  Allocation stops rather than
        evict any block of the chain being extended (or just restored):
        a restore must not cannibalize its own prefix."""
        if self._host is None:
            return 0
        cap = max(0, (len(tokens) - 1) // self.block)
        keys = chunk_keys(tokens[:cap * self.block], self.block)
        # device walk: the chain restore continues, protected from the
        # allocator below (match_prefix's touch already refreshed these
        # at admission, but a tiny pool can still reach them)
        parent, k0, protected = -1, 0, set()
        for k, key in enumerate(keys):
            b = self._node.get((parent, key[1]))
            if b is None:
                break
            parent = b
            protected.add(b)
            k0 = k + 1
        spec = self._block_spec()
        entries, errors = [], 0
        for k in range(k0, cap):
            if not self._host.has(keys[k]):
                break
            leaves = self._host.fetch(keys[k], spec)
            if leaves is None:
                errors += 1
                self._emit("host_error", rid=rid,
                           key=BlockStore.key_digest(keys[k]))
                break
            entries.append((keys[k][1], leaves))
        if not errors and len(entries) < max(1, int(min_blocks)):
            return 0
        if not entries:
            return 0
        blocks = []
        for _ in entries:
            if not self._free:
                if not self._lru:
                    break
                if min(self._lru, key=self._lru.get) in protected:
                    break
            blocks.append(self.alloc_block())
            protected.add(blocks[-1])
        entries = entries[:len(blocks)]
        if not blocks:
            return 0
        # one functional scatter per pool leaf for the whole restored run
        def stack(li, which):
            parts = [e[1][li][which] for e in entries]
            if isinstance(parts[0], tuple):
                return tuple(np.stack([p[j] for p in parts])
                             for j in range(len(parts[0])))
            return np.stack(parts)
        stacked = [(stack(li, 0), stack(li, 1))
                   for li in range(len(self.caches))]
        self.caches = kv_transfer(self.caches, blocks, stacked)
        nbytes = 0
        for b, (chunk, leaves) in zip(blocks, entries):
            key = (parent, chunk)
            self._node[key] = b
            self._key_of[b] = key
            self._kids.setdefault(parent, set()).add(b)
            self.refcnt[b] = 0
            self._tick += 1
            self._lru[b] = self._tick
            parent = b
            nbytes += sum(_leaf_nbytes(kk) + _leaf_nbytes(vv)
                          for kk, vv in leaves)
        self._emit("restore", rid=rid, n_blocks=len(blocks), bytes=nbytes,
                   key=BlockStore.key_digest(self._content_key(blocks[0])))
        return len(blocks)

    def pump_host_tier(self):
        """Materialize every staged demotion into the host store — the
        engine calls this BETWEEN scheduler steps (never inside the
        dispatch loop; the ``_kv_transfer`` block lands here, where the
        eviction-time gathers finished long ago).  Returns blocks
        demoted."""
        if self._host is None or not self._pending_demote:
            return 0
        staged, self._pending_demote = self._pending_demote, []
        demoted = 0
        for keys, leaves in staged:
            host = _kv_transfer(leaves)

            def cut(leaf, i):
                if isinstance(leaf, tuple):
                    return tuple(cut(x, i) for x in leaf)
                return np.ascontiguousarray(leaf[i])
            stored_n, stored_bytes = 0, 0
            for i, key in enumerate(keys):
                per_block = [(cut(kk, i), cut(vv, i)) for kk, vv in host]
                stored, evicted = self._host.put(key, per_block)
                for ek in evicted:
                    self._emit("host_evict",
                               key=BlockStore.key_digest(ek))
                if stored:
                    stored_n += 1
                    stored_bytes += self._host.nbytes_of(key)
            if stored_n:
                demoted += stored_n
                self._emit("demote", n_blocks=stored_n,
                           bytes=stored_bytes,
                           key=BlockStore.key_digest(keys[0]))
        return demoted

    def corrupt_host(self, tokens=None, mode="truncate"):
        """Damage the host-tier entries along ``tokens``'s chunk chain
        (or every entry when None) — the manager half of the
        ``FaultPlan(host_tier_corrupt=...)`` seam.  Returns entries
        damaged."""
        if self._host is None:
            return 0
        if tokens is None:
            return self._host.corrupt(None, mode=mode)
        n = 0
        for key in chunk_keys(tokens, self.block):
            if self._host.has(key):
                n += self._host.corrupt(key, mode=mode)
        return n

    # ------------------------------------------------- block-chain transfer
    # The prefill/decode split (serving/disagg.py) ships a finished
    # request's KV as its BLOCK CHAIN: export gathers the chain's rows out
    # of every pool leaf ([n, C, Hkv, D] data + [n, C, Hkv] int8 scales —
    # the head axis stays at index 2, so the TP pool pspec applies to the
    # transfer leaves unchanged), import scatters them into freshly
    # allocated blocks of ANOTHER pool, and splice maps those blocks under
    # a fresh slot's table row.  Block-table indirection is what makes the
    # handoff shape-free: the decode programs see new table VALUES, never
    # new shapes, so a migrated request decodes with zero retraces.

    def block_chain(self, rid):
        """The physical block ids backing request ``rid``'s mapped chain,
        logical order.  Public accessor for export / accounting tests —
        disagg code never walks ``block_tables``/``_mapped`` directly."""
        for slot, r in enumerate(self.reqs):
            if r is not None and r.rid == rid:
                return [int(self.block_tables[slot, w])
                        for w in range(self._mapped[slot])]
        raise KeyError(f"no resident request with rid {rid!r}")

    def export_chain(self, blocks):
        """Gather chain ``blocks``'s rows out of every pool leaf ->
        per-layer ``(k, v)`` transfer leaves (``[n, C, Hkv, D]`` data,
        plus ``[n, C, Hkv]`` scales on int8 pools).  An eager device
        gather: the copies are materialized in device program order, so
        the source blocks may be released (and even rewritten by later
        dispatches) immediately after this returns."""
        for b in blocks:
            self._check_block(int(b))
        ids = jnp.asarray(np.asarray(blocks, np.int32))

        def take(leaf):
            if isinstance(leaf, tuple):
                return (leaf[0][ids], leaf[1][ids])
            return leaf[ids]
        return [(take(k), take(v)) for k, v in self.caches]

    def import_chain(self, leaves):
        """Scatter transfer ``leaves`` (``export_chain``'s output, one
        ``(k, v)`` per layer) into freshly allocated blocks of THIS pool;
        returns the new block ids (each refcount 1, owned by the caller
        until spliced or freed).  All-or-nothing: if the pool cannot
        cover the whole chain, every partially allocated block is
        returned to the free list and ``KVPoolExhausted`` propagates —
        the migration abort path leaks nothing."""
        if len(leaves) != len(self.caches):
            raise ValueError(
                f"import_chain: {len(leaves)} layers of transfer leaves "
                f"for a {len(self.caches)}-layer pool")
        if isinstance(leaves[0][0], tuple) != isinstance(
                self.caches[0][0], tuple):
            raise ValueError(
                "import_chain: transfer-leaf structure does not match "
                "this pool's KV quantization (int8 pools carry "
                "(data, scale) leaf pairs) — source and destination "
                "engines must use the same kv_dtype")
        k0 = leaves[0][0]
        n = (k0[0] if isinstance(k0, tuple) else k0).shape[0]
        blocks = []
        try:
            for _ in range(n):
                blocks.append(self.alloc_block())
        except KVPoolExhausted:
            for b in blocks:
                self.free_block(b)
            raise
        ids = jnp.asarray(np.asarray(blocks, np.int32))

        def put(pool, leaf):
            if isinstance(pool, tuple):
                return (pool[0].at[ids].set(leaf[0].astype(pool[0].dtype)),
                        pool[1].at[ids].set(leaf[1].astype(pool[1].dtype)))
            return pool.at[ids].set(leaf.astype(pool.dtype))
        self.caches = [(put(kc, lk), put(vc, lv))
                       for (kc, vc), (lk, lv) in zip(self.caches, leaves)]
        return blocks

    def splice_chain(self, slot, blocks):
        """Map imported ``blocks`` at the head of fresh ``slot``'s chain
        (the decode-side half of a migration).  Unlike ``adopt_prefix``
        the blocks are already OWNED (refcount 1 from ``import_chain``),
        so ownership transfers instead of bumping — a block someone else
        still references cannot be spliced."""
        if self._mapped[slot]:
            raise ValueError(
                f"splice_chain: slot {slot} already maps "
                f"{self._mapped[slot]} blocks")
        for b in blocks:
            b = int(b)
            self._check_block(b)
            if self.refcnt[b] != 1:
                raise ValueError(
                    f"splice_chain: block {b} has refcount "
                    f"{int(self.refcnt[b])}, expected exclusive ownership "
                    "(1) from import_chain")
        for w, b in enumerate(blocks):
            self.block_tables[slot, w] = int(b)
        self._mapped[slot] = len(blocks)

    # -------------------------------------------------------------- slots
    def release(self, slot):
        """Retire ``slot``: unreference its whole chain (shared prefix
        blocks may stay EVICTABLE for the next identical prompt), reset
        the table row to the sentinel, clear the reservation.  The draft
        chain is unreferenced LEAF-FIRST so a shared draft parent stays
        registered until its registered children are gone (draft blocks
        free outright at refcount 0, unregistering as they go)."""
        super().release(slot)
        for w in range(self._mapped[slot]):
            self.free_block(int(self.block_tables[slot, w]))
        self.block_tables[slot, :] = self.num_blocks
        self._mapped[slot] = 0
        for w in range(self._dmapped[slot] - 1, -1, -1):
            self.free_block(int(self.draft_tables[slot, w]))
        self.draft_tables[slot, :] = self.num_blocks
        self._dmapped[slot] = 0
        self._resv_left[slot] = 0

    # -------------------------------------------------------------- device
    def device_tables(self):
        """The traced ``[B, W]`` block-table operand for one dispatch."""
        return jnp.asarray(self.block_tables)

    def device_draft_tables(self):
        """The traced ``[B, W]`` DRAFT block-table operand — same pool,
        second tenant."""
        return jnp.asarray(self.draft_tables)
