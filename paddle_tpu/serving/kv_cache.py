"""KV-cache + slot state machine for the serving engine.

Extracted from serving/engine.py so placement policies (tensor-parallel
head sharding today, paged block tables next — ROADMAP item 2) plug in
underneath the scheduler without re-threading it.  The manager owns the
three per-slot facts the engine's scheduling logic reads and the device
programs consume:

* ``caches`` — the per-layer ``(k, v)`` pytrees, ``[B, Lmax, Hkv, D]``
  each, preallocated once (ops.decode_attention.init_kv_cache) and
  thereafter only REBOUND by the engine to each dispatch's donated
  outputs.  With ``sharding`` set (a ``NamedSharding`` over the head
  axis — serving/sharding.kv_cache_pspec) the zeros are placed sharded
  at construction, so every later donated output inherits the layout and
  no per-step resharding ever happens.
* ``lengths`` — the host int32 mirror of each slot's device write offset
  (prompt + emitted so far).  The engine bumps it as dispatches go out;
  ``device_lengths`` masks it through
  ops.decode_attention.masked_lengths, which parks every dead slot at
  ``max_len`` so its cache writes DROP — retirement needs no reshape,
  copy-out, or recompile (the write-drop parking invariant).
* ``reqs`` — slot -> live Request (None = free).  Slot allocation is
  lowest-free-first; the engine compares stored Request objects by
  identity at drain time to discard stale pipelined tokens, so the
  manager never recycles state, only the slot index.

Everything here is host-side bookkeeping plus ONE eager masking op;
nothing dispatches a compiled step — that stays the engine's job.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops.decode_attention import init_kv_cache, masked_lengths

__all__ = ["KVCacheManager"]


class KVCacheManager:
    """Slot allocator + KV-cache owner for one fixed-batch engine."""

    def __init__(self, n_layers, batch_size, max_len, num_kv_heads,
                 head_dim, dtype, sharding=None):
        self.batch_size = int(batch_size)
        self.max_len = int(max_len)
        caches = [init_kv_cache(self.batch_size, self.max_len,
                                num_kv_heads, head_dim, dtype)
                  for _ in range(n_layers)]
        if sharding is not None:
            caches = [(jax.device_put(k, sharding),
                       jax.device_put(v, sharding)) for k, v in caches]
        self.caches = caches
        self.sharding = sharding
        # host mirrors of per-slot device state
        self.lengths = np.zeros((self.batch_size,), np.int32)
        self.reqs = [None] * self.batch_size

    # ------------------------------------------------------------- slots
    def free_slots(self):
        """Free slot indices, lowest first (the admission fill order)."""
        return [i for i in range(self.batch_size) if self.reqs[i] is None]

    def occupied(self):
        """Count of slots holding a live request."""
        return sum(r is not None for r in self.reqs)

    def any_live(self):
        return any(r is not None for r in self.reqs)

    def assign(self, slot, request):
        """Bind ``request`` to ``slot`` (admission).  Assigning over a
        live slot raises: the old occupant's cache rows would be silently
        orphaned and its retirement would then double-free the slot."""
        if self.reqs[slot] is not None:
            raise ValueError(
                f"slot {slot} already holds request "
                f"{getattr(self.reqs[slot], 'rid', None)!r} — release it "
                "before assigning (double-assign orphans the occupant)")
        self.reqs[slot] = request

    def release(self, slot):
        """Free ``slot`` (retirement).  The cache rows are NOT touched:
        ``device_lengths`` parks the slot at ``max_len`` so subsequent
        writes drop, and the next occupant's prefill overwrites them.
        Releasing a free slot raises: a silent double-free lets two
        admissions claim the same slot from ``free_slots``."""
        if self.reqs[slot] is None:
            raise ValueError(
                f"slot {slot} is already free — double-release corrupts "
                "the slot free list")
        self.reqs[slot] = None

    # ------------------------------------------------------------ device
    def device_lengths(self, active):
        """The device lengths operand for one dispatch: the host mirror
        with every non-``active`` slot masked to ``max_len`` (write-drop
        parking)."""
        return masked_lengths(jnp.asarray(self.lengths),
                              jnp.asarray(active), self.max_len)
