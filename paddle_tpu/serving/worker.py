"""Standalone fleet worker process: one PrefillWorker or DecodeWorker
on its own mesh, driven over a lightweight control channel.

``python -m paddle_tpu.serving.worker <config.json> <role> <idx>`` is
the process entry the fleet launcher (serving/launch.py) spawns.  Each
worker is a full engine in its own process — its own jax platform/
device configuration (set BEFORE jax initializes, the same bootstrap
discipline as tests/_mp_mesh_worker.py), its own compile cache, its own
metrics registry — which is the whole point of disaggregation: the
prefill mesh and the decode mesh stop sharing anything but the KV wire.

Two planes, two sockets:

* **control plane** — a UDS the worker listens on; the parent connects
  and exchanges length-prefixed pickled dicts.  Commands (``submit``,
  ``cancel``, ``stats``, ``healthz``, ``drain``, ``close``) carry a
  ``req`` id and get a matching ``reply``; the worker interleaves
  spontaneous **events** (``ready``, ``first``, ``tokens``,
  ``retired``, ``shadow_failed``, ``adopted``, ``xfer_err``, ``hb``,
  ``drained``) on the same stream.  The parent's ``FleetCoordinator``
  turns these into the familiar ``Replica`` surface.
* **data plane** — serving/transport.py's ``SocketTransport``.  A
  decode worker listens at its configured KV endpoint; a prefill worker
  lazily connects one sender per decode peer and ships each finished
  request's block chain with enough metadata (prompt, budget, first
  token) for the decode side to rebuild the caller's Request and
  ``adopt_prefilled`` it.

The serve loop never blocks on either plane: control reads are
selector-gated with a zero timeout while the engine has work, the KV
sender streams on its background thread, and the decode pump drains
``kv_transfer_recv()`` (complete chains only — the PTL017-sanctioned
non-blocking inbox).  SIGTERM flips the worker into draining: no new
admissions, resident requests run to their terminal status, a
``drained`` event, exit 0.
"""

from __future__ import annotations

import json
import logging
import os
import pickle
import selectors
import signal
import socket
import struct
import sys
import time

_LOG = logging.getLogger(__name__)

_LEN = struct.Struct("<I")
_MAX_MSG = 1 << 28


# ---------------------------------------------------------------------------
# control-plane framing (stdlib-only: launch.py imports these without
# touching jax)
# ---------------------------------------------------------------------------

def send_msg(sock, obj):
    blob = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_LEN.pack(len(blob)) + blob)


class FrameReader:
    """Incremental parser over a non-blocking socket: feed whatever
    bytes arrived, get complete messages out.  ``eof`` latches when the
    peer closes."""

    def __init__(self):
        self._buf = bytearray()
        self.eof = False

    def feed(self, data):
        if not data:
            self.eof = True
        else:
            self._buf += data

    def messages(self):
        out = []
        while True:
            if len(self._buf) < 4:
                break
            (n,) = _LEN.unpack_from(self._buf, 0)
            if n > _MAX_MSG:
                raise ValueError(f"oversized control frame ({n} bytes)")
            if len(self._buf) < 4 + n:
                break
            out.append(pickle.loads(bytes(self._buf[4:4 + n])))
            del self._buf[:4 + n]
        return out


def pump_socket(sock, reader):
    """Drain whatever the non-blocking socket holds into the reader;
    returns the complete messages that produced."""
    while True:
        try:
            data = sock.recv(65536)
        except (BlockingIOError, InterruptedError):
            break
        except OSError:
            reader.eof = True
            break
        reader.feed(data)
        if not data:
            break
    return reader.messages()


# ---------------------------------------------------------------------------
# the worker process
# ---------------------------------------------------------------------------

class _WorkerProc:
    """One role's serve loop.  Heavy imports (jax, the engine) happen in
    ``start()`` — after ``main()`` pinned the jax platform config."""

    def __init__(self, cfg, role, idx):
        self.cfg = cfg
        self.role = role
        self.idx = int(idx)
        self.name = f"{role}{idx}"
        self.draining = False
        self._ctl_listener = None
        self._ctl = None
        self._reader = FrameReader()
        self._sel = selectors.DefaultSelector()
        self._hb_t = 0.0
        self._events = []

    # ----------------------------------------------------------- bootstrap
    def start(self):
        ctl_path = self.cfg["control"][self.name]
        try:
            os.unlink(ctl_path)
        except FileNotFoundError:
            pass
        self._ctl_listener = socket.socket(socket.AF_UNIX,
                                           socket.SOCK_STREAM)
        self._ctl_listener.bind(ctl_path)
        self._ctl_listener.listen(1)

        from ..observability import MetricsRegistry
        from .metrics import DisaggMetrics
        self.registry = MetricsRegistry()
        self._dm = DisaggMetrics(self.registry, self.name)
        self._build_engine()

        conn, _ = self._ctl_listener.accept()
        conn.setblocking(False)
        self._ctl = conn
        self._sel.register(conn, selectors.EVENT_READ)
        self._event("ready", pid=os.getpid(), role=self.role,
                    pool=self._pool)
        self._flush_events()

    def _build_model(self, m=None):
        import paddle_tpu as paddle
        from ..models.llama import LlamaConfig, LlamaForCausalLM
        if m is None:
            m = self.cfg.get("model", {})
        if m.get("kind", "llama") != "llama" or \
                m.get("preset", "tiny") != "tiny":
            raise ValueError(f"unsupported model spec {m!r}")
        paddle.seed(int(m.get("seed", 0)))
        kw = {}
        if m.get("num_hidden_layers") is not None:
            kw["num_hidden_layers"] = int(m["num_hidden_layers"])
        cfg = LlamaConfig.tiny(dtype=m.get("dtype", "float32"), **kw)
        model = LlamaForCausalLM(cfg)
        model.eval()
        return model

    def _build_engine(self):
        from .disagg import DecodeWorker, PrefillWorker
        from .transport import SocketTransport, pool_spec
        model = self._build_model()
        kw = dict(self.cfg.get("engine", {}))
        kw.update(self.cfg.get(self.role, {}) or {})
        kw["registry"] = self.registry
        spec = kw.pop("spec", None)
        if self.role != "prefill" and spec is not None:
            # launch-config spec block: {"source": ..., "spec_k": ...,
            # "draft_model": {<model spec>}} — the draft model is BUILT
            # here, in the worker process (model objects don't cross the
            # config pipe)
            from .engine import SpecConfig
            if isinstance(spec, dict):
                spec = dict(spec)
                dm = spec.pop("draft_model", None)
                if dm is not None:
                    dm = self._build_model(dict(dm))
                spec = SpecConfig(draft_model=dm, **spec)
            kw["spec"] = spec
        if self.role == "prefill":
            kw.pop("mode", None)
            kw.pop("spec_k", None)
            self.worker = PrefillWorker(model, name=self.name, **kw)
            self.worker._sink = self._on_prefilled
            self._pool = pool_spec(self.worker.engine.kv_manager)
            self._senders = {}          # decode name -> SocketTransport
            self._meta = {}             # rid -> submit metadata
            self._shadow_objs = {}      # rid -> (shadow Request, _)
        else:
            self.worker = DecodeWorker(model, name=self.name, **kw)
            self._pool = pool_spec(self.worker.engine.kv_manager)
            self._kvx = SocketTransport.listen(
                self.cfg["endpoints"][self.name], self._pool,
                name=f"{self.name}-kvx")
            self._pending = []          # chains awaiting adoption
            self._resident = {}         # rid -> Request
            self._tok_out = {}          # rid -> emitted-but-unsent ids
            self._stall_mark = {}       # rid -> first stalled-at
        self.engine = self.worker.engine

    # ----------------------------------------------------- event plumbing
    def _event(self, ev, **kw):
        kw["ev"] = ev
        kw["name"] = self.name
        self._events.append(kw)

    def _flush_events(self):
        if self._ctl is None:
            return
        while self._events:
            msg = self._events.pop(0)
            try:
                send_msg(self._ctl, msg)
            except OSError:
                self._reader.eof = True
                return

    # --------------------------------------------------------- prefill side
    def _on_prefilled(self, worker, shadow, slot, first):
        """The engine's completion hook, fleet edition: emit the first
        token to the parent immediately (TTFT rides the control plane),
        then — unless the token finished the request — export the chain
        and hand it to the decode peer's background sender."""
        meta = self._meta.get(shadow.rid)
        if meta is None:
            return
        first = int(first)
        final = (meta["max_new"] <= 1
                 or (meta.get("eos") is not None
                     and first == int(meta["eos"])))
        if final:
            self._event("first", rid=shadow.rid, token=first, final=True)
            self._meta.pop(shadow.rid, None)
            return
        kv = self.engine.kv_manager
        chain = kv.block_chain(shadow.rid)
        leaves = kv.export_chain(chain)
        meta = dict(meta, first=first)
        try:
            sender = self._sender_for(meta["decode"])
            _, nbytes = sender.send(shadow.rid, leaves, meta=meta)
        except Exception as e:  # noqa: BLE001 — parent re-routes
            self._event("xfer_err", rid=shadow.rid,
                        error=f"{type(e).__name__}: {e}")
            self._meta.pop(shadow.rid, None)
            # The cached sender is poisoned (its peer died or its stream
            # broke mid-chain); evict it so the next chain reconnects —
            # a respawned peer listens at the same endpoint.
            stale = self._senders.pop(meta["decode"], None)
            if stale is not None:
                try:
                    stale.close()
                except Exception:  # noqa: BLE001 — already broken
                    pass
            return
        self._dm.transfer_bytes.inc(nbytes)
        self._meta.pop(shadow.rid, None)  # handed off: nothing left here
        self._event("first", rid=shadow.rid, token=first, final=False,
                    nbytes=nbytes, n_blocks=len(chain))

    def _sender_for(self, decode_name):
        from .transport import SocketTransport
        s = self._senders.get(decode_name)
        if s is None:
            s = SocketTransport.connect(
                self.cfg["endpoints"][decode_name], self._pool,
                name=f"{self.name}->{decode_name}")
            self._senders[decode_name] = s
        return s

    def _sweep_shadows(self):
        for rid, (shadow, _) in list(self._shadow_objs.items()):
            if not shadow.done:
                continue
            del self._shadow_objs[rid]
            if shadow.status != "done":
                self._meta.pop(rid, None)
                self._event("shadow_failed", rid=rid, status=shadow.status)

    # ---------------------------------------------------------- decode side
    def _pump_chains(self):
        """Adopt every complete chain the transport holds; defer the
        rest.  The overlap-stall clock starts the moment a chain is
        in flight while this engine could adopt — the window a blocking
        transport would have stalled the step loop."""
        import numpy as np
        now = time.perf_counter()
        free = self.engine.stats()["slots_occupied"] < \
            self.engine.stats()["slots_total"]
        if free:
            for rid, _meta in self._kvx.inflight_chains():
                self._stall_mark.setdefault(rid, now)
        self._pending.extend(self._kvx.kv_transfer_recv())
        keep = []
        for entry in self._pending:
            rid, meta = entry["rid"], entry["meta"]
            user = entry.get("_user")
            if user is None:
                from .engine import Request
                user = Request(
                    np.asarray(meta["prompt"], dtype=np.int32),
                    int(meta["max_new"]),
                    eos_token_id=meta.get("eos"), rid=rid,
                    slo_class=meta.get("slo_class"),
                    priority=int(meta.get("priority", 0)))
                user.t_submit = now
                user.output_ids.append(int(meta["first"]))
                user.t_first = now
                user.stream_cb = self._collect_tokens
                entry["_user"] = user
            if not self.engine.can_adopt(user):
                keep.append(entry)
                continue
            from .engine import EngineOverloaded
            from .kv_cache import KVPoolExhausted
            try:
                self.engine.adopt_prefilled(user, int(meta["first"]),
                                            entry["leaves"])
            except (EngineOverloaded, KVPoolExhausted):
                keep.append(entry)
                continue
            wire = (entry["t_done"] or now) - entry["t_begin"]
            mark = self._stall_mark.pop(rid, None)
            self._dm.transfer_seconds.observe(wire)
            self._dm.overlap_stall.observe(
                max(0.0, now - mark) if mark is not None else 0.0)
            self._dm.migration("ok")
            self._resident[rid] = user
            self._event("adopted", rid=rid)
        self._pending = keep

    def _collect_tokens(self, req, new_ids):
        self._tok_out.setdefault(req.rid, []).extend(
            int(i) for i in new_ids)

    def _sweep_decode(self):
        for rid, ids in list(self._tok_out.items()):
            if ids:
                self._event("tokens", rid=rid, ids=list(ids))
                ids.clear()
        for rid in list(self._resident):
            u = self._resident[rid]
            if u.done:
                del self._resident[rid]
                self._tok_out.pop(rid, None)
                self._event("retired", rid=rid, status=u.status)

    # ------------------------------------------------------------ commands
    def _handle(self, msg):
        cmd = msg.get("cmd")
        req = msg.get("req")

        def reply(**kw):
            kw.setdefault("ok", True)
            kw["reply"] = req
            try:
                send_msg(self._ctl, kw)
            except OSError:
                self._reader.eof = True

        if cmd == "submit":
            if self.role != "prefill":
                reply(ok=False, etype="ValueError",
                      error="decode workers take chains, not submits")
                return
            if self.draining:
                reply(ok=False, etype="EngineOverloaded",
                      error="worker is draining")
                return
            import numpy as np
            from .engine import Request
            shadow = Request(np.asarray(msg["prompt"], dtype=np.int32), 1,
                             rid=msg["rid"],
                             slo_class=msg.get("slo_class"),
                             priority=int(msg.get("priority", 0)))
            try:
                self.engine.submit(shadow)
            except Exception as e:  # noqa: BLE001 — etype crosses the wire
                reply(ok=False, etype=type(e).__name__, error=str(e))
                return
            self._meta[msg["rid"]] = {
                "prompt": [int(i) for i in msg["prompt"]],
                "max_new": int(msg["max_new"]),
                "eos": msg.get("eos"),
                "slo_class": msg.get("slo_class"),
                "priority": int(msg.get("priority", 0)),
                "decode": msg["decode"],
            }
            self._shadow_objs[msg["rid"]] = (shadow, None)
            reply()
        elif cmd == "cancel":
            found = self.engine.cancel(msg["rid"])
            if self.role == "prefill":
                self._meta.pop(msg["rid"], None)
            else:
                # Drop an un-adopted chain too: the parent gave up on
                # this handoff and re-routed — adopting it later would
                # decode a ghost nobody is listening to.
                before = len(self._pending)
                self._pending = [e for e in self._pending
                                 if e["rid"] != msg["rid"]]
                found = found or len(self._pending) != before
            reply(found=bool(found))
        elif cmd == "stats":
            reply(stats=self._stats())
        elif cmd == "healthz":
            reply(t=time.time(), draining=self.draining)
        elif cmd == "drain":
            self.draining = True
            reply()
        elif cmd == "close":
            self.draining = True
            self._closing = True
            reply()
        else:
            reply(ok=False, etype="ValueError",
                  error=f"unknown command {cmd!r}")

    def _stats(self):
        from ..observability.compilecache import all_monitors
        traces = {}
        for mon in all_monitors():
            for key, n in mon.trace_counts().items():
                traces[key] = traces.get(key, 0) + n
        out = {
            "name": self.name,
            "role": self.role,
            "engine": self.engine.stats(),
            "traces": traces,
            "kv_transfer_p50_s": self._dm.transfer_seconds.percentile(50),
            "overlap_stall_p50_s": self._dm.overlap_stall.percentile(50),
        }
        em = getattr(self.engine, "_m", None)
        if em is not None:
            out["adm_tpot_p95_s"] = em.tpot_admission.percentile(95)
        if self.role == "decode":
            out["transport"] = self._kvx.stats()
            out["pending_chains"] = len(self._pending)
        return out

    # ----------------------------------------------------------- serve loop
    def _has_work(self):
        if self.engine.has_work:
            return True
        if self.role == "decode":
            return bool(self._pending) or bool(self._resident) \
                or bool(self._kvx.inflight_chains())
        return bool(self._meta)

    def serve(self):
        self._closing = False
        hb = float(self.cfg.get("heartbeat_s", 1.0))
        # deadlock watchdog on the serve loop itself: the loop is
        # selector-gated (never sleeps more than 50 ms), so a stale
        # iteration beat means the loop is truly wedged — a deadlocked
        # step dispatch, a blocking handler — and the watchdog dumps
        # every thread's stack through the engine's flight recorder
        from paddle_tpu.observability.watchdog import DeadlockWatchdog
        wd_s = float(self.cfg.get("watchdog_s", 30.0) or 0.0)
        self._wd_beat = time.time()
        wd = None
        if wd_s > 0:
            wd = DeadlockWatchdog(
                lambda: self._wd_beat, stall_after=wd_s,
                recorder=self.engine.recorder, registry=self.registry,
                component=self.name).start()
        try:
            while True:
                self._wd_beat = time.time()
                busy = self._has_work()
                for key, _ in self._sel.select(0 if busy else 0.05):
                    for msg in pump_socket(key.fileobj, self._reader):
                        # host-side control plane: the np.asarray it
                        # reaches converts a submit's prompt list, not
                        # device leaves
                        self._handle(msg)  # tpu-lint: ignore[PTL004]
                if self._reader.eof:
                    # parent went away: drain what is resident and exit
                    self.draining = True
                    self._closing = True
                if self.role == "decode":
                    # chain leaves arrive as numpy off the wire; the
                    # np.asarray here wraps them for import, no device
                    # sync
                    self._pump_chains()  # tpu-lint: ignore[PTL004]
                if self.engine.has_work:
                    self.engine.step()
                if self.role == "decode":
                    self._sweep_decode()
                else:
                    self._sweep_shadows()
                now = time.monotonic()
                if now - self._hb_t >= hb:
                    self._hb_t = now
                    self._event("hb", t=time.time())
                self._flush_events()
                if self.draining and not self._has_work():
                    self._event("drained")
                    self._flush_events()
                    break
        finally:
            if wd is not None:
                wd.stop()
        self.shutdown()

    def shutdown(self):
        try:
            self.engine.close()
        except Exception:  # noqa: BLE001 — best-effort teardown
            pass
        if self.role == "prefill":
            for s in self._senders.values():
                try:
                    s.flush(timeout=5.0)
                except Exception:  # noqa: BLE001
                    pass
                s.close()
        else:
            self._kvx.close()
        self._flush_events()
        for sock in (self._ctl, self._ctl_listener):
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if len(argv) != 3:
        print("usage: python -m paddle_tpu.serving.worker "
              "<config.json> <prefill|decode> <idx>", file=sys.stderr)
        return 2
    cfg_path, role, idx = argv
    with open(cfg_path) as f:
        cfg = json.load(f)
    if role not in ("prefill", "decode"):
        print(f"unknown role {role!r}", file=sys.stderr)
        return 2

    logging.basicConfig(
        level=logging.INFO,
        format=f"%(asctime)s {role}{idx} %(levelname)s %(message)s")

    # jax platform config MUST land before jax initializes a backend —
    # same bootstrap order as tests/_mp_mesh_worker.py
    import jax
    jax.config.update("jax_platforms", cfg.get("platform", "cpu"))
    ndev = int(cfg.get("devices_per_worker", 1))
    if cfg.get("platform", "cpu") == "cpu" and ndev > 1:
        jax.config.update("jax_num_cpu_devices", ndev)

    proc = _WorkerProc(cfg, role, idx)
    signal.signal(signal.SIGTERM, lambda *_: setattr(proc, "draining", True))
    proc.start()
    proc.serve()
    return 0


if __name__ == "__main__":
    sys.exit(main())
