"""Replica: the reviewed engine-handle surface the traffic layer uses.

One :class:`Replica` wraps one :class:`~paddle_tpu.serving.engine.
ServingEngine` and is the ONLY way the router/server layer talks to it —
every method below delegates to a public engine API (``submit`` /
``cancel`` / ``step`` / ``run`` / ``drain`` / ``close`` / ``stats`` /
``prefix_lookup`` / ``slo_tracker`` / ``debug_sources``), never to a
private attribute.  That boundary is the point: the prefill/decode
split (serving/disagg.py) replaces the engine behind this handle
without the router noticing — ``Replica(DisaggCoordinator(...))`` is
exactly how a disaggregated deployment enters a router — and the handle
stays small enough to review as an API.

A Replica adds no threading, no queueing and no policy — it is a name
plus delegation.  Scheduling stays in the engine; placement stays in the
router.  With one replica and default priorities the handle is
transparent: token streams through it are byte-identical to driving the
engine directly (tested: tests/test_serving_router.py).
"""
from __future__ import annotations

__all__ = ["Replica"]


class Replica:
    """A named handle on one serving engine.

    ``name`` labels the replica in router metrics
    (``serving_replica_backlog{replica=...}``) and debug snapshots; it
    must be unique within a router.  ``engine`` is any object exposing
    the ServingEngine surface listed in the module docstring — stubs
    satisfy it in the router unit tests, which is exactly what makes the
    handle an API rather than a wrapper.
    """

    def __init__(self, engine, name="replica0"):
        self.engine = engine
        self.name = str(name)

    def __repr__(self):
        return f"Replica({self.name!r})"

    # ------------------------------------------------------------ lifecycle
    def submit(self, request):
        """Hand ``request`` to the engine's bounded admission queue.
        Raises ``EngineOverloaded`` when the engine sheds it — the
        router's cue to try the next candidate."""
        return self.engine.submit(request)

    def cancel(self, rid):
        return self.engine.cancel(rid)

    @property
    def has_work(self):
        return self.engine.has_work

    def step(self):
        """One scheduler iteration; returns tokens emitted."""
        return self.engine.step()

    def run(self):
        return self.engine.run()

    def drain(self):
        return self.engine.drain()

    def close(self):
        return self.engine.close()

    # ------------------------------------------------------------ placement
    @property
    def block_size(self):
        """Paged KV block size in tokens (None on dense engines)."""
        return self.engine.kv_block

    def prefix_match(self, tokens):
        """Longest prefix of ``tokens`` this replica already caches, in
        tokens, across BOTH serving tiers (device radix blocks plus the
        host tier's restorable continuation) — the authoritative half of
        the router's prefix-aware probe (the mirror is the predictive
        half)."""
        return self.engine.prefix_lookup(tokens)

    def queue_depth(self):
        return self.engine.queue_depth()

    def stats(self):
        """The engine's scheduling snapshot, tagged with this replica's
        name (JSON-ready)."""
        s = dict(self.engine.stats())
        s["replica"] = self.name
        return s

    def backlog(self):
        """Queued plus resident requests — the least-backlog routing
        score (resident work drains over the same steps queued work
        waits on, so both load the replica)."""
        s = self.engine.stats()
        return s["queue_depth"] + s["slots_occupied"]

    def burn_rate(self, slo_class="interactive"):
        """The replica's windowed SLO error-budget burn for
        ``slo_class`` — the least-backlog tiebreak (between two equally
        loaded replicas, route away from the one already failing its
        objective)."""
        return self.engine.slo_tracker.burn_rate(slo_class)

    # ------------------------------------------------------------ debugging
    def debug_sources(self):
        """The engine's ``/debug`` sources, name-prefixed so N replicas
        coexist under one ``MetricsExporter``."""
        return {f"{self.name}_{k}": fn
                for k, fn in self.engine.debug_sources().items()}
