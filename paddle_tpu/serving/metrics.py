"""Pre-bound observability series for one serving engine.

Extracted from serving/engine.py alongside the KVCacheManager so the
engine file holds scheduling logic only.  The series live in ``registry``
(default: the process-wide one) keyed by a ``policy`` label, so a
continuous engine and its gang baseline stay separable in one scrape.
All instrumentation is host-side bookkeeping — the compiled device
programs are untouched, which is what keeps the instrumented engine's
token outputs byte-identical to an uninstrumented run (tested:
tests/test_observability.py).
"""
from __future__ import annotations

from paddle_tpu.observability.metrics import get_registry
from paddle_tpu.observability.trace import span

__all__ = ["EngineMetrics", "DisaggMetrics"]


class EngineMetrics:
    """One engine's metric children, bound once at construction."""

    def __init__(self, registry, policy, batch_size, mesh_devices=1):
        reg = registry if registry is not None else get_registry()
        self.registry = reg
        L = ("policy",)
        lbl = {"policy": policy}
        # sharded engines label their spans with the mesh device count so
        # a single-chip run ("" — the default every host span gets) and a
        # TP run stay separable per scrape; the gauge carries the count
        mesh_label = str(mesh_devices) if mesh_devices > 1 else ""
        self.mesh_devices = reg.gauge(
            "serving_mesh_devices",
            "devices the engine's compiled programs span (1 = single-chip)",
            L).labels(**lbl)
        self.mesh_devices.set(mesh_devices)
        self.queue_depth = reg.gauge(
            "serving_queue_depth", "requests waiting for a slot",
            L).labels(**lbl)
        self.slots_occupied = reg.gauge(
            "serving_slots_occupied", "batch slots holding a live request",
            L).labels(**lbl)
        self.slots_total = reg.gauge(
            "serving_slots_total", "engine batch size", L).labels(**lbl)
        self.slots_total.set(batch_size)
        self.admitted = reg.counter(
            "serving_requests_admitted_total",
            "requests admitted into a slot", L).labels(**lbl)
        self.retired = reg.counter(
            "serving_requests_retired_total",
            "requests completed (EOS or max_new_tokens)", L).labels(**lbl)
        self.emitted = reg.counter(
            "serving_tokens_emitted_total",
            "tokens delivered to requests", L).labels(**lbl)
        self.steps = reg.counter(
            "serving_steps_total", "scheduler iterations", L).labels(**lbl)
        self._prefills = reg.counter(
            "serving_prefill_total", "slot prefills by prompt bucket",
            ("policy", "bucket"))
        self._policy = policy
        self.queue_wait = reg.histogram(
            "serving_queue_wait_seconds",
            "submit -> slot admission", L).labels(**lbl)
        self.ttft = reg.histogram(
            "serving_ttft_seconds", "submit -> first token", L).labels(**lbl)
        self.tpot = reg.histogram(
            "serving_tpot_seconds",
            "mean per-token time after the first", L).labels(**lbl)
        self.e2e = reg.histogram(
            "serving_e2e_seconds", "submit -> completion", L).labels(**lbl)
        # request-lifecycle phase histograms, fed from the RequestTrace at
        # retirement: the three legs of queued -> prefilling -> decoding ->
        # terminal (TTFT/TPOT above already cover the composite views)
        self.queue_seconds = reg.histogram(
            "serving_queue_seconds",
            "lifecycle phase: submit -> slot admission (RequestTrace)",
            L).labels(**lbl)
        self.prefill_seconds = reg.histogram(
            "serving_prefill_seconds",
            "lifecycle phase: slot admission -> first token (RequestTrace)",
            L).labels(**lbl)
        self.decode_seconds = reg.histogram(
            "serving_decode_seconds",
            "lifecycle phase: first token -> terminal status (RequestTrace)",
            L).labels(**lbl)
        # anomaly auto-dumps of the flight recorder, by trigger; every
        # reason child is pre-registered so a first scrape before any
        # anomaly shows the full zero-valued series set
        self._recorder_dumps = reg.counter(
            "flight_recorder_dumps_total",
            "anomaly-triggered flight-recorder snapshots, by trigger",
            ("policy", "reason"))
        for reason in ("timed_out", "poisoned", "retry_exhausted",
                       "stall"):
            self._recorder_dumps.labels(policy=policy, reason=reason)
        # wall-clock stamp of the most recent scheduler step: /healthz
        # derives "last-step age" from it, so a wedged engine (stuck
        # dispatch, dead loop) is visible to a router's health check
        # without parsing the full /metrics page
        self.last_step_time = reg.gauge(
            "serving_last_step_unixtime",
            "time.time() of the engine's most recent scheduler step "
            "(0 until the first step)", L).labels(**lbl)
        # keyed by exception type so a scrape distinguishes a buggy user
        # callback (TypeError) from an injected crash; the bare series is
        # pre-registered under error="Exception" so the family exports
        # zero-valued before the first crash
        self._stream_cb_errors = reg.counter(
            "serving_stream_cb_errors_total",
            "stream_cb exceptions swallowed by the scheduler, by "
            "exception type", ("policy", "error"))
        self._stream_cb_errors.labels(policy=policy, error="Exception")
        # reliability counters (pre-bound here so a Prometheus scrape sees
        # zero-valued series before the first shed/timeout/cancel/poison —
        # the registry convention every other engine series follows)
        self.shed = reg.counter(
            "serving_requests_shed_total",
            "requests rejected at submit() by the bounded admission "
            "queue (load shedding)", L).labels(**lbl)
        self.timed_out = reg.counter(
            "serving_requests_timed_out_total",
            "requests retired by deadline_ms expiry", L).labels(**lbl)
        self.cancelled = reg.counter(
            "serving_requests_cancelled_total",
            "requests retired by host-side cancel()/close()",
            L).labels(**lbl)
        self.poisoned = reg.counter(
            "serving_requests_poisoned_total",
            "requests quarantined after non-finite logits",
            L).labels(**lbl)
        self.dispatch_retries = reg.counter(
            "serving_dispatch_retries_total",
            "transient dispatch/drain failures retried with backoff",
            L).labels(**lbl)
        self.spec_drafted = reg.counter(
            "serving_spec_drafted_total",
            "draft tokens proposed per speculative round", L).labels(**lbl)
        self.spec_accepted = reg.counter(
            "serving_spec_accepted_total",
            "draft tokens accepted by the verify forward", L).labels(**lbl)
        # speculative drafting series, source-labeled: the accept-rate
        # gauge is keyed by the DRAFT SOURCE (prompt_lookup = n-gram
        # history mining, draft_model = the resident shrunk-llama
        # drafter) so an A/B scrape separates the two policies; both
        # children pre-registered, the engine points ``set_spec_source``
        # at its active one.  ``spec_draft_k`` tracks the depth actually
        # in effect — it MOVES under the adaptive-k ladder
        self.spec_accept_rate = reg.gauge(
            "serving_spec_accept_rate",
            "cumulative accepted/drafted ratio, by draft source",
            ("policy", "source"))
        for source in ("prompt_lookup", "draft_model"):
            self.spec_accept_rate.labels(policy=policy, source=source)
        self._spec_source = "prompt_lookup"
        self._spec_draft_source = reg.gauge(
            "serving_spec_draft_source",
            "draft-source info gauge: the child whose source label names "
            "the engine's drafting policy reads 1, the other "
            "pre-registered child 0", ("policy", "source"))
        for source in ("prompt_lookup", "draft_model"):
            self._spec_draft_source.labels(policy=policy, source=source) \
                .set(0)
        self.spec_draft_k = reg.gauge(
            "serving_spec_draft_k",
            "draft tokens per speculative round currently in effect "
            "(moves under the adaptive-k policy; fixed-k engines hold "
            "the constructor knob)", L).labels(**lbl)
        self.prefill_chunks = reg.counter(
            "serving_prefill_chunks_total",
            "prompt chunks dispatched by the chunked-prefill path",
            L).labels(**lbl)
        self.prefill_backlog = reg.gauge(
            "serving_prefill_backlog",
            "prompt chunks still to dispatch across slots mid-prefill",
            L).labels(**lbl)
        self.tpot_admission = reg.histogram(
            "serving_tpot_during_admission_seconds",
            "per-token decode interval observed while a prefill "
            "(monolithic or chunked) was in progress — the decode-"
            "interference histogram", L).labels(**lbl)
        self.pipeline_stall = reg.histogram(
            "serving_pipeline_stall_seconds",
            "drain-side block waiting on the inflight dispatch",
            L).labels(**lbl)
        self.inflight = reg.gauge(
            "serving_inflight_steps",
            "device steps dispatched but not yet drained", L).labels(**lbl)
        # paged-KV series (PagedKVCacheManager): block-pool occupancy,
        # the token-budget admission numerator, and the prefix-reuse
        # counters the shared-prefix bench derives its hit rate from
        # (reuse / prompt tokens).  Zero-valued on dense engines —
        # pre-registered like every other family
        # pool occupancy is TENANT-split: target = the served model's
        # chains plus evictable cached prefixes, draft = the resident
        # draft model's live chains (freed outright at refcount 0, so
        # the draft child returns to 0 after drain — the tenancy
        # accounting invariant tests pin)
        self.kv_blocks_used = reg.gauge(
            "serving_kv_blocks_used",
            "KV pool blocks live or holding an evictable cached prefix, "
            "by tenant model", ("policy", "model"))
        for model in ("target", "draft"):
            self.kv_blocks_used.labels(policy=policy, model=model)
        self.kv_blocks_free = reg.gauge(
            "serving_kv_blocks_free",
            "KV pool blocks on the free list", L).labels(**lbl)
        self.live_tokens = reg.gauge(
            "serving_live_tokens",
            "context tokens held by live slots (token-budget admission "
            "numerator; dense strands batch*max_len minus this)",
            L).labels(**lbl)
        self.prefix_reuse_tokens = reg.counter(
            "serving_prefix_reuse_tokens_total",
            "prompt tokens satisfied from cached prefix blocks instead "
            "of being prefilled", L).labels(**lbl)
        self.prompt_tokens = reg.counter(
            "serving_prompt_tokens_total",
            "prompt tokens admitted on the paged path (prefix hit-rate "
            "denominator)", L).labels(**lbl)
        # priority preemption (paged engines): parks, and the suffix
        # tokens the resumes actually re-prefilled — the recompute cost
        # the EVICTABLE park keeps small
        self.preempted = reg.counter(
            "serving_preempted_total",
            "resident requests parked by priority preemption (blocks "
            "released EVICTABLE — the radix chain survives for the "
            "suffix-cost resume)", L).labels(**lbl)
        self.preempt_resume_tokens = reg.counter(
            "serving_preempt_resume_tokens_total",
            "suffix tokens prefilled when preempted requests resumed "
            "(the adopted prefix rows were free — this counter IS the "
            "preemption recompute cost)", L).labels(**lbl)
        # tiered KV cache (host_tier_bytes=): host-store occupancy
        # gauges, tier-labeled hit counters (every tier child
        # pre-registered so a first scrape shows the full zero-valued
        # set), demotion/restore volumes, validation failures, and the
        # restore latency the restore-vs-reprefill crossover reads
        self.kv_host_blocks = reg.gauge(
            "serving_kv_host_blocks",
            "KV blocks resident in the host-RAM demotion tier",
            L).labels(**lbl)
        self.kv_host_bytes = reg.gauge(
            "serving_kv_host_bytes",
            "bytes resident in the host-RAM demotion tier (its LRU "
            "evicts at the host_tier_bytes budget)", L).labels(**lbl)
        self._prefix_hits = reg.counter(
            "serving_prefix_hits_total",
            "admissions that adopted a cached prefix, by serving tier "
            "(device = radix blocks already in the pool, host = blocks "
            "restored from the host tier, fleet = chains imported from "
            "another engine)", ("policy", "tier"))
        for tier in ("device", "host", "fleet"):
            self._prefix_hits.labels(policy=policy, tier=tier)
        self.tier_demotions = reg.counter(
            "serving_tier_demotions_total",
            "KV blocks demoted (evicted device chain copied into the "
            "host tier off the step path)", L).labels(**lbl)
        self.tier_restores = reg.counter(
            "serving_tier_restores_total",
            "KV blocks restored from the host tier at admission (a "
            "kv_transfer device_put, not a suffix prefill)",
            L).labels(**lbl)
        self.host_tier_errors = reg.counter(
            "serving_host_tier_errors_total",
            "host-tier entries dropped by restore-time validation "
            "(structure or CRC mismatch) — admission fell back to "
            "suffix prefill instead of splicing wrong bytes",
            L).labels(**lbl)
        self.tier_restore_seconds = reg.histogram(
            "serving_tier_restore_seconds",
            "admission-side wall time of one host-tier chain restore "
            "(fetch + validate + device scatter)", L).labels(**lbl)
        # KV quantization (kv_dtype=): an INFO gauge — one child per
        # known mode, the active one reads 1 — so a scrape (and
        # /debug/flightrecorder's kv_quant dispatch detail) states the
        # storage mode without string-valued metrics, plus the analytic
        # per-context-token KV traffic at int8 (0 on unquantized
        # engines; the bench A/B pins it at ~0.53x the bf16 column)
        self._kv_quant_mode = reg.gauge(
            "serving_kv_quant_mode",
            "KV cache quantization mode info gauge: the child whose "
            "mode label names the active storage scheme reads 1, every "
            "other pre-registered child 0", ("policy", "mode"))
        for mode in ("off", "int8"):
            self._kv_quant_mode.labels(policy=policy, mode=mode).set(0)
        self.hbm_gb_per_tok_q8 = reg.gauge(
            "serving_hbm_gb_per_tok_q8",
            "analytic KV bytes (GB) read per context token at int8 "
            "storage: layers * 2 * Hkv * (D + 2 scale bytes); zero when "
            "kv_dtype is unquantized", L).labels(**lbl)
        # decode-kernel selection (attn_impl=) and weight quantization
        # (weight_dtype=): the same info-gauge shape as kv_quant_mode —
        # every known child pre-registered to 0 so a scrape always shows
        # the full mode set, the active child set to 1 at construction —
        # plus the analytic int8-weight traffic column the bench A/B
        # pins against the bf16-weight baseline
        self._decode_kernel = reg.gauge(
            "serving_decode_kernel",
            "decode cache-read implementation info gauge: 'fused' (the "
            "Pallas gather+dequant+softmax kernel) or 'reference' (the "
            "chunked lax.while_loop); the active child reads 1",
            ("policy", "impl"))
        for impl in ("reference", "fused"):
            self._decode_kernel.labels(policy=policy, impl=impl).set(0)
        # prefill-kernel selection (prefill_impl=) mirrors the decode
        # info gauge, and tp_overlap is a plain valued gauge — the
        # segment count itself (0 = single fused matmul, no overlap)
        self._prefill_kernel = reg.gauge(
            "serving_prefill_kernel",
            "chunked-prefill implementation info gauge: 'fused' (the "
            "Pallas prefill+append kernel) or 'reference' (the dense "
            "fold + scatter append); the active child reads 1",
            ("policy", "impl"))
        for impl in ("reference", "fused"):
            self._prefill_kernel.labels(policy=policy, impl=impl).set(0)
        self._tp_overlap_mode = reg.gauge(
            "serving_tp_overlap_mode",
            "row-parallel TP overlap segment count: 0 when the "
            "per-layer psum runs as one fused reduction, N>=2 when the "
            "wo/down matmuls are split into N output-feature segments "
            "so each segment's collective overlaps the next matmul",
            L).labels(**lbl)
        self._weight_quant_mode = reg.gauge(
            "serving_weight_quant_mode",
            "decode matmul weight quantization mode info gauge: the "
            "child whose mode label names the active storage scheme "
            "reads 1, every other pre-registered child 0",
            ("policy", "mode"))
        for mode in ("off", "int8"):
            self._weight_quant_mode.labels(policy=policy, mode=mode).set(0)
        self.hbm_gb_per_tok_w8 = reg.gauge(
            "serving_hbm_gb_per_tok_w8",
            "analytic decode-weight bytes (GB) read per generated token "
            "at int8 storage: every projection element once (1 byte) + "
            "2 f16 scale bytes per output channel; zero when "
            "weight_dtype is unquantized", L).labels(**lbl)
        self.span_step = span("serving.step", registry=reg,
                              mesh=mesh_label)
        self.span_prefill = span("serving.prefill", registry=reg,
                                 mesh=mesh_label)
        self.span_decode = span("serving.decode", registry=reg,
                                mesh=mesh_label)
        self.span_spec = span("serving.spec_step", registry=reg,
                              mesh=mesh_label)

    def prefill(self, bucket):
        self._prefills.labels(policy=self._policy, bucket=bucket).inc()

    def prefix_hit(self, tier):
        """Count one prefix-adopting admission against ``tier``
        ('device' | 'host' | 'fleet')."""
        self._prefix_hits.labels(policy=self._policy, tier=tier).inc()

    def set_kv_quant(self, mode):
        """Point the kv-quant info gauge at ``mode`` (exactly one child
        reads 1 after this — the engine calls it once at construction)."""
        for m in ("off", "int8"):
            self._kv_quant_mode.labels(policy=self._policy, mode=m).set(
                1 if m == mode else 0)

    def set_decode_kernel(self, impl):
        """Point the decode-kernel info gauge at ``impl`` ('reference' or
        'fused') — the engine calls it once at construction."""
        for i in ("reference", "fused"):
            self._decode_kernel.labels(policy=self._policy, impl=i).set(
                1 if i == impl else 0)

    def set_prefill_kernel(self, impl):
        """Point the prefill-kernel info gauge at ``impl`` ('reference'
        or 'fused') — the engine calls it once at construction."""
        for i in ("reference", "fused"):
            self._prefill_kernel.labels(policy=self._policy, impl=i).set(
                1 if i == impl else 0)

    def set_tp_overlap(self, segments):
        """Record the TP-overlap segment count (0 = overlap off)."""
        self._tp_overlap_mode.set(int(segments))

    def set_weight_quant(self, mode):
        """Point the weight-quant info gauge at ``mode`` ('off' or
        'int8') — the engine calls it once at construction."""
        for m in ("off", "int8"):
            self._weight_quant_mode.labels(policy=self._policy, mode=m).set(
                1 if m == mode else 0)

    def stream_cb_error(self, etype):
        self._stream_cb_errors.labels(
            policy=self._policy, error=etype).inc()

    def recorder_dump(self, reason):
        """Count one anomaly auto-dump (FlightRecorder ``on_dump`` hook)."""
        self._recorder_dumps.labels(
            policy=self._policy, reason=reason).inc()

    def observe_phases(self, durations):
        """Feed the lifecycle phase histograms from a RequestTrace's
        ``durations()`` dict (absent legs are skipped — a shed request
        has no decode phase to observe)."""
        v = durations.get("queue")
        if v is not None:
            self.queue_seconds.observe(v)
        v = durations.get("prefill")
        if v is not None:
            self.prefill_seconds.observe(v)
        v = durations.get("decode")
        if v is not None:
            self.decode_seconds.observe(v)

    def terminal(self, status):
        """Bump the reliability counter for a non-``done`` terminal
        status (the ``done`` path keeps its dedicated ``retired``
        counter)."""
        c = {"shed": self.shed, "timed_out": self.timed_out,
             "cancelled": self.cancelled,
             "poisoned": self.poisoned}.get(status)
        if c is not None:
            c.inc()

    def set_spec_source(self, source):
        """Point the draft-source info gauge at ``source`` and route
        subsequent ``spec_round`` accept-rate updates to that child —
        the engine calls it once at construction."""
        self._spec_source = source
        for s in ("prompt_lookup", "draft_model"):
            self._spec_draft_source.labels(
                policy=self._policy, source=s).set(1 if s == source else 0)

    def set_kv_blocks(self, target_used, draft_used, free):
        """Post the tenant-split pool occupancy in one call (the
        engine's ``_kv_event`` hook)."""
        self.kv_blocks_used.labels(
            policy=self._policy, model="target").set(target_used)
        self.kv_blocks_used.labels(
            policy=self._policy, model="draft").set(draft_used)
        self.kv_blocks_free.set(free)

    def spec_round(self, drafted, accepted):
        self.spec_drafted.inc(drafted)
        self.spec_accepted.inc(accepted)
        total = self.spec_drafted.value
        if total:
            self.spec_accept_rate.labels(
                policy=self._policy, source=self._spec_source).set(
                self.spec_accepted.value / total)


class DisaggMetrics:
    """One DisaggCoordinator's migration series (serving/disagg.py),
    keyed by the coordinator's ``name`` label — a fleet of disagg cells
    stays separable in one scrape.  Every series (and every known label
    child) is pre-registered at construction, the registry convention:
    a scrape before the first migration shows the full zero-valued set."""

    def __init__(self, registry, name):
        reg = registry if registry is not None else get_registry()
        self.registry = reg
        L = ("coordinator",)
        lbl = {"coordinator": name}
        self.transfer_seconds = reg.histogram(
            "serving_kv_transfer_seconds",
            "one migration's KV handoff: block-chain export on the "
            "prefill pool through import into the decode pool",
            L).labels(**lbl)
        self.transfer_bytes = reg.counter(
            "serving_kv_transfer_bytes_total",
            "KV cache bytes shipped prefill -> decode (data + int8 "
            "scale leaves, every layer)", L).labels(**lbl)
        self._migrations = reg.counter(
            "serving_migrations_total",
            "prefill -> decode migrations by outcome: ok (spliced and "
            "decoding) or aborted (cancelled/expired before adoption)",
            ("coordinator", "outcome"))
        for outcome in ("ok", "aborted"):
            self._migrations.labels(coordinator=name, outcome=outcome)
        self.prefill_backlog = reg.gauge(
            "serving_prefill_worker_backlog",
            "requests queued or resident across the prefill workers",
            L).labels(**lbl)
        self.decode_backlog = reg.gauge(
            "serving_decode_worker_backlog",
            "requests resident across the decode workers plus "
            "migrations awaiting adoption", L).labels(**lbl)
        self.worker_restarts = reg.counter(
            "serving_worker_restarts_total",
            "worker processes respawned after a death was detected "
            "(fleet launcher / FaultPlan worker_kill)", L).labels(**lbl)
        self.orphan_reprefills = reg.counter(
            "serving_orphan_reprefills_total",
            "requests orphaned by a decode-worker death and resumed as "
            "a suffix prefill (prompt + emitted tokens)", L).labels(**lbl)
        self.overlap_stall = reg.histogram(
            "serving_kv_transfer_overlap_stall_seconds",
            "time a migration spent holding up an available decode slot "
            "because its chain bytes were still on the wire (0 = the "
            "transfer fully overlapped decode steps)", L).labels(**lbl)
        self._name = name

    def migration(self, outcome):
        self._migrations.labels(
            coordinator=self._name, outcome=outcome).inc()
