"""Prefix-aware request router over N serving replicas.

SGLang-style cache-aware placement (the RadixAttention lineage): the
fleet-level prefix hit rate is a PLACEMENT property — two requests
sharing a prompt prefix only reuse KV if they land on the SAME replica.
Round-robin splits every prefix family across the fleet and forfeits
most of the per-replica radix cache; this router instead sends each
request to the replica already holding the longest cached prefix of its
prompt, and falls back to least-backlog placement (SLO burn-rate
tiebreak) when no replica holds a meaningful match.

Two sources answer the "who holds my prefix" probe:

* ``Replica.prefix_match`` — the engine's own radix map, authoritative
  but LATE: an engine registers a prefix only at first-token emission
  (after the finite check), several scheduler steps after admission.
* a host-side **radix mirror** per replica (token-chunk keys, the same
  key shape as ``PagedKVCacheManager``), fed at ROUTE time with every
  prompt the router places — predictive, so the second request of a
  burst of identical prompts follows the first immediately instead of
  round-robining away while the first is still prefilling.

The router takes the max of both.  Placement is the only thing decided
here — admission, scheduling and preemption stay in the engine behind
the :class:`~paddle_tpu.serving.replica.Replica` handle.  Shed-on-
overload rides the engine's ``EngineOverloaded``: a shed at the chosen
replica falls through the remaining candidates in plan order, and only
when EVERY replica sheds does the router re-raise to the caller.

With one replica the plan is trivially that replica, so N=1 routing is
byte-identical to driving the engine directly (tested).  Off-path cost
when ``instrument=False`` and no registry: pure host dict walks — no
metric touches, no device work.
"""
from __future__ import annotations

import numpy as np

from paddle_tpu.serving.engine import EngineOverloaded

__all__ = ["Router"]

# the reason label values of serving_router_requests_total, pre-registered
# per replica at construction so a first scrape shows the full matrix
_ROUTE_REASONS = ("prefix", "backlog", "round_robin", "shed")


class _RadixMirror:
    """Host-side predictive mirror of one replica's prefix map.

    Same chunking rule as ``PagedKVCacheManager``: only full ``block``-
    token chunks are matchable, keyed ``(parent, chunk) -> node``, and a
    probe is capped at ``(len-1)//block`` chunks so the engine always has
    at least one suffix token to prefill.  Inserted at route time; never
    pruned — a stale entry costs one mis-routed request (the engine-side
    probe still wins the max), not correctness."""

    def __init__(self, block):
        self.block = int(block)
        self._node = {}
        self._n_nodes = 0

    def _chunks(self, tokens, n):
        C = self.block
        for k in range(n):
            yield tuple(int(t) for t in tokens[k * C:(k + 1) * C])

    def insert(self, tokens):
        parent = -1
        for chunk in self._chunks(tokens, len(tokens) // self.block):
            key = (parent, chunk)
            node = self._node.get(key)
            if node is None:
                self._n_nodes += 1
                node = self._node[key] = self._n_nodes
            parent = node

    def match(self, tokens):
        """Matched-token count (multiple of ``block``)."""
        parent, matched = -1, 0
        cap = max(0, (len(tokens) - 1) // self.block)
        for chunk in self._chunks(tokens, cap):
            node = self._node.get((parent, chunk))
            if node is None:
                break
            matched += self.block
            parent = node
        return matched


class Router:
    """Fan requests across ``replicas`` (:class:`Replica` handles with
    unique names).

    ``policy``: ``"prefix"`` (cache-aware, the default) or
    ``"round_robin"`` (the placement-oblivious A/B baseline — same shed
    fallback, no prefix probe).  ``min_match``: the smallest prefix
    match (tokens) worth routing on; below it placement is least-backlog
    (default: one KV block — the smallest reusable unit).  ``registry``
    + ``instrument`` gate the router metric children, pre-registered at
    construction: ``serving_router_requests_total{replica,reason}``,
    ``serving_router_prefix_hit_rate`` (fleet reuse/prompt token ratio)
    and ``serving_replica_backlog{replica}``.
    """

    def __init__(self, replicas, policy="prefix", min_match=None,
                 registry=None, instrument=True):
        if policy not in ("prefix", "round_robin"):
            raise ValueError(f"unknown router policy {policy!r}")
        self._reps = list(replicas)
        if not self._reps:
            raise ValueError("Router needs at least one replica")
        names = [rep.name for rep in self._reps]
        if len(set(names)) != len(names):
            raise ValueError(f"replica names must be unique, got {names}")
        self.policy = policy
        self._mirrors = {
            rep.name: (_RadixMirror(rep.block_size)
                       if rep.block_size else None)
            for rep in self._reps}
        blocks = [rep.block_size for rep in self._reps if rep.block_size]
        self._min_match = (int(min_match) if min_match is not None
                           else (min(blocks) if blocks else 1))
        self._rr = 0
        self._routed = {reason: 0 for reason in _ROUTE_REASONS}
        self._requests = self._backlog_g = self._hit_rate_g = None
        if instrument and registry is not None:
            self._requests = registry.counter(
                "serving_router_requests_total",
                "requests placed by the router, by replica and reason "
                "(prefix = cache-aware hit, backlog = least-backlog "
                "fallback, round_robin = baseline policy, shed = every "
                "replica refused)", ("replica", "reason"))
            self._backlog_g = registry.gauge(
                "serving_replica_backlog",
                "queued + resident requests per replica (the router's "
                "least-backlog score)", ("replica",))
            self._hit_rate_g = registry.gauge(
                "serving_router_prefix_hit_rate",
                "fleet prefix hit rate: cumulative prefix-reuse tokens / "
                "prompt tokens summed over every replica")
            for name in names:
                self._backlog_g.labels(replica=name).set(0)
                for reason in _ROUTE_REASONS:
                    self._requests.labels(replica=name, reason=reason)

    # ------------------------------------------------------------ placement
    def _plan(self, request):
        """Ranked ``(replica, reason)`` candidates for one request.
        Ranking never mutates router state — sheds walk the same list."""
        by_load = sorted(
            self._reps,
            key=lambda rep: (rep.backlog(),
                             rep.burn_rate(request.slo_class
                                           or "interactive")))
        if self.policy == "round_robin":
            n = len(self._reps)
            order = [self._reps[(self._rr + k) % n] for k in range(n)]
            self._rr += 1
            return [(rep, "round_robin") for rep in order]
        scores = {}
        for rep in self._reps:
            mirror = self._mirrors[rep.name]
            matched = rep.prefix_match(request.prompt_ids)
            if mirror is not None:
                matched = max(matched, mirror.match(request.prompt_ids))
            scores[rep.name] = matched
        best = max(scores.values())
        if best < self._min_match:
            return [(rep, "backlog") for rep in by_load]
        # longest match wins; equal matches break on load; replicas with
        # no match trail as least-backlog fallbacks for the shed walk
        ranked = sorted(by_load, key=lambda rep: -scores[rep.name])
        return [(rep, "prefix" if scores[rep.name] >= self._min_match
                 else "backlog") for rep in ranked]

    def submit(self, request):
        """Place ``request`` on the best replica, falling through the
        candidate list on ``EngineOverloaded``; re-raises only when every
        replica sheds."""
        plan = self._plan(request)
        last_err = None
        for rep, reason in plan:
            try:
                rep.submit(request)
            except EngineOverloaded as e:
                # the engine stamped status="shed"; clear it before the
                # next candidate sees the request (status is terminal —
                # it must describe the FINAL outcome, not the detour)
                request.status = None
                last_err = e
                continue
            mirror = self._mirrors[rep.name]
            if mirror is not None:
                mirror.insert(np.asarray(request.prompt_ids).reshape(-1))
            self._routed[reason] += 1
            if self._requests is not None:
                self._requests.labels(replica=rep.name,
                                      reason=reason).inc()
            self._refresh_gauges()
            return request
        request.status = "shed"
        self._routed["shed"] += 1
        if self._requests is not None:
            self._requests.labels(replica=plan[0][0].name,
                                  reason="shed").inc()
        raise last_err

    def cancel(self, rid):
        return any([rep.cancel(rid) for rep in self._reps])

    # ------------------------------------------------------------ driving
    @property
    def has_work(self):
        return any(rep.has_work for rep in self._reps)

    def step(self):
        """One scheduler iteration on every replica with work; returns
        total tokens emitted."""
        emitted = 0
        for rep in self._reps:
            if rep.has_work:
                emitted += rep.step()
        self._refresh_gauges()
        return emitted

    def run(self):
        while self.has_work:
            self.step()

    def drain(self):
        """Drain every replica; merged ``{rid: terminal status}``."""
        out = {}
        for rep in self._reps:
            out.update(rep.drain())
        self._refresh_gauges()
        return out

    def close(self):
        out = {}
        for rep in self._reps:
            out.update(rep.close())
        self._refresh_gauges()
        return out

    # ------------------------------------------------------------ telemetry
    def hit_rate(self):
        """Fleet prefix hit rate: Σ reuse tokens / Σ prompt tokens over
        every replica (0.0 before any paged admission)."""
        reuse = prompt = 0
        for rep in self._reps:
            s = rep.stats()
            reuse += s.get("prefix_reuse_tokens", 0)
            prompt += s.get("prompt_tokens", 0)
        return reuse / prompt if prompt else 0.0

    def _refresh_gauges(self):
        if self._backlog_g is None:
            return
        for rep in self._reps:
            self._backlog_g.labels(replica=rep.name).set(rep.backlog())
        self._hit_rate_g.set(self.hit_rate())

    def snapshot(self):
        """JSON-ready router state for the ``/debug/router`` endpoint."""
        return {
            "policy": self.policy,
            "min_match": self._min_match,
            "routed": dict(self._routed),
            "hit_rate": self.hit_rate(),
            "replicas": [{
                **rep.stats(),
                "backlog": rep.backlog(),
                "mirror_nodes": (
                    self._mirrors[rep.name]._n_nodes
                    if self._mirrors[rep.name] is not None else 0),
            } for rep in self._reps],
        }

    def debug_sources(self):
        """``{name: callable}`` for ``MetricsExporter``: ``/debug/router``
        plus every replica's name-prefixed engine sources."""
        out = {"router": self.snapshot}
        for rep in self._reps:
            out.update(rep.debug_sources())
        return out
