"""Cross-process KV-chain transport: length-prefixed socket framing of a
migration's block-chain leaves (serving/disagg.py's ``KVTransport``
contract over UDS or TCP).

PR 15 split prefill and decode onto dedicated workers but carried every
chain through in-process transports; this module is the bytes-on-a-wire
half that turns the split into a deployable fleet (Mooncake/DistServe's
KV-transfer plane).  One chain rides the socket as a framed stream::

    frame   := u32 length (LE) | u8 type | payload[length-1]
    type C  := control — a pickled dict ({"kind": ...})
    type D  := data — raw little-endian leaf bytes, chunk-sized

    hello(C: magic, pool geometry/dtype)  ->  ok(C) | reject(C)
    chain(C: rid, meta, leaf descriptors, data_bytes)
    data(D) * ceil(bytes/chunk)           --  per leaf component, in
                                              layer-major (k, v) order,
                                              int8 data before scale
    end(C: rid)

Design points, each load-bearing:

* **The handshake fronts the structure guard.**  ``import_chain``
  raises on a quantization-structure mismatch only after the leaves
  exist on the destination; the ``hello`` carries the pool's layer
  count, block geometry ``[*, C, Hkv, D]`` and dtype structure, so a
  mismatched pairing is rejected at *connect* time — before a single
  chain byte moves.
* **``send`` never blocks the caller.**  It enqueues the chain and
  returns ``(handle, nbytes)`` immediately; a background sender thread
  (``kv_transfer_send`` — the PTL017-sanctioned seam) pulls leaves to
  host and streams the frames, so the ~ms-scale transfer overlaps the
  decode steps running in the caller's loop.  The receive side
  reassembles complete chains into an inbox; ``ready(handle)`` lets the
  coordinator's pump defer an unarrived chain instead of stalling.
* **One serialization path.**  ``encode_chain``/``decode_chain`` are
  the exact wire framing as a contiguous blob; ``PickleTransport``
  (demoted to a test-only fallback) routes through them, so the codec
  the fleet ships is the codec every tier-1 byte-identity test
  exercises.
"""

from __future__ import annotations

import io
import logging
import os
import pickle
import socket
import struct
import tempfile
import threading
import time
from collections import OrderedDict, deque

import numpy as np

from .disagg import KVTransport, chain_nbytes

__all__ = [
    "SocketTransport",
    "pool_spec",
    "encode_chain",
    "decode_chain",
    "iter_chain_frames",
    "chain_wire_nbytes",
]

_LOG = logging.getLogger(__name__)

MAGIC = "PTKV1"
DEFAULT_CHUNK = 1 << 20
_FRAME_CTRL = b"C"
_FRAME_DATA = b"D"
_LEN = struct.Struct("<I")
# sanity bound on a single frame: the largest data frame is `chunk`
# bytes and control frames are small — anything past this is a
# corrupted length prefix, not a real frame
_MAX_FRAME = 1 << 30


# ---------------------------------------------------------------------------
# pool geometry
# ---------------------------------------------------------------------------

def pool_spec(kv):
    """The geometry/dtype identity of a ``PagedKVCacheManager``'s pool —
    everything ``import_chain`` would reject a mismatched chain over,
    lifted into the connect-time handshake: layer count, block width,
    KV head geometry, leaf dtype, and the int8 ``(data, scale)``
    structure."""
    k0 = kv.caches[0][0]
    quantized = isinstance(k0, tuple)
    data = k0[0] if quantized else k0
    spec = {
        "n_layers": len(kv.caches),
        "block": int(data.shape[1]),
        "num_kv_heads": int(data.shape[2]),
        "head_dim": int(data.shape[3]),
        "dtype": str(np.dtype(data.dtype)),
        "quantized": quantized,
    }
    if quantized:
        spec["scale_dtype"] = str(np.dtype(k0[1].dtype))
    return spec


def _pool_mismatch(mine, theirs):
    """Human-readable list of differing pool-spec keys (empty = match)."""
    keys = sorted(set(mine) | set(theirs))
    return [f"{k}: ours={mine.get(k)!r} theirs={theirs.get(k)!r}"
            for k in keys if mine.get(k) != theirs.get(k)]


# ---------------------------------------------------------------------------
# codec: chain <-> frames
# ---------------------------------------------------------------------------

def _frame(ftype, payload):
    return _LEN.pack(1 + len(payload)) + ftype + payload


def _ctrl(obj):
    return _frame(_FRAME_CTRL,
                  pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL))


def _component_descs(leaf):
    """Flat ``(shape, dtype)`` descriptors for one transfer leaf — one
    entry for a plain array, two (data then scale) for an int8 tuple."""
    if isinstance(leaf, tuple):
        return [{"q": True, "shape": tuple(leaf[0].shape),
                 "dtype": str(np.dtype(leaf[0].dtype))},
                {"q": True, "shape": tuple(leaf[1].shape),
                 "dtype": str(np.dtype(leaf[1].dtype))}]
    return [{"q": False, "shape": tuple(leaf.shape),
             "dtype": str(np.dtype(leaf.dtype))}]


def _chain_descs(leaves):
    """Per-layer ``[k_descs, v_descs]`` descriptor table plus the total
    raw data byte count (shape x itemsize — no host copy needed)."""
    descs, total = [], 0
    for k, v in leaves:
        kd, vd = _component_descs(k), _component_descs(v)
        descs.append([kd, vd])
        for d in kd + vd:
            total += int(np.prod(d["shape"], dtype=np.int64)
                         * np.dtype(d["dtype"]).itemsize)
    return descs, total


def _iter_component_arrays(leaves):
    for k, v in leaves:
        for leaf in (k, v):
            if isinstance(leaf, tuple):
                yield leaf[0]
                yield leaf[1]
            else:
                yield leaf


def iter_chain_frames(rid, leaves, meta=None, chunk=DEFAULT_CHUNK):
    """Yield the framed wire stream for one chain: the ``chain`` control
    header, the chunked data frames, the ``end`` trailer.  Device leaves
    are pulled to host lazily, one component at a time — on the sender
    thread this is where the device->host copy overlaps decode."""
    descs, total = _chain_descs(leaves)
    yield _ctrl({"kind": "chain", "rid": rid, "meta": meta,
                 "descs": descs, "data_bytes": int(total)})
    for arr in _iter_component_arrays(leaves):
        raw = np.ascontiguousarray(np.asarray(arr)).tobytes()
        for off in range(0, len(raw), chunk):
            yield _frame(_FRAME_DATA, raw[off:off + chunk])
    yield _ctrl({"kind": "end", "rid": rid})


def chain_wire_nbytes(rid, leaves, meta=None, chunk=DEFAULT_CHUNK):
    """Exact wire size of ``iter_chain_frames(rid, leaves, meta, chunk)``
    without materializing any data frame (header/trailer are built — they
    are small — and the data-frame overhead is counted analytically)."""
    descs, total = _chain_descs(leaves)
    n = len(_ctrl({"kind": "chain", "rid": rid, "meta": meta,
                   "descs": descs, "data_bytes": int(total)}))
    n += len(_ctrl({"kind": "end", "rid": rid}))
    for d in (dd for kd, vd in descs for dd in kd + vd):
        size = int(np.prod(d["shape"], dtype=np.int64)
                   * np.dtype(d["dtype"]).itemsize)
        n += size + 5 * max(1, -(-size // chunk)) if size else 5
    return n


def encode_chain(rid, leaves, meta=None, chunk=DEFAULT_CHUNK):
    """The full wire stream as one contiguous blob — what
    ``PickleTransport`` round-trips, byte-for-byte the socket framing."""
    return b"".join(iter_chain_frames(rid, leaves, meta=meta, chunk=chunk))


def _rebuild_leaves(descs, data):
    """Reassemble transfer leaves from the descriptor table plus the
    concatenated raw bytes.  Raises ``ValueError`` when the byte count
    disagrees with the descriptors (truncated or corrupted stream)."""
    mv = memoryview(data)
    off = 0
    leaves = []
    for kd, vd in descs:
        pair = []
        for comps in (kd, vd):
            arrs = []
            for d in comps:
                size = int(np.prod(d["shape"], dtype=np.int64)
                           * np.dtype(d["dtype"]).itemsize)
                if off + size > len(mv):
                    raise ValueError(
                        "truncated chain data: descriptors need "
                        f"{off + size} bytes, stream carries {len(mv)}")
                arrs.append(np.frombuffer(
                    mv[off:off + size], dtype=np.dtype(d["dtype"])
                ).reshape(d["shape"]))
                off += size
            pair.append(tuple(arrs) if len(arrs) == 2 else arrs[0])
        leaves.append((pair[0], pair[1]))
    if off != len(mv):
        raise ValueError(
            f"chain data overrun: descriptors cover {off} bytes, "
            f"stream carries {len(mv)}")
    return leaves


def _parse_frames(blob):
    """Iterate ``(type, payload)`` over a contiguous blob, raising
    ``ValueError`` on any truncation or corrupted length prefix."""
    mv = memoryview(blob)
    off = 0
    while off < len(mv):
        if off + 4 > len(mv):
            raise ValueError("truncated chain blob: partial frame length")
        (n,) = _LEN.unpack_from(mv, off)
        if n < 1 or n > _MAX_FRAME:
            raise ValueError(f"corrupted frame length {n}")
        off += 4
        if off + n > len(mv):
            raise ValueError(
                f"truncated chain blob: frame needs {n} bytes, "
                f"{len(mv) - off} remain")
        yield bytes(mv[off:off + 1]), mv[off + 1:off + n]
        off += n


def decode_chain(blob):
    """Decode one ``encode_chain`` blob back into ``(rid, leaves,
    meta)``.  Strict: the control sequence must be ``chain`` -> data ->
    ``end`` with the advertised byte count, and any truncation raises
    ``ValueError``."""
    frames = _parse_frames(blob)
    try:
        ftype, payload = next(frames)
    except StopIteration:
        raise ValueError("empty chain blob") from None
    if ftype != _FRAME_CTRL:
        raise ValueError("chain blob must open with a control frame")
    head = pickle.loads(payload)
    if head.get("kind") != "chain":
        raise ValueError(f"unexpected opening frame kind {head.get('kind')!r}")
    buf = io.BytesIO()
    done = False
    for ftype, payload in frames:
        if ftype == _FRAME_DATA:
            if done:
                raise ValueError("data frame after end-of-chain trailer")
            buf.write(payload)
        else:
            tail = pickle.loads(payload)
            if tail.get("kind") != "end" or tail.get("rid") != head["rid"]:
                raise ValueError("malformed end-of-chain trailer")
            done = True
    if not done:
        raise ValueError("truncated chain blob: missing end-of-chain trailer")
    data = buf.getvalue()
    if len(data) != head["data_bytes"]:
        raise ValueError(
            f"truncated chain blob: header advertises "
            f"{head['data_bytes']} data bytes, stream carries {len(data)}")
    return head["rid"], _rebuild_leaves(head["descs"], data), head["meta"]


# ---------------------------------------------------------------------------
# sockets
# ---------------------------------------------------------------------------

def parse_endpoint(ep):
    """``"unix:/path/kv.sock"`` -> ``("unix", path)``;
    ``"tcp:host:port"`` -> ``("tcp", (host, port))``."""
    if ep.startswith("unix:"):
        return "unix", ep[len("unix:"):]
    if ep.startswith("tcp:"):
        host, _, port = ep[len("tcp:"):].rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(f"malformed tcp endpoint {ep!r} "
                             "(want tcp:host:port)")
        return "tcp", (host, int(port))
    raise ValueError(f"unknown endpoint scheme {ep!r} "
                     "(want unix:/path or tcp:host:port)")


def _make_socket(kind):
    if kind == "unix":
        return socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    return socket.socket(socket.AF_INET, socket.SOCK_STREAM)


def _read_exact(sock, n, deadline=None):
    """Blocking exact read with an optional absolute deadline; b"" on a
    clean EOF at a frame boundary, ``TimeoutError`` past the deadline."""
    buf = bytearray()
    while len(buf) < n:
        if deadline is not None:
            left = deadline - time.monotonic()
            if left <= 0:
                raise TimeoutError("transport read timed out")
            sock.settimeout(min(left, 1.0))
        try:
            got = sock.recv(n - len(buf))
        except socket.timeout:
            continue
        if not got:
            if buf:
                raise ConnectionError("peer closed mid-frame")
            return b""
        buf += got
    return bytes(buf)


def _read_frame(sock, deadline=None):
    head = _read_exact(sock, 4, deadline)
    if not head:
        return None, None
    (n,) = _LEN.unpack(head)
    if n < 1 or n > _MAX_FRAME:
        raise ValueError(f"corrupted frame length {n}")
    body = _read_exact(sock, n, deadline)
    if len(body) != n:
        raise ConnectionError("peer closed mid-frame")
    return body[:1], body[1:]


class SocketTransport(KVTransport):
    """``KVTransport`` over a stream socket (UDS or TCP).

    Construction is via the three factories:

    * ``SocketTransport.listen(endpoint, pool)`` — the decode-side
      receiver: accepts sender connections (rejecting mismatched pool
      geometry at handshake), reassembles chains into an inbox.
    * ``SocketTransport.connect(endpoint, pool)`` — the prefill-side
      sender: handshakes once, then ``send`` enqueues chains to the
      background ``kv_transfer_send`` streamer.
    * ``SocketTransport.loopback(pool)`` — both halves over a private
      UDS in one process (the coordinator/test path): ``send`` and
      ``recv``/``ready`` on one object, with a real socket between.

    ``send(rid, leaves, meta=None)`` returns ``(rid, nbytes)`` where
    ``nbytes`` is the exact framed wire size; it never blocks on the
    transfer.  ``recv(handle)`` blocks until the chain arrives (the
    pump avoids that by gating on ``ready(handle)``);
    ``kv_transfer_recv()`` drains every complete chain — the worker-
    process pump entry point, sanctioned by tpu-lint PTL017 alongside
    ``kv_transfer_send``."""

    def __init__(self, pool, *, chunk=DEFAULT_CHUNK, name="kvx",
                 recv_timeout=60.0):
        self._pool = dict(pool)
        self._chunk = int(chunk)
        self._name = name
        self._recv_timeout = float(recv_timeout)
        self._cv = threading.Condition()
        self._closed = False
        # sender half
        self._sock = None
        self._sq = deque()
        self._send_exc = None
        self._sender = None
        self._busy = False
        self._sent_chains = 0
        self._sent_bytes = 0
        # receiver half
        self._listener = None
        self._accept_thread = None
        self._conns = []
        self._threads = []
        self._inflight = OrderedDict()   # rid -> entry (header seen)
        self._inbox = OrderedDict()      # rid -> entry (complete)
        self._recv_chains = 0
        self._recv_bytes = 0
        self._own_path = None
        self._own_dir = None

    # ------------------------------------------------------------ factories
    @classmethod
    def listen(cls, endpoint, pool, **kw):
        t = cls(pool, **kw)
        kind, addr = parse_endpoint(endpoint)
        sock = _make_socket(kind)
        if kind == "unix":
            try:
                os.unlink(addr)
            except FileNotFoundError:
                pass
            t._own_path = addr
        else:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        sock.bind(addr)
        sock.listen(16)
        t._listener = sock
        t.endpoint = endpoint
        t._accept_thread = threading.Thread(
            target=t._accept_main, name=f"{t._name}-accept", daemon=True)
        t._accept_thread.start()
        return t

    @classmethod
    def connect(cls, endpoint, pool, timeout=10.0, **kw):
        t = cls(pool, **kw)
        t._connect_sender(endpoint, timeout)
        t.endpoint = endpoint
        return t

    @classmethod
    def loopback(cls, pool, dir=None, **kw):
        own_dir = None
        if dir is None:
            dir = own_dir = tempfile.mkdtemp(prefix="ptkv-")
        path = os.path.join(dir, "kv.sock")
        t = cls.listen(f"unix:{path}", pool, **kw)
        t._own_dir = own_dir
        t._connect_sender(f"unix:{path}", timeout=10.0)
        return t

    # ------------------------------------------------------------ handshake
    def _connect_sender(self, endpoint, timeout):
        kind, addr = parse_endpoint(endpoint)
        deadline = time.monotonic() + timeout
        sock = None
        while True:
            sock = _make_socket(kind)
            sock.settimeout(max(0.05, deadline - time.monotonic()))
            try:
                sock.connect(addr)
                break
            except (ConnectionRefusedError, FileNotFoundError,
                    socket.timeout, OSError):
                sock.close()
                if time.monotonic() >= deadline:
                    raise TimeoutError(
                        f"KV transport: no listener at {endpoint} within "
                        f"{timeout:.1f}s")
                time.sleep(0.02)
        sock.sendall(_ctrl({"kind": "hello", "magic": MAGIC,
                            "pool": self._pool}))
        ftype, payload = _read_frame(sock, time.monotonic() + timeout)
        if ftype != _FRAME_CTRL:
            sock.close()
            raise ConnectionError("KV transport: handshake reply missing")
        reply = pickle.loads(payload)
        if reply.get("kind") != "ok":
            sock.close()
            raise ValueError(
                "KV transport handshake rejected: "
                + str(reply.get("error", "unknown")))
        self._sock = sock
        self._sender = threading.Thread(
            target=self._sender_main, name=f"{self._name}-send", daemon=True)
        self._sender.start()

    # --------------------------------------------------------------- sender
    def send(self, rid, leaves, meta=None):
        if self._sock is None:
            raise RuntimeError("receive-only SocketTransport cannot send "
                               "(use SocketTransport.connect/loopback)")
        with self._cv:
            if self._send_exc is not None:
                raise self._send_exc
            if self._closed:
                raise RuntimeError("SocketTransport is closed")
            self._sq.append((rid, leaves, meta))
            self._cv.notify_all()
        nbytes = chain_wire_nbytes(rid, leaves, meta=meta, chunk=self._chunk)
        return rid, nbytes

    def kv_transfer_send(self, rid, leaves, meta=None):
        """Blocking chunk-streamed write of one chain — runs on the
        background sender thread (the PTL017-sanctioned transfer seam);
        step loops go through ``send``, which only enqueues."""
        for frame in iter_chain_frames(rid, leaves, meta=meta,
                                       chunk=self._chunk):
            self._sock.sendall(frame)

    def _sender_main(self):
        while True:
            with self._cv:
                while not self._sq and not self._closed:
                    self._cv.wait(0.2)
                if not self._sq and self._closed:
                    return
                rid, leaves, meta = self._sq.popleft()
                self._busy = True
            try:
                t0 = time.perf_counter()
                self.kv_transfer_send(rid, leaves, meta=meta)
                dt = time.perf_counter() - t0
            except Exception as e:  # noqa: BLE001 — surfaced via send()
                with self._cv:
                    self._send_exc = e
                    self._busy = False
                    self._cv.notify_all()
                return
            with self._cv:
                self._busy = False
                self._sent_chains += 1
                self._sent_bytes += chain_nbytes(leaves)
                self._last_send_s = dt
                self._cv.notify_all()

    def flush(self, timeout=30.0):
        """Block until every enqueued chain is on the wire (drain /
        shutdown path, never the step loop).  Raises the sender thread's
        stored error, if any."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._sq or self._busy:
                if self._send_exc is not None:
                    raise self._send_exc
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError("SocketTransport.flush timed out")
                self._cv.wait(min(left, 0.2))
            if self._send_exc is not None:
                raise self._send_exc

    # ------------------------------------------------------------- receiver
    def _accept_main(self):
        while True:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return  # listener closed
            if self._closed:
                conn.close()
                return
            th = threading.Thread(target=self._serve_conn, args=(conn,),
                                  name=f"{self._name}-conn", daemon=True)
            self._conns.append(conn)
            self._threads.append(th)
            th.start()

    def _serve_conn(self, conn):
        try:
            ftype, payload = _read_frame(conn)
            if ftype != _FRAME_CTRL:
                return
            hello = pickle.loads(payload)
            if hello.get("kind") != "hello" or hello.get("magic") != MAGIC:
                conn.sendall(_ctrl({"kind": "reject",
                                    "error": "bad magic/hello"}))
                return
            diff = _pool_mismatch(self._pool, hello.get("pool") or {})
            if diff:
                conn.sendall(_ctrl({
                    "kind": "reject",
                    "error": "pool geometry/dtype mismatch — "
                             + "; ".join(diff)}))
                return
            conn.sendall(_ctrl({"kind": "ok", "pool": self._pool}))
            self._recv_chains_loop(conn)
        except (ConnectionError, ValueError, OSError, EOFError,
                pickle.UnpicklingError) as e:
            if not self._closed:
                _LOG.warning("KV transport connection dropped: %s", e)
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _recv_chains_loop(self, conn):
        cur = None      # (rid, header, BytesIO)
        while not self._closed:
            try:
                ftype, payload = _read_frame(conn)
            except TimeoutError:
                continue
            if ftype is None:
                break  # clean EOF
            if ftype == _FRAME_DATA:
                if cur is None:
                    raise ValueError("data frame outside a chain")
                cur[2].write(payload)
                continue
            msg = pickle.loads(payload)
            if msg["kind"] == "chain":
                entry = {"rid": msg["rid"], "meta": msg["meta"],
                         "leaves": None, "t_begin": time.perf_counter(),
                         "t_done": None}
                cur = (msg["rid"], msg, io.BytesIO())
                with self._cv:
                    self._inflight[msg["rid"]] = entry
                    self._cv.notify_all()
            elif msg["kind"] == "end":
                if cur is None or msg["rid"] != cur[0]:
                    raise ValueError("malformed end-of-chain trailer")
                rid, head, buf = cur
                cur = None
                data = buf.getvalue()
                if len(data) != head["data_bytes"]:
                    raise ValueError("chain data byte-count mismatch")
                leaves = _rebuild_leaves(head["descs"], data)
                with self._cv:
                    entry = self._inflight.pop(rid, None) or {
                        "rid": rid, "meta": head["meta"],
                        "t_begin": time.perf_counter()}
                    entry["leaves"] = leaves
                    entry["t_done"] = time.perf_counter()
                    self._inbox[rid] = entry
                    self._recv_chains += 1
                    self._recv_bytes += len(data)
                    self._cv.notify_all()
            else:
                raise ValueError(f"unexpected control kind {msg['kind']!r}")
        if cur is not None:
            with self._cv:
                self._inflight.pop(cur[0], None)

    # ------------------------------------------------------ receive surface
    def ready(self, handle):
        with self._cv:
            if self._send_exc is not None:
                raise self._send_exc
            return handle in self._inbox

    def recv(self, handle, timeout=None):
        if self._listener is None:
            raise RuntimeError("send-only SocketTransport cannot recv "
                               "(the listener lives in the decode process)")
        deadline = time.monotonic() + (timeout if timeout is not None
                                       else self._recv_timeout)
        with self._cv:
            while handle not in self._inbox:
                if self._send_exc is not None:
                    raise self._send_exc
                if self._closed:
                    raise RuntimeError("SocketTransport is closed")
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(
                        f"chain {handle!r} never arrived "
                        f"({self._recv_timeout:.1f}s)")
                self._cv.wait(min(left, 0.2))
            return self._inbox.pop(handle)["leaves"]

    def transfer_seconds(self, handle):
        with self._cv:
            e = self._inbox.get(handle)
            if e is None or e["t_done"] is None:
                return None
            return e["t_done"] - e["t_begin"]

    def kv_transfer_recv(self):
        """Drain every COMPLETE chain from the inbox, arrival order —
        the worker-process pump entry (PTL017-sanctioned): returns
        ``[{rid, leaves, meta, t_begin, t_done}, ...]`` and never
        blocks."""
        with self._cv:
            out = list(self._inbox.values())
            self._inbox.clear()
        return out

    def inflight_chains(self):
        """Chains whose header arrived but whose bytes are still on the
        wire: ``[(rid, meta), ...]`` — the overlap-stall probe set."""
        with self._cv:
            return [(e["rid"], e["meta"]) for e in self._inflight.values()]

    # ---------------------------------------------------------------- admin
    def stats(self):
        with self._cv:
            return {
                "sent_chains": self._sent_chains,
                "sent_bytes": self._sent_bytes,
                "recv_chains": self._recv_chains,
                "recv_bytes": self._recv_bytes,
                "send_queue": len(self._sq),
                "inflight": len(self._inflight),
                "inbox": len(self._inbox),
            }

    def close(self):
        with self._cv:
            if self._closed:
                return
            self._closed = True
            self._cv.notify_all()
        if self._sender is not None:
            self._sender.join(timeout=2.0)
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        for conn in self._conns:
            try:
                conn.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=2.0)
        for th in self._threads:
            th.join(timeout=2.0)
        if self._own_path is not None:
            try:
                os.unlink(self._own_path)
            except OSError:
                pass
        if self._own_dir is not None:
            try:
                os.rmdir(self._own_dir)
            except OSError:
                pass
