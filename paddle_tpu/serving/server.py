"""Streaming HTTP front end for the serving stack — stdlib asyncio only.

Same dependency policy as the observability exporter: nothing beyond the
standard library, so the front end ships wherever the engine does.  One
:class:`ServingServer` owns two threads next to the caller's:

* the **event-loop thread** runs an asyncio socket server.  Handlers
  never block (tpu-lint PTL013 polices exactly this file's failure
  mode): a generate request is handed to the driver through a
  thread-safe queue, its admission future awaited via
  ``asyncio.wrap_future``, and its tokens arrive on an ``asyncio.Queue``
  fed by ``loop.call_soon_threadsafe`` from the engine's ``stream_cb``.
* the **driver thread** owns the router/engine: it drains the submit
  handoff queue, steps the router while work exists, and notifies
  handlers whose requests reached a terminal status.  Every device
  interaction — including the engine's sanctioned blocking
  ``_host_fetch`` sync — happens HERE, never on the event loop.

API (JSON over HTTP/1.1, ``Connection: close``):

``POST /generate`` — body ``{"prompt_ids": [...], "max_new_tokens": N,
"eos_token_id"?, "deadline_ms"?, "slo_class"?, "priority"?:
"interactive"|"batch"|int, "stream"?: bool}``.  With ``stream`` (the
default) the response is ``application/x-ndjson``: one
``{"rid", "token_ids"}`` line per emission batch — over the engine's
existing ``stream_cb``, so chunk boundaries ARE the engine's emission
boundaries — then a final ``{"done": true, "rid", "status",
"n_tokens", "preempts"}`` line.  ``stream: false`` buffers and returns
one JSON object.  A fleet-wide shed maps to 503, a validation error to
400.  ``GET /healthz`` reports liveness plus the router snapshot's
vitals.  Priority classes map onto the engine's preemption integers
(``PRIORITY_CLASSES``); an int passes through.
"""
from __future__ import annotations

import asyncio
import contextlib
import json
import queue
import threading
from concurrent.futures import Future

from paddle_tpu.serving.engine import EngineOverloaded, Request

__all__ = ["PRIORITY_CLASSES", "ServingServer"]

# request priority classes -> engine preemption priorities.  Interactive
# traffic outranks batch by enough headroom that deployments can slot
# custom integer tiers between them without redefining the classes.
PRIORITY_CLASSES = {"batch": 0, "interactive": 10}

_DONE = object()   # terminal sentinel on each handler's token queue

_REASONS = {200: "OK", 400: "Bad Request", 404: "Not Found",
            503: "Service Unavailable"}


def _priority_of(value):
    """Engine priority int for a request body's ``priority`` field."""
    if isinstance(value, str):
        try:
            return PRIORITY_CLASSES[value]
        except KeyError:
            raise ValueError(
                f"unknown priority class {value!r} (known: "
                f"{sorted(PRIORITY_CLASSES)}, or an int)") from None
    return int(value)


class ServingServer:
    """Asyncio HTTP server over a :class:`~paddle_tpu.serving.router.
    Router` (anything with ``submit``/``step``/``has_work`` works — a
    bare :class:`Replica` drives a single engine).

    ``host``/``port`` bind the listener (``port=0`` picks a free port,
    published on ``self.port`` after ``start()``).  ``poll_interval``
    bounds the driver thread's idle wait — the latency floor between a
    submit landing and the driver picking it up when the fleet was
    quiescent.  ``start()`` returns self; ``close()`` stops both
    threads (the router/engines stay open — their lifecycle belongs to
    whoever built them)."""

    def __init__(self, router, host="127.0.0.1", port=0,
                 poll_interval=0.002):
        self._router = router
        self._host = host
        self._port = int(port)
        self._poll = float(poll_interval)
        self.port = None
        self._submits = queue.Queue()
        self._watch = {}
        self._watch_lock = threading.Lock()
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._ready = threading.Event()
        self._boot_err = None
        self._loop = None
        self._stopping = None
        self._aio = None
        self._driver = None

    # ------------------------------------------------------------ lifecycle
    def start(self):
        self._aio = threading.Thread(target=self._aio_main,
                                     name="serving-http", daemon=True)
        self._aio.start()
        if not self._ready.wait(timeout=10):
            raise RuntimeError("serving HTTP listener failed to start")
        if self._boot_err is not None:
            raise self._boot_err
        self._driver = threading.Thread(target=self._drive,
                                        name="serving-driver", daemon=True)
        self._driver.start()
        return self

    def close(self):
        """Stop the driver and the listener.  Idempotent.  In-flight
        requests keep whatever tokens they have; the router and its
        engines are left to their owner."""
        self._stop.set()
        self._wake.set()
        if self._driver is not None:
            self._driver.join(timeout=10)
            self._driver = None
        if self._loop is not None:
            with contextlib.suppress(RuntimeError):   # loop already closed
                self._loop.call_soon_threadsafe(self._stopping.set)
            self._loop = None
        if self._aio is not None:
            self._aio.join(timeout=10)
            self._aio = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------- driver thread
    def _drive(self):
        """The engine-owning loop: drain submit handoffs, step the
        router while work exists, notify finished handlers.  The ONLY
        thread that touches the router after ``start()`` — handlers
        reach it exclusively through ``_submits``."""
        router = self._router
        while not self._stop.is_set():
            busy = False
            while True:
                try:
                    req, fut = self._submits.get_nowait()
                except queue.Empty:
                    break
                busy = True
                try:
                    router.submit(req)
                    fut.set_result(req.rid)
                except Exception as e:
                    self._unwatch(req)
                    fut.set_exception(e)
            if router.has_work:
                busy = True
                router.step()
            self._notify_terminal()
            if not busy:
                # idle: park on the wake event (NOT time.sleep — this
                # loop dispatches compiled steps, PTL008's domain) until
                # a submit lands or poll_interval passes
                self._wake.wait(timeout=self._poll)
                self._wake.clear()

    def _unwatch(self, req):
        with self._watch_lock:
            self._watch.pop(id(req), None)

    def _notify_terminal(self):
        """Wake every handler whose request reached a terminal status.
        Runs on the driver thread AFTER the step that finished the
        request, so the sentinel is scheduled behind the request's last
        ``stream_cb`` tokens on the loop's FIFO callback queue — the
        handler never truncates a stream."""
        with self._watch_lock:
            done = [w for w in self._watch.values()
                    if w[0].status is not None]
            for req, _, _ in done:
                del self._watch[id(req)]
        for _, loop, q in done:
            loop.call_soon_threadsafe(q.put_nowait, _DONE)

    # ---------------------------------------------------- event-loop thread
    def _aio_main(self):
        try:
            asyncio.run(self._serve())
        except Exception as e:
            self._boot_err = e
            self._ready.set()

    async def _serve(self):
        self._loop = asyncio.get_running_loop()
        self._stopping = asyncio.Event()
        server = await asyncio.start_server(self._handle, self._host,
                                            self._port)
        self.port = server.sockets[0].getsockname()[1]
        self._ready.set()
        async with server:
            await self._stopping.wait()

    async def _handle(self, reader, writer):
        try:
            try:
                head = await asyncio.wait_for(
                    reader.readuntil(b"\r\n\r\n"), timeout=30)
            except (asyncio.IncompleteReadError, asyncio.TimeoutError,
                    ConnectionError):
                return
            line, _, raw_headers = head.partition(b"\r\n")
            parts = line.decode("latin-1").split()
            if len(parts) < 2:
                await self._respond(writer, 400,
                                    {"error": "malformed request line"})
                return
            method, path = parts[0].upper(), parts[1]
            headers = {}
            for h in raw_headers.split(b"\r\n"):
                k, sep, v = h.decode("latin-1").partition(":")
                if sep:
                    headers[k.strip().lower()] = v.strip()
            if method == "GET" and path == "/healthz":
                await self._respond(writer, 200, self._health())
            elif method == "POST" and path == "/generate":
                n = int(headers.get("content-length", "0"))
                body = await reader.readexactly(n) if n else b""
                await self._generate(writer, body)
            else:
                await self._respond(
                    writer, 404, {"error": f"no route {method} {path}"})
        except ConnectionError:
            pass   # client went away mid-write; nothing to salvage
        finally:
            with contextlib.suppress(Exception):
                writer.close()
                await writer.wait_closed()

    def _health(self):
        return {"ok": True,
                "has_work": bool(self._router.has_work),
                "policy": getattr(self._router, "policy", None)}

    async def _respond(self, writer, code, obj):
        payload = json.dumps(obj).encode()
        writer.write(
            (f"HTTP/1.1 {code} {_REASONS.get(code, 'OK')}\r\n"
             "Content-Type: application/json\r\n"
             f"Content-Length: {len(payload)}\r\n"
             "Connection: close\r\n\r\n").encode("latin-1") + payload)
        await writer.drain()

    async def _generate(self, writer, body):
        try:
            spec = json.loads(body or b"{}")
            req = Request(
                spec["prompt_ids"], spec.get("max_new_tokens", 16),
                eos_token_id=spec.get("eos_token_id"),
                deadline_ms=spec.get("deadline_ms"),
                slo_class=spec.get("slo_class"),
                priority=_priority_of(spec.get("priority", 0)))
        except (KeyError, TypeError, ValueError) as e:
            await self._respond(writer, 400, {"error": str(e)})
            return
        stream = bool(spec.get("stream", True))
        loop = asyncio.get_running_loop()
        toks = asyncio.Queue()

        def push(_req, new_ids, _loop=loop, _q=toks):
            # engine thread -> event loop; list() copies before crossing
            _loop.call_soon_threadsafe(
                _q.put_nowait, [int(t) for t in new_ids])

        req.stream_cb = push
        fut = Future()
        with self._watch_lock:
            self._watch[id(req)] = (req, loop, toks)
        self._submits.put((req, fut))
        self._wake.set()
        try:
            rid = await asyncio.wrap_future(fut)
        except EngineOverloaded as e:
            await self._respond(writer, 503,
                                {"error": str(e), "status": "shed"})
            return
        except (TypeError, ValueError) as e:
            await self._respond(writer, 400, {"error": str(e)})
            return
        if stream:
            writer.write(b"HTTP/1.1 200 OK\r\n"
                         b"Content-Type: application/x-ndjson\r\n"
                         b"Cache-Control: no-store\r\n"
                         b"Connection: close\r\n\r\n")
            await writer.drain()
        while True:
            item = await toks.get()
            if item is _DONE:
                break
            if stream:
                writer.write(json.dumps(
                    {"rid": rid, "token_ids": item}).encode() + b"\n")
                await writer.drain()
        summary = {"done": True, "rid": rid, "status": req.status,
                   "n_tokens": len(req.output_ids),
                   "preempts": req.preempts}
        if stream:
            writer.write(json.dumps(summary).encode() + b"\n")
            await writer.drain()
        else:
            summary["token_ids"] = [int(t) for t in req.output_ids]
            await self._respond(writer, 200, summary)
