"""Tensor-parallel sharding for the serving hot path (GSPMD).

The engine's compiled programs (models/llama_decode.py) are pure functions
over a params pytree + KV caches, so mesh parallelism is a PLACEMENT
decision, not a code change: pick a ``PartitionSpec`` per parameter, place
the weights once, and re-``jit`` the same impl bodies with explicit in/out
shardings — XLA's SPMD partitioner inserts the collectives.  This module
owns that decision for llama serving:

* ``match_partition_rules(rules, params)`` — the fmengine/fmtrainer idiom:
  a regex → ``PartitionSpec`` table applied to the "/"-joined tree path of
  every leaf.  Scalars (and size-1 leaves) are always replicated (``PS()``);
  an unmatched non-scalar raises — silent replication of a 30B weight is
  exactly the OOM this module exists to prevent.
* ``llama_tp_rules(axis)`` — Megatron-style tensor parallelism for the
  decode params pytree: attention qkv and the MLP gate/up are COLUMN-
  parallel (output features split: ``PS(None, axis)``), the return
  projections wo/down are ROW-parallel (input features split:
  ``PS(axis, None)`` — each shard holds exactly the rows its column-
  parallel producer computed, so the only collective per layer pair is
  one psum on the residual add).  Embeddings, norms, the lm_head and the
  rope tables replicate: they are small, and a replicated lm_head keeps
  the sampled token replicated — which is what lets the host scheduler
  stay mesh-oblivious.
* ``kv_cache_pspec(axis)`` — the KV cache ``[B, Lmax, Hkv, D]`` shards
  along the HEAD axis (``PS(None, None, axis, None)``).  Decode is
  HBM-bound on KV reads (ops/decode_attention.py), and attention is
  embarrassingly parallel over heads: each chip reads only its
  ``Hkv / N`` heads — per-chip KV bytes/token drop by N, which is the
  capacity lever (the ``serving_hbm_gb_per_tok_tp`` bench column).  The
  chunked online-softmax read needs no change: its softmax/max/sum
  reductions run over the per-head chunk axis, never across heads, and
  its trip count reduces over the (replicated) lengths — head sharding
  splits only the vmapped head dimension.
* ``serving_tp_programs(...)`` — the four serving entry points re-jitted
  over the SAME impl bodies with sharded params/caches in+out, replicated
  ``cur``/``lengths``/``hist`` (the host-facing operands), and donated
  cache buffers.  Instances are cached process-wide keyed by
  (mesh, specs, statics): two engines on one mesh share compiled
  programs, exactly like the module-level single-device jits — which is
  what keeps warm sharded steps at zero retraces (``assert_no_retrace``).

Replicated-scheduler-state invariant: everything the host scheduler
touches (``cur``, ``lengths``, the spec history, emitted token blocks)
goes in and comes out replicated, so the pipelined double-buffer, chunked
prefill admission and ``_host_fetch`` drain in serving/engine.py run
UNCHANGED on a mesh — a replicated array fetches like a single-device one.
"""
from __future__ import annotations

import re

import numpy as np

import jax
from jax.sharding import NamedSharding, PartitionSpec as PS

from paddle_tpu.models.llama_decode import (
    _mon, _serving_decode_steps_impl, _serving_prefill_chunk_impl,
    _serving_prefill_slot_impl, _serving_spec_draft_step_impl,
    _serving_spec_step_impl,
)

__all__ = ["match_partition_rules", "llama_tp_rules", "kv_cache_pspec",
           "kv_scale_pspec", "kv_transfer_shardings",
           "shard_decode_params", "serving_tp_programs", "TPPrograms"]


def _path_str(path):
    """tree path entries (DictKey/SequenceKey/...) -> "layers/0/wq"."""
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


def match_partition_rules(rules, params):
    """Map ``rules`` — an ordered ``(regex, PartitionSpec)`` table — over a
    params pytree, returning the matching PartitionSpec pytree.

    Each leaf's tree path is joined with "/" (``layers/3/wq``) and matched
    with ``re.search``; the FIRST matching rule wins, so put specific
    rules above catch-alls.  Scalar and size-1 leaves short-circuit to
    ``PS()`` (nothing to shard; rope scalars and norm epsilons never need
    rules).  A non-scalar leaf no rule matches raises ``ValueError`` —
    a new parameter must get an explicit placement decision, not a silent
    full replica on every chip."""
    def spec_of(path, leaf):
        name = _path_str(path)
        shape = getattr(leaf, "shape", ())
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            return PS()
        for rule, spec in rules:
            if re.search(rule, name):
                return spec
        raise ValueError(f"no partition rule matched param {name!r} "
                         f"with shape {tuple(shape)}")
    return jax.tree_util.tree_map_with_path(spec_of, params)


def llama_tp_rules(axis="mp"):
    """Megatron-style tensor-parallel rules for the llama decode pytree
    (module docstring has the column/row-parallel rationale)."""
    return (
        # int8 weight scales (quantize_decode_weights): a column-parallel
        # weight's [out] scale shards with its output features; a
        # row-parallel weight's scale multiplies the POST-psum product, so
        # every chip needs the whole vector — replicate.  Listed first:
        # the $-anchored weight rules below can never match "*_scale", but
        # rule order documents the pairing.
        (r"(^|/)(wq|wk|wv|gate|up)_scale$", PS(axis)),
        (r"(^|/)(wo|down)_scale$", PS()),
        # column-parallel: split output features across the mesh
        (r"(^|/)(wq|wk|wv|gate|up)$", PS(None, axis)),
        # row-parallel: split input features; psum rejoins on the residual
        (r"(^|/)(wo|down)$", PS(axis, None)),
        # small + host-facing: replicate (keeps sampled tokens replicated)
        (r"(^|/)(embed|norm|lm_head|ln1|ln2)$", PS()),
        (r"(^|/)_rope($|/)", PS()),
    )


def kv_cache_pspec(axis="mp"):
    """KV cache ``[B, Lmax, Hkv, D]`` sharded along the head axis."""
    return PS(None, None, axis, None)


def kv_scale_pspec(axis="mp"):
    """int8-cache scale array ``[B, Lmax, Hkv]`` / ``[N, C, Hkv]`` sharded
    along the head axis — the data spec minus the trailing ``D`` axis, so
    each chip holds exactly the scales for its own heads and the in-loop
    dequant stays collective-free like the data read."""
    return PS(None, None, axis)


def kv_transfer_shardings(mesh, axis="mp"):
    """Placement for migration transfer leaves (serving/disagg.py): a
    block chain's ``[n_blocks, C, Hkv, D]`` data leaves keep the head
    axis at index 2 — exactly the pool layout — so the pool specs apply
    to the transfer unchanged, and an ``InProcessTransport.send`` onto a
    TP decode worker lands each leaf already head-sharded: the splice is
    a sharded scatter with no resharding copy.  Returns ``(data_sharding,
    scale_sharding)``; pass both to the transport."""
    return (NamedSharding(mesh, kv_cache_pspec(axis)),
            NamedSharding(mesh, kv_scale_pspec(axis)))


def _tp_geometry_check(params, mesh, axis):
    """Every sharded dimension must divide by the mesh axis size — an
    indivisible placement would silently pad on some backends and raise on
    others; fail loudly at engine construction instead."""
    n = int(mesh.shape[axis])
    specs = match_partition_rules(llama_tp_rules(axis), params)
    bad = []

    def chk(path, leaf, spec):
        for dim, ax in enumerate(spec):
            if ax is None:
                continue
            if int(leaf.shape[dim]) % n:
                bad.append(f"{_path_str(path)} dim {dim} "
                           f"({leaf.shape[dim]} % {n} != 0)")
    jax.tree_util.tree_map_with_path(chk, params, specs)
    if bad:
        raise ValueError(
            f"model not shardable {n}-way along mesh axis {axis!r}: "
            + "; ".join(bad))
    return specs


def shard_decode_params(params, mesh, axis="mp"):
    """Place the decode params pytree onto ``mesh`` under the llama TP
    rules (validated for divisibility).  Returns ``(sharded_params,
    specs)`` — a one-time placement at engine construction; after it the
    sharded jits consume the weights in place with zero per-step
    transfers."""
    specs = _tp_geometry_check(params, mesh, axis)
    sharded = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, specs)
    return sharded, specs


class TPPrograms:
    """The four serving entry points jitted with explicit mesh shardings.

    Statics (``cfg``, ``n_steps``, ``spec_k``, ``with_hist``,
    ``chunk_size``) are closed over — the engine fixes them at
    construction, and closing over them keeps every TP program's calling
    convention all-positional so ``in_shardings`` line up by position.
    Cache buffers are donated exactly like the single-device exports
    (plus the spec history on prefill, which the engine carries forward).
    Each wrapper dispatches through the SAME ``_mon`` program name as its
    single-device twin, so compile-cache hit/miss telemetry and
    ``assert_no_retrace`` see one program family per entry point.

    ``paged=True`` builds the block-table variants: decode/spec/pchunk
    grow one trailing replicated ``tables`` operand and the cache
    shardings apply to the ``[num_blocks, C, Hkv, D]`` pools (same
    ``kv_cache_pspec`` — the head axis is index 2 in both geometries).
    ``prefill_slot`` stays dense-only; the paged engine always runs
    chunked prefill.
    """

    def __init__(self, mesh, axis, cfg, param_specs, n_layers, *,
                 sync_every, spec_k, with_hist, chunk_size, paged=False,
                 program_key=None, dcfg=None, dparam_specs=None,
                 d_layers=0):
        repl = NamedSharding(mesh, PS())
        pshard = jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), param_specs,
            is_leaf=lambda x: isinstance(x, PS))
        dsh = NamedSharding(mesh, kv_cache_pspec(axis))
        quant = getattr(program_key, "kv_dtype", None) == "int8"
        ssh = NamedSharding(mesh, kv_scale_pspec(axis)) if quant else None
        # int8 caches are nested (data, scale) leaves: the sharding pytree
        # mirrors that structure, scales head-sharded on their own (3-axis)
        # spec — out_shardings extend to the scale leaf automatically
        leaf = (dsh, ssh) if quant else dsh
        cshard = [(leaf,) * 2 for _ in range(n_layers)]
        hshard = repl if with_hist else None
        self.mesh = mesh
        self.axis = axis
        self.n_devices = int(mesh.shape[axis])
        self.cache_sharding = dsh if n_layers else repl
        self.scale_sharding = ssh
        # resident draft model: its params shard under the same TP rules,
        # and its caches — whether the shared pool's first d_layers arrays
        # (paged) or the separate dense twins — keep the head axis at the
        # same index, so the target's cache leaf sharding applies verbatim
        dpshard = None
        if dparam_specs is not None:
            dpshard = jax.tree_util.tree_map(
                lambda s: NamedSharding(mesh, s), dparam_specs,
                is_leaf=lambda x: isinstance(x, PS))
        dcshard = [(leaf,) * 2 for _ in range(d_layers)]

        if paged:
            # paged programs take one extra trailing operand: the [B, W]
            # block tables, replicated like every other host-facing array
            # (the pool itself stays head-sharded — head axis is index 2
            # in both the dense [B, Lmax, Hkv, D] and pool [N, C, Hkv, D]
            # geometries, so kv_cache_pspec applies unchanged)
            def decode(params, cur, caches, dev_lengths, tables):
                return _serving_decode_steps_impl(
                    params, cfg, cur, caches, dev_lengths,
                    n_steps=sync_every, chunk_size=chunk_size,
                    block_tables=tables, program_key=program_key)
            self.decode_steps = _mon.wrap("serving_decode_steps", jax.jit(
                decode,
                in_shardings=(pshard, repl, cshard, repl, repl),
                out_shardings=(repl, repl, cshard),
                donate_argnums=(2,)))

            def spec(params, cur, caches, dev_lengths, hist, hist_len,
                     active, tables):
                return _serving_spec_step_impl(
                    params, cfg, cur, caches, dev_lengths, hist, hist_len,
                    active, spec_k=spec_k, chunk_size=chunk_size,
                    block_tables=tables, program_key=program_key)
            self.spec_step = _mon.wrap("serving_spec_step", jax.jit(
                spec,
                in_shardings=(pshard, repl, cshard, repl, repl, repl,
                              repl, repl),
                out_shardings=(repl, repl, repl, repl, repl, cshard, repl,
                               repl)))

            if dpshard is not None:
                # draft-model speculative round over the SHARED pool: the
                # draft's k decode steps ride the first d_layers pool
                # arrays through their own block tables, then the verify
                # forward reads the full target caches — one program, no
                # host hop between draft and verify.  dcaches is None
                # (paged), so the trailing output subtree is empty and its
                # repl spec binds nothing.
                def dspec(params, dparams, cur, caches, dev_lengths,
                          active, tables, dtables):
                    return _serving_spec_draft_step_impl(
                        params, dparams, cfg, dcfg, cur, caches, None,
                        dev_lengths, active, spec_k=spec_k,
                        chunk_size=chunk_size, block_tables=tables,
                        draft_tables=dtables, program_key=program_key)
                self.spec_draft_step = _mon.wrap(
                    "serving_spec_draft_step", jax.jit(
                        dspec,
                        in_shardings=(pshard, dpshard, repl, cshard, repl,
                                      repl, repl, repl),
                        out_shardings=(repl, repl, repl, repl, repl,
                                       cshard, repl)))

                def dpchunk(params, tokens, offset, prompt_len, caches,
                            slot, tables):
                    return _serving_prefill_chunk_impl(
                        params, dcfg, tokens, offset, prompt_len, caches,
                        slot, with_hist=False, chunk_size=chunk_size,
                        block_tables=tables, program_key=program_key)
                self.draft_prefill_chunk = _mon.wrap(
                    "serving_prefill_chunk", jax.jit(
                        dpchunk,
                        in_shardings=(dpshard, repl, repl, repl, dcshard,
                                      repl, repl),
                        out_shardings=(repl, repl, dcshard, repl, repl),
                        donate_argnums=(4,)))

            def pchunk(params, tokens, offset, prompt_len, caches, slot,
                       hist, hist_len, tables):
                return _serving_prefill_chunk_impl(
                    params, cfg, tokens, offset, prompt_len, caches, slot,
                    hist=hist, hist_len=hist_len, with_hist=with_hist,
                    chunk_size=chunk_size, block_tables=tables,
                    program_key=program_key)
            self.prefill_chunk = _mon.wrap("serving_prefill_chunk", jax.jit(
                pchunk,
                in_shardings=(pshard, repl, repl, repl, cshard, repl,
                              hshard, repl, repl),
                out_shardings=(repl, repl, cshard, hshard, repl),
                donate_argnums=(4, 6) if with_hist else (4,)))
        else:
            def decode(params, cur, caches, dev_lengths):
                return _serving_decode_steps_impl(
                    params, cfg, cur, caches, dev_lengths,
                    n_steps=sync_every, chunk_size=chunk_size,
                    program_key=program_key)
            self.decode_steps = _mon.wrap("serving_decode_steps", jax.jit(
                decode,
                in_shardings=(pshard, repl, cshard, repl),
                out_shardings=(repl, repl, cshard),
                donate_argnums=(2,)))

            def spec(params, cur, caches, dev_lengths, hist, hist_len,
                     active):
                return _serving_spec_step_impl(
                    params, cfg, cur, caches, dev_lengths, hist, hist_len,
                    active, spec_k=spec_k, chunk_size=chunk_size,
                    program_key=program_key)
            self.spec_step = _mon.wrap("serving_spec_step", jax.jit(
                spec,
                in_shardings=(pshard, repl, cshard, repl, repl, repl,
                              repl),
                out_shardings=(repl, repl, repl, repl, repl, cshard, repl,
                               repl)))

            if dpshard is not None:
                # dense twin: the draft's separate [B, Lmax, Hkv, D]
                # caches travel as an explicit operand and come back
                # updated (no donation — spec programs never donate, the
                # engine re-dispatches on transient device errors)
                def dspec(params, dparams, cur, caches, dcaches,
                          dev_lengths, active):
                    return _serving_spec_draft_step_impl(
                        params, dparams, cfg, dcfg, cur, caches, dcaches,
                        dev_lengths, active, spec_k=spec_k,
                        chunk_size=chunk_size, program_key=program_key)
                self.spec_draft_step = _mon.wrap(
                    "serving_spec_draft_step", jax.jit(
                        dspec,
                        in_shardings=(pshard, dpshard, repl, cshard,
                                      dcshard, repl, repl),
                        out_shardings=(repl, repl, repl, repl, repl,
                                       cshard, dcshard)))

                def dpchunk(params, tokens, offset, prompt_len, caches,
                            slot):
                    return _serving_prefill_chunk_impl(
                        params, dcfg, tokens, offset, prompt_len, caches,
                        slot, with_hist=False, chunk_size=chunk_size,
                        program_key=program_key)
                self.draft_prefill_chunk = _mon.wrap(
                    "serving_prefill_chunk", jax.jit(
                        dpchunk,
                        in_shardings=(dpshard, repl, repl, repl, dcshard,
                                      repl),
                        out_shardings=(repl, repl, dcshard, repl, repl),
                        donate_argnums=(4,)))

            def pchunk(params, tokens, offset, prompt_len, caches, slot,
                       hist, hist_len):
                return _serving_prefill_chunk_impl(
                    params, cfg, tokens, offset, prompt_len, caches, slot,
                    hist=hist, hist_len=hist_len, with_hist=with_hist,
                    chunk_size=chunk_size, program_key=program_key)
            self.prefill_chunk = _mon.wrap("serving_prefill_chunk", jax.jit(
                pchunk,
                in_shardings=(pshard, repl, repl, repl, cshard, repl,
                              hshard, repl),
                out_shardings=(repl, repl, cshard, hshard, repl),
                donate_argnums=(4, 6) if with_hist else (4,)))

        def pslot(params, tokens, prompt_len, caches, slot, hist, hist_len):
            return _serving_prefill_slot_impl(
                params, cfg, tokens, prompt_len, caches, slot,
                hist=hist, hist_len=hist_len, with_hist=with_hist,
                chunk_size=chunk_size, program_key=program_key)
        self.prefill_slot = _mon.wrap("serving_prefill_slot", jax.jit(
            pslot,
            in_shardings=(pshard, repl, repl, cshard, repl, hshard, repl),
            out_shardings=(repl, repl, cshard, hshard, repl),
            donate_argnums=(3, 5) if with_hist else (3,)))


# process-wide: two engines with the same (mesh, specs, statics) must
# share compiled programs — per-engine jits would retrace per engine and
# break the warm-path zero-retrace guarantee the single-device engine has
_PROGRAMS = {}


def serving_tp_programs(mesh, axis, cfg, param_specs, n_layers, *,
                        sync_every, spec_k, with_hist, chunk_size,
                        paged=False, program_key=None, dcfg=None,
                        dparam_specs=None, d_layers=0):
    """Cached ``TPPrograms`` factory (see class docstring).

    ``program_key`` is the frozen :class:`~paddle_tpu.serving.program_key.
    ProgramKey` of static kernel/precision axes — one hashable value in
    the cache key covers every registry axis (attn_impl, prefill_impl,
    kv_dtype, weight_dtype, tp_overlap, draft_source, spec_depth,
    spec_tree), so two engines differing in any axis compile separate
    program families while identical engines share.  ``dcfg`` /
    ``dparam_specs`` / ``d_layers`` describe the resident draft model
    (draft_model source only) and fork the key like any other static.
    """
    leaves, treedef = jax.tree_util.tree_flatten(
        param_specs, is_leaf=lambda x: isinstance(x, PS))
    dleaves, dtreedef = jax.tree_util.tree_flatten(
        dparam_specs, is_leaf=lambda x: isinstance(x, PS))
    key = (mesh, axis, cfg, tuple(leaves), treedef, n_layers,
           sync_every, spec_k, with_hist, chunk_size, paged, program_key,
           dcfg, tuple(dleaves), dtreedef, d_layers)
    progs = _PROGRAMS.get(key)
    if progs is None:
        progs = _PROGRAMS[key] = TPPrograms(
            mesh, axis, cfg, param_specs, n_layers, sync_every=sync_every,
            spec_k=spec_k, with_hist=with_hist, chunk_size=chunk_size,
            paged=paged, program_key=program_key, dcfg=dcfg,
            dparam_specs=dparam_specs, d_layers=d_layers)
    return progs
