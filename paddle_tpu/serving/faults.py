"""Deterministic fault injection for the serving engine.

A ``FaultPlan`` is a seeded, declarative schedule of failures threaded
through the engine's test-only seams (``ServingEngine(faults=...)``).
Every fault the reliability layer claims to survive is injected here and
proved in tier-1 (tests/test_serving_reliability.py) instead of asserted
in prose:

* **dispatch errors** — ``maybe_dispatch_error`` raises
  ``InjectedDispatchError`` at the engine's dispatch/drain fault points
  (``dispatch_error_steps``: exact scheduler-step indices;
  ``dispatch_error_rate``: a seeded per-step Bernoulli draw).  Each
  chosen step fails ``dispatch_error_attempts`` consecutive attempts
  (default 1) and then succeeds, so the bounded-retry path is exercised
  end to end; raising the attempt count past the engine's
  ``retry_attempts`` proves retry exhaustion.  The error fires BEFORE
  the real device dispatch, so a retried attempt re-issues an identical
  program — the byte-identity-under-retry invariant costs nothing.
* **poison payloads** — ``poison`` maps ``rid -> step``: from that
  scheduler step on, the engine overwrites one KV row of the request's
  slot with NaN (eagerly, between compiled steps).  Per-row attention
  isolation confines the damage to that slot; the jitted finite-logits
  flag then quarantines it with terminal status ``poisoned``.
* **slow steps** — ``slow_steps`` maps ``step -> seconds``:
  ``maybe_slow_step`` blocks the host that long at the top of the step
  (SLO / deadline-expiry pressure without touching device work).
* **stream_cb crashes** — ``cb_crash_steps``: ``maybe_crash_stream_cb``
  raises ``InjectedStreamCbError`` inside the engine's emission callback
  guard, proving a crashing user callback is counted and survived.
* **host-tier corruption** — ``host_tier_corrupt`` maps ``step ->
  chain``: at that scheduler step the host KV tier's entries along the
  chain's token ids are damaged (``None`` or ``"*"`` damages every
  stored entry; a ``(tokens, mode)`` pair picks ``"truncate"`` — a
  structural length mismatch — or ``"garble"`` — flipped payload bytes
  under a stale CRC).  The next restore must detect the damage, drop
  the entry, count ``serving_host_tier_errors_total`` and fall back to
  suffix prefill — wrong bytes are never spliced into the pool.
* **worker deaths** — ``worker_kill`` maps ``step -> worker name`` (or a
  tuple of names): at that coordinator step the named fleet worker is
  declared dead (``DisaggCoordinator(faults=...)`` drops it mid-stream;
  the multi-process launcher SIGKILLs the actual process).  The
  coordinator must recover every in-flight request — orphaned decode
  streams resume as a suffix prefill of prompt + emitted tokens — and
  never hang.

``stats`` counts every fault actually fired, so a bench/test can assert
the plan executed (a plan whose faults never fire proves nothing).
Determinism: the only randomness is ``random.Random(seed)`` consumed in
engine-step order — two runs of the same workload against the same plan
inject identically.
"""
from __future__ import annotations

import random
import time

__all__ = ["FaultPlan", "InjectedDispatchError", "InjectedStreamCbError"]


class InjectedDispatchError(RuntimeError):
    """Stands in for a transient ``XlaRuntimeError`` at a dispatch/drain
    fault point — retryable by design."""


class InjectedStreamCbError(RuntimeError):
    """Raised inside ``stream_cb`` delivery to simulate a crashing user
    callback."""


class FaultPlan:
    """Seeded schedule of injected failures (module docstring)."""

    def __init__(self, seed=0, dispatch_error_steps=(),
                 dispatch_error_rate=0.0, dispatch_error_attempts=1,
                 poison=None, slow_steps=None, cb_crash_steps=(),
                 worker_kill=None, host_tier_corrupt=None):
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        self.dispatch_error_steps = set(dispatch_error_steps)
        self.dispatch_error_rate = float(dispatch_error_rate)
        self.dispatch_error_attempts = max(1, int(dispatch_error_attempts))
        self.poison = dict(poison or {})            # rid -> step index
        self.slow_steps = dict(slow_steps or {})    # step index -> seconds
        self.cb_crash_steps = set(cb_crash_steps)
        self.worker_kill = dict(worker_kill or {})  # step -> name(s)
        # step -> chain: token ids, None/"*" (= every entry), or a
        # (tokens, mode) pair naming "truncate" / "garble"
        self.host_tier_corrupt = dict(host_tier_corrupt or {})
        self._killed_steps = set()
        self._corrupted_steps = set()
        self._poisoned = set()
        self._rate_drawn = {}                       # step -> bool (memoized)
        self._fired = {}                            # step -> errors raised
        self.stats = {"dispatch_errors": 0, "poisoned": 0,
                      "slow_steps": 0, "cb_crashes": 0,
                      "worker_kills": 0, "host_corrupts": 0}

    # ------------------------------------------------------- dispatch faults
    def _step_faulty(self, step):
        if step in self.dispatch_error_steps:
            return True
        if self.dispatch_error_rate <= 0.0:
            return False
        # memoize the draw per step: the engine probes the same step from
        # both its dispatch and drain fault points, and a retry must see
        # the same verdict for its attempt accounting to mean anything
        drawn = self._rate_drawn.get(step)
        if drawn is None:
            drawn = self._rng.random() < self.dispatch_error_rate
            self._rate_drawn[step] = drawn
        return drawn

    def maybe_dispatch_error(self, kind, step, attempt):
        """Raise ``InjectedDispatchError`` when ``step`` is scheduled to
        fail and fewer than ``dispatch_error_attempts`` errors have been
        raised for it so far.  The budget is per STEP, not per fault
        point: the engine probes several seams per step (flush / dispatch
        / drain), and a step scheduled for one transient fault should
        fail exactly once, at the first seam that asks.  ``kind`` labels
        the seam ("dispatch" / "drain") in the error message."""
        if not self._step_faulty(step):
            return
        n = self._fired.get(step, 0)
        if n >= self.dispatch_error_attempts:
            return
        self._fired[step] = n + 1
        self.stats["dispatch_errors"] += 1
        raise InjectedDispatchError(
            f"injected {kind} fault at step {step} (attempt {attempt})")

    # --------------------------------------------------------- poison faults
    def poison_due(self, rid, step):
        """True when ``rid`` is scheduled for poisoning at or before
        ``step`` and has not been injected yet (the engine defers
        injection until the slot has cache rows to corrupt)."""
        due = self.poison.get(rid)
        return (due is not None and step >= due
                and rid not in self._poisoned)

    def mark_poisoned(self, rid):
        self._poisoned.add(rid)
        self.stats["poisoned"] += 1

    # ----------------------------------------------------------- slow steps
    def maybe_slow_step(self, step):
        """Block the host for the step's scheduled stall, if any.  Returns
        the seconds actually slept (0.0 when the step is clean) so the
        engine can attribute the injected stall in its flight recorder."""
        s = self.slow_steps.get(step)
        if not s:
            return 0.0
        self.stats["slow_steps"] += 1
        time.sleep(float(s))
        return float(s)

    # --------------------------------------------------------- worker deaths
    def worker_kills_due(self, step):
        """Worker names scheduled to die at or before ``step`` that have
        not fired yet (fires once per scheduled step).  The at-or-before
        semantics mean a kill scheduled for a step the driver skipped
        (e.g. the coordinator quiesced early) still lands on the next
        probe instead of silently never firing."""
        names = []
        for due in sorted(self.worker_kill):
            if due > step or due in self._killed_steps:
                continue
            self._killed_steps.add(due)
            victim = self.worker_kill[due]
            if isinstance(victim, (list, tuple, set)):
                names.extend(victim)
            else:
                names.append(victim)
        self.stats["worker_kills"] += len(names)
        return names

    # ------------------------------------------------- host-tier corruption
    def host_corrupts_due(self, step):
        """Damage payloads scheduled at or before ``step`` that have not
        fired yet, as ``(tokens, mode)`` pairs (``tokens`` None = every
        stored entry; mode defaults to "truncate").  Same at-or-before,
        fire-once semantics as ``worker_kills_due`` — a payload scheduled
        for a skipped step lands on the next probe."""
        out = []
        for due in sorted(self.host_tier_corrupt):
            if due > step or due in self._corrupted_steps:
                continue
            self._corrupted_steps.add(due)
            chain, mode = self.host_tier_corrupt[due], "truncate"
            if (isinstance(chain, tuple) and len(chain) == 2
                    and isinstance(chain[1], str)
                    and chain[1] in ("truncate", "garble")):
                chain, mode = chain
            if isinstance(chain, str) and chain == "*":
                chain = None
            out.append((chain, mode))
        self.stats["host_corrupts"] += len(out)
        return out

    # -------------------------------------------------------- introspection
    def snapshot(self):
        """JSON-ready plan summary for the engine's ``/debug/*`` views:
        the configured schedule plus the fire counts — a postmortem reader
        sees WHAT was injected next to the events it caused."""
        return {
            "seed": self.seed,
            "dispatch_error_steps": sorted(self.dispatch_error_steps),
            "dispatch_error_rate": self.dispatch_error_rate,
            "dispatch_error_attempts": self.dispatch_error_attempts,
            "poison": dict(self.poison),
            "slow_steps": dict(self.slow_steps),
            "cb_crash_steps": sorted(self.cb_crash_steps),
            "worker_kill": {
                int(k): (sorted(v) if isinstance(v, (list, tuple, set))
                         else v)
                for k, v in self.worker_kill.items()},
            "host_tier_corrupt": {
                int(k): ("*" if v is None or (isinstance(v, str)
                                              and v == "*") else "chain")
                for k, v in self.host_tier_corrupt.items()},
            "stats": dict(self.stats),
        }

    # ------------------------------------------------------ stream_cb faults
    def maybe_crash_stream_cb(self, step):
        if step in self.cb_crash_steps:
            self.stats["cb_crashes"] += 1
            raise InjectedStreamCbError(
                f"injected stream_cb crash at step {step}")
