"""Disaggregated prefill/decode serving: dedicated worker roles with a
paged-KV-block handoff.

Prefill is compute-bound (one long arithmetic-dense pass over the prompt)
and decode is HBM-bound (one token of compute per step against the whole
cache); co-scheduling them on one mesh makes each the other's noisy
neighbor — the ``serving_tpot_during_admission_seconds`` histogram
measures exactly this tax, and chunked prefill only *budgets* around it.
DistServe and Mooncake showed the capacity architecture that removes it:
split the two phases onto dedicated workers and make the KV cache the
transfer unit.  The paged block pool (serving/kv_cache.py) makes that
nearly free here, because block-table indirection means a KV handoff
changes operand *values*, never program shapes:

* A **PrefillWorker** owns admission and runs ONLY
  ``serving_prefill_chunk`` programs (``ServingEngine(prefill_only=
  True)`` — a decode dispatch on it is a hard error).  Every request it
  accepts carries ``max_new_tokens=1``: the final prefill chunk's argmax
  IS its first token, after which the request retires and its block
  chain is exported.
* A **DecodeWorker** owns a block pool plus the decode/spec dispatch and
  accepts migrated requests through ``ServingEngine.adopt_prefilled``:
  imported blocks are spliced under a fresh slot's table row, the decode
  carry is seeded (cur = first token, length = prompt) exactly where a
  local prefill would have left it, and from the next dispatch on the
  slot is indistinguishable from a locally prefilled one — the
  byte-identity AND zero-retrace argument in one.
* A **KVTransport** ships a completed request's block chain — the
  ``[n_blocks, C, Hkv, D]`` data leaves plus ``[n_blocks, C, Hkv]`` int8
  scale leaves per layer — between pools.  ``InProcessTransport`` is the
  device-to-device ``device_put`` path (CI-testable on one process);
  ``serving/transport.py``'s ``SocketTransport`` is the real bytes-on-a-
  wire path (UDS/TCP, background-thread streaming so the transfer
  overlaps decode steps); ``PickleTransport`` survives as a test-only
  fallback that round-trips the same socket framing through a blob.
* The **DisaggCoordinator** glues them behind the SAME engine surface
  ``serving/replica.py`` programs against (submit/cancel/step/run/drain/
  close/stats/prefix_lookup/...), so the router and the HTTP front end
  compose over a disaggregated deployment unchanged:
  ``Replica(DisaggCoordinator(...))`` just works.

TTFT rides the handoff: the first token is emitted on the caller's
request the moment the prefill worker surfaces it — BEFORE the transfer
is paid — so disaggregation adds nothing to time-to-first-token, while
decode TPOT is freed from admission interference entirely.

The transfer itself must never serialize a worker's step loop — a
blocking ``send``/``recv`` between compiled dispatches stalls every
live slot behind one request's migration.  Here all transport calls sit
in the coordinator's pump, OUTSIDE both workers' dispatch loops; the
tpu-lint PTL017 rule polices the anti-pattern in tree code.
"""

from __future__ import annotations

import logging
import time
from collections import deque

import numpy as np

import jax

from .engine import (EngineOverloaded, Request, ServingEngine,
                     _backoff_sleep)
from .kv_cache import KVPoolExhausted
from .metrics import DisaggMetrics

__all__ = [
    "KVTransport",
    "InProcessTransport",
    "PickleTransport",
    "PrefillWorker",
    "DecodeWorker",
    "DisaggCoordinator",
]

_LOG = logging.getLogger(__name__)


def chain_nbytes(leaves):
    """Wire size of a transfer chain: summed ``nbytes`` over every data
    and scale leaf (the int8 pool's per-layer ``(data, scale)`` tuples
    count both)."""
    total = 0
    for k, v in leaves:
        for leaf in (k, v):
            if isinstance(leaf, tuple):
                total += int(leaf[0].nbytes) + int(leaf[1].nbytes)
            else:
                total += int(leaf.nbytes)
    return total


class KVTransport:
    """Moves one request's exported block chain between KV pools.

    ``send`` is called on the prefill side with the chain's per-layer
    ``(k, v)`` transfer leaves (``PagedKVCacheManager.export_chain``
    output — already materialized copies, independent of the source
    pool) and returns ``(handle, nbytes)``: an opaque ticket plus the
    bytes that hit the wire.  ``recv`` redeems the handle on the decode
    side into leaves ready for ``import_chain``.  The split is what
    makes the interface process-boundary-ready: a real multi-host
    transport resolves the handle remotely; in-process ones just carry
    the leaves through.

    Transports are invoked from the coordinator's migration pump, never
    from inside a worker's step-dispatch loop — a blocking transfer
    there stalls every live slot behind one migration (tpu-lint
    PTL017)."""

    def send(self, rid, leaves):
        raise NotImplementedError

    def recv(self, handle):
        raise NotImplementedError

    def ready(self, handle):
        """True when ``recv(handle)`` would return without blocking.
        In-process transports complete at ``send``; a wire transport
        overrides this so the coordinator's pump can defer an unarrived
        chain instead of stalling the step loop on it."""
        return True

    def transfer_seconds(self, handle):
        """Observed wire time for a completed transfer, or None when the
        transport has no independent clock (in-process handoffs)."""
        return None


class InProcessTransport(KVTransport):
    """Device-to-device handoff for workers sharing one process: one
    ``jax.device_put`` per leaf.  With ``shardings`` — the ``(data,
    scale)`` pair from ``serving.sharding.kv_transfer_shardings`` — each
    leaf is placed directly under the decode pool's head-sharded layout,
    so a TP decode worker splices without a resharding copy; without, the
    default-device copy preserves single-device semantics."""

    def __init__(self, shardings=None):
        if shardings is None:
            self._data = self._scale = None
        else:
            self._data, self._scale = shardings

    def _put(self, leaf):
        if isinstance(leaf, tuple):
            if self._data is None:
                return (jax.device_put(leaf[0]), jax.device_put(leaf[1]))
            return (jax.device_put(leaf[0], self._data),
                    jax.device_put(leaf[1], self._scale))
        if self._data is None:
            return jax.device_put(leaf)
        return jax.device_put(leaf, self._data)

    def send(self, rid, leaves):
        out = [(self._put(k), self._put(v)) for k, v in leaves]
        return out, chain_nbytes(leaves)

    def recv(self, handle):
        return handle


class PickleTransport(KVTransport):
    """DEPRECATED test-only fallback: one chain round-tripped through an
    actual ``bytes`` blob in one process — proving nothing in the
    migration path assumes device-to-device reachability, without
    sockets.  The framing IS ``serving/transport.py``'s wire codec
    (``encode_chain``/``decode_chain``), so there is exactly one
    serialization path and ``nbytes`` is the same framed wire size
    ``SocketTransport`` accounts; real deployments use
    ``SocketTransport`` (this class logs a one-time pointer there).
    The decode side re-uploads during ``import_chain``'s pool scatter,
    so the leaves come back as numpy and that is fine."""

    _warned = False

    def send(self, rid, leaves):
        if not PickleTransport._warned:
            PickleTransport._warned = True
            _LOG.warning(
                "PickleTransport is deprecated to a test-only fallback: "
                "use serving.transport.SocketTransport for anything that "
                "crosses a process boundary")
        from .transport import encode_chain
        blob = encode_chain(rid, leaves)
        return blob, len(blob)

    def recv(self, handle):
        from .transport import decode_chain
        _, leaves, _ = decode_chain(handle)
        return leaves


class PrefillWorker:
    """Admission + chunked prefill, nothing else: wraps a
    ``ServingEngine(prefill_only=True)`` whose every request carries
    ``max_new_tokens=1``.  When a request's final chunk lands, the
    engine's ``on_prefilled`` hook fires with the slot still mapped —
    the coordinator exports the block chain right there, then the
    request retires on the engine's normal path and its blocks recycle.

    ``mode`` is pinned to ``"greedy"``: the only token a prefill worker
    ever produces is the final chunk's argmax, which is identical under
    greedy and speculative decoding — spec workers pair a greedy
    prefill worker with a spec decode worker."""

    def __init__(self, model, name="prefill0", **engine_kw):
        engine_kw.setdefault("mode", "greedy")
        # drafting is a DECODE concern: the prefill worker runs greedy
        # first-token-only, so a fleet-level spec config never reaches it
        engine_kw.pop("spec", None)
        engine_kw["prefill_only"] = True
        engine_kw["on_prefilled"] = self._fire
        self.name = name
        self.detokenizer = engine_kw.get("detokenizer")
        self._sink = None  # bound by the coordinator
        self.engine = ServingEngine(model, **engine_kw)

    def _fire(self, request, slot, first):
        if self._sink is not None:
            self._sink(self, request, slot, first)

    def backlog(self):
        s = self.engine.stats()
        return s["queue_depth"] + s["slots_occupied"]


class DecodeWorker:
    """The decode half: a plain paged continuous-batching engine that
    never sees a prompt — requests enter through
    ``ServingEngine.adopt_prefilled`` with their first token and their
    imported block chain, and leave through the engine's ordinary
    retire paths.  Spec decoding, int8 KV, preemption and deadlines all
    apply unchanged."""

    def __init__(self, model, name="decode0", **engine_kw):
        self.name = name
        self.engine = ServingEngine(model, **engine_kw)
        if self.engine.kv_block is None:
            raise ValueError(
                "DecodeWorker requires a paged engine (kv_block=): the "
                "block pool is the migration transfer unit")

    def backlog(self):
        return self.engine.stats()["slots_occupied"]


class _Ticket:
    """One migration in flight: the request's first token plus the
    transport handle its chain rode out on.  ``stall_since`` is stamped
    the first time a decode worker had capacity but the chain's bytes
    were still on the wire — the transfer-induced stall the overlap
    design exists to keep at zero."""

    __slots__ = ("rid", "first", "handle", "n_blocks", "nbytes", "sent_s",
                 "stall_since")

    def __init__(self, rid, first, handle, n_blocks, nbytes, sent_s):
        self.rid = rid
        self.first = first
        self.handle = handle
        self.n_blocks = n_blocks
        self.nbytes = nbytes
        self.sent_s = sent_s
        self.stall_since = None


class _FleetSLO:
    """Aggregated SLO view over the decode engines' trackers (decode
    owns retirement, so that is where attainment is observed).  The
    router reads one number — worst-case burn rate across the fleet."""

    def __init__(self, trackers):
        self._trackers = [t for t in trackers if t is not None]

    def observe(self, request):
        if self._trackers:
            self._trackers[0].observe(request)

    def burn_rate(self, slo_class="interactive"):
        if not self._trackers:
            return 0.0
        return max(t.burn_rate(slo_class) for t in self._trackers)


class DisaggCoordinator:
    """Drives a prefill/decode split behind the single-engine surface
    ``serving/replica.py`` expects, so the router and HTTP server
    compose over it unchanged::

        pw = PrefillWorker(model, kv_block=16, **geom)
        dw = DecodeWorker(model, kv_block=16, **geom)
        coord = DisaggCoordinator(pw, dw)
        coord.submit(Request(prompt, max_new_tokens=64))
        coord.run()                      # or: Router([Replica(coord)])

    Lifecycle of one request: ``submit`` validates it against the decode
    fleet (``adoption_viable`` — a request that could never fit must
    shed at the front door, not abort mid-migration), then enters a
    ``max_new_tokens=1`` *shadow* with the same rid into the least-
    backlogged prefill worker.  When the shadow's final chunk lands the
    ``on_prefilled`` hook emits the first token on the CALLER's request
    immediately — TTFT rides the handoff, the transfer is paid after —
    exports the block chain and ``transport.send``s it.  The migration
    pump then places each pending chain on a decode worker gated by
    ``can_adopt`` (a False defers to the next step; capacity arrives as
    decode slots retire), redeems the handle and splices via
    ``adopt_prefilled``.  Tokens 2..N stream from the decode engine's
    ordinary paths.  Cancellation/expiry between handoff and adoption
    aborts the migration (``serving_migrations_total{outcome=
    "aborted"}``); the imported-side rollback is ``import_chain``'s.

    Byte identity with the colocated engine holds per request (greedy
    and spec, f32 and int8 KV): the adopted slot enters the decode
    dispatch with the same cur/length/block-table VALUES a local prefill
    would have produced, under unchanged program shapes — which is also
    why the warm decode worker never retraces across migrations."""

    def __init__(self, prefill, decode, transport=None, name="disagg0",
                 registry=None, instrument=True, faults=None):
        self._prefill = (list(prefill)
                         if isinstance(prefill, (list, tuple))
                         else [prefill])
        self._decode = (list(decode)
                        if isinstance(decode, (list, tuple))
                        else [decode])
        if not self._prefill or not self._decode:
            raise ValueError("DisaggCoordinator needs at least one "
                             "prefill and one decode worker")
        blocks = {w.engine.kv_block
                  for w in self._prefill + self._decode}
        if None in blocks or len(blocks) != 1:
            raise ValueError(
                "all workers must run paged KV with one common block "
                f"size (the transfer unit); got {sorted(map(str, blocks))}")
        for w in self._prefill:
            w._sink = self._on_prefilled
        self.name = name
        self._transport = transport if transport is not None \
            else InProcessTransport()
        self._m = DisaggMetrics(registry, name) if instrument else None
        self._users = {}      # rid -> caller Request, until terminal
        self._shadows = {}    # rid -> (shadow Request, PrefillWorker)
        self._owner = {}      # rid -> DecodeWorker, after adoption
        self._migrating = deque()
        self._finished = []
        self._rids = set()
        self._next_rid = 0
        self._slo = _FleetSLO([w.engine.slo_tracker for w in self._decode])
        self._n_ok = 0
        self._n_aborted = 0
        self._hook_emitted = 0
        self._adopted = 0
        self._faults = faults
        self._dead = set()      # worker names declared dead
        self._step_idx = 0
        self._attempt = {}      # root rid -> resume attempts so far
        self._proxy = {}        # attempt rid -> root caller Request
        self._active = {}       # root rid -> live attempt rid
        self._stall_t0 = None   # run()'s no-progress clock

    # -------------------------------------------------------- live fleet
    def _live_prefill(self):
        return [w for w in self._prefill if w.name not in self._dead]

    def _live_decode(self):
        return [w for w in self._decode if w.name not in self._dead]

    # ------------------------------------------------------------ submit
    def submit(self, request):
        """Admit ``request`` into the split: decode-side viability check,
        then a ``max_new_tokens=1`` shadow with the same rid into the
        least-backlogged prefill worker.  Raises ``ValueError`` for
        requests that could never fit either side and propagates
        ``EngineOverloaded`` (status ``"shed"``) from the prefill
        worker's bounded admission queue."""
        live = self._live_decode()
        if not live or not any(w.engine.adoption_viable(request)
                               for w in live):
            raise ValueError(
                "request can never fit any live decode worker (prompt "
                "bucket / max_len budget): prefilling it would strand a "
                "migration")
        rid_given = request.rid is not None
        if rid_given and request.rid in self._rids:
            raise ValueError(
                f"rid {request.rid!r} is already in use by another "
                "request on this coordinator")
        rid = request.rid if rid_given else self._next_rid
        shadow = Request(request.prompt_ids, 1, rid=rid,
                         deadline_ms=request.deadline_ms,
                         slo_class=request.slo_class,
                         priority=request.priority)
        live_prefill = self._live_prefill()
        if not live_prefill:
            raise ValueError("no live prefill worker to admit into")
        worker = min(live_prefill, key=lambda w: w.backlog())
        try:
            worker.engine.submit(shadow)
        except EngineOverloaded:
            # mirror the engine's shed contract on the caller's request:
            # a shed request never consumed coordinator state
            request.status = "shed"
            raise
        if rid_given:
            if isinstance(rid, int):
                self._next_rid = max(self._next_rid, rid + 1)
        else:
            request.rid = rid
            self._next_rid += 1
        self._rids.add(rid)
        request.t_submit = shadow.t_submit
        if request.deadline_ms is not None:
            request._t_deadline = request.t_submit \
                + request.deadline_ms / 1e3
        self._users[rid] = request
        self._shadows[rid] = (shadow, worker)
        return request

    # ----------------------------------------------------------- handoff
    def _on_prefilled(self, worker, shadow, slot, first):
        """The prefill engine's completion hook: fires inside its
        first-token flush with the chain still mapped.  Emit the first
        token on the caller's request NOW (TTFT never waits on the
        transfer), then export and send the chain — unless the token
        already completed the request, in which case there is nothing
        to migrate."""
        user = self._users.get(shadow.rid)
        if user is None or user.done:
            return  # cancelled between dispatch and flush: chain recycles
        self._emit_first(user, int(first), worker)
        if user.done:
            return
        kv = worker.engine.kv_manager
        chain = kv.block_chain(shadow.rid)
        t0 = time.perf_counter()
        leaves = kv.export_chain(chain)
        handle, nbytes = self._transport.send(shadow.rid, leaves)
        sent_s = time.perf_counter() - t0
        if self._m is not None:
            self._m.transfer_bytes.inc(nbytes)
        rec = worker.engine.recorder
        if rec is not None:
            rec.record("migrate_out", rid=shadow.rid,
                       n_blocks=len(chain), bytes=nbytes)
        self._migrating.append(_Ticket(shadow.rid, int(first), handle,
                                       len(chain), nbytes, sent_s))

    def _emit_first(self, user, first, worker):
        user.output_ids.append(first)
        user.t_first = time.perf_counter()
        self._hook_emitted += 1
        if worker.detokenizer is not None:
            user.text = worker.detokenizer(list(user.output_ids))
        if user.stream_cb is not None:
            try:
                user.stream_cb(user, [first])
            except Exception as e:
                if not user._cb_err_logged:
                    user._cb_err_logged = True
                    _LOG.warning(
                        "stream_cb for request %r raised %s: %s",
                        user.rid, type(e).__name__, e)
        if len(user.output_ids) >= user.max_new_tokens or (
                user.eos_token_id is not None
                and first == int(user.eos_token_id)):
            self._retire_waiting(user, "done")

    def _retire_waiting(self, user, status):
        """Finalize a request the decode fleet never owned: done at the
        first token, or cancelled/expired between handoff and adoption.
        A resume attempt finalizes its ROOT request — the caller only
        ever sees the Request they submitted."""
        user.status = status
        user.done = True
        user.t_done = time.perf_counter()
        self._users.pop(user.rid, None)
        root = self._proxy.pop(user.rid, None)
        if root is not None:
            self._finalize_root(root, status)
            return
        self._finished.append(user)
        self._slo.observe(user)

    def _finalize_root(self, root, status, observe=True):
        """Stamp a terminal status on a resume attempt's root request.
        ``observe=False`` when the decode engine already observed SLO
        attainment on the attempt (avoids double counting)."""
        self._active.pop(root.rid, None)
        root.status = status
        root.done = True
        root.t_done = time.perf_counter()
        self._finished.append(root)
        if observe:
            self._slo.observe(root)

    def _abort(self, ticket):
        self._n_aborted += 1
        if self._m is not None:
            self._m.migration("aborted")

    # -------------------------------------------------------------- step
    def step(self):
        """One coordinator iteration: step the prefill fleet (handoffs
        fire inside, emitting first tokens), propagate shadow failures,
        pump pending migrations onto decode workers, step the decode
        fleet.  Returns tokens emitted on caller requests."""
        self._step_idx += 1
        if self._faults is not None:
            for name in self._faults.worker_kills_due(self._step_idx):
                self.kill_worker(name)
        self._hook_emitted = 0
        for w in self._live_prefill():
            if w.engine.has_work:
                w.engine.step()
        emitted = self._hook_emitted
        self._harvest_shadows()
        self._pump_migrations()
        for w in self._live_decode():
            if w.engine.has_work:
                emitted += w.engine.step()
        self._collect()
        self._update_gauges()
        return emitted

    def _harvest_shadows(self):
        """Drop retired shadows; a shadow that retired with anything but
        ``"done"`` (timed out mid-prefill, poisoned, cancelled) never
        reached the handoff — propagate its terminal status to the
        caller's request."""
        for rid in list(self._shadows):
            shadow, _ = self._shadows[rid]
            if not shadow.done:
                continue
            del self._shadows[rid]
            if shadow.status == "done":
                continue
            user = self._users.get(rid)
            if user is not None and not user.done:
                self._retire_waiting(user, shadow.status)

    def _pump_migrations(self):
        """Place pending chains, FIFO: abort dead ones (cancelled /
        past-deadline), defer those no decode worker can adopt yet OR
        whose bytes are still on the wire (``transport.ready`` — the
        step loop never blocks on a transfer), and splice the rest
        (``transport.recv`` + ``adopt_prefilled``) onto the least-loaded
        worker that has room."""
        self._adopted = 0
        keep = deque()
        now = time.perf_counter()
        live = self._live_decode()
        while self._migrating:
            t = self._migrating.popleft()
            user = self._users.get(t.rid)
            if user is None or user.done:
                self._abort(t)
                continue
            if user._t_deadline is not None and now > user._t_deadline:
                self._retire_waiting(user, "timed_out")
                self._abort(t)
                continue
            if not live:
                # every decode worker is dead: terminal, never hang
                self._retire_waiting(user, "cancelled")
                self._abort(t)
                continue
            cands = [w for w in live if w.engine.can_adopt(user)]
            if not cands:
                keep.append(t)
                continue
            if not self._transport.ready(t.handle):
                # capacity is waiting on the wire — the stall the
                # background sender exists to keep at zero
                if t.stall_since is None:
                    t.stall_since = time.perf_counter()
                keep.append(t)
                continue
            w = min(cands, key=lambda c: c.backlog())
            wire_s = self._transport.transfer_seconds(t.handle)
            t1 = time.perf_counter()
            try:
                leaves = self._transport.recv(t.handle)
                slot = w.engine.adopt_prefilled(user, t.first, leaves)
            except (EngineOverloaded, KVPoolExhausted):
                keep.append(t)  # raced with the gate: retry next step
                continue
            self._owner[t.rid] = w
            self._adopted += 1
            self._n_ok += 1
            if self._m is not None:
                self._m.transfer_seconds.observe(
                    t.sent_s + (wire_s or 0.0)
                    + (time.perf_counter() - t1))
                self._m.overlap_stall.observe(
                    0.0 if t.stall_since is None
                    else time.perf_counter() - t.stall_since)
                self._m.migration("ok")
            rec = w.engine.recorder
            if rec is not None:
                rec.record("migrate_in", rid=t.rid, slot=slot,
                           n_blocks=t.n_blocks, bytes=t.nbytes)
        self._migrating = keep

    def _collect(self):
        """Sweep caller requests the decode fleet finished into the
        coordinator's completion list (the engines stamped status /
        t_done on the shared Request objects).  A finished resume
        attempt finalizes its root instead — the engine already streamed
        its tokens onto the root via the forwarding callback and
        observed SLO attainment on the attempt."""
        for rid in list(self._users):
            u = self._users[rid]
            if u.done:
                del self._users[rid]
                self._owner.pop(rid, None)
                root = self._proxy.pop(rid, None)
                if root is not None:
                    self._finalize_root(root, u.status, observe=False)
                else:
                    self._finished.append(u)

    # ------------------------------------------------------ worker death
    def kill_worker(self, name):
        """Declare the named worker dead (FaultPlan ``worker_kill`` seam;
        callable directly in tests).  Its engine is never touched again
        — a dead process answers nothing — and every in-flight request
        it held is recovered: shadows resubmit to a surviving prefill
        worker, adopted decode streams re-prefill their suffix (prompt +
        all emitted tokens) through ``_reprefill``.  Requests that no
        survivor can host retire with a clean terminal status; nothing
        ever hangs on a corpse.  Returns True if the name was a live
        worker."""
        w = next((x for x in self._prefill + self._decode
                  if x.name == name and x.name not in self._dead), None)
        if w is None:
            return False
        self._dead.add(name)
        _LOG.warning("disagg worker %r died; recovering its in-flight "
                     "requests", name)
        if w in self._prefill:
            self._reassign_shadows(w)
        else:
            self._recover_orphans(w)
        return True

    def _reassign_shadows(self, dead):
        """Shadows the dead prefill worker held (queued or mid-prefill)
        restart from scratch on the least-backlogged survivor — prefill
        produced nothing externally visible yet, so a fresh shadow with
        the same rid is byte-identical."""
        for rid in list(self._shadows):
            shadow, worker = self._shadows[rid]
            if worker is not dead:
                continue
            del self._shadows[rid]
            user = self._users.get(rid)
            if user is None or user.done:
                continue
            live = self._live_prefill()
            if not live:
                self._retire_waiting(user, "cancelled")
                continue
            replacement = Request(shadow.prompt_ids, 1, rid=rid,
                                  slo_class=shadow.slo_class,
                                  priority=shadow.priority)
            target = min(live, key=lambda w: w.backlog())
            try:
                target.engine.submit(replacement)
            except EngineOverloaded:
                self._retire_waiting(user, "shed")
                continue
            replacement._t_deadline = user._t_deadline
            self._shadows[rid] = (replacement, target)

    def _recover_orphans(self, dead):
        """Requests the dead decode worker owned lose their KV blocks
        with the process; the radix story makes recovery a suffix
        prefill — re-prefill prompt + every emitted token, whose final
        chunk's argmax IS the next token of the uninterrupted greedy
        stream (the preemption-resume identity, engine
        ``_admission_ids``)."""
        for rid, owner in list(self._owner.items()):
            if owner is not dead:
                continue
            self._owner.pop(rid)
            user = self._users.get(rid)
            if user is None or user.done:
                continue
            self._users.pop(rid)
            self._reprefill(user)

    def _reprefill(self, user):
        """Resume an orphaned stream as a fresh attempt: a new derived
        rid (engines never recycle rids), prompt' = prompt + emitted
        tokens, max_new' = remaining budget.  The attempt's emissions
        forward onto the root request, so the caller's stream continues
        byte-identically; terminal statuses finalize the root."""
        root = self._proxy.pop(user.rid, None) or user
        self._active.pop(root.rid, None)
        k = len(root.output_ids)
        remaining = root.max_new_tokens - k
        if remaining <= 0:
            self._finalize_root(root, "done")
            return
        attempt = self._attempt.get(root.rid, 0) + 1
        self._attempt[root.rid] = attempt
        arid = f"{root.rid}~r{attempt}"
        prompt = np.concatenate(
            [np.asarray(root.prompt_ids, dtype=np.int32).ravel(),
             np.asarray(root.output_ids, dtype=np.int32).ravel()])
        resume = Request(prompt, remaining, rid=arid,
                         eos_token_id=root.eos_token_id,
                         stream_cb=self._forward_cb(root),
                         slo_class=root.slo_class,
                         priority=root.priority)
        resume._t_deadline = root._t_deadline
        live = self._live_prefill()
        if not live or not any(w.engine.adoption_viable(resume)
                               for w in self._live_decode()):
            self._finalize_root(root, "cancelled")
            return
        shadow = Request(prompt, 1, rid=arid, slo_class=root.slo_class,
                         priority=root.priority)
        target = min(live, key=lambda w: w.backlog())
        try:
            target.engine.submit(shadow)
        except EngineOverloaded:
            self._finalize_root(root, "shed")
            return
        shadow._t_deadline = root._t_deadline
        self._rids.add(arid)
        self._users[arid] = resume
        self._shadows[arid] = (shadow, target)
        self._proxy[arid] = root
        self._active[root.rid] = arid
        if self._m is not None:
            self._m.orphan_reprefills.inc()
        _LOG.info("re-prefilling orphaned request %r as %r (%d tokens "
                  "already emitted, %d remaining)", root.rid, arid, k,
                  remaining)

    def _forward_cb(self, root):
        """A resume attempt's stream_cb: splice its emissions onto the
        root request (output_ids, first-token stamp, caller callback)."""
        def cb(req, new_ids):
            root.output_ids.extend(int(i) for i in new_ids)
            if root.t_first is None:
                root.t_first = req.t_first
            if root.stream_cb is not None:
                try:
                    root.stream_cb(root, new_ids)
                except Exception as e:
                    if not root._cb_err_logged:
                        root._cb_err_logged = True
                        _LOG.warning(
                            "stream_cb for request %r raised %s: %s",
                            root.rid, type(e).__name__, e)
        return cb

    def _update_gauges(self):
        if self._m is None:
            return
        self._m.prefill_backlog.set(
            sum(w.backlog() for w in self._live_prefill()))
        self._m.decode_backlog.set(
            sum(w.backlog() for w in self._live_decode())
            + len(self._migrating))

    # -------------------------------------------------- run / drain / close
    @property
    def has_work(self):
        return (bool(self._shadows) or bool(self._migrating)
                or any(w.engine.has_work
                       for w in self._live_prefill()
                       + self._live_decode()))

    def run(self, stall_timeout=30.0):
        """Drive ``step()`` to quiescence; returns finished requests in
        completion order.  Two stuck shapes are distinguished: chains
        whose bytes are still on the wire wait (``_backoff_sleep`` — the
        sanctioned pause — under ``stall_timeout``), while a migration
        no decode worker can EVER place (pool smaller than one request's
        budget) raises immediately — ``submit``'s viability gate makes
        the latter unreachable for sanely sized pools."""
        while self.has_work:
            self.step()
            if not (self._migrating and self._adopted == 0
                    and not self._shadows
                    and not any(w.engine.has_work
                                for w in self._live_prefill()
                                + self._live_decode())):
                self._stall_t0 = None
                continue
            in_flight = [t for t in self._migrating
                         if not self._transport.ready(t.handle)]
            if not in_flight:
                raise RuntimeError(
                    f"{len(self._migrating)} migration(s) pending but "
                    "every decode worker is idle and none can adopt — "
                    "decode pool too small for the request's budget")
            if self._stall_t0 is None:
                self._stall_t0 = time.perf_counter()
            elif time.perf_counter() - self._stall_t0 > stall_timeout:
                raise RuntimeError(
                    f"{len(in_flight)} migration chain(s) still on the "
                    f"wire after {stall_timeout:.0f}s with the fleet "
                    "idle — transport stalled or sender died")
            _backoff_sleep(0.002)
        self._stall_t0 = None
        return self._finished

    def drain(self):
        """Run to quiescence, then return ``{rid: terminal status}`` —
        the graceful-shutdown half of ``close()``."""
        self.run()
        return {r.rid: r.status for r in self._finished}

    def close(self):
        """Abort outstanding work cleanly: close the prefill fleet
        (queued/mid-prefill shadows cancel, propagating to their
        callers), abort pending migrations, close the decode fleet.
        Idempotent; returns ``{rid: terminal status}``."""
        for w in self._live_prefill():
            w.engine.close()
        self._harvest_shadows()
        while self._migrating:
            t = self._migrating.popleft()
            user = self._users.get(t.rid)
            self._abort(t)
            if user is not None and not user.done:
                self._retire_waiting(user, "cancelled")
        for w in self._live_decode():
            w.engine.close()
        self._collect()
        for rid in list(self._users):  # defensive: nothing should remain
            self._retire_waiting(self._users[rid], "cancelled")
        self._update_gauges()
        return {r.rid: r.status for r in self._finished}

    def cancel(self, rid):
        """Cancel ``rid`` wherever it is: shadow mid-prefill, chain
        mid-migration, adopted on a decode worker, or resumed under a
        derived attempt rid after a worker death.  Returns True if found
        live."""
        rid = self._active.get(rid, rid)
        sh = self._shadows.get(rid)
        if sh is not None:
            shadow, worker = sh
            found = worker.engine.cancel(rid)
            self._harvest_shadows()
            return found
        for t in self._migrating:
            if t.rid == rid:
                self._migrating.remove(t)
                self._abort(t)
                user = self._users.get(rid)
                if user is not None and not user.done:
                    self._retire_waiting(user, "cancelled")
                return True
        w = self._owner.get(rid)
        if w is not None:
            found = w.engine.cancel(rid)
            self._collect()
            return found
        return False

    # ------------------------------------------------- fleet introspection
    @property
    def kv_block(self):
        return self._decode[0].engine.kv_block

    @property
    def slo_tracker(self):
        return self._slo

    def queue_depth(self):
        """Work admitted but not yet decoding: prefill backlogs plus
        chains awaiting adoption."""
        return (sum(w.engine.queue_depth() for w in self._live_prefill())
                + len(self._migrating))

    def prefix_lookup(self, tokens):
        """Longest cached prefix across the live PREFILL fleet — that is
        the side where a hit skips work (adoption always imports the
        full chain).  Tier-aware: each engine's probe counts its device
        radix match plus its host-tier continuation."""
        return max((w.engine.prefix_lookup(tokens)
                    for w in self._live_prefill()), default=0)

    def stats(self):
        """One engine-shaped snapshot over the split (the keys
        ``Replica``/``Router`` read, aggregated), plus migration
        counters.  Prompt/reuse tallies come from the prefill side only
        — adoption re-counts prompt tokens on the decode engines and
        double-counting would skew the router's placement signal."""
        ps = [w.engine.stats() for w in self._live_prefill()]
        ds = [w.engine.stats() for w in self._live_decode()]
        return {
            "queue_depth": self.queue_depth(),
            "slots_occupied": sum(s["slots_occupied"] for s in ds),
            "slots_total": sum(s["slots_total"] for s in ds),
            "prefill_slots": sum(s["slots_occupied"] for s in ps),
            "inflight": sum(s["inflight"] for s in ps + ds),
            "live_tokens": sum(s["live_tokens"] for s in ps + ds),
            "prompt_tokens": sum(s["prompt_tokens"] for s in ps),
            "prefix_reuse_tokens": sum(s["prefix_reuse_tokens"]
                                       for s in ps),
            "preempted": sum(s["preempted"] for s in ds),
            "preempt_resume_suffix_tokens":
                sum(s["preempt_resume_suffix_tokens"] for s in ds),
            "preempt_resume_total_tokens":
                sum(s["preempt_resume_total_tokens"] for s in ds),
            "prefill_workers": len(self._live_prefill()),
            "decode_workers": len(self._live_decode()),
            "workers_dead": len(self._dead),
            "orphan_reprefills": sum(self._attempt.values()),
            "migrations_ok": self._n_ok,
            "migrations_aborted": self._n_aborted,
            "migrations_pending": len(self._migrating),
        }

    def debug_sources(self):
        """Worker-prefixed union of every engine's debug endpoints."""
        out = {}
        for w in self._prefill + self._decode:
            for key, fn in w.engine.debug_sources().items():
                out[f"{w.name}_{key}"] = fn
        return out
