"""paddle.inference (reference python/paddle/inference/__init__.py over
paddle/fluid/inference/api/analysis_predictor.h:105).

TPU-native deployment: the saved model is a serialized jax.export artifact
(paddle.jit.save writes model.jaxexport next to the weights); the Predictor
deserializes and executes it — the analysis-pass pipeline of the reference is
XLA's own optimization pipeline here."""
from paddle_tpu.inference.passes import (  # noqa: F401
    PassPipeline, apply_inference_passes, conv_bn_fuse_pass,
    delete_dropout_op_pass,
)
from paddle_tpu.inference.wrapper import (
    Config, DataType, PlaceType, Predictor, PredictorPool, Tensor,
    convert_to_mixed_precision, create_predictor, get_num_bytes_of_data_type,
    get_trt_compile_version, get_trt_runtime_version, get_version,
)

__all__ = [
    'Config', 'DataType', 'PlaceType', 'PrecisionType', 'Tensor', 'Predictor',
    'PredictorPool', 'create_predictor', 'get_version',
    'get_num_bytes_of_data_type', 'get_trt_compile_version',
    'get_trt_runtime_version', 'convert_to_mixed_precision',
]


class PrecisionType:
    Float32 = 0
    Half = 1
    Bfloat16 = 2
    Int8 = 3
