"""Inference optimization passes (reference paddle/fluid/framework/ir/ pass
pipeline + paddle_infer pass_builder API).

TPU-native split of responsibilities: the graph-level fusions the reference
implements as IR passes (elementwise fusion, transpose folding, gemm
epilogues...) are XLA's job and happen in every jit compile.  What XLA
canNOT do is rewrite PARAMETERS — those passes operate here at the Layer
level, before export/jit:

* ``conv_bn_fuse_pass`` — fold an inference-mode BatchNorm's affine
  transform into the preceding conv's weight/bias inside Sequential
  containers (the classic deploy-time rewrite; reference
  ir/conv_bn_fuse_pass.cc), replacing the BN with Identity — the BN memory
  pass is removed entirely rather than left for the compiler to fuse.
* ``delete_dropout_op_pass`` — replace Dropout layers with identity
  (reference ir/delete_dropout_op_pass.cc); eval-mode dropout is already
  identity, this makes it structural.

``PassPipeline`` mirrors the reference pass_builder: an ordered list the
user can inspect, delete from, or append custom callables to.
"""
from __future__ import annotations

import numpy as np

__all__ = ["PassPipeline", "conv_bn_fuse_pass", "delete_dropout_op_pass",
           "apply_inference_passes"]


def _iter_named_children(layer):
    return list(getattr(layer, "_sub_layers", {}).items())


def conv_bn_fuse_pass(model):
    """Fold BatchNorm (inference stats) into an immediately preceding
    Conv2D inside ``nn.Sequential`` containers ONLY — in a Sequential,
    adjacency IS dataflow, so the rewrite cannot touch a conv whose output
    has other consumers (the reference pass checks the same single-consumer
    property on the graph):
        w' = w * gamma / sqrt(var + eps)   (per out-channel)
        b' = (b - mean) * gamma / sqrt(var + eps) + beta
    The fused BN is REPLACED by nn.Identity (exact; no residual
    x/sqrt(1+eps) pass).  Returns the number of fused pairs."""
    import jax.numpy as jnp

    from paddle_tpu import nn

    if getattr(model, "training", False):
        raise RuntimeError(
            "conv_bn_fuse_pass is an inference-only rewrite: call "
            "model.eval() first (train-mode BN uses batch stats and would "
            "double-transform activations)")
    fused = 0
    # single-consumer check (the reference pass's graph property): a conv
    # module that appears under MORE than one parent is shared — folding one
    # consumer's BN into it would corrupt every other consumer, so count
    # occurrences first and fuse only convs with exactly one appearance
    conv_count = {}

    def count(layer, seen_layers):
        if id(layer) in seen_layers:
            return
        seen_layers.add(id(layer))
        for _, child in _iter_named_children(layer):
            if isinstance(child, nn.Conv2D):
                conv_count[id(child)] = conv_count.get(id(child), 0) + 1
            count(child, seen_layers)

    count(model, set())

    def visit(layer):
        nonlocal fused
        children = _iter_named_children(layer)
        in_seq = isinstance(layer, nn.Sequential)
        for i in range(len(children) - 1):
            (_, conv), (bn_name, bn) = children[i], children[i + 1]
            if not in_seq:
                continue  # attribute adjacency is NOT dataflow; skip
            if not (isinstance(conv, nn.Conv2D)
                    and isinstance(bn, (nn.BatchNorm2D, nn.BatchNorm))):
                continue
            if getattr(conv, "_groups", 1) not in (1,):
                continue  # grouped convs keep their BN (reference skip list)
            if conv_count.get(id(conv), 0) != 1:
                continue  # shared conv: other consumers would see fused weights
            mean = np.asarray(bn._mean.numpy(), np.float64)
            # affine-less BN (weight_attr/bias_attr=False): gamma=1, beta=0
            gamma = (np.asarray(bn.weight.numpy(), np.float64)
                     if bn.weight is not None else np.ones_like(mean))
            beta = (np.asarray(bn.bias.numpy(), np.float64)
                    if bn.bias is not None else np.zeros_like(mean))
            var = np.asarray(bn._variance.numpy(), np.float64)
            eps = float(getattr(bn, "_epsilon", 1e-5))
            scale = gamma / np.sqrt(var + eps)

            w_dtype = np.asarray(conv.weight.numpy()).dtype
            w = np.asarray(conv.weight.numpy(), np.float64)
            w = w * scale[:, None, None, None]  # OIHW: scale out-channels
            conv.weight._data = jnp.asarray(w.astype(w_dtype))
            b = (np.asarray(conv.bias.numpy(), np.float64)
                 if conv.bias is not None else np.zeros_like(mean))
            b = (b - mean) * scale + beta
            if conv.bias is not None:
                conv.bias._data = jnp.asarray(
                    b.astype(np.asarray(conv.bias.numpy()).dtype))
            else:
                from paddle_tpu.tensor.tensor import Parameter

                # the ORIGINAL weight dtype — the float64 math intermediate
                # must never leak into a parameter
                conv.bias = Parameter(jnp.asarray(b.astype(w_dtype)))
            # the BN is gone, not neutralized: a zero-mean/unit-var affine
            # still divides by sqrt(1+eps)
            layer._sub_layers[bn_name] = nn.Identity()
            fused += 1
        for _, child in children:
            visit(child)

    visit(model)
    return fused


def delete_dropout_op_pass(model):
    """Swap Dropout layers for Identity (structural, not just eval-mode)."""
    from paddle_tpu import nn

    removed = 0

    def visit(layer):
        nonlocal removed
        for name, child in _iter_named_children(layer):
            if isinstance(child, (nn.Dropout, nn.Dropout2D, nn.Dropout3D)):
                layer._sub_layers[name] = nn.Identity()
                removed += 1
            else:
                visit(child)

    visit(model)
    return removed


_DEFAULT_PASSES = [
    ("conv_bn_fuse_pass", conv_bn_fuse_pass),
    ("delete_dropout_op_pass", delete_dropout_op_pass),
]


class PassPipeline:
    """reference pass_builder(): ordered, user-editable pass list."""

    def __init__(self, passes=None):
        self._passes = list(passes if passes is not None else _DEFAULT_PASSES)

    def all_passes(self):
        return [n for n, _ in self._passes]

    def delete_pass(self, name):
        self._passes = [(n, f) for n, f in self._passes if n != name]

    def append_pass(self, name, fn):
        self._passes.append((name, fn))

    def insert_pass(self, idx, name, fn):
        self._passes.insert(idx, (name, fn))

    def apply(self, model):
        stats = {}
        for name, fn in self._passes:
            stats[name] = fn(model)
        return stats


def apply_inference_passes(model, pipeline=None):
    """Run the (default) pass pipeline over a Layer in place; returns the
    per-pass rewrite counts."""
    return (pipeline or PassPipeline()).apply(model)
