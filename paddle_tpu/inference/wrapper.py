"""Predictor/Config (reference python/paddle/inference/wrapper.py)."""
from __future__ import annotations

import enum
import os

import jax.numpy as jnp
import numpy as np


class DataType(enum.Enum):
    FLOAT32 = 0
    FLOAT16 = 1
    INT32 = 2
    INT64 = 3
    UINT8 = 4
    INT8 = 5
    BOOL = 6
    BFLOAT16 = 7


def get_num_bytes_of_data_type(dtype):
    return {DataType.FLOAT32: 4, DataType.FLOAT16: 2, DataType.INT32: 4,
            DataType.INT64: 8, DataType.UINT8: 1, DataType.INT8: 1,
            DataType.BOOL: 1, DataType.BFLOAT16: 2}[dtype]


class PlaceType(enum.Enum):
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM = 3
    TPU = 4


class Config:
    """reference paddle_infer.Config: model paths + device/optimization knobs.
    XLA replaces the IR-pass pipeline, so most switches are bookkeeping."""

    def __init__(self, model_path=None, params_path=None):
        self._model_path = model_path
        self._params_path = params_path
        self._device = "tpu"
        self._device_id = 0
        self._enable_memory_optim = True
        self._ir_optim = True
        self._num_threads = 1

    def set_prog_file(self, path):
        self._model_path = path

    def set_params_file(self, path):
        self._params_path = path

    def prog_file(self):
        return self._model_path

    def params_file(self):
        return self._params_path

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0, precision=None):
        self._device, self._device_id = "gpu", device_id

    def disable_gpu(self):
        self._device = "cpu"

    def enable_custom_device(self, device_type, device_id=0):
        self._device, self._device_id = device_type, device_id

    def use_gpu(self):
        return self._device == "gpu"

    def enable_memory_optim(self, x=True):
        self._enable_memory_optim = x

    def switch_ir_optim(self, x=True):
        self._ir_optim = x

    def pass_builder(self):
        """reference Config.pass_builder(): the editable parameter-rewrite
        pass pipeline (inference/passes.py).  Graph-level fusions stay XLA's
        job; these passes apply to a live Layer before jit.save/export via
        paddle.inference.apply_inference_passes(model, config.pass_builder())."""
        if not hasattr(self, "_pass_pipeline"):
            from paddle_tpu.inference.passes import PassPipeline

            self._pass_pipeline = PassPipeline()
        return self._pass_pipeline

    def set_cpu_math_library_num_threads(self, n):
        self._num_threads = n

    # -- engine knobs with no TPU analog: warn, don't silently accept --------
    # (same honesty standard as DistributedStrategy: a knob either works or
    #  tells the user it does nothing here)
    def _warn_unsupported(self, knob, why):
        import warnings

        warnings.warn(
            f"Config.{knob} has no effect on the TPU backend ({why}); "
            "XLA is the optimization pipeline here",
            UserWarning, stacklevel=3,
        )

    def enable_tensorrt_engine(self, *a, **kw):
        self._warn_unsupported("enable_tensorrt_engine", "TensorRT is CUDA-only")

    def enable_tuned_tensorrt_dynamic_shape(self, *a, **kw):
        self._warn_unsupported(
            "enable_tuned_tensorrt_dynamic_shape", "TensorRT is CUDA-only")

    def set_trt_dynamic_shape_info(self, *a, **kw):
        self._warn_unsupported(
            "set_trt_dynamic_shape_info", "TensorRT is CUDA-only")

    def enable_mkldnn(self, *a, **kw):
        self._warn_unsupported("enable_mkldnn", "oneDNN is a CPU library")

    def enable_mkldnn_bfloat16(self, *a, **kw):
        self._warn_unsupported("enable_mkldnn_bfloat16", "oneDNN is a CPU library")

    def enable_mkldnn_int8(self, *a, **kw):
        self._warn_unsupported("enable_mkldnn_int8", "oneDNN is a CPU library")

    def enable_lite_engine(self, *a, **kw):
        self._warn_unsupported("enable_lite_engine", "Paddle-Lite targets mobile")

    def enable_xpu(self, *a, **kw):
        self._warn_unsupported("enable_xpu", "Kunlun XPU runtime not present")

    def exp_disable_tensorrt_ops(self, *a, **kw):
        self._warn_unsupported("exp_disable_tensorrt_ops", "TensorRT is CUDA-only")

    def summary(self):
        return f"Config(model={self._model_path}, device={self._device})"


class Tensor:
    """Handle to one predictor input/output (reference paddle_infer.Tensor)."""

    def __init__(self, name, store):
        self._name = name
        self._store = store

    def name(self):
        return self._name

    def copy_from_cpu(self, data):
        self._store[self._name] = np.ascontiguousarray(data)

    def copy_to_cpu(self):
        return np.asarray(self._store[self._name])

    def shape(self):
        return list(np.asarray(self._store[self._name]).shape)

    def reshape(self, shape):
        self._store[self._name] = np.zeros(shape, np.float32)


class Predictor:
    """Loads a paddle.jit.save'd model and runs it (AnalysisPredictor parity:
    load → (XLA) optimize → run)."""

    def __init__(self, config):
        self._config = config
        base = config.prog_file()
        if base is None:
            raise ValueError("Config needs the model path prefix")
        import json

        with open(base + ".pdmodel.json") as f:
            meta = json.load(f)
        self._specs = meta["input_specs"]
        self._exported = None
        if os.path.exists(base + ".jaxexport"):
            from jax import export as _jexport

            with open(base + ".jaxexport", "rb") as f:
                self._exported = _jexport.deserialize(bytearray(f.read()))
        self._inputs = {f"x{i}": None for i in range(len(self._specs))}
        self._outputs = {}
        # the analysis/optimization step: with ir_optim on (default) the
        # deserialized computation is wrapped in jax.jit, so repeated run()
        # calls hit one compiled executable (XLA is the pass pipeline);
        # switching it off executes the artifact unoptimized per call —
        # the reference's switch_ir_optim semantics at the StableHLO level
        self._call = None
        if self._exported is not None:
            import jax as _jax

            call = self._exported.call
            self._call = _jax.jit(call) if config._ir_optim else call

    def get_input_names(self):
        return list(self._inputs.keys())

    def get_input_handle(self, name):
        return Tensor(name, self._inputs)

    def get_output_names(self):
        return list(self._outputs.keys())

    def get_output_handle(self, name):
        return Tensor(name, self._outputs)

    def run(self, inputs=None):
        if inputs is not None:
            arrs = [np.asarray(t) if not hasattr(t, "numpy") else t.numpy() for t in inputs]
        else:
            arrs = [self._inputs[k] for k in self.get_input_names()]
        if self._exported is None:
            raise RuntimeError("no executable artifact (.jaxexport) next to the model")
        out = self._call(*[jnp.asarray(a) for a in arrs])
        leaves = out if isinstance(out, (list, tuple)) else [out]
        self._outputs.clear()
        res = []
        for i, o in enumerate(leaves):
            self._outputs[f"out{i}"] = np.asarray(o)
            from paddle_tpu.tensor.tensor import Tensor as EagerTensor

            res.append(EagerTensor(jnp.asarray(o)))
        return res

    def clone(self):
        return Predictor(self._config)


class PredictorPool:
    def __init__(self, config, size=1):
        self._predictors = [Predictor(config) for _ in range(size)]

    def retrieve(self, idx):
        return self._predictors[idx]


def create_predictor(config):
    return Predictor(config)


def get_version():
    import paddle_tpu

    return paddle_tpu.__version__


def get_trt_compile_version():
    return (0, 0, 0)


def get_trt_runtime_version():
    return (0, 0, 0)


def convert_to_mixed_precision(model_file, params_file, mixed_model_file,
                               mixed_params_file, mixed_precision=None,
                               backend=None, keep_io_types=True, black_list=None,
                               **kw):
    """On TPU, precision policy is applied at jit time (paddle.amp); copy through."""
    import shutil

    for src, dst in ((model_file, mixed_model_file), (params_file, mixed_params_file)):
        if src and dst and os.path.exists(src):
            os.makedirs(os.path.dirname(dst) or ".", exist_ok=True)
            shutil.copy(src, dst)
