"""paddle.vision.ops (reference python/paddle/vision/ops.py): detection ops.

TPU-native formulations: box ops are vectorized jnp; NMS-style sequential
selection uses host numpy (it is post-processing, as in the reference's CPU
kernels); roi_align/deform_conv are gather+einsum programs XLA can fuse."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.autograd.engine import apply
from paddle_tpu.nn.layer.layers import Layer
from paddle_tpu.nn.layer.container import Sequential
from paddle_tpu.tensor.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


# --------------------------------------------------------------------- nms ----
def _iou_matrix(boxes):
    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    area = (x2 - x1) * (y2 - y1)
    xx1 = np.maximum(x1[:, None], x1[None, :])
    yy1 = np.maximum(y1[:, None], y1[None, :])
    xx2 = np.minimum(x2[:, None], x2[None, :])
    yy2 = np.minimum(y2[:, None], y2[None, :])
    inter = np.clip(xx2 - xx1, 0, None) * np.clip(yy2 - yy1, 0, None)
    return inter / np.maximum(area[:, None] + area[None, :] - inter, 1e-10)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None, categories=None,
        top_k=None, name=None):
    """reference vision/ops.py:1934 nms (optionally category-aware)."""
    b = np.asarray(boxes.numpy() if isinstance(boxes, Tensor) else boxes, np.float64)
    n = b.shape[0]
    s = np.asarray(scores.numpy() if isinstance(scores, Tensor) else scores, np.float64) if scores is not None else None

    def _nms_single(idxs):
        order = idxs[np.argsort(-s[idxs])] if s is not None else idxs
        keep = []
        iou = _iou_matrix(b)
        suppressed = np.zeros(n, bool)
        for i in order:
            if suppressed[i]:
                continue
            keep.append(i)
            suppressed |= iou[i] > iou_threshold
            suppressed[i] = True
        return np.asarray(keep, np.int64)

    if category_idxs is None:
        keep = _nms_single(np.arange(n))
    else:
        cat = np.asarray(category_idxs.numpy() if isinstance(category_idxs, Tensor) else category_idxs)
        parts = [
            _nms_single(np.flatnonzero(cat == c)) for c in (categories or np.unique(cat))
        ]
        keep = np.concatenate([p for p in parts if len(p)]) if parts else np.zeros(0, np.int64)
        if s is not None:
            keep = keep[np.argsort(-s[keep])]
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(keep)


def matrix_nms(bboxes, scores, score_threshold, post_threshold, nms_top_k,
               keep_top_k, use_gaussian=False, gaussian_sigma=2.0,
               background_label=0, normalized=True, return_index=False,
               return_rois_num=True, name=None):
    """reference vision/ops.py:2358 matrix_nms (SOLOv2 decay formulation)."""
    bb = np.asarray(bboxes.numpy(), np.float64)  # (N, M, 4)
    sc = np.asarray(scores.numpy(), np.float64)  # (N, C, M)
    all_out, all_idx, rois_num = [], [], []
    for bi in range(bb.shape[0]):
        outs = []
        idxs = []
        for c in range(sc.shape[1]):
            if c == background_label:
                continue
            s_c = sc[bi, c]
            valid = np.flatnonzero(s_c > score_threshold)
            if valid.size == 0:
                continue
            order = valid[np.argsort(-s_c[valid])][:nms_top_k]
            boxes_c = bb[bi][order]
            scores_c = s_c[order]
            iou = _iou_matrix(boxes_c)
            iou = np.triu(iou, 1)
            # iou_cmax[i] = max IoU of candidate i with any higher-scored one
            iou_cmax = iou.max(0) if len(order) else np.zeros(0)
            # decay of candidate i = min over higher-ranked j of f(iou[j,i],
            # iou_cmax[j]); rows j>=i hold iou 0 and contribute values >= 1,
            # so a final clip at 1 reproduces the reference's min_decay=1 seed
            # (matrix_nms_kernel.cc decay_score: linear (1-iou)/(1-max_iou),
            # gaussian exp((max_iou^2-iou^2)*sigma) -- sigma MULTIPLIES).
            if use_gaussian:
                decay = np.exp((iou_cmax[:, None] ** 2 - iou ** 2) * gaussian_sigma)
            else:
                decay = (1 - iou) / np.maximum(1 - iou_cmax[:, None], 1e-10)
            decayed = scores_c * np.minimum(decay.min(0), 1.0)
            keep = decayed > post_threshold
            for j in np.flatnonzero(keep):
                outs.append([c, decayed[j], *boxes_c[j]])
                idxs.append(order[j] + bi * bb.shape[1])
        outs = np.asarray(outs, np.float32).reshape(-1, 6)
        idxs = np.asarray(idxs, np.int64)
        if keep_top_k > 0 and len(outs) > keep_top_k:  # -1 = keep all
            sel = np.argsort(-outs[:, 1])[:keep_top_k]
            outs, idxs = outs[sel], idxs[sel]
        all_out.append(outs)
        all_idx.append(idxs)
        rois_num.append(len(outs))
    out = Tensor(np.concatenate(all_out, 0) if all_out else np.zeros((0, 6), np.float32))
    res = [out]
    if return_index:
        res.append(Tensor(np.concatenate(all_idx, 0)))
    if return_rois_num:
        res.append(Tensor(np.asarray(rois_num, np.int32)))
    return res[0] if len(res) == 1 else tuple(res)


# --------------------------------------------------------------- roi pooling --
def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """reference vision/ops.py:1705: bilinear-sampled average pooling per RoI."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    # adaptive sampling count (reference: ceil(roi_size / bin) per RoI).  XLA
    # needs a static grid, so take the max over the (concrete, eager) boxes,
    # bounded to keep the gather tractable.
    if sampling_ratio <= 0:
        bx_np = np.asarray(boxes.numpy() if isinstance(boxes, Tensor) else boxes, np.float64)
        if bx_np.size:
            max_h = float(np.max(bx_np[:, 3] - bx_np[:, 1])) * spatial_scale
            max_w = float(np.max(bx_np[:, 2] - bx_np[:, 0])) * spatial_scale
            sampling_ratio = int(min(8, max(1, np.ceil(max(max_h / ph, max_w / pw)))))
        else:
            sampling_ratio = 2

    def f(feat, bxs, bnum):
        n, c, h, w = feat.shape
        # map each roi to its batch image
        batch_idx = jnp.repeat(jnp.arange(n), bnum, total_repeat_length=bxs.shape[0])
        offset = 0.5 if aligned else 0.0
        x1 = bxs[:, 0] * spatial_scale - offset
        y1 = bxs[:, 1] * spatial_scale - offset
        x2 = bxs[:, 2] * spatial_scale - offset
        y2 = bxs[:, 3] * spatial_scale - offset
        roi_w = x2 - x1
        roi_h = y2 - y1
        if not aligned:
            roi_w = jnp.maximum(roi_w, 1.0)
            roi_h = jnp.maximum(roi_h, 1.0)
        sr = sampling_ratio
        # sample grid: (R, ph, sr) x (R, pw, sr)
        ys = (y1[:, None, None] + (jnp.arange(ph)[None, :, None] +
              (jnp.arange(sr)[None, None, :] + 0.5) / sr) * (roi_h[:, None, None] / ph))
        xs = (x1[:, None, None] + (jnp.arange(pw)[None, :, None] +
              (jnp.arange(sr)[None, None, :] + 0.5) / sr) * (roi_w[:, None, None] / pw))

        def bilinear(img, yy, xx):
            # img: (C, H, W); yy/xx: grids
            yy = jnp.clip(yy, 0, h - 1)
            xx = jnp.clip(xx, 0, w - 1)
            y0 = jnp.floor(yy).astype(jnp.int32)
            x0 = jnp.floor(xx).astype(jnp.int32)
            y1_ = jnp.minimum(y0 + 1, h - 1)
            x1_ = jnp.minimum(x0 + 1, w - 1)
            wy = yy - y0
            wx = xx - x0
            v00 = img[:, y0, x0]
            v01 = img[:, y0, x1_]
            v10 = img[:, y1_, x0]
            v11 = img[:, y1_, x1_]
            return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
                    + v10 * wy * (1 - wx) + v11 * wy * wx)

        def per_roi(r):
            img = feat[batch_idx[r]]
            yy = ys[r][:, None, :, None]            # (ph,1,sr,1)
            xx = xs[r][None, :, None, :]            # (1,pw,1,sr)
            yy = jnp.broadcast_to(yy, (ph, pw, sr, sr))
            xx = jnp.broadcast_to(xx, (ph, pw, sr, sr))
            vals = bilinear(img, yy.reshape(-1), xx.reshape(-1))  # (C, ph*pw*sr*sr)
            vals = vals.reshape(c, ph, pw, sr, sr)
            return vals.mean((-1, -2))

        return jax.vmap(per_roi)(jnp.arange(bxs.shape[0]))

    return apply("roi_align", f, _t(x), _t(boxes), _t(boxes_num))


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """reference vision/ops.py:1572: max pooling per RoI bin (host loop: RoI
    counts are small post-processing work)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    feat = x.numpy()
    bxs = boxes.numpy()
    bnum = np.asarray(boxes_num.numpy(), np.int64)
    n, c, h, w = feat.shape
    batch_idx = np.repeat(np.arange(n), bnum)
    outs = np.zeros((bxs.shape[0], c, ph, pw), feat.dtype)
    for r in range(bxs.shape[0]):
        img = feat[batch_idx[r]]
        x1 = int(np.round(bxs[r, 0] * spatial_scale))
        y1 = int(np.round(bxs[r, 1] * spatial_scale))
        x2 = int(np.round(bxs[r, 2] * spatial_scale))
        y2 = int(np.round(bxs[r, 3] * spatial_scale))
        rh = max(y2 - y1 + 1, 1)
        rw = max(x2 - x1 + 1, 1)
        for i in range(ph):
            for j in range(pw):
                ys0 = min(max(y1 + int(np.floor(i * rh / ph)), 0), h)
                ys1 = min(max(y1 + int(np.ceil((i + 1) * rh / ph)), 0), h)
                xs0 = min(max(x1 + int(np.floor(j * rw / pw)), 0), w)
                xs1 = min(max(x1 + int(np.ceil((j + 1) * rw / pw)), 0), w)
                if ys1 > ys0 and xs1 > xs0:
                    outs[r, :, i, j] = img[:, ys0:ys1, xs0:xs1].max((1, 2))
    return Tensor(outs)


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """reference vision/ops.py:1441: position-sensitive RoI average pooling."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    feat = x.numpy()
    bxs = boxes.numpy()
    bnum = np.asarray(boxes_num.numpy(), np.int64)
    n, c, h, w = feat.shape
    assert c % (ph * pw) == 0, "channels must be divisible by pooled_h*pooled_w"
    oc = c // (ph * pw)
    batch_idx = np.repeat(np.arange(n), bnum)
    outs = np.zeros((bxs.shape[0], oc, ph, pw), feat.dtype)
    for r in range(bxs.shape[0]):
        img = feat[batch_idx[r]]
        x1 = bxs[r, 0] * spatial_scale
        y1 = bxs[r, 1] * spatial_scale
        x2 = bxs[r, 2] * spatial_scale
        y2 = bxs[r, 3] * spatial_scale
        rh = max(y2 - y1, 0.1)
        rw = max(x2 - x1, 0.1)
        for i in range(ph):
            for j in range(pw):
                ys0 = min(max(int(np.floor(y1 + i * rh / ph)), 0), h)
                ys1 = min(max(int(np.ceil(y1 + (i + 1) * rh / ph)), 0), h)
                xs0 = min(max(int(np.floor(x1 + j * rw / pw)), 0), w)
                xs1 = min(max(int(np.ceil(x1 + (j + 1) * rw / pw)), 0), w)
                ch = (i * pw + j) * oc
                if ys1 > ys0 and xs1 > xs0:
                    outs[r, :, i, j] = img[ch:ch + oc, ys0:ys1, xs0:xs1].mean((1, 2))
    return Tensor(outs)


class RoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._output_size, self._spatial_scale)


class RoIAlign(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num, aligned=True):
        return roi_align(x, boxes, boxes_num, self._output_size,
                         self._spatial_scale, aligned=aligned)


class PSRoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self._output_size, self._spatial_scale)


# ------------------------------------------------------------- deform conv ----
def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0, dilation=1,
                  deformable_groups=1, groups=1, mask=None, name=None):
    """reference vision/ops.py:766 (DCNv1 when mask None, DCNv2 with mask):
    bilinear sampling at offset positions + matmul — pure gather/einsum."""
    stride = (stride, stride) if isinstance(stride, int) else tuple(stride)
    padding = (padding, padding) if isinstance(padding, int) else tuple(padding)
    dilation = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)

    def f(xa, off, wgt, *rest):
        n, cin, h, w = xa.shape
        cout, cin_g, kh, kw = wgt.shape
        sh, sw = stride
        ph, pw = padding
        dh, dw = dilation
        out_h = (h + 2 * ph - dh * (kh - 1) - 1) // sh + 1
        out_w = (w + 2 * pw - dw * (kw - 1) - 1) // sw + 1
        xa_p = jnp.pad(xa, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
        hp, wp = h + 2 * ph, w + 2 * pw
        # base sampling grid: (out_h, out_w, kh, kw)
        base_y = (jnp.arange(out_h) * sh)[:, None, None, None] + (jnp.arange(kh) * dh)[None, None, :, None]
        base_x = (jnp.arange(out_w) * sw)[None, :, None, None] + (jnp.arange(kw) * dw)[None, None, None, :]
        base_y = jnp.broadcast_to(base_y, (out_h, out_w, kh, kw)).astype(xa.dtype)
        base_x = jnp.broadcast_to(base_x, (out_h, out_w, kh, kw)).astype(xa.dtype)
        # offsets: (N, 2*dg*kh*kw, out_h, out_w) → (N, dg, kh, kw, 2, oh, ow)
        off = off.reshape(n, deformable_groups, kh * kw, 2, out_h, out_w)
        off_y = jnp.moveaxis(off[:, :, :, 0], -2, 2)  # (n, dg, oh, khkw, ow)? keep simple:
        off_y = off[:, :, :, 0].transpose(0, 1, 3, 4, 2).reshape(n, deformable_groups, out_h, out_w, kh, kw)
        off_x = off[:, :, :, 1].transpose(0, 1, 3, 4, 2).reshape(n, deformable_groups, out_h, out_w, kh, kw)
        sample_y = base_y[None, None] + off_y
        sample_x = base_x[None, None] + off_x

        if mask is not None:
            m = rest[-1].reshape(n, deformable_groups, kh * kw, out_h, out_w)
            m = m.transpose(0, 1, 3, 4, 2).reshape(n, deformable_groups, out_h, out_w, kh, kw)
        else:
            m = None

        cpg = cin // deformable_groups  # channels per deformable group

        def bilinear(img, yy, xx):
            # img: (C, H, W), yy/xx: (...,) returns (C, ...)
            valid = (yy > -1) & (yy < hp) & (xx > -1) & (xx < wp)
            yy = jnp.clip(yy, 0, hp - 1)
            xx = jnp.clip(xx, 0, wp - 1)
            y0 = jnp.floor(yy).astype(jnp.int32)
            x0 = jnp.floor(xx).astype(jnp.int32)
            y1 = jnp.minimum(y0 + 1, hp - 1)
            x1 = jnp.minimum(x0 + 1, wp - 1)
            wy = yy - y0
            wx = xx - x0
            v = (img[:, y0, x0] * (1 - wy) * (1 - wx) + img[:, y0, x1] * (1 - wy) * wx
                 + img[:, y1, x0] * wy * (1 - wx) + img[:, y1, x1] * wy * wx)
            return v * valid

        def per_image(img, sy, sx, mm):
            # per deformable group sampling
            cols = []
            for g in range(deformable_groups):
                sub = img[g * cpg:(g + 1) * cpg]
                vals = bilinear(sub, sy[g].reshape(-1), sx[g].reshape(-1))
                vals = vals.reshape(cpg, out_h, out_w, kh, kw)
                if mm is not None:
                    vals = vals * mm[g][None]
                cols.append(vals)
            return jnp.concatenate(cols, 0)  # (cin, oh, ow, kh, kw)

        cols = jax.vmap(per_image)(xa_p, sample_y, sample_x,
                                   m if m is not None else jnp.ones((n, deformable_groups, out_h, out_w, kh, kw), xa.dtype))
        # grouped conv as einsum
        cols = cols.reshape(n, groups, cin // groups, out_h, out_w, kh, kw)
        wgt_g = wgt.reshape(groups, cout // groups, cin_g, kh, kw)
        out = jnp.einsum("ngcxyhw,gochw->ngoxy", cols, wgt_g).reshape(n, cout, out_h, out_w)
        if bias is not None:
            out = out + rest[0].reshape(1, -1, 1, 1)
        return out

    args = [_t(x), _t(offset), _t(weight)]
    if bias is not None:
        args.append(_t(bias))
    if mask is not None:
        args.append(_t(mask))
    return apply("deform_conv2d", f, *args)


class DeformConv2D(Layer):
    """reference vision/ops.py:973."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, deformable_groups=1, groups=1, weight_attr=None,
                 bias_attr=None):
        super().__init__()
        ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) else tuple(kernel_size)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._deformable_groups = deformable_groups
        self._groups = groups
        self.weight = self.create_parameter([out_channels, in_channels // groups, *ks],
                                            attr=weight_attr)
        self.bias = self.create_parameter([out_channels], attr=bias_attr, is_bias=True)

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias, self._stride,
                             self._padding, self._dilation, self._deformable_groups,
                             self._groups, mask)


# ------------------------------------------------------------------- boxes ----
def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0, name=None):
    """reference vision/ops.py:584."""

    def f(pb, tb, *rest):
        pbv = rest[0] if rest else None
        norm = 0.0 if box_normalized else 1.0
        pw = pb[:, 2] - pb[:, 0] + norm
        phh = pb[:, 3] - pb[:, 1] + norm
        px = pb[:, 0] + pw * 0.5
        py = pb[:, 1] + phh * 0.5
        if code_type == "encode_center_size":
            tw = tb[:, 2] - tb[:, 0] + norm
            th = tb[:, 3] - tb[:, 1] + norm
            tx = tb[:, 0] + tw * 0.5
            ty = tb[:, 1] + th * 0.5
            ox = (tx[:, None] - px[None, :]) / pw[None, :]
            oy = (ty[:, None] - py[None, :]) / phh[None, :]
            ow = jnp.log(jnp.abs(tw[:, None] / pw[None, :]))
            oh = jnp.log(jnp.abs(th[:, None] / phh[None, :]))
            out = jnp.stack([ox, oy, ow, oh], -1)
            if pbv is not None:
                v = pbv if pbv.ndim == 1 else pbv
                out = out / (v[None, :, :] if v.ndim == 2 else v[None, None, :])
            return out
        # decode_center_size
        if axis == 0:
            pw_, ph_, px_, py_ = pw[:, None], phh[:, None], px[:, None], py[:, None]
            if pbv is not None:
                v = pbv[:, None, :] if pbv.ndim == 2 else pbv[None, None, :]
            slice_axis = 1
        else:
            pw_, ph_, px_, py_ = pw[None, :], phh[None, :], px[None, :], py[None, :]
            if pbv is not None:
                v = pbv[None, :, :] if pbv.ndim == 2 else pbv[None, None, :]
        t = tb
        if pbv is not None:
            t = tb * v
        ox = t[..., 0] * pw_ + px_
        oy = t[..., 1] * ph_ + py_
        ow = jnp.exp(t[..., 2]) * pw_
        oh = jnp.exp(t[..., 3]) * ph_
        return jnp.stack([ox - ow / 2,
                          oy - oh / 2,
                          ox + ow / 2 - norm,
                          oy + oh / 2 - norm], -1)

    args = [_t(prior_box), _t(target_box)]
    if prior_box_var is not None and not isinstance(prior_box_var, (list, tuple)):
        args.append(_t(prior_box_var))
        return apply("box_coder", f, *args)
    elif isinstance(prior_box_var, (list, tuple)):
        args.append(_t(jnp.asarray(prior_box_var, jnp.float32)))
        return apply("box_coder", f, *args)
    return apply("box_coder", f, *args)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False, steps=(0.0, 0.0),
              offset=0.5, min_max_aspect_ratios_order=False, name=None):
    """reference vision/ops.py:438 (SSD prior boxes)."""
    fh, fw = input.shape[2], input.shape[3]
    ih, iw = image.shape[2], image.shape[3]
    step_h = steps[1] or ih / fh
    step_w = steps[0] or iw / fw
    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - e) < 1e-6 for e in ars):
            ars.append(ar)
            if flip:
                ars.append(1.0 / ar)
    boxes = []
    vars_ = []
    for i in range(fh):
        for j in range(fw):
            cx = (j + offset) * step_w
            cy = (i + offset) * step_h
            cell = []
            for k, ms in enumerate(min_sizes):
                cell.append((ms, ms))
                if max_sizes:
                    bs = np.sqrt(ms * max_sizes[k])
                    cell.append((bs, bs))
                for ar in ars:
                    if abs(ar - 1.0) < 1e-6:
                        continue
                    cell.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
            for (bw, bh) in cell:
                box = [(cx - bw / 2) / iw, (cy - bh / 2) / ih,
                       (cx + bw / 2) / iw, (cy + bh / 2) / ih]
                if clip:
                    box = np.clip(box, 0, 1).tolist()
                boxes.append(box)
                vars_.append(variance)
    nprior = len(boxes) // (fh * fw)
    out = np.asarray(boxes, np.float32).reshape(fh, fw, nprior, 4)
    var = np.asarray(vars_, np.float32).reshape(fh, fw, nprior, 4)
    return Tensor(out), Tensor(var)


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, name=None, scale_x_y=1.0, iou_aware=False,
             iou_aware_factor=0.5):
    """reference vision/ops.py:277: decode YOLOv3 head output to boxes+scores."""

    def f(xa, imgs):
        n, c, h, w = xa.shape
        na = len(anchors) // 2
        anc = jnp.asarray(anchors, xa.dtype).reshape(na, 2)
        pred = xa.reshape(n, na, -1, h, w)  # (N, na, 5+cls(+iou), H, W)
        if iou_aware:
            ioup = jax.nn.sigmoid(pred[:, :, -1])
            pred = pred[:, :, :-1]
        gx = jnp.arange(w, dtype=xa.dtype)[None, None, None, :]
        gy = jnp.arange(h, dtype=xa.dtype)[None, None, :, None]
        bx = (jax.nn.sigmoid(pred[:, :, 0]) * scale_x_y - (scale_x_y - 1) / 2 + gx) / w
        by = (jax.nn.sigmoid(pred[:, :, 1]) * scale_x_y - (scale_x_y - 1) / 2 + gy) / h
        bw = jnp.exp(pred[:, :, 2]) * anc[None, :, 0, None, None] / (w * downsample_ratio)
        bh = jnp.exp(pred[:, :, 3]) * anc[None, :, 1, None, None] / (h * downsample_ratio)
        conf = jax.nn.sigmoid(pred[:, :, 4])
        if iou_aware:
            conf = conf ** (1 - iou_aware_factor) * ioup ** iou_aware_factor
        probs = jax.nn.sigmoid(pred[:, :, 5:5 + class_num]) * conf[:, :, None]
        conf_mask = conf > conf_thresh
        imgw = imgs[:, 1].astype(xa.dtype)[:, None, None, None]
        imgh = imgs[:, 0].astype(xa.dtype)[:, None, None, None]
        x1 = (bx - bw / 2) * imgw
        y1 = (by - bh / 2) * imgh
        x2 = (bx + bw / 2) * imgw
        y2 = (by + bh / 2) * imgh
        if clip_bbox:
            x1 = jnp.clip(x1, 0, imgw - 1)
            y1 = jnp.clip(y1, 0, imgh - 1)
            x2 = jnp.clip(x2, 0, imgw - 1)
            y2 = jnp.clip(y2, 0, imgh - 1)
        boxes = jnp.stack([x1, y1, x2, y2], -1) * conf_mask[..., None]
        boxes = boxes.reshape(n, -1, 4)
        scores = (probs * conf_mask[:, :, None]).transpose(0, 1, 3, 4, 2).reshape(n, -1, class_num)
        return boxes, scores

    return apply("yolo_box", f, _t(x), _t(img_size))


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False, rois_num=None, name=None):
    """reference vision/ops.py:1175: route each RoI to an FPN level by scale."""
    rois = np.asarray(fpn_rois.numpy(), np.float64)
    off = 1.0 if pixel_offset else 0.0
    scale = np.sqrt(np.clip(rois[:, 2] - rois[:, 0] + off, 0, None)
                    * np.clip(rois[:, 3] - rois[:, 1] + off, 0, None))
    level = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    level = np.clip(level, min_level, max_level).astype(np.int64)
    outs, idxs, nums = [], [], []
    # per-image ownership of each RoI (for per-level per-image counts)
    if rois_num is not None:
        rn = np.asarray(rois_num.numpy() if isinstance(rois_num, Tensor) else rois_num, np.int64)
        img_of_roi = np.repeat(np.arange(len(rn)), rn)
    for lv in range(min_level, max_level + 1):
        sel = np.flatnonzero(level == lv)
        outs.append(Tensor(rois[sel].astype(np.float32)))
        idxs.append(sel)
        if rois_num is not None:
            nums.append(Tensor(np.bincount(img_of_roi[sel], minlength=len(rn)).astype(np.int32)))
    order = np.concatenate(idxs) if idxs else np.zeros(0, np.int64)
    restore = np.argsort(order)
    restore_ind = Tensor(restore.astype(np.int32).reshape(-1, 1))
    if rois_num is not None:
        return outs, restore_ind, nums
    return outs, restore_ind


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000, nms_thresh=0.5,
                       min_size=0.1, eta=1.0, pixel_offset=False,
                       return_rois_num=False, name=None):
    """reference vision/ops.py:2106 (RPN proposal generation, single-image loop)."""
    sc = np.asarray(scores.numpy(), np.float64)       # (N, A, H, W)
    deltas = np.asarray(bbox_deltas.numpy(), np.float64)  # (N, 4A, H, W)
    anchs = np.asarray(anchors.numpy(), np.float64).reshape(-1, 4)
    vars_ = np.asarray(variances.numpy(), np.float64).reshape(-1, 4)
    imgs = np.asarray(img_size.numpy(), np.float64)
    n = sc.shape[0]
    all_rois, all_scores, nums = [], [], []
    off = 1.0 if pixel_offset else 0.0
    for b in range(n):
        s = sc[b].transpose(1, 2, 0).reshape(-1)
        d = deltas[b].reshape(-1, 4, sc.shape[2], sc.shape[3]).transpose(2, 3, 0, 1).reshape(-1, 4)
        order = np.argsort(-s)[:pre_nms_top_n]
        s, d, a, v = s[order], d[order], anchs[order], vars_[order]
        aw = a[:, 2] - a[:, 0] + off
        ah = a[:, 3] - a[:, 1] + off
        ax = a[:, 0] + aw / 2
        ay = a[:, 1] + ah / 2
        cx = v[:, 0] * d[:, 0] * aw + ax
        cy = v[:, 1] * d[:, 1] * ah + ay
        ww = np.exp(np.minimum(v[:, 2] * d[:, 2], np.log(1000 / 16))) * aw
        hh = np.exp(np.minimum(v[:, 3] * d[:, 3], np.log(1000 / 16))) * ah
        props = np.stack([cx - ww / 2 + 0 * off, cy - hh / 2, cx + ww / 2 - off, cy + hh / 2 - off], -1)
        ih, iw = imgs[b][0], imgs[b][1]
        props[:, 0::2] = np.clip(props[:, 0::2], 0, iw - off)
        props[:, 1::2] = np.clip(props[:, 1::2], 0, ih - off)
        keep = ((props[:, 2] - props[:, 0] + off >= min_size)
                & (props[:, 3] - props[:, 1] + off >= min_size))
        props, s = props[keep], s[keep]
        keep_idx = nms(Tensor(props.astype(np.float32)), nms_thresh, Tensor(s.astype(np.float32))).numpy()[:post_nms_top_n]
        all_rois.append(props[keep_idx].astype(np.float32))
        all_scores.append(s[keep_idx].astype(np.float32))
        nums.append(len(keep_idx))
    rois = Tensor(np.concatenate(all_rois, 0) if all_rois else np.zeros((0, 4), np.float32))
    rscores = Tensor(np.concatenate(all_scores, 0) if all_scores else np.zeros((0,), np.float32))
    if return_rois_num:
        return rois, rscores, Tensor(np.asarray(nums, np.int32))
    return rois, rscores


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num, ignore_thresh,
              downsample_ratio, gt_score=None, use_label_smooth=True, name=None,
              scale_x_y=1.0):
    """YOLOv3 loss (reference phi/kernels/cpu/yolo_loss_kernel.cc YoloLossKernel).

    Eager host op like the other detection losses here: the per-gt anchor
    matching is data-dependent sequential selection.  gt_box is normalized
    [cx, cy, w, h]; x is [N, mask_num*(5+C), H, W] with per-anchor channel
    layout [tx, ty, tw, th, obj, cls...].  Returns per-image loss [N].
    """
    xv = np.asarray(x.numpy() if isinstance(x, Tensor) else x, np.float64)
    gtb = np.asarray(gt_box.numpy() if isinstance(gt_box, Tensor) else gt_box, np.float64)
    gtl = np.asarray(gt_label.numpy() if isinstance(gt_label, Tensor) else gt_label, np.int64)
    anchors = [int(a) for a in anchors]
    mask = [int(a) for a in anchor_mask]
    n, _, h, w = xv.shape
    an_num, m, nc = len(anchors) // 2, len(mask), int(class_num)
    nb = gtb.shape[1]
    input_size = downsample_ratio * h
    sxy = float(scale_x_y)
    bias = -0.5 * (sxy - 1.0)
    gts = (np.ones((n, nb)) if gt_score is None
           else np.asarray(gt_score.numpy() if isinstance(gt_score, Tensor) else gt_score, np.float64))
    if use_label_smooth:
        sw = min(1.0 / nc, 1.0 / 40)
        lab_pos, lab_neg = 1.0 - sw, sw
    else:
        lab_pos, lab_neg = 1.0, 0.0
    xv = xv.reshape(n, m, 5 + nc, h, w)

    def sce(logit, label):  # numerically-stable sigmoid cross-entropy
        return np.maximum(logit, 0) - logit * label + np.log1p(np.exp(-np.abs(logit)))

    def iou_cw(x1, y1, w1, h1, x2, y2, w2, h2):
        ow = np.minimum(x1 + w1 / 2, x2 + w2 / 2) - np.maximum(x1 - w1 / 2, x2 - w2 / 2)
        oh = np.minimum(y1 + h1 / 2, y2 + h2 / 2) - np.maximum(y1 - h1 / 2, y2 - h2 / 2)
        inter = np.where((ow < 0) | (oh < 0), 0.0, ow * oh)
        return inter / np.maximum(w1 * h1 + w2 * h2 - inter, 1e-10)

    sig = lambda v: 1.0 / (1.0 + np.exp(-v))
    # decoded pred boxes per cell (normalized; reference GetYoloBox divides
    # x/y by grid_size=h and w/h by input_size)
    gx = np.arange(w, dtype=np.float64)[None, None, None, :]
    gy = np.arange(h, dtype=np.float64)[None, None, :, None]
    aw = np.asarray([anchors[2 * a] for a in mask], np.float64)[None, :, None, None]
    ah = np.asarray([anchors[2 * a + 1] for a in mask], np.float64)[None, :, None, None]
    px = (gx + sig(xv[:, :, 0]) * sxy + bias) / h
    py = (gy + sig(xv[:, :, 1]) * sxy + bias) / h
    pw = np.exp(xv[:, :, 2]) * aw / input_size
    ph = np.exp(xv[:, :, 3]) * ah / input_size

    valid = (gtb[:, :, 2] >= 1e-6) & (gtb[:, :, 3] >= 1e-6)
    # objness mask: -1 = ignored (best gt IoU > thresh), 0 = negative,
    # score = positive (set below at the matched cell)
    obj_mask = np.zeros((n, m, h, w))
    best_iou = np.zeros((n, m, h, w))
    for t in range(nb):
        gx_, gy_, gw_, gh_ = (gtb[:, t, k][:, None, None, None] for k in range(4))
        iou = iou_cw(px, py, pw, ph, gx_, gy_, gw_, gh_)
        iou = np.where(valid[:, t][:, None, None, None], iou, 0.0)
        best_iou = np.maximum(best_iou, iou)
    obj_mask[best_iou > ignore_thresh] = -1.0

    loss = np.zeros(n)
    an_w = np.asarray(anchors[0::2], np.float64) / input_size
    an_h = np.asarray(anchors[1::2], np.float64) / input_size
    for i in range(n):
        for t in range(nb):
            if not valid[i, t]:
                continue
            gcx, gcy, gw_, gh_ = gtb[i, t]
            gi = min(max(int(gcx * w), 0), w - 1)
            gj = min(max(int(gcy * h), 0), h - 1)
            # best anchor for this gt by shape-only IoU
            a_iou = iou_cw(0.0, 0.0, an_w, an_h, 0.0, 0.0, gw_, gh_)
            best_n = int(np.argmax(a_iou))
            mask_idx = mask.index(best_n) if best_n in mask else -1
            if mask_idx < 0:
                continue
            score = gts[i, t]
            cell = xv[i, mask_idx, :, gj, gi]
            # NOTE: tx deliberately uses h while gi came from w — the
            # reference kernel passes grid_size=h into CalcBoxLocationLoss
            # (yolo_loss_kernel.cc:336 'h') though gi = int(gt.x * w)
            # (:299); faithful parity includes its square-map assumption
            tx = gcx * h - gi
            ty = gcy * h - gj
            tw = np.log(max(gw_ * input_size / anchors[2 * best_n], 1e-10))
            th = np.log(max(gh_ * input_size / anchors[2 * best_n + 1], 1e-10))
            box_scale = (2.0 - gw_ * gh_) * score
            loss[i] += (sce(cell[0], tx) + sce(cell[1], ty)) * box_scale
            loss[i] += (abs(cell[2] - tw) + abs(cell[3] - th)) * box_scale
            obj_mask[i, mask_idx, gj, gi] = score
            label = int(gtl[i, t])
            cls_tgt = np.full(nc, lab_neg)
            if 0 <= label < nc:
                cls_tgt[label] = lab_pos
            loss[i] += float(np.sum(sce(cell[5:], cls_tgt)) * score)
    # objectness: positives weighted by mixup score, ignored cells skipped
    obj_logit = xv[:, :, 4]
    pos = obj_mask > 1e-5
    neg = (obj_mask <= 1e-5) & (obj_mask > -0.5)
    loss += np.sum(sce(obj_logit, 1.0) * obj_mask * pos, axis=(1, 2, 3))
    loss += np.sum(sce(obj_logit, 0.0) * neg, axis=(1, 2, 3))
    return Tensor(loss.astype(np.float32))


# --------------------------------------------------------------------- misc ----
class ConvNormActivation(Sequential):
    """reference vision/ops.py:1877."""

    def __init__(self, in_channels, out_channels, kernel_size=3, stride=1,
                 padding=None, groups=1, norm_layer=None, activation_layer=None,
                 dilation=1, bias=None):
        from paddle_tpu.nn.layer.conv import Conv2D
        from paddle_tpu.nn.layer.norm import BatchNorm2D
        from paddle_tpu.nn.layer.activation import ReLU

        if padding is None:
            padding = (kernel_size - 1) // 2 * dilation
        if norm_layer is None:
            norm_layer = BatchNorm2D
        if activation_layer is None:
            activation_layer = ReLU
        if bias is None:
            bias = norm_layer is None
        layers = [Conv2D(in_channels, out_channels, kernel_size, stride, padding,
                         dilation=dilation, groups=groups,
                         bias_attr=None if bias else False)]
        if norm_layer is not None:
            layers.append(norm_layer(out_channels))
        if activation_layer is not None:
            layers.append(activation_layer())
        super().__init__(*layers)


def read_file(filename, name=None):
    with open(filename, "rb") as f:
        data = np.frombuffer(f.read(), np.uint8)
    return Tensor(data)


def decode_jpeg(x, mode='unchanged', name=None):
    import io

    from PIL import Image

    raw = bytes(np.asarray(x.numpy(), np.uint8))
    img = Image.open(io.BytesIO(raw))
    if mode == 'gray':
        img = img.convert('L')
    elif mode == 'rgb':
        img = img.convert('RGB')
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(np.ascontiguousarray(arr))
