"""SqueezeNet (reference python/paddle/vision/models/squeezenet.py)."""
import paddle_tpu.nn as nn
import paddle_tpu.tensor.manipulation as M

__all__ = ["SqueezeNet", "squeezenet1_0", "squeezenet1_1"]


class _Fire(nn.Layer):
    def __init__(self, in_c, squeeze_c, e1_c, e3_c):
        super().__init__()
        self.squeeze = nn.Conv2D(in_c, squeeze_c, 1)
        self.relu = nn.ReLU()
        self.expand1 = nn.Conv2D(squeeze_c, e1_c, 1)
        self.expand3 = nn.Conv2D(squeeze_c, e3_c, 3, padding=1)

    def forward(self, x):
        x = self.relu(self.squeeze(x))
        return M.concat(
            [self.relu(self.expand1(x)), self.relu(self.expand3(x))], axis=1)


class SqueezeNet(nn.Layer):
    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        self.version = version
        self.num_classes = num_classes
        self.with_pool = with_pool
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                _Fire(128, 32, 128, 128),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                _Fire(512, 64, 256, 256),
            )
        elif version == "1.1":
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2, padding=1), nn.ReLU(),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256),
            )
        else:
            raise ValueError(f"unsupported SqueezeNet version {version}")
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.5), nn.Conv2D(512, num_classes, 1), nn.ReLU(),
            )
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = self.classifier(x)
        if self.with_pool:
            x = self.pool(x)
        return M.flatten(x, 1)


def _squeezenet(version, pretrained, **kwargs):
    from paddle_tpu.vision.models._pretrained import load_pretrained

    model = SqueezeNet(version=version, **kwargs)
    if pretrained:
        load_pretrained(model, f"squeezenet{version.replace('.', '_')}")
    return model


def squeezenet1_0(pretrained=False, **kwargs):
    return _squeezenet("1.0", pretrained, **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    return _squeezenet("1.1", pretrained, **kwargs)
