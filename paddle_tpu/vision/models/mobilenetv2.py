"""MobileNetV2 (reference python/paddle/vision/models/mobilenetv2.py)."""
import paddle_tpu.nn as nn
import paddle_tpu.tensor.manipulation as M

__all__ = ["MobileNetV2", "mobilenet_v2"]


def _make_divisible(v, divisor=8, min_value=None):
    if min_value is None:
        min_value = divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class _ConvBNReLU(nn.Sequential):
    def __init__(self, in_c, out_c, kernel=3, stride=1, groups=1):
        super().__init__(
            nn.Conv2D(in_c, out_c, kernel, stride=stride,
                      padding=(kernel - 1) // 2, groups=groups,
                      bias_attr=False),
            nn.BatchNorm2D(out_c),
            nn.ReLU6(),
        )


class _InvertedResidual(nn.Layer):
    def __init__(self, inp, oup, stride, expand_ratio):
        super().__init__()
        hidden = int(round(inp * expand_ratio))
        self.use_res = stride == 1 and inp == oup
        layers = []
        if expand_ratio != 1:
            layers.append(_ConvBNReLU(inp, hidden, kernel=1))
        layers += [
            _ConvBNReLU(hidden, hidden, stride=stride, groups=hidden),
            nn.Conv2D(hidden, oup, 1, bias_attr=False),
            nn.BatchNorm2D(oup),
        ]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        return x + self.conv(x) if self.use_res else self.conv(x)


class MobileNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = [
            (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
            (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1),
        ]
        in_c = _make_divisible(32 * scale)
        last_c = _make_divisible(1280 * max(1.0, scale))
        feats = [_ConvBNReLU(3, in_c, stride=2)]
        for t, c, n, s in cfg:
            out_c = _make_divisible(c * scale)
            for i in range(n):
                feats.append(_InvertedResidual(
                    in_c, out_c, s if i == 0 else 1, t))
                in_c = out_c
        feats.append(_ConvBNReLU(in_c, last_c, kernel=1))
        self.features = nn.Sequential(*feats)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.2), nn.Linear(last_c, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(M.flatten(x, 1))
        return x


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    from paddle_tpu.vision.models._pretrained import load_pretrained

    model = MobileNetV2(scale=scale, **kwargs)
    if pretrained:
        load_pretrained(model, "mobilenet_v2")
    return model
