"""MobileNetV3 (reference python/paddle/vision/models/mobilenetv3.py)."""
import paddle_tpu.nn as nn
import paddle_tpu.tensor.manipulation as M

from paddle_tpu.vision.models.mobilenetv2 import _make_divisible

__all__ = ["MobileNetV3Small", "MobileNetV3Large", "mobilenet_v3_small",
           "mobilenet_v3_large"]


class _SqueezeExcite(nn.Layer):
    def __init__(self, c, squeeze_c):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(c, squeeze_c, 1)
        self.relu = nn.ReLU()
        self.fc2 = nn.Conv2D(squeeze_c, c, 1)
        self.hsig = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hsig(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class _InvertedResidualV3(nn.Layer):
    def __init__(self, in_c, exp_c, out_c, kernel, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and in_c == out_c
        act_layer = nn.Hardswish if act == "hardswish" else nn.ReLU
        layers = []
        if exp_c != in_c:
            layers += [nn.Conv2D(in_c, exp_c, 1, bias_attr=False),
                       nn.BatchNorm2D(exp_c), act_layer()]
        layers += [
            nn.Conv2D(exp_c, exp_c, kernel, stride=stride,
                      padding=(kernel - 1) // 2, groups=exp_c,
                      bias_attr=False),
            nn.BatchNorm2D(exp_c), act_layer(),
        ]
        if use_se:
            layers.append(_SqueezeExcite(exp_c, _make_divisible(exp_c // 4)))
        layers += [nn.Conv2D(exp_c, out_c, 1, bias_attr=False),
                   nn.BatchNorm2D(out_c)]
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        return x + self.block(x) if self.use_res else self.block(x)


class _MobileNetV3(nn.Layer):
    def __init__(self, cfg, last_exp, last_channel, scale=1.0,
                 num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        in_c = _make_divisible(16 * scale)
        self.stem = nn.Sequential(
            nn.Conv2D(3, in_c, 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(in_c), nn.Hardswish(),
        )
        blocks = []
        for k, exp, out, se, act, s in cfg:
            exp_c = _make_divisible(exp * scale)
            out_c = _make_divisible(out * scale)
            blocks.append(_InvertedResidualV3(in_c, exp_c, out_c, k, s, se, act))
            in_c = out_c
        self.blocks = nn.Sequential(*blocks)
        last_c = _make_divisible(last_exp * scale)
        self.head_conv = nn.Sequential(
            nn.Conv2D(in_c, last_c, 1, bias_attr=False),
            nn.BatchNorm2D(last_c), nn.Hardswish(),
        )
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(last_c, last_channel), nn.Hardswish(), nn.Dropout(0.2),
                nn.Linear(last_channel, num_classes),
            )

    def forward(self, x):
        x = self.head_conv(self.blocks(self.stem(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(M.flatten(x, 1))
        return x


# (kernel, expansion, out, use_se, activation, stride)
_SMALL = [
    (3, 16, 16, True, "relu", 2), (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1), (5, 96, 40, True, "hardswish", 2),
    (5, 240, 40, True, "hardswish", 1), (5, 240, 40, True, "hardswish", 1),
    (5, 120, 48, True, "hardswish", 1), (5, 144, 48, True, "hardswish", 1),
    (5, 288, 96, True, "hardswish", 2), (5, 576, 96, True, "hardswish", 1),
    (5, 576, 96, True, "hardswish", 1),
]
_LARGE = [
    (3, 16, 16, False, "relu", 1), (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1), (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1), (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hardswish", 2), (3, 200, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1), (3, 184, 80, False, "hardswish", 1),
    (3, 480, 112, True, "hardswish", 1), (3, 672, 112, True, "hardswish", 1),
    (5, 672, 160, True, "hardswish", 2), (5, 960, 160, True, "hardswish", 1),
    (5, 960, 160, True, "hardswish", 1),
]


class MobileNetV3Small(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        # reference mobilenetv3.py: Small last_channel = divisible(1024*scale)
        super().__init__(_SMALL, 576, _make_divisible(1024 * scale), scale,
                         num_classes, with_pool)


class MobileNetV3Large(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        # reference mobilenetv3.py: Large last_channel = divisible(1280*scale)
        super().__init__(_LARGE, 960, _make_divisible(1280 * scale), scale,
                         num_classes, with_pool)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    from paddle_tpu.vision.models._pretrained import load_pretrained

    model = MobileNetV3Small(scale=scale, **kwargs)
    if pretrained:
        load_pretrained(model, "mobilenet_v3_small")
    return model


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    from paddle_tpu.vision.models._pretrained import load_pretrained

    model = MobileNetV3Large(scale=scale, **kwargs)
    if pretrained:
        load_pretrained(model, "mobilenet_v3_large")
    return model
