"""paddle.vision.models (python/paddle/vision/models parity: all 14 model
families of the reference __init__, hub-pretrained via _pretrained.py)."""
from paddle_tpu.vision.models.alexnet import AlexNet, alexnet  # noqa: F401
from paddle_tpu.vision.models.densenet import (  # noqa: F401
    DenseNet, densenet121, densenet161, densenet169, densenet201, densenet264,
)
from paddle_tpu.vision.models.googlenet import GoogLeNet, googlenet  # noqa: F401
from paddle_tpu.vision.models.inceptionv3 import (  # noqa: F401
    InceptionV3, inception_v3,
)
from paddle_tpu.vision.models.lenet import LeNet  # noqa: F401
from paddle_tpu.vision.models.mobilenet import (  # noqa: F401
    MobileNetV1, mobilenet_v1,
)
from paddle_tpu.vision.models.mobilenetv2 import (  # noqa: F401
    MobileNetV2, mobilenet_v2,
)
from paddle_tpu.vision.models.mobilenetv3 import (  # noqa: F401
    MobileNetV3Large, MobileNetV3Small, mobilenet_v3_large, mobilenet_v3_small,
)
from paddle_tpu.vision.models.resnet import (  # noqa: F401
    ResNet, resnet18, resnet34, resnet50, resnet101, resnet152,
    resnext50_32x4d, resnext50_64x4d, resnext101_32x4d, resnext101_64x4d,
    resnext152_32x4d, resnext152_64x4d, wide_resnet50_2, wide_resnet101_2,
)
from paddle_tpu.vision.models.shufflenetv2 import (  # noqa: F401
    ShuffleNetV2, shufflenet_v2_swish, shufflenet_v2_x0_5, shufflenet_v2_x0_25,
    shufflenet_v2_x0_33, shufflenet_v2_x1_0, shufflenet_v2_x1_5,
    shufflenet_v2_x2_0,
)
from paddle_tpu.vision.models.squeezenet import (  # noqa: F401
    SqueezeNet, squeezenet1_0, squeezenet1_1,
)
from paddle_tpu.vision.models.vgg import VGG, vgg11, vgg13, vgg16, vgg19  # noqa: F401

__all__ = [
    'ResNet', 'resnet18', 'resnet34', 'resnet50', 'resnet101', 'resnet152',
    'resnext50_32x4d', 'resnext50_64x4d', 'resnext101_32x4d',
    'resnext101_64x4d', 'resnext152_32x4d', 'resnext152_64x4d',
    'wide_resnet50_2', 'wide_resnet101_2',
    'VGG', 'vgg11', 'vgg13', 'vgg16', 'vgg19',
    'MobileNetV1', 'mobilenet_v1', 'MobileNetV2', 'mobilenet_v2',
    'MobileNetV3Small', 'MobileNetV3Large', 'mobilenet_v3_small',
    'mobilenet_v3_large',
    'LeNet',
    'DenseNet', 'densenet121', 'densenet161', 'densenet169', 'densenet201',
    'densenet264',
    'AlexNet', 'alexnet',
    'InceptionV3', 'inception_v3',
    'SqueezeNet', 'squeezenet1_0', 'squeezenet1_1',
    'GoogLeNet', 'googlenet',
    'ShuffleNetV2', 'shufflenet_v2_x0_25', 'shufflenet_v2_x0_33',
    'shufflenet_v2_x0_5', 'shufflenet_v2_x1_0', 'shufflenet_v2_x1_5',
    'shufflenet_v2_x2_0', 'shufflenet_v2_swish',
]
