"""ShuffleNetV2 (reference python/paddle/vision/models/shufflenetv2.py)."""
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.tensor.manipulation as M

__all__ = [
    "ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
    "shufflenet_v2_x0_5", "shufflenet_v2_x1_0", "shufflenet_v2_x1_5",
    "shufflenet_v2_x2_0", "shufflenet_v2_swish",
]

_STAGE_OUT = {
    0.25: (24, 24, 48, 96, 512),
    0.33: (24, 32, 64, 128, 512),
    0.5: (24, 48, 96, 192, 1024),
    1.0: (24, 116, 232, 464, 1024),
    1.5: (24, 176, 352, 704, 1024),
    2.0: (24, 244, 488, 976, 2048),
}
_REPEATS = (4, 8, 4)


class _ShuffleUnit(nn.Layer):
    def __init__(self, in_c, out_c, stride, act):
        super().__init__()
        self.stride = stride
        branch_c = out_c // 2
        act_layer = nn.Swish if act == "swish" else nn.ReLU
        if stride > 1:
            self.branch1 = nn.Sequential(
                nn.Conv2D(in_c, in_c, 3, stride=stride, padding=1,
                          groups=in_c, bias_attr=False),
                nn.BatchNorm2D(in_c),
                nn.Conv2D(in_c, branch_c, 1, bias_attr=False),
                nn.BatchNorm2D(branch_c), act_layer(),
            )
            b2_in = in_c
        else:
            self.branch1 = None
            b2_in = in_c // 2
        self.branch2 = nn.Sequential(
            nn.Conv2D(b2_in, branch_c, 1, bias_attr=False),
            nn.BatchNorm2D(branch_c), act_layer(),
            nn.Conv2D(branch_c, branch_c, 3, stride=stride, padding=1,
                      groups=branch_c, bias_attr=False),
            nn.BatchNorm2D(branch_c),
            nn.Conv2D(branch_c, branch_c, 1, bias_attr=False),
            nn.BatchNorm2D(branch_c), act_layer(),
        )

    def forward(self, x):
        if self.stride > 1:
            out = M.concat([self.branch1(x), self.branch2(x)], axis=1)
        else:
            c = x.shape[1] // 2
            x1, x2 = x[:, :c], x[:, c:]
            out = M.concat([x1, self.branch2(x2)], axis=1)
        return F.channel_shuffle(out, 2)


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000, with_pool=True):
        super().__init__()
        if scale not in _STAGE_OUT:
            raise ValueError(f"scale must be one of {sorted(_STAGE_OUT)}")
        self.num_classes = num_classes
        self.with_pool = with_pool
        c0, c1, c2, c3, c_last = _STAGE_OUT[scale]
        act_layer = nn.Swish if act == "swish" else nn.ReLU
        self.stem = nn.Sequential(
            nn.Conv2D(3, c0, 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(c0), act_layer(),
            nn.MaxPool2D(3, stride=2, padding=1),
        )
        blocks = []
        in_c = c0
        for out_c, n in zip((c1, c2, c3), _REPEATS):
            for i in range(n):
                blocks.append(_ShuffleUnit(in_c, out_c, 2 if i == 0 else 1,
                                           act))
                in_c = out_c
        self.blocks = nn.Sequential(*blocks)
        self.head = nn.Sequential(
            nn.Conv2D(in_c, c_last, 1, bias_attr=False),
            nn.BatchNorm2D(c_last), act_layer(),
        )
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(c_last, num_classes)

    def forward(self, x):
        x = self.head(self.blocks(self.stem(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(M.flatten(x, 1))
        return x


def _shufflenet(arch, scale, act, pretrained, **kwargs):
    from paddle_tpu.vision.models._pretrained import load_pretrained

    model = ShuffleNetV2(scale=scale, act=act, **kwargs)
    if pretrained:
        load_pretrained(model, arch)
    return model


def shufflenet_v2_x0_25(pretrained=False, **kw):
    return _shufflenet("shufflenet_v2_x0_25", 0.25, "relu", pretrained, **kw)


def shufflenet_v2_x0_33(pretrained=False, **kw):
    return _shufflenet("shufflenet_v2_x0_33", 0.33, "relu", pretrained, **kw)


def shufflenet_v2_x0_5(pretrained=False, **kw):
    return _shufflenet("shufflenet_v2_x0_5", 0.5, "relu", pretrained, **kw)


def shufflenet_v2_x1_0(pretrained=False, **kw):
    return _shufflenet("shufflenet_v2_x1_0", 1.0, "relu", pretrained, **kw)


def shufflenet_v2_x1_5(pretrained=False, **kw):
    return _shufflenet("shufflenet_v2_x1_5", 1.5, "relu", pretrained, **kw)


def shufflenet_v2_x2_0(pretrained=False, **kw):
    return _shufflenet("shufflenet_v2_x2_0", 2.0, "relu", pretrained, **kw)


def shufflenet_v2_swish(pretrained=False, **kw):
    return _shufflenet("shufflenet_v2_swish", 1.0, "swish", pretrained, **kw)
