"""Hub-based pretrained weight loading for the vision zoo (reference:
python/paddle/vision/models/*.py model_urls + utils/download.py).

Every family's ``pretrained=True`` routes here: resolve the canonical
paddle-hapi URL through the weights cache (zero-egress environments use a
pre-seeded ``~/.cache/paddle_tpu/hapi/weights``), paddle.load the .pdparams,
and set_state_dict into the freshly-built model."""
from __future__ import annotations

_BASE = "https://paddle-hapi.bj.bcebos.com/models/"

# arch -> filename at the paddle-hapi bucket (md5 checked only when given)
MODEL_URLS = {
    name: f"{_BASE}{name}.pdparams"
    for name in [
        "alexnet", "googlenet", "inception_v3",
        "mobilenet_v1", "mobilenet_v2",
        "mobilenet_v3_small", "mobilenet_v3_large",
        "squeezenet1_0", "squeezenet1_1",
        "densenet121", "densenet161", "densenet169", "densenet201",
        "densenet264",
        "shufflenet_v2_x0_25", "shufflenet_v2_x0_33", "shufflenet_v2_x0_5",
        "shufflenet_v2_x1_0", "shufflenet_v2_x1_5", "shufflenet_v2_x2_0",
        "shufflenet_v2_swish",
        "resnet18", "resnet34", "resnet50", "resnet101", "resnet152",
        "resnext50_32x4d", "resnext50_64x4d", "resnext101_32x4d",
        "resnext101_64x4d", "resnext152_32x4d", "resnext152_64x4d",
        "wide_resnet50_2", "wide_resnet101_2",
        "vgg11", "vgg13", "vgg16", "vgg19", "lenet",
    ]
}


def load_pretrained(model, arch):
    """Fill ``model`` with the hub weights for ``arch`` (in place)."""
    import paddle_tpu as paddle
    from paddle_tpu.utils.download import get_weights_path_from_url

    if arch not in MODEL_URLS:
        raise ValueError(f"no pretrained weights registered for {arch!r}")
    path = get_weights_path_from_url(MODEL_URLS[arch])
    state = paddle.load(path)
    model.set_state_dict(state)
    return model
