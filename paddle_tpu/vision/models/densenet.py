"""DenseNet (reference python/paddle/vision/models/densenet.py)."""
import paddle_tpu.nn as nn
import paddle_tpu.tensor.manipulation as M

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201", "densenet264"]

_CFG = {
    121: (64, 32, (6, 12, 24, 16)),
    161: (96, 48, (6, 12, 36, 24)),
    169: (64, 32, (6, 12, 32, 32)),
    201: (64, 32, (6, 12, 48, 32)),
    264: (64, 32, (6, 12, 64, 48)),
}


class _DenseLayer(nn.Layer):
    def __init__(self, in_c, growth_rate, bn_size, dropout):
        super().__init__()
        self.bn1 = nn.BatchNorm2D(in_c)
        self.conv1 = nn.Conv2D(in_c, bn_size * growth_rate, 1, bias_attr=False)
        self.bn2 = nn.BatchNorm2D(bn_size * growth_rate)
        self.conv2 = nn.Conv2D(bn_size * growth_rate, growth_rate, 3,
                               padding=1, bias_attr=False)
        self.relu = nn.ReLU()
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x):
        y = self.conv1(self.relu(self.bn1(x)))
        y = self.conv2(self.relu(self.bn2(y)))
        if self.dropout is not None:
            y = self.dropout(y)
        return M.concat([x, y], axis=1)


class _Transition(nn.Layer):
    def __init__(self, in_c, out_c):
        super().__init__()
        self.bn = nn.BatchNorm2D(in_c)
        self.relu = nn.ReLU()
        self.conv = nn.Conv2D(in_c, out_c, 1, bias_attr=False)
        self.pool = nn.AvgPool2D(2, stride=2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.bn(x))))


class DenseNet(nn.Layer):
    def __init__(self, layers=121, bn_size=4, dropout=0.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        if layers not in _CFG:
            raise ValueError(f"layers must be one of {sorted(_CFG)}")
        num_init, growth, blocks = _CFG[layers]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            nn.Conv2D(3, num_init, 7, stride=2, padding=3, bias_attr=False),
            nn.BatchNorm2D(num_init), nn.ReLU(),
            nn.MaxPool2D(3, stride=2, padding=1),
        )
        feats = []
        c = num_init
        for i, n in enumerate(blocks):
            for _ in range(n):
                feats.append(_DenseLayer(c, growth, bn_size, dropout))
                c += growth
            if i != len(blocks) - 1:
                feats.append(_Transition(c, c // 2))
                c //= 2
        self.features = nn.Sequential(*feats)
        self.bn_final = nn.BatchNorm2D(c)
        self.relu = nn.ReLU()
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(c, num_classes)

    def forward(self, x):
        x = self.relu(self.bn_final(self.features(self.stem(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(M.flatten(x, 1))
        return x


def _densenet(layers, pretrained, **kwargs):
    from paddle_tpu.vision.models._pretrained import load_pretrained

    model = DenseNet(layers=layers, **kwargs)
    if pretrained:
        load_pretrained(model, f"densenet{layers}")
    return model


def densenet121(pretrained=False, **kwargs):
    return _densenet(121, pretrained, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return _densenet(161, pretrained, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return _densenet(169, pretrained, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return _densenet(201, pretrained, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return _densenet(264, pretrained, **kwargs)
