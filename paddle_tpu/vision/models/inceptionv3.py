"""InceptionV3 (reference python/paddle/vision/models/inceptionv3.py)."""
import paddle_tpu.nn as nn
import paddle_tpu.tensor.manipulation as M

__all__ = ["InceptionV3", "inception_v3"]


class _ConvBN(nn.Layer):
    def __init__(self, in_c, out_c, kernel, stride=1, padding=0):
        super().__init__()
        self.conv = nn.Conv2D(in_c, out_c, kernel, stride=stride,
                              padding=padding, bias_attr=False)
        self.bn = nn.BatchNorm2D(out_c)
        self.relu = nn.ReLU()

    def forward(self, x):
        return self.relu(self.bn(self.conv(x)))


class _InceptionA(nn.Layer):
    def __init__(self, in_c, pool_c):
        super().__init__()
        self.b1 = _ConvBN(in_c, 64, 1)
        self.b5 = nn.Sequential(_ConvBN(in_c, 48, 1),
                                _ConvBN(48, 64, 5, padding=2))
        self.b3 = nn.Sequential(_ConvBN(in_c, 64, 1),
                                _ConvBN(64, 96, 3, padding=1),
                                _ConvBN(96, 96, 3, padding=1))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _ConvBN(in_c, pool_c, 1))

    def forward(self, x):
        return M.concat([self.b1(x), self.b5(x), self.b3(x), self.bp(x)], 1)


class _ReductionA(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b3 = _ConvBN(in_c, 384, 3, stride=2)
        self.b3d = nn.Sequential(_ConvBN(in_c, 64, 1),
                                 _ConvBN(64, 96, 3, padding=1),
                                 _ConvBN(96, 96, 3, stride=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return M.concat([self.b3(x), self.b3d(x), self.pool(x)], 1)


class _InceptionB(nn.Layer):
    def __init__(self, in_c, c7):
        super().__init__()
        self.b1 = _ConvBN(in_c, 192, 1)
        self.b7 = nn.Sequential(
            _ConvBN(in_c, c7, 1),
            _ConvBN(c7, c7, (1, 7), padding=(0, 3)),
            _ConvBN(c7, 192, (7, 1), padding=(3, 0)),
        )
        self.b7d = nn.Sequential(
            _ConvBN(in_c, c7, 1),
            _ConvBN(c7, c7, (7, 1), padding=(3, 0)),
            _ConvBN(c7, c7, (1, 7), padding=(0, 3)),
            _ConvBN(c7, c7, (7, 1), padding=(3, 0)),
            _ConvBN(c7, 192, (1, 7), padding=(0, 3)),
        )
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _ConvBN(in_c, 192, 1))

    def forward(self, x):
        return M.concat([self.b1(x), self.b7(x), self.b7d(x), self.bp(x)], 1)


class _ReductionB(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b3 = nn.Sequential(_ConvBN(in_c, 192, 1),
                                _ConvBN(192, 320, 3, stride=2))
        self.b7 = nn.Sequential(
            _ConvBN(in_c, 192, 1),
            _ConvBN(192, 192, (1, 7), padding=(0, 3)),
            _ConvBN(192, 192, (7, 1), padding=(3, 0)),
            _ConvBN(192, 192, 3, stride=2),
        )
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return M.concat([self.b3(x), self.b7(x), self.pool(x)], 1)


class _InceptionC(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b1 = _ConvBN(in_c, 320, 1)
        self.b3_stem = _ConvBN(in_c, 384, 1)
        self.b3_a = _ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.b3_b = _ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.b3d_stem = nn.Sequential(_ConvBN(in_c, 448, 1),
                                      _ConvBN(448, 384, 3, padding=1))
        self.b3d_a = _ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.b3d_b = _ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _ConvBN(in_c, 192, 1))

    def forward(self, x):
        s = self.b3_stem(x)
        d = self.b3d_stem(x)
        return M.concat(
            [self.b1(x), self.b3_a(s), self.b3_b(s),
             self.b3d_a(d), self.b3d_b(d), self.bp(x)], 1)


class InceptionV3(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _ConvBN(3, 32, 3, stride=2), _ConvBN(32, 32, 3),
            _ConvBN(32, 64, 3, padding=1), nn.MaxPool2D(3, stride=2),
            _ConvBN(64, 80, 1), _ConvBN(80, 192, 3),
            nn.MaxPool2D(3, stride=2),
        )
        self.blocks = nn.Sequential(
            _InceptionA(192, 32), _InceptionA(256, 64), _InceptionA(288, 64),
            _ReductionA(288),
            _InceptionB(768, 128), _InceptionB(768, 160),
            _InceptionB(768, 160), _InceptionB(768, 192),
            _ReductionB(768),
            _InceptionC(1280), _InceptionC(2048),
        )
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.2)
            self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(M.flatten(x, 1)))
        return x


def inception_v3(pretrained=False, **kwargs):
    from paddle_tpu.vision.models._pretrained import load_pretrained

    model = InceptionV3(**kwargs)
    if pretrained:
        load_pretrained(model, "inception_v3")
    return model
