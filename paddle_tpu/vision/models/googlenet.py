"""GoogLeNet / Inception v1 (reference python/paddle/vision/models/googlenet.py).

forward returns ``(out, aux1, aux2)`` like the reference — the two auxiliary
classifier heads used for deep supervision during training."""
import paddle_tpu.nn as nn
import paddle_tpu.tensor.manipulation as M

__all__ = ["GoogLeNet", "googlenet"]


class _BasicConv(nn.Layer):
    def __init__(self, in_c, out_c, kernel, stride=1, padding=0):
        super().__init__()
        self.conv = nn.Conv2D(in_c, out_c, kernel, stride=stride,
                              padding=padding)
        self.relu = nn.ReLU()

    def forward(self, x):
        return self.relu(self.conv(x))


class _Inception(nn.Layer):
    def __init__(self, in_c, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.b1 = _BasicConv(in_c, c1, 1)
        self.b2 = nn.Sequential(_BasicConv(in_c, c3r, 1),
                                _BasicConv(c3r, c3, 3, padding=1))
        self.b3 = nn.Sequential(_BasicConv(in_c, c5r, 1),
                                _BasicConv(c5r, c5, 5, padding=2))
        self.b4 = nn.Sequential(nn.MaxPool2D(3, stride=1, padding=1),
                                _BasicConv(in_c, proj, 1))

    def forward(self, x):
        return M.concat(
            [self.b1(x), self.b2(x), self.b3(x), self.b4(x)], axis=1)


class _AuxHead(nn.Layer):
    def __init__(self, in_c, num_classes):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D((4, 4))
        self.conv = _BasicConv(in_c, 128, 1)
        self.fc1 = nn.Linear(128 * 16, 1024)
        self.relu = nn.ReLU()
        self.dropout = nn.Dropout(0.7)
        self.fc2 = nn.Linear(1024, num_classes)

    def forward(self, x):
        x = self.conv(self.pool(x))
        x = self.relu(self.fc1(M.flatten(x, 1)))
        return self.fc2(self.dropout(x))


class GoogLeNet(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _BasicConv(3, 64, 7, stride=2, padding=3),
            nn.MaxPool2D(3, stride=2, ceil_mode=True),
            _BasicConv(64, 64, 1),
            _BasicConv(64, 192, 3, padding=1),
            nn.MaxPool2D(3, stride=2, ceil_mode=True),
        )
        self.inc3a = _Inception(192, 64, 96, 128, 16, 32, 32)
        self.inc3b = _Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = nn.MaxPool2D(3, stride=2, ceil_mode=True)
        self.inc4a = _Inception(480, 192, 96, 208, 16, 48, 64)
        self.inc4b = _Inception(512, 160, 112, 224, 24, 64, 64)
        self.inc4c = _Inception(512, 128, 128, 256, 24, 64, 64)
        self.inc4d = _Inception(512, 112, 144, 288, 32, 64, 64)
        self.inc4e = _Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(3, stride=2, ceil_mode=True)
        self.inc5a = _Inception(832, 256, 160, 320, 32, 128, 128)
        self.inc5b = _Inception(832, 384, 192, 384, 48, 128, 128)
        if with_pool:
            self.pool5 = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.dropout = nn.Dropout(0.2)
            self.fc = nn.Linear(1024, num_classes)
            self.aux1 = _AuxHead(512, num_classes)
            self.aux2 = _AuxHead(528, num_classes)

    def forward(self, x):
        x = self.pool3(self.inc3b(self.inc3a(self.stem(x))))
        x = self.inc4a(x)
        aux1 = self.aux1(x) if self.num_classes > 0 else None
        x = self.inc4d(self.inc4c(self.inc4b(x)))
        aux2 = self.aux2(x) if self.num_classes > 0 else None
        x = self.pool4(self.inc4e(x))
        x = self.inc5b(self.inc5a(x))
        if self.with_pool:
            x = self.pool5(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(M.flatten(x, 1)))
        return x, aux1, aux2


def googlenet(pretrained=False, **kwargs):
    from paddle_tpu.vision.models._pretrained import load_pretrained

    model = GoogLeNet(**kwargs)
    if pretrained:
        load_pretrained(model, "googlenet")
    return model
