"""paddle.vision.datasets (python/paddle/vision/datasets parity).

Zero-egress environment: the reference's downloaders can't run, so each dataset
loads from a local file if given, and otherwise raises with instructions.
``FakeData`` (the reference has an equivalent test-double pattern in
test/legacy_test) generates deterministic synthetic images for pipelines and
benchmarks.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from paddle_tpu.io.dataset import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "FakeData",
           "DatasetFolder", "ImageFolder", "Flowers", "VOC2012"]


class FakeData(Dataset):
    """Deterministic synthetic image classification data."""

    def __init__(self, num_samples=1000, image_shape=(3, 224, 224),
                 num_classes=1000, transform=None, seed=0):
        self.num_samples = num_samples
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self.seed = seed

    def __getitem__(self, idx):
        rng = np.random.RandomState(self.seed + idx)
        img = rng.randint(0, 256, self.image_shape).astype(np.float32) / 255.0
        label = np.int64(rng.randint(0, self.num_classes))
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return self.num_samples


def _need_file(path, what):
    if path is None or not os.path.exists(path):
        raise ValueError(
            f"{what} requires a local data file (downloads are disabled in "
            f"this environment); pass the path explicitly, got {path!r}"
        )


class MNIST(Dataset):
    NAME = "mnist"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend="cv2"):
        _need_file(image_path, type(self).__name__)
        _need_file(label_path, type(self).__name__)
        self.mode = mode
        self.transform = transform
        with gzip.open(label_path, "rb") as f:
            magic, n = struct.unpack(">II", f.read(8))
            self.labels = np.frombuffer(f.read(), dtype=np.uint8).astype(np.int64)
        with gzip.open(image_path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            self.images = np.frombuffer(f.read(), dtype=np.uint8).reshape(
                n, rows, cols
            )

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)[..., None]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.labels)


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"


class Cifar10(Dataset):
    _batches_train = [f"data_batch_{i}" for i in range(1, 6)]
    _batches_test = ["test_batch"]
    _key_prefix = "cifar-10-batches-py"
    _label_key = b"labels"

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend="cv2"):
        _need_file(data_file, type(self).__name__)
        self.transform = transform
        names = self._batches_train if mode == "train" else self._batches_test
        imgs, labels = [], []
        with tarfile.open(data_file, "r:gz") as tf:
            for m in tf.getmembers():
                if any(m.name.endswith(b) for b in names):
                    d = pickle.load(tf.extractfile(m), encoding="bytes")
                    imgs.append(d[b"data"])
                    labels.extend(d[self._label_key])
        self.images = np.concatenate(imgs).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(labels, dtype=np.int64)

    def __getitem__(self, idx):
        img = self.images[idx].transpose(1, 2, 0)  # HWC for transforms
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.labels)


class Cifar100(Cifar10):
    _batches_train = ["train"]
    _batches_test = ["test"]
    _key_prefix = "cifar-100-python"
    _label_key = b"fine_labels"


class Flowers(Dataset):
    """Oxford Flowers102 from local files (reference
    python/paddle/vision/datasets/flowers.py:54): ``data_file`` is the
    102flowers .tgz of jpgs, ``label_file``/``setid_file`` the .mat
    annotation files (parsed via scipy.io.loadmat, like the reference).
    No auto-download (this framework's local-file dataset policy)."""

    # the reference DELIBERATELY swaps trnid/tstid (flowers.py:48-51: the
    # official "test" split is the larger one, so it serves as train)
    _flag = {"train": "tstid", "test": "trnid", "valid": "valid"}

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend="cv2"):
        assert mode.lower() in ("train", "valid", "test"), mode
        _need_file(data_file, type(self).__name__)
        _need_file(label_file, type(self).__name__)
        _need_file(setid_file, type(self).__name__)
        import scipy.io as scio

        self.transform = transform
        self.backend = backend
        self._tar = tarfile.open(data_file)
        self._members = {m.name: m for m in self._tar.getmembers()}
        self.labels = scio.loadmat(label_file)["labels"][0]
        self.indexes = scio.loadmat(setid_file)[
            self._flag[mode.lower()]][0]

    def __getitem__(self, idx):
        import io as _io

        from PIL import Image

        index = int(self.indexes[idx])
        label = np.array([self.labels[index - 1]], dtype=np.int64)
        name = "jpg/image_%05d.jpg" % index
        raw = self._tar.extractfile(self._members[name]).read()
        image = Image.open(_io.BytesIO(raw))
        if self.backend == "cv2":
            image = np.array(image)
        if self.transform is not None:
            image = self.transform(image)
        return image, label

    def __len__(self):
        return len(self.indexes)


class VOC2012(Dataset):
    """Pascal VOC2012 segmentation pairs from the local VOCtrainval tar
    (reference python/paddle/vision/datasets/voc2012.py:54): image jpg +
    label png streamed straight out of the archive, segmentation split
    lists from ImageSets/Segmentation/{train,trainval,val}.txt."""

    _SET = "VOCdevkit/VOC2012/ImageSets/Segmentation/{}.txt"
    _DATA = "VOCdevkit/VOC2012/JPEGImages/{}.jpg"
    _LABEL = "VOCdevkit/VOC2012/SegmentationClass/{}.png"
    # reference voc2012.py:51: 'train' is the trainval union, 'test' the
    # train list (the real test annotations are not in the archive)
    _flag = {"train": "trainval", "test": "train", "valid": "val"}

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend="cv2"):
        assert mode.lower() in ("train", "valid", "test"), mode
        _need_file(data_file, type(self).__name__)
        self.transform = transform
        self.backend = backend
        self._tar = tarfile.open(data_file)
        self._members = {m.name: m for m in self._tar.getmembers()}
        flag = self._flag[mode.lower()]
        sets = self._tar.extractfile(self._members[self._SET.format(flag)])
        self.data, self.labels = [], []
        for line in sets:
            name = line.strip().decode("utf-8")
            if not name:
                continue
            self.data.append(self._DATA.format(name))
            self.labels.append(self._LABEL.format(name))

    def __getitem__(self, idx):
        import io as _io

        from PIL import Image

        img = Image.open(_io.BytesIO(
            self._tar.extractfile(self._members[self.data[idx]]).read()))
        label = Image.open(_io.BytesIO(
            self._tar.extractfile(self._members[self.labels[idx]]).read()))
        if self.backend == "cv2":
            img = np.array(img)
            label = np.array(label)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.data)


_IMG_EXTS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".npy")


class DatasetFolder(Dataset):
    """Directory-per-class layout; .npy images supported natively (PIL-free)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or self._default_loader
        exts = extensions or _IMG_EXTS
        classes = sorted(
            d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d))
        )
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fn in sorted(os.listdir(cdir)):
                ok = (is_valid_file(fn) if is_valid_file
                      else fn.lower().endswith(tuple(exts)))
                if ok:
                    self.samples.append((os.path.join(cdir, fn),
                                         self.class_to_idx[c]))

    @staticmethod
    def _default_loader(path):
        if path.endswith(".npy"):
            return np.load(path)
        raise NotImplementedError(
            "non-.npy image decoding requires cv2/PIL; provide a custom loader"
        )

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(target)

    def __len__(self):
        return len(self.samples)


class ImageFolder(DatasetFolder):
    """Flat folder of images, no labels."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or DatasetFolder._default_loader
        exts = extensions or _IMG_EXTS
        self.samples = [
            os.path.join(root, fn) for fn in sorted(os.listdir(root))
            if (is_valid_file(fn) if is_valid_file
                else fn.lower().endswith(tuple(exts)))
        ]

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)
