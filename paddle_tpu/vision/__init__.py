"""paddle.vision namespace (python/paddle/vision parity, SURVEY.md §2.10)."""
from paddle_tpu.vision import datasets, models, ops, transforms  # noqa: F401
from paddle_tpu.vision.models import (  # noqa: F401
    LeNet, MobileNetV1, ResNet, VGG, mobilenet_v1, resnet18, resnet34,
    resnet50, resnet101, resnet152, vgg11, vgg13, vgg16, vgg19,
)


def set_image_backend(backend):
    if backend not in ("cv2", "pil", "tensor"):
        raise ValueError(f"unsupported backend {backend}")
    global _image_backend
    _image_backend = backend


_image_backend = "cv2"


def get_image_backend():
    return _image_backend


def image_load(path, backend=None):
    import numpy as np

    if str(path).endswith(".npy"):
        return np.load(path)
    raise NotImplementedError("image decoding requires cv2/PIL (not bundled)")
