"""paddle.vision.transforms (python/paddle/vision/transforms parity)."""
from paddle_tpu.vision.transforms import functional  # noqa: F401
from paddle_tpu.vision.transforms.functional import (  # noqa: F401
    adjust_brightness, adjust_contrast, adjust_hue, adjust_saturation, affine,
    center_crop, crop, erase, hflip, normalize, pad, perspective, resize,
    rotate, to_grayscale, to_tensor, vflip,
)
from paddle_tpu.vision.transforms.transforms import (  # noqa: F401
    BaseTransform, BrightnessTransform, CenterCrop, ColorJitter, Compose,
    ContrastTransform, Grayscale, HueTransform, Normalize, Pad, RandomAffine,
    RandomCrop, RandomErasing, RandomHorizontalFlip, RandomPerspective,
    RandomResizedCrop, RandomRotation, RandomVerticalFlip, Resize,
    SaturationTransform, ToTensor, Transpose,
)
