"""paddle.vision.transforms (python/paddle/vision/transforms parity)."""
from paddle_tpu.vision.transforms import functional  # noqa: F401
from paddle_tpu.vision.transforms.transforms import (  # noqa: F401
    BaseTransform, BrightnessTransform, CenterCrop, Compose, ContrastTransform,
    Grayscale, Normalize, Pad, RandomCrop, RandomHorizontalFlip,
    RandomResizedCrop, RandomVerticalFlip, Resize, ToTensor, Transpose,
)
