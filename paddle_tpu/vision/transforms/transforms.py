"""Transform classes (python/paddle/vision/transforms/transforms.py parity)."""
from __future__ import annotations

import random

import numpy as np

from paddle_tpu.vision.transforms import functional as F

__all__ = [
    "BaseTransform", "Compose", "ToTensor", "Resize", "RandomResizedCrop",
    "CenterCrop", "RandomHorizontalFlip", "RandomVerticalFlip", "Normalize",
    "Transpose", "Pad", "RandomCrop", "Grayscale", "BrightnessTransform",
    "ContrastTransform",
]


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __init__(self, keys=None):
        self.keys = keys

    def _apply_image(self, img):
        raise NotImplementedError

    def __call__(self, inputs):
        if isinstance(inputs, tuple) and self.keys:
            out = []
            for key, data in zip(self.keys, inputs):
                out.append(self._apply_image(data) if key == "image" else data)
            return tuple(out)
        return self._apply_image(inputs)


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        return F.to_tensor(img, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return F.resize(img, self.size, self.interpolation)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        img = np.asarray(img)
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = np.exp(random.uniform(np.log(self.ratio[0]), np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                top = random.randint(0, h - ch)
                left = random.randint(0, w - cw)
                return F.resize(F.crop(img, top, left, ch, cw), self.size,
                                self.interpolation)
        return F.resize(F.center_crop(img, min(h, w)), self.size,
                        self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = size

    def _apply_image(self, img):
        return F.center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        if self.padding is not None:
            img = F.pad(img, self.padding, self.fill, self.padding_mode)
        img = np.asarray(img)
        h, w = img.shape[:2]
        th, tw = self.size
        if self.pad_if_needed and (h < th or w < tw):
            img = F.pad(img, (0, max(th - h, 0), 0, max(tw - w, 0)), self.fill,
                        self.padding_mode)
            h, w = img.shape[:2]
        top = random.randint(0, h - th)
        left = random.randint(0, w - tw)
        return F.crop(img, top, left, th, tw)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return F.hflip(img) if random.random() < self.prob else img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return F.vflip(img) if random.random() < self.prob else img


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        super().__init__(keys)
        if isinstance(mean, (int, float)):
            mean = [mean] * 3
        if isinstance(std, (int, float)):
            std = [std] * 3
        self.mean = mean
        self.std = std
        self.data_format = data_format

    def _apply_image(self, img):
        return F.normalize(img, self.mean, self.std, self.data_format)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        return F.transpose(img, self.order)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return F.pad(img, self.padding, self.fill, self.padding_mode)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return F.to_grayscale(img, self.num_output_channels)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_brightness(img, factor)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_contrast(img, factor)
