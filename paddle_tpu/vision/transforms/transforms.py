"""Transform classes (python/paddle/vision/transforms/transforms.py parity)."""
from __future__ import annotations

import random

import numpy as np

from paddle_tpu.vision.transforms import functional as F

__all__ = [
    "BaseTransform", "Compose", "ToTensor", "Resize", "RandomResizedCrop",
    "CenterCrop", "RandomHorizontalFlip", "RandomVerticalFlip", "Normalize",
    "Transpose", "Pad", "RandomCrop", "Grayscale", "BrightnessTransform",
    "ContrastTransform",
]


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __init__(self, keys=None):
        self.keys = keys

    def _apply_image(self, img):
        raise NotImplementedError

    def __call__(self, inputs):
        if isinstance(inputs, tuple) and self.keys:
            out = []
            for key, data in zip(self.keys, inputs):
                out.append(self._apply_image(data) if key == "image" else data)
            return tuple(out)
        return self._apply_image(inputs)


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        return F.to_tensor(img, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return F.resize(img, self.size, self.interpolation)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        img = np.asarray(img)
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = np.exp(random.uniform(np.log(self.ratio[0]), np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                top = random.randint(0, h - ch)
                left = random.randint(0, w - cw)
                return F.resize(F.crop(img, top, left, ch, cw), self.size,
                                self.interpolation)
        return F.resize(F.center_crop(img, min(h, w)), self.size,
                        self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = size

    def _apply_image(self, img):
        return F.center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        if self.padding is not None:
            img = F.pad(img, self.padding, self.fill, self.padding_mode)
        img = np.asarray(img)
        h, w = img.shape[:2]
        th, tw = self.size
        if self.pad_if_needed and (h < th or w < tw):
            img = F.pad(img, (0, max(th - h, 0), 0, max(tw - w, 0)), self.fill,
                        self.padding_mode)
            h, w = img.shape[:2]
        top = random.randint(0, h - th)
        left = random.randint(0, w - tw)
        return F.crop(img, top, left, th, tw)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return F.hflip(img) if random.random() < self.prob else img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return F.vflip(img) if random.random() < self.prob else img


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        super().__init__(keys)
        if isinstance(mean, (int, float)):
            mean = [mean] * 3
        if isinstance(std, (int, float)):
            std = [std] * 3
        self.mean = mean
        self.std = std
        self.data_format = data_format

    def _apply_image(self, img):
        return F.normalize(img, self.mean, self.std, self.data_format)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        return F.transpose(img, self.order)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return F.pad(img, self.padding, self.fill, self.padding_mode)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return F.to_grayscale(img, self.num_output_channels)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_brightness(img, factor)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_contrast(img, factor)


class SaturationTransform(BaseTransform):
    def __init__(self, value=0.0, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        f = 1.0 + np.random.uniform(-self.value, self.value)
        return F.adjust_saturation(img, f)


class HueTransform(BaseTransform):
    def __init__(self, value=0.0, keys=None):
        super().__init__(keys)
        self.value = min(value, 0.5)

    def _apply_image(self, img):
        return F.adjust_hue(img, np.random.uniform(-self.value, self.value))


class ColorJitter(BaseTransform):
    """Random brightness/contrast/saturation/hue (reference transforms.py)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0, keys=None):
        super().__init__(keys)
        self.b, self.c, self.s, self.h = brightness, contrast, saturation, hue

    def _apply_image(self, img):
        if self.b:
            img = F.adjust_brightness(img, 1 + np.random.uniform(-self.b, self.b))
        if self.c:
            img = F.adjust_contrast(img, 1 + np.random.uniform(-self.c, self.c))
        if self.s:
            img = F.adjust_saturation(img, 1 + np.random.uniform(-self.s, self.s))
        if self.h:
            img = F.adjust_hue(img, np.random.uniform(-self.h, self.h))
        return img


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        self.degrees = (-degrees, degrees) if np.isscalar(degrees) else tuple(degrees)
        self.kw = dict(interpolation=interpolation, expand=expand, center=center)

    def _apply_image(self, img):
        angle = np.random.uniform(*self.degrees)
        return F.rotate(img, angle, **self.kw)


class RandomAffine(BaseTransform):
    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        super().__init__(keys)
        self.degrees = (-degrees, degrees) if np.isscalar(degrees) else tuple(degrees)
        self.translate = translate
        self.scale = scale
        self.shear = shear
        self.fill = fill
        self.center = center

    def _apply_image(self, img):
        h, w = np.asarray(img).shape[:2] if np.asarray(img).ndim == 3 else np.asarray(img).shape[-2:]
        angle = np.random.uniform(*self.degrees)
        tx = ty = 0
        if self.translate:
            tx = np.random.uniform(-self.translate[0], self.translate[0]) * w
            ty = np.random.uniform(-self.translate[1], self.translate[1]) * h
        sc = np.random.uniform(*self.scale) if self.scale else 1.0
        sh = (np.random.uniform(-self.shear, self.shear) if np.isscalar(self.shear)
              else np.random.uniform(*self.shear[:2])) if self.shear else 0.0
        return F.affine(img, angle=angle, translate=(tx, ty), scale=sc,
                        shear=(sh, 0.0), fill=self.fill, center=self.center)


class RandomPerspective(BaseTransform):
    def __init__(self, prob=0.5, distortion_scale=0.5, interpolation="nearest",
                 fill=0, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.scale = distortion_scale
        self.interpolation = interpolation
        self.fill = fill

    def _apply_image(self, img):
        if np.random.rand() >= self.prob:
            return img
        h, w = np.asarray(img).shape[:2]
        d = self.scale
        half_h, half_w = int(h * d / 2), int(w * d / 2)
        tl = (np.random.randint(0, half_w + 1), np.random.randint(0, half_h + 1))
        tr = (w - 1 - np.random.randint(0, half_w + 1), np.random.randint(0, half_h + 1))
        br = (w - 1 - np.random.randint(0, half_w + 1), h - 1 - np.random.randint(0, half_h + 1))
        bl = (np.random.randint(0, half_w + 1), h - 1 - np.random.randint(0, half_h + 1))
        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        return F.perspective(img, start, [tl, tr, br, bl],
                             interpolation=self.interpolation, fill=self.fill)


class RandomErasing(BaseTransform):
    """Random rectangle erase on CHW tensors (reference transforms.py)."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3), value=0,
                 inplace=False, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value

    def _apply_image(self, img):
        if np.random.rand() >= self.prob:
            return img
        arr = np.asarray(img.numpy() if hasattr(img, "numpy") else img)
        # Tensors are CHW; ndarray images are HWC (channels last, 1/3/4)
        hwc_layout = (not hasattr(img, "numpy")) and arr.ndim == 3 and arr.shape[-1] in (1, 3, 4)
        h, w = (arr.shape[0], arr.shape[1]) if hwc_layout else arr.shape[-2:]
        area = h * w
        for _ in range(10):
            target = np.random.uniform(*self.scale) * area
            ar = np.random.uniform(*self.ratio)
            eh = int(round(np.sqrt(target * ar)))
            ew = int(round(np.sqrt(target / ar)))
            if eh < h and ew < w:
                i = np.random.randint(0, h - eh)
                j = np.random.randint(0, w - ew)
                if hwc_layout:
                    out = arr.copy()
                    out[i:i + eh, j:j + ew, :] = self.value
                    return out
                return F.erase(img, i, j, eh, ew, self.value)
        return img
