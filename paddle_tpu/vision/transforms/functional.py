"""Transform functionals on numpy HWC images
(python/paddle/vision/transforms/functional*.py parity; numpy backend — PIL is
not a dependency of the TPU build, host-side image work is numpy/CPU)."""
from __future__ import annotations

import numbers

import numpy as np

__all__ = [
    "to_tensor", "resize", "pad", "crop", "center_crop", "hflip", "vflip",
    "normalize", "transpose", "adjust_brightness", "adjust_contrast",
    "rotate", "to_grayscale",
]


def _as_hwc(img):
    img = np.asarray(img)
    if img.ndim == 2:
        img = img[:, :, None]
    return img


def to_tensor(pic, data_format="CHW"):
    from paddle_tpu.tensor.tensor import Tensor

    img = _as_hwc(pic).astype(np.float32)
    if img.dtype == np.float32 and np.asarray(pic).dtype == np.uint8:
        img = img / 255.0
    elif np.asarray(pic).dtype == np.uint8:
        img = img / 255.0
    if data_format == "CHW":
        img = img.transpose(2, 0, 1)
    return Tensor(img)


def resize(img, size, interpolation="bilinear"):
    img = _as_hwc(img)
    h, w = img.shape[:2]
    if isinstance(size, int):
        if h < w:
            oh, ow = size, int(size * w / h)
        else:
            oh, ow = int(size * h / w), size
    else:
        oh, ow = size
    ys = (np.arange(oh) + 0.5) * h / oh - 0.5
    xs = (np.arange(ow) + 0.5) * w / ow - 0.5
    if interpolation == "nearest":
        yi = np.clip(np.round(ys).astype(int), 0, h - 1)
        xi = np.clip(np.round(xs).astype(int), 0, w - 1)
        return img[yi][:, xi]
    y0 = np.clip(np.floor(ys).astype(int), 0, h - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, w - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = np.clip(ys - y0, 0, 1)[:, None, None]
    wx = np.clip(xs - x0, 0, 1)[None, :, None]
    f = img.astype(np.float32)
    out = (f[y0][:, x0] * (1 - wy) * (1 - wx) + f[y1][:, x0] * wy * (1 - wx)
           + f[y0][:, x1] * (1 - wy) * wx + f[y1][:, x1] * wy * wx)
    return out.astype(img.dtype) if img.dtype == np.uint8 else out


def pad(img, padding, fill=0, padding_mode="constant"):
    img = _as_hwc(img)
    if isinstance(padding, numbers.Number):
        pl = pr = pt = pb = int(padding)
    elif len(padding) == 2:
        pl, pt = padding
        pr, pb = padding
    else:
        pl, pt, pr, pb = padding
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    kwargs = {"constant_values": fill} if mode == "constant" else {}
    return np.pad(img, ((pt, pb), (pl, pr), (0, 0)), mode=mode, **kwargs)


def crop(img, top, left, height, width):
    return _as_hwc(img)[top:top + height, left:left + width]


def center_crop(img, output_size):
    img = _as_hwc(img)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    h, w = img.shape[:2]
    th, tw = output_size
    i = int(round((h - th) / 2.0))
    j = int(round((w - tw) / 2.0))
    return crop(img, i, j, th, tw)


def hflip(img):
    return _as_hwc(img)[:, ::-1]


def vflip(img):
    return _as_hwc(img)[::-1]


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    from paddle_tpu.tensor.tensor import Tensor

    is_tensor = isinstance(img, Tensor)
    arr = np.asarray(img.numpy() if is_tensor else img, dtype=np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        arr = (arr - mean[:, None, None]) / std[:, None, None]
    else:
        arr = (arr - mean) / std
    return Tensor(arr) if is_tensor else arr


def transpose(img, order=(2, 0, 1)):
    return _as_hwc(img).transpose(order)


def adjust_brightness(img, factor):
    img = _as_hwc(img)
    out = img.astype(np.float32) * factor
    return np.clip(out, 0, 255).astype(img.dtype)


def adjust_contrast(img, factor):
    img = _as_hwc(img)
    mean = img.astype(np.float32).mean()
    out = (img.astype(np.float32) - mean) * factor + mean
    return np.clip(out, 0, 255).astype(img.dtype)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    img = _as_hwc(img)
    k = int(round(angle / 90.0)) % 4
    if abs(angle - 90 * round(angle / 90.0)) > 1e-6:
        raise NotImplementedError(
            "only multiples of 90 degrees supported by the numpy backend"
        )
    return np.rot90(img, k)


def to_grayscale(img, num_output_channels=1):
    img = _as_hwc(img).astype(np.float32)
    g = img[..., 0] * 0.299 + img[..., 1] * 0.587 + img[..., 2] * 0.114
    g = g[..., None]
    if num_output_channels == 3:
        g = np.repeat(g, 3, axis=-1)
    return g
