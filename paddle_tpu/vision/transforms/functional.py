"""Transform functionals on numpy HWC images
(python/paddle/vision/transforms/functional*.py parity; numpy backend — PIL is
not a dependency of the TPU build, host-side image work is numpy/CPU)."""
from __future__ import annotations

import numbers

import numpy as np

__all__ = [
    "to_tensor", "resize", "pad", "crop", "center_crop", "hflip", "vflip",
    "normalize", "transpose", "adjust_brightness", "adjust_contrast",
    "rotate", "to_grayscale",
]


def _as_hwc(img):
    img = np.asarray(img)
    if img.ndim == 2:
        img = img[:, :, None]
    return img


def to_tensor(pic, data_format="CHW"):
    from paddle_tpu.tensor.tensor import Tensor

    img = _as_hwc(pic).astype(np.float32)
    if img.dtype == np.float32 and np.asarray(pic).dtype == np.uint8:
        img = img / 255.0
    elif np.asarray(pic).dtype == np.uint8:
        img = img / 255.0
    if data_format == "CHW":
        img = img.transpose(2, 0, 1)
    return Tensor(img)


def resize(img, size, interpolation="bilinear"):
    img = _as_hwc(img)
    h, w = img.shape[:2]
    if isinstance(size, int):
        if h < w:
            oh, ow = size, int(size * w / h)
        else:
            oh, ow = int(size * h / w), size
    else:
        oh, ow = size
    ys = (np.arange(oh) + 0.5) * h / oh - 0.5
    xs = (np.arange(ow) + 0.5) * w / ow - 0.5
    if interpolation == "nearest":
        yi = np.clip(np.round(ys).astype(int), 0, h - 1)
        xi = np.clip(np.round(xs).astype(int), 0, w - 1)
        return img[yi][:, xi]
    y0 = np.clip(np.floor(ys).astype(int), 0, h - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, w - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = np.clip(ys - y0, 0, 1)[:, None, None]
    wx = np.clip(xs - x0, 0, 1)[None, :, None]
    f = img.astype(np.float32)
    out = (f[y0][:, x0] * (1 - wy) * (1 - wx) + f[y1][:, x0] * wy * (1 - wx)
           + f[y0][:, x1] * (1 - wy) * wx + f[y1][:, x1] * wy * wx)
    return out.astype(img.dtype) if img.dtype == np.uint8 else out


def pad(img, padding, fill=0, padding_mode="constant"):
    img = _as_hwc(img)
    if isinstance(padding, numbers.Number):
        pl = pr = pt = pb = int(padding)
    elif len(padding) == 2:
        pl, pt = padding
        pr, pb = padding
    else:
        pl, pt, pr, pb = padding
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    kwargs = {"constant_values": fill} if mode == "constant" else {}
    return np.pad(img, ((pt, pb), (pl, pr), (0, 0)), mode=mode, **kwargs)


def crop(img, top, left, height, width):
    return _as_hwc(img)[top:top + height, left:left + width]


def center_crop(img, output_size):
    img = _as_hwc(img)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    h, w = img.shape[:2]
    th, tw = output_size
    i = int(round((h - th) / 2.0))
    j = int(round((w - tw) / 2.0))
    return crop(img, i, j, th, tw)


def hflip(img):
    return _as_hwc(img)[:, ::-1]


def vflip(img):
    return _as_hwc(img)[::-1]


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    from paddle_tpu.tensor.tensor import Tensor

    is_tensor = isinstance(img, Tensor)
    arr = np.asarray(img.numpy() if is_tensor else img, dtype=np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        arr = (arr - mean[:, None, None]) / std[:, None, None]
    else:
        arr = (arr - mean) / std
    return Tensor(arr) if is_tensor else arr


def transpose(img, order=(2, 0, 1)):
    return _as_hwc(img).transpose(order)


def adjust_brightness(img, factor):
    img = _as_hwc(img)
    out = img.astype(np.float32) * factor
    return np.clip(out, 0, 255).astype(img.dtype)


def adjust_contrast(img, factor):
    img = _as_hwc(img)
    mean = img.astype(np.float32).mean()
    out = (img.astype(np.float32) - mean) * factor + mean
    return np.clip(out, 0, 255).astype(img.dtype)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    hwc = _as_hwc(img)
    if abs(angle - 90 * round(angle / 90.0)) <= 1e-6:
        return np.rot90(hwc, int(round(angle / 90.0)) % 4)
    if expand:
        raise NotImplementedError(
            "rotate(expand=True) with non-right angles is not implemented; "
            "the canvas is kept at the input size"
        )
    # arbitrary angles: affine warp (negated — affine() maps output←input);
    # sampling is nearest-neighbor regardless of `interpolation`
    return affine(hwc, angle=-angle, center=center, fill=fill,
                  interpolation=interpolation)


def to_grayscale(img, num_output_channels=1):
    img = _as_hwc(img).astype(np.float32)
    g = img[..., 0] * 0.299 + img[..., 1] * 0.587 + img[..., 2] * 0.114
    g = g[..., None]
    if num_output_channels == 3:
        g = np.repeat(g, 3, axis=-1)
    return g


def adjust_saturation(img, factor):
    """Blend with the grayscale image (reference functional.py adjust_saturation)."""
    hwc = _as_hwc(img)
    x = hwc.astype(np.float32)
    gray = x @ np.asarray([0.299, 0.587, 0.114], np.float32)
    out = factor * x + (1 - factor) * gray[..., None]
    hi = 255.0 if hwc.dtype == np.uint8 or x.max() > 1.5 else 1.0
    return np.clip(out, 0, hi).astype(hwc.dtype)


def adjust_hue(img, factor):
    """Shift hue in HSV space by factor∈[-0.5, 0.5] (reference adjust_hue)."""
    hwc = _as_hwc(img).astype(np.float32)
    scale = 255.0 if hwc.max() > 1.5 else 1.0
    x = hwc / scale
    mx = x.max(-1)
    mn = x.min(-1)
    diff = mx - mn + 1e-12
    r, g, b = x[..., 0], x[..., 1], x[..., 2]
    h = np.where(mx == r, ((g - b) / diff) % 6,
                 np.where(mx == g, (b - r) / diff + 2, (r - g) / diff + 4)) / 6.0
    s = np.where(mx > 0, diff / (mx + 1e-12), 0.0)
    v = mx
    h = (h + factor) % 1.0
    i = np.floor(h * 6).astype(np.int32) % 6
    f = h * 6 - np.floor(h * 6)
    p = v * (1 - s)
    q = v * (1 - f * s)
    t = v * (1 - (1 - f) * s)
    conds = [(i == k)[..., None] for k in range(6)]
    out = np.select(
        conds,
        [np.stack([v, t, p], -1), np.stack([q, v, p], -1), np.stack([p, v, t], -1),
         np.stack([p, q, v], -1), np.stack([t, p, v], -1), np.stack([v, p, q], -1)],
    )
    out = out * scale
    return out.astype(_as_hwc(img).dtype)


def _affine_matrix(angle, translate, scale, shear, center):
    rot = np.deg2rad(angle)
    sx, sy = np.deg2rad(shear[0]), np.deg2rad(shear[1])
    cx, cy = center
    tx, ty = translate
    # RSS (rotate-scale-shear) about center, then translate
    a = np.cos(rot - sy) / np.cos(sy)
    b = -np.cos(rot - sy) * np.tan(sx) / np.cos(sy) - np.sin(rot)
    c = np.sin(rot - sy) / np.cos(sy)
    d = -np.sin(rot - sy) * np.tan(sx) / np.cos(sy) + np.cos(rot)
    m = np.array([[a, b, 0.0], [c, d, 0.0]]) * scale
    m[0, 2] = tx + cx - m[0, 0] * cx - m[0, 1] * cy
    m[1, 2] = ty + cy - m[1, 0] * cx - m[1, 1] * cy
    return m


def _sample_inverse(hwc, inv_map, fill=0):
    h, w = hwc.shape[:2]
    ys, xs = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    src = inv_map(xs, ys)
    sx, sy = src
    valid = (sx >= 0) & (sx <= w - 1) & (sy >= 0) & (sy <= h - 1)
    sxc = np.clip(np.round(sx).astype(np.int32), 0, w - 1)
    syc = np.clip(np.round(sy).astype(np.int32), 0, h - 1)
    out = hwc[syc, sxc]
    out[~valid] = fill
    return out


def affine(img, angle=0.0, translate=(0, 0), scale=1.0, shear=(0.0, 0.0),
           interpolation="nearest", fill=0, center=None):
    """Affine warp (reference functional.py affine), nearest sampling."""
    hwc = _as_hwc(img)
    h, w = hwc.shape[:2]
    if center is None:
        center = ((w - 1) / 2, (h - 1) / 2)
    if np.isscalar(shear):
        shear = (float(shear), 0.0)
    m = _affine_matrix(angle, translate, scale, shear, center)
    minv = np.linalg.inv(np.vstack([m, [0, 0, 1]]))[:2]

    def inv_map(xs, ys):
        sx = minv[0, 0] * xs + minv[0, 1] * ys + minv[0, 2]
        sy = minv[1, 0] * xs + minv[1, 1] * ys + minv[1, 2]
        return sx, sy

    return _sample_inverse(hwc, inv_map, fill)


def perspective(img, startpoints, endpoints, interpolation="nearest", fill=0):
    """Perspective warp from 4 point pairs (reference functional.py perspective)."""
    hwc = _as_hwc(img)
    A = []
    B = []
    for (sx, sy), (ex, ey) in zip(startpoints, endpoints):
        A.append([ex, ey, 1, 0, 0, 0, -sx * ex, -sx * ey])
        A.append([0, 0, 0, ex, ey, 1, -sy * ex, -sy * ey])
        B += [sx, sy]
    coef = np.linalg.lstsq(np.asarray(A, np.float64), np.asarray(B, np.float64), rcond=None)[0]
    a, b, c, d, e, f, g, hcf = coef

    def inv_map(xs, ys):
        den = g * xs + hcf * ys + 1
        return (a * xs + b * ys + c) / den, (d * xs + e * ys + f) / den

    return _sample_inverse(hwc, inv_map, fill)


def erase(img, i, j, h, w, v, inplace=False):
    """Erase a region (reference functional.py erase); img CHW tensor/array."""
    from paddle_tpu.tensor.tensor import Tensor as _T

    if isinstance(img, _T):
        arr = img.numpy().copy()
        arr[..., i:i + h, j:j + w] = v
        return _T(arr)
    arr = img if inplace else img.copy()
    arr[..., i:i + h, j:j + w] = v
    return arr
