"""paddle.onnx parity (reference: python/paddle/onnx/export.py, which defers to the
paddle2onnx package).  The TPU-native interchange format is StableHLO
(paddle_tpu.jit.save / paddle_tpu.inference); ONNX export additionally requires the
optional ``onnx`` package, which is not in this image, so the API is gated.
"""
from __future__ import annotations

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    try:
        import onnx  # noqa: F401
    except ImportError:
        raise RuntimeError(
            "paddle_tpu.onnx.export requires the optional 'onnx' package, which is "
            "not installed. For deployment use paddle_tpu.jit.save (StableHLO), the "
            "TPU-native exchange format, instead."
        )
    raise NotImplementedError(
        "ONNX export is not yet implemented; use paddle_tpu.jit.save (StableHLO).")
