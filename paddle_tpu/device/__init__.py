"""Device management facade (analog of python/paddle/device/__init__.py in the
reference, which resolves custom device types via core.get_all_custom_device_type —
python/paddle/device/__init__.py:201-313).

The heavy lifting lives in paddle_tpu.core.device; this package adds the ``cuda`` /
``xpu`` compatibility namespaces (memory stats map onto jax device memory stats) and
stream/event objects whose synchronization semantics collapse onto XLA's ordered
execution per device.
"""
from __future__ import annotations

import contextlib

import jax

from paddle_tpu.core.device import (  # noqa: F401
    CPUPlace,
    CUDAPinnedPlace,
    CUDAPlace,
    CustomPlace,
    Place,
    TPUPlace,
    XPUPlace,
    current_place,
    device_count,
    device_guard,
    get_all_custom_device_type,
    get_all_device_type,
    get_device,
    is_compiled_with_cuda,
    is_compiled_with_custom_device,
    is_compiled_with_tpu,
    is_compiled_with_xpu,
    set_device,
    synchronize,
)

from paddle_tpu.device import cuda, xpu  # noqa: F401,E402

__all__ = [
    "get_device", "set_device", "device_count", "synchronize",
    "get_available_device", "get_available_custom_device",
    "get_all_device_type", "get_all_custom_device_type",
    "is_compiled_with_cuda", "is_compiled_with_xpu", "is_compiled_with_tpu",
    "is_compiled_with_custom_device", "is_compiled_with_rocm",
    "is_compiled_with_cinn", "is_compiled_with_distribute",
    "is_compiled_with_ipu", "is_compiled_with_mlu", "is_compiled_with_npu",
    "Stream", "Event", "stream_guard", "current_stream",
    "cuda", "xpu", "IPUPlace", "MLUPlace", "NPUPlace",
]


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_cinn() -> bool:
    # the XLA compiler is always present — it is this framework's CINN
    return True


def is_compiled_with_distribute() -> bool:
    return True


def is_compiled_with_ipu() -> bool:
    return False


def is_compiled_with_mlu() -> bool:
    return False


def is_compiled_with_npu() -> bool:
    return False


def IPUPlace(*a):  # pragma: no cover - parity shim
    raise RuntimeError("IPU is not supported by paddle_tpu")


def MLUPlace(*a):  # pragma: no cover - parity shim
    raise RuntimeError("MLU is not supported by paddle_tpu")


def NPUPlace(*a):  # pragma: no cover - parity shim
    raise RuntimeError("NPU is not supported by paddle_tpu")


def get_available_device():
    """List of device strings usable with ``set_device`` (e.g. ['tpu:0', ...])."""
    out = []
    counts = {}
    for d in jax.devices():
        kind = {"gpu": "gpu", "tpu": "tpu", "cpu": "cpu"}.get(d.platform, d.platform)
        i = counts.get(kind, 0)
        counts[kind] = i + 1
        out.append(f"{kind}:{i}" if kind != "cpu" else "cpu")
    return out


def get_available_custom_device():
    return [d for d in get_available_device()
            if d.split(":")[0] not in ("cpu", "gpu", "tpu")]


class Event:
    """Device event.  XLA executes each device's work in program order, so an event
    is simply a marker tensor; ``synchronize`` blocks until prior work finished
    (analog of phi::event::Event, paddle/phi/backends/event.cc)."""

    def __init__(self, device=None, enable_timing=False, blocking=False,
                 interprocess=False):
        self._device = device
        self._marker = None

    def record(self, stream=None):
        # block_until_ready on a trivial computation after queued work acts as a
        # completion marker for everything enqueued so far on the device.
        import jax.numpy as jnp

        self._marker = jnp.zeros((), jnp.int32) + 0

    def query(self) -> bool:
        if self._marker is None:
            return True
        return self._marker.is_ready()

    def synchronize(self):
        if self._marker is not None:
            self._marker.block_until_ready()

    def elapsed_time(self, end_event) -> float:  # pragma: no cover - timing shim
        return 0.0


class Stream:
    """Device stream.  XLA owns stream assignment (its latency-hiding scheduler is
    the analog of Paddle's multi-stream executor, SURVEY.md §5.8); this object keeps
    the API surface (wait_event/wait_stream/record_event/synchronize)."""

    def __init__(self, device=None, priority=2):
        self.device = device
        self.priority = priority

    def wait_event(self, event: Event):
        event.synchronize()

    def wait_stream(self, stream: "Stream"):
        stream.synchronize()

    def record_event(self, event: Event = None) -> Event:
        event = event or Event(self.device)
        event.record(self)
        return event

    def synchronize(self):
        synchronize()

    @property
    def stream_base(self):
        return self


_current_stream = Stream()


def current_stream(device=None) -> Stream:
    return _current_stream


def set_stream(stream: Stream) -> Stream:
    global _current_stream
    prev = _current_stream
    _current_stream = stream
    return prev


@contextlib.contextmanager
def stream_guard(stream: Stream):
    prev = set_stream(stream)
    try:
        yield
    finally:
        set_stream(prev)
