"""paddle.device.xpu compatibility namespace (reference: python/paddle/device/xpu/)."""
from __future__ import annotations


def device_count() -> int:
    return 0


def is_available() -> bool:
    return False


def synchronize(device=None):
    pass


def empty_cache():
    pass
