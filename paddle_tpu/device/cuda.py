"""paddle.device.cuda compatibility namespace.

On TPU there is no CUDA, but user code ported from the reference calls
``paddle.device.cuda.max_memory_allocated()`` etc. (python/paddle/device/cuda/,
phi/core/memory/stats.cc).  These map onto jax's per-device memory stats so the
calls keep working and report real accelerator numbers.
"""
from __future__ import annotations

import jax


def _stats(device=None) -> dict:
    devs = jax.devices()
    idx = 0
    if isinstance(device, int):
        idx = device
    elif device is not None and hasattr(device, "get_device_id"):
        idx = device.get_device_id()
    try:
        return devs[idx].memory_stats() or {}
    except Exception:
        return {}


def device_count() -> int:
    return sum(1 for d in jax.devices() if d.platform != "cpu")


def is_available() -> bool:
    return device_count() > 0


def current_device():
    return 0


def get_device_name(device=None) -> str:
    devs = [d for d in jax.devices() if d.platform != "cpu"] or jax.devices()
    idx = device if isinstance(device, int) else 0
    return devs[min(idx, len(devs) - 1)].device_kind


def get_device_capability(device=None):
    return (0, 0)


def get_device_properties(device=None):
    class _Props:
        pass

    p = _Props()
    p.name = get_device_name(device)
    stats = _stats(device)
    p.total_memory = stats.get("bytes_limit", 0)
    p.major, p.minor = 0, 0
    p.multi_processor_count = 0
    return p


def max_memory_allocated(device=None) -> int:
    return _stats(device).get("peak_bytes_in_use", 0)


def max_memory_reserved(device=None) -> int:
    return _stats(device).get("peak_bytes_in_use", 0)


def memory_allocated(device=None) -> int:
    return _stats(device).get("bytes_in_use", 0)


def memory_reserved(device=None) -> int:
    return _stats(device).get("bytes_reserved", _stats(device).get("bytes_in_use", 0))


def reset_max_memory_allocated(device=None):
    pass


def reset_max_memory_reserved(device=None):
    pass


def empty_cache():
    # XLA's allocator manages HBM; donation/deallocation is automatic.
    pass


def synchronize(device=None):
    from paddle_tpu.core.device import synchronize as _sync

    _sync(device)


def stream_guard(stream):
    from paddle_tpu.device import stream_guard as _sg

    return _sg(stream)


def current_stream(device=None):
    from paddle_tpu.device import current_stream as _cs

    return _cs(device)
