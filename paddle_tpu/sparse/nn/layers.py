"""paddle.sparse.nn layers (reference python/paddle/sparse/nn/layer/)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from paddle_tpu.nn.layer.layers import Layer
from paddle_tpu.sparse.nn import functional as F
from paddle_tpu.sparse.tensor import SparseCooTensor, _coo, _wrap_like
from paddle_tpu.tensor.tensor import Tensor


class ReLU(Layer):
    def forward(self, x):
        return F.relu(x)


class ReLU6(Layer):
    def forward(self, x):
        return F.relu6(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self._negative_slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self._negative_slope)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.softmax(x, self._axis)


class BatchNorm(Layer):
    """Sparse BatchNorm (reference sparse/nn/layer/norm.py): normalizes the
    values tensor over nnz per channel (channels-last)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC", name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        from paddle_tpu.nn import initializer as I

        self.weight = self.create_parameter([num_features], default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter([num_features], is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros((num_features,), jnp.float32)))
        self.register_buffer("_variance", Tensor(jnp.ones((num_features,), jnp.float32)))

    def forward(self, x):
        mat = _coo(x)
        vals = mat.data  # (nnz, C)
        if self.training:
            mean = vals.mean(0)
            var = vals.var(0)
            m = self._momentum
            self._mean.copy_(Tensor(m * self._mean.data + (1 - m) * mean))
            self._variance.copy_(Tensor(m * self._variance.data + (1 - m) * var))
        else:
            mean, var = self._mean.data, self._variance.data
        out = (vals - mean) / jnp.sqrt(var + self._epsilon)
        out = out * self.weight.data + self.bias.data
        return _wrap_like(x, jsparse.BCOO((out, mat.indices), shape=mat.shape))


class SyncBatchNorm(BatchNorm):
    """Single-process fallback == BatchNorm; under pjit the mean/var reduce is
    global automatically (XLA SPMD)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        return layer


class _SparseConv(Layer):
    def __init__(self, dims, subm, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format=None):
        super().__init__()
        self._dims = dims
        self._subm = subm
        ks = kernel_size if isinstance(kernel_size, (list, tuple)) else (kernel_size,) * dims
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        w_shape = tuple(ks) + (in_channels // groups, out_channels)
        self.weight = self.create_parameter(list(w_shape))
        self.bias = self.create_parameter([out_channels], is_bias=True) if bias_attr is not False else None

    def forward(self, x):
        fn = {
            (2, False): F.conv2d, (3, False): F.conv3d,
            (2, True): F.subm_conv2d, (3, True): F.subm_conv3d,
        }[(self._dims, self._subm)]
        return fn(x, self.weight, bias=self.bias, stride=self._stride,
                  padding=self._padding, dilation=self._dilation, groups=self._groups)


class Conv2D(_SparseConv):
    def __init__(self, in_channels, out_channels, kernel_size, **kw):
        super().__init__(2, False, in_channels, out_channels, kernel_size, **kw)


class Conv3D(_SparseConv):
    def __init__(self, in_channels, out_channels, kernel_size, **kw):
        super().__init__(3, False, in_channels, out_channels, kernel_size, **kw)


class SubmConv2D(_SparseConv):
    def __init__(self, in_channels, out_channels, kernel_size, **kw):
        kw.pop("key", None)
        super().__init__(2, True, in_channels, out_channels, kernel_size, **kw)


class SubmConv3D(_SparseConv):
    def __init__(self, in_channels, out_channels, kernel_size, **kw):
        kw.pop("key", None)
        super().__init__(3, True, in_channels, out_channels, kernel_size, **kw)


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, data_format="NDHWC", name=None):
        super().__init__()
        self._kernel_size = kernel_size
        self._stride = stride
        self._padding = padding
        self._ceil_mode = ceil_mode

    def forward(self, x):
        return F.max_pool3d(x, self._kernel_size, stride=self._stride,
                            padding=self._padding, ceil_mode=self._ceil_mode)
