"""paddle.sparse.nn.functional (reference python/paddle/sparse/nn/functional/)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from paddle_tpu.sparse.tensor import SparseCooTensor, SparseCsrTensor, _coo, _wrap_like
from paddle_tpu.sparse.unary import _valmap
from paddle_tpu.tensor.tensor import Tensor

relu = _valmap(jax.nn.relu)
relu6 = _valmap(lambda v: jnp.clip(v, 0, 6))


def leaky_relu(x, negative_slope=0.01, name=None):
    return _valmap(lambda v: jnp.where(v >= 0, v, negative_slope * v))(x)


def softmax(x, axis=-1, name=None):
    """Softmax over the non-zero entries of each row (reference sparse softmax
    semantics: zeros are treated as -inf / excluded)."""
    dense = x._mat.todense()
    neg = jnp.where(dense != 0, dense, -jnp.inf)
    sm = jax.nn.softmax(neg, axis=axis)
    sm = jnp.where(dense != 0, sm, 0.0)
    out = jsparse.BCOO.fromdense(sm)
    return _wrap_like(x, out)


def attention(query, key, value, sparse_mask, key_padding_mask=None, attn_mask=None, name=None):
    """Sparse-mask scaled-dot-product attention (reference
    sparse/nn/functional/transformer.py): scores computed only at mask nnz."""
    from paddle_tpu.sparse.binary import masked_matmul

    q = query.data
    k = key.data
    v = value.data
    d = q.shape[-1]
    # batched dense fallback over the mask pattern (B,H small on TPU tests)
    scores = jnp.einsum("...id,...jd->...ij", q, k) / jnp.sqrt(d)
    mask_dense = _coo(sparse_mask).todense() != 0
    # paddle documents mask shape [batch*num_heads, L, L]; scores are (B, H, L, L)
    if mask_dense.ndim == 3 and scores.ndim == 4:
        mask_dense = mask_dense.reshape(scores.shape)
    scores = jnp.where(mask_dense, scores, -jnp.inf)
    if key_padding_mask is not None:
        scores = scores + key_padding_mask.data[:, None, None, :]
    if attn_mask is not None:
        scores = scores + attn_mask.data
    att = jax.nn.softmax(scores, -1)
    att = jnp.where(jnp.isnan(att), 0.0, att)
    return Tensor(jnp.einsum("...ij,...jd->...id", att, v))


def _dense_conv(x, weight, bias, stride, padding, dilation, groups, dims, subm):
    """Reference sparse convs (conv2d/conv3d/subm_*) computed on the dense view;
    sparsity of the output follows conv(dense) (submanifold: input pattern)."""
    from paddle_tpu.nn.functional.conv import conv2d, conv3d

    dense = Tensor(_coo(x).todense())
    # paddle sparse conv layout is channels-last (NDHWC); dense conv expects NCDHW
    perm_in = (0, dims + 1) + tuple(range(1, dims + 1))
    perm_out = (0,) + tuple(range(2, dims + 2)) + (1,)
    xt = Tensor(jnp.transpose(dense.data, perm_in))
    # paddle sparse weight layout (k..., Cin, Cout) → dense conv (Cout, Cin, k...)
    w = jnp.transpose(weight.data, (dims + 1, dims) + tuple(range(dims)))
    fn = conv3d if dims == 3 else conv2d
    out = fn(xt, Tensor(w), bias=bias, stride=stride, padding=padding,
             dilation=dilation, groups=groups)
    out_cl = jnp.transpose(out.data, perm_out)
    if subm:
        mask = (_coo(x).todense() != 0).any(-1, keepdims=True)
        out_cl = jnp.where(mask, out_cl, 0.0)
    return SparseCooTensor(jsparse.BCOO.fromdense(out_cl, n_dense=1))


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NHWC", name=None):
    return _dense_conv(x, weight, bias, stride, padding, dilation, groups, 2, False)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NDHWC", name=None):
    return _dense_conv(x, weight, bias, stride, padding, dilation, groups, 3, False)


def subm_conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NHWC", key=None, name=None):
    return _dense_conv(x, weight, bias, stride, padding, dilation, groups, 2, True)


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NDHWC", key=None, name=None):
    return _dense_conv(x, weight, bias, stride, padding, dilation, groups, 3, True)


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, data_format="NDHWC", name=None):
    from paddle_tpu.nn.functional.pooling import max_pool3d as dense_mp3

    dense = Tensor(_coo(x).todense())
    xt = Tensor(jnp.transpose(dense.data, (0, 4, 1, 2, 3)))
    out = dense_mp3(xt, kernel_size, stride=stride, padding=padding, ceil_mode=ceil_mode)
    out_cl = jnp.transpose(out.data, (0, 2, 3, 4, 1))
    return SparseCooTensor(jsparse.BCOO.fromdense(out_cl, n_dense=1))
