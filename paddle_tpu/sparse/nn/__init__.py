"""paddle.sparse.nn (reference python/paddle/sparse/nn/__init__.py)."""
from paddle_tpu.sparse.nn import functional
from paddle_tpu.sparse.nn.layers import (
    ReLU, ReLU6, LeakyReLU, Softmax, BatchNorm, SyncBatchNorm,
    Conv2D, Conv3D, SubmConv2D, SubmConv3D, MaxPool3D,
)

__all__ = [
    'ReLU', 'ReLU6', 'LeakyReLU', 'Softmax', 'BatchNorm', 'SyncBatchNorm',
    'Conv2D', 'Conv3D', 'SubmConv2D', 'SubmConv3D', 'MaxPool3D', 'functional',
]
