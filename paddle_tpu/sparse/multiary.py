"""Sparse multiary ops (reference python/paddle/sparse/multiary.py)."""
from paddle_tpu.sparse.binary import addmm  # noqa: F401
