"""Sparse binary ops (reference python/paddle/sparse/binary.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from paddle_tpu.sparse.tensor import (
    SparseCooTensor, SparseCsrTensor, SparseTensor, _coo, _wrap_like,
)
from paddle_tpu.tensor.tensor import Tensor


def _elementwise(op_name, fn):
    def op(x, y, name=None):
        if isinstance(x, SparseTensor) and isinstance(y, SparseTensor):
            out = jsparse.sparsify(fn)(_coo(x), _coo(y))
            return _wrap_like(x, out)
        raise TypeError(f"sparse.{op_name} expects two sparse tensors")

    return op


add = _elementwise("add", jnp.add)
subtract = _elementwise("subtract", jnp.subtract)


def multiply(x, y, name=None):
    # sparsify(multiply) of two sparse operands keeps union structure with zeros —
    # fine numerically (paddle semantics are elementwise on the dense view)
    out = jsparse.sparsify(jnp.multiply)(_coo(x), _coo(y))
    return _wrap_like(x, out)


def divide(x, y, name=None):
    xd, yd = _coo(x).todense(), _coo(y).todense()
    return _wrap_like(x, jsparse.BCOO.fromdense(xd / yd))


def matmul(x, y, name=None):
    """sparse @ dense, sparse @ sparse, dense @ sparse (reference binary.py matmul)."""
    if isinstance(x, SparseTensor) and isinstance(y, SparseTensor):
        out = _coo(x) @ _coo(y)
        return _wrap_like(x, out if isinstance(out, jsparse.BCOO) else jsparse.BCOO.fromdense(out))
    if isinstance(x, SparseTensor):
        yd = y.data if isinstance(y, Tensor) else jnp.asarray(y)
        return Tensor(_coo(x) @ yd)
    xd = x.data if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(xd @ _coo(y))


def mv(x, vec, name=None):
    v = vec.data if isinstance(vec, Tensor) else jnp.asarray(vec)
    return Tensor(_coo(x) @ v)


def masked_matmul(x, y, mask, name=None):
    """(dense x dense) * sparse-mask → sparse (reference masked_matmul): compute only
    the entries present in mask via gather-dot — SDDMM."""
    xd = x.data if isinstance(x, Tensor) else jnp.asarray(x)
    yd = y.data if isinstance(y, Tensor) else jnp.asarray(y)
    m = _coo(mask)
    # supports batched [*, M, K] @ [*, K, N]: leading index columns are batch dims
    rows = m.indices[:, -2]
    cols = m.indices[:, -1]
    batch = tuple(m.indices[:, i] for i in range(m.indices.shape[1] - 2))
    x_rows = xd[batch + (rows,)] if batch else xd[rows]                    # (nnz, K)
    yt = jnp.swapaxes(yd, -1, -2)
    y_cols = yt[batch + (cols,)] if batch else yt[cols]                    # (nnz, K)
    vals = jnp.einsum("nk,nk->n", x_rows, y_cols)
    return _wrap_like(mask, jsparse.BCOO((vals, m.indices), shape=m.shape))


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """beta*input + alpha*(x@y) (reference binary.py addmm)."""
    xy = matmul(x, y)
    if isinstance(xy, SparseTensor) and isinstance(input, SparseTensor):
        out = jsparse.sparsify(lambda a, b: beta * a + alpha * b)(_coo(input), _coo(xy))
        return _wrap_like(input, out)
    inp = input.data if isinstance(input, Tensor) else _coo(input).todense()
    xyd = xy.data if isinstance(xy, Tensor) else _coo(xy).todense()
    return Tensor(beta * inp + alpha * xyd)


def mask_as(x, mask, name=None):
    """Take dense x's values at mask's sparsity pattern."""
    xd = x.data if isinstance(x, Tensor) else jnp.asarray(x)
    m = _coo(mask)
    idx = tuple(m.indices[:, i] for i in range(m.indices.shape[1]))
    vals = xd[idx]
    return _wrap_like(mask, jsparse.BCOO((vals, m.indices), shape=m.shape))


def is_same_shape(x, y):
    return list(x.shape) == list(y.shape)
