"""Sparse tensor creation (reference python/paddle/sparse/creation.py)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from paddle_tpu.sparse.tensor import SparseCooTensor, SparseCsrTensor
from paddle_tpu.tensor.tensor import Tensor


def _arr(x, dtype=None):
    if isinstance(x, Tensor):
        a = x.data
    else:
        a = jnp.asarray(np.asarray(x))
    if dtype is not None:
        a = a.astype(dtype)
    return a


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None, stop_gradient=True):
    idx = _arr(indices).astype(jnp.int32)  # (sparse_dim, nnz) paddle layout
    vals = _arr(values, dtype)
    if vals.dtype == jnp.float64 and dtype is None:
        vals = vals.astype(jnp.float32)
    if shape is None:
        dense_part = vals.shape[1:]
        sp_shape = tuple(int(i) for i in (idx.max(axis=1) + 1)) if idx.size else (0,) * idx.shape[0]
        shape = sp_shape + dense_part
    mat = jsparse.BCOO((vals, idx.T), shape=tuple(shape))
    return SparseCooTensor(mat)


def sparse_csr_tensor(crows, cols, values, shape=None, dtype=None, place=None, stop_gradient=True):
    indptr = _arr(crows).astype(jnp.int32)
    indices = _arr(cols).astype(jnp.int32)
    vals = _arr(values, dtype)
    if vals.dtype == jnp.float64 and dtype is None:
        vals = vals.astype(jnp.float32)
    if shape is None:
        shape = (indptr.shape[0] - 1, int(indices.max()) + 1)
    mat = jsparse.BCSR((vals, indices, indptr), shape=tuple(shape))
    return SparseCsrTensor(mat)
