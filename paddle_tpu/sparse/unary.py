"""Sparse unary ops (reference python/paddle/sparse/unary.py): applied to the
values, preserving structure."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from paddle_tpu.sparse.tensor import (
    SparseCooTensor, SparseCsrTensor, SparseTensor, _coo, _wrap_like,
)
from paddle_tpu.tensor.tensor import Tensor


def _valmap(fn):
    def op(x, name=None):
        mat = x._mat
        if isinstance(x, SparseCooTensor):
            return SparseCooTensor(jsparse.BCOO((fn(mat.data), mat.indices), shape=mat.shape))
        return SparseCsrTensor(jsparse.BCSR((fn(mat.data), mat.indices, mat.indptr), shape=mat.shape))

    return op


sin = _valmap(jnp.sin)
tan = _valmap(jnp.tan)
asin = _valmap(jnp.arcsin)
atan = _valmap(jnp.arctan)
sinh = _valmap(jnp.sinh)
tanh = _valmap(jnp.tanh)
asinh = _valmap(jnp.arcsinh)
atanh = _valmap(jnp.arctanh)
sqrt = _valmap(jnp.sqrt)
square = _valmap(jnp.square)
log1p = _valmap(jnp.log1p)
abs = _valmap(jnp.abs)
neg = _valmap(jnp.negative)
expm1 = _valmap(jnp.expm1)
deg2rad = _valmap(jnp.deg2rad)
rad2deg = _valmap(jnp.rad2deg)
isnan = _valmap(jnp.isnan)


def pow(x, factor, name=None):
    return _valmap(lambda v: jnp.power(v, factor))(x)


def cast(x, index_dtype=None, value_dtype=None, name=None):
    from paddle_tpu.core.dtype import convert_dtype

    mat = _coo(x)
    data = mat.data if value_dtype is None else mat.data.astype(convert_dtype(value_dtype))
    idx = mat.indices if index_dtype is None else mat.indices.astype(convert_dtype(index_dtype))
    return _wrap_like(x, jsparse.BCOO((data, idx), shape=mat.shape))


def coalesce(x, name=None):
    mat = _coo(x).sum_duplicates(remove_zeros=False)
    return SparseCooTensor(mat)


def transpose(x, perm, name=None):
    mat = _coo(x)
    out = jsparse.bcoo_transpose(mat, permutation=tuple(perm))
    return _wrap_like(x, out)


def reshape(x, shape, name=None):
    mat = _coo(x)
    shape = tuple(int(s) if s != -1 else -1 for s in shape)
    if -1 in shape:
        known = 1
        for s in shape:
            if s != -1:
                known *= s
        total = 1
        for s in mat.shape:
            total *= s
        shape = tuple(total // known if s == -1 else s for s in shape)
    out = jsparse.bcoo_reshape(mat, new_sizes=shape)
    return _wrap_like(x, out)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    mat = _coo(x)
    if axis is None:
        out = mat.data.sum()
        return Tensor(out if dtype is None else out.astype(dtype))
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    axes = tuple(a % mat.ndim for a in axes)
    out = jsparse.sparsify(lambda m: m.sum(axes))(mat)
    if not isinstance(out, jsparse.BCOO):
        return Tensor(out)
    if keepdim:
        kshape = tuple(1 if i in axes else s for i, s in enumerate(mat.shape))
        out = jsparse.bcoo_reshape(out, new_sizes=kshape)
    return _wrap_like(x, out)


def slice(x, axes, starts, ends, name=None):
    mat = _coo(x)
    start = [0] * mat.ndim
    limit = list(mat.shape)
    for a, s, e in zip(axes, starts, ends):
        a = a % mat.ndim
        s = s if s >= 0 else mat.shape[a] + s
        e = e if e >= 0 else mat.shape[a] + e
        start[a] = s
        limit[a] = min(e, mat.shape[a])
    out = jsparse.bcoo_slice(mat, start_indices=start, limit_indices=limit)
    return _wrap_like(x, out)


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    from paddle_tpu.tensor.linalg import pca_lowrank as dense_pca

    return dense_pca(x.to_dense(), q=q, center=center, niter=niter)
