"""Sparse tensor types backed by jax.experimental.sparse (BCOO/BCSR).

TPU-native analog of the reference's SparseCooTensor/SparseCsrTensor
(paddle/phi/core/sparse_coo_tensor.h, sparse_csr_tensor.h): COO keeps an
(nnz, ndim) index matrix + values vector; CSR keeps crows/cols/values.
Compute routes through jax.experimental.sparse kernels (bcoo_dot_general uses
gather/scatter lowering that XLA maps onto the TPU efficiently).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from paddle_tpu.tensor.tensor import Tensor


class SparseTensor:
    """Common behavior for COO/CSR wrappers."""

    @property
    def shape(self):
        return list(self._mat.shape)

    @property
    def dtype(self):
        return self._mat.dtype

    @property
    def ndim(self):
        return self._mat.ndim

    def nnz(self):
        return int(self._mat.nse)

    def to_dense(self):
        return Tensor(self._mat.todense())

    def numpy(self):
        return self.to_dense().numpy()

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return isinstance(self, SparseCooTensor)

    def is_sparse_csr(self):
        return isinstance(self, SparseCsrTensor)

    def __repr__(self):
        return f"{type(self).__name__}(shape={self.shape}, nnz={self.nnz()}, dtype={self.dtype})"


class SparseCooTensor(SparseTensor):
    def __init__(self, mat: jsparse.BCOO):
        self._mat = mat

    def indices(self):
        return Tensor(self._mat.indices.T.astype(jnp.int64))

    def values(self):
        return Tensor(self._mat.data)

    def coalesce(self):
        from paddle_tpu.sparse.unary import coalesce

        return coalesce(self)

    def to_sparse_csr(self):
        m = self._mat.sum_duplicates(remove_zeros=False)
        bcsr = jsparse.BCSR.from_bcoo(m)
        return SparseCsrTensor(bcsr)

    def to_sparse_coo(self, sparse_dim=None):
        return self

    def transpose(self, perm):
        from paddle_tpu.sparse.unary import transpose

        return transpose(self, perm)


class SparseCsrTensor(SparseTensor):
    def __init__(self, mat: jsparse.BCSR):
        self._mat = mat

    def crows(self):
        return Tensor(self._mat.indptr.astype(jnp.int64))

    def cols(self):
        return Tensor(self._mat.indices.astype(jnp.int64))

    def values(self):
        return Tensor(self._mat.data)

    def to_sparse_coo(self, sparse_dim=None):
        return SparseCooTensor(self._mat.to_bcoo())

    def to_sparse_csr(self):
        return self


def _dense_data(x):
    if isinstance(x, Tensor):
        return x.data
    if isinstance(x, SparseTensor):
        return x._mat.todense()
    return jnp.asarray(x)


def _coo(x) -> jsparse.BCOO:
    if isinstance(x, SparseCooTensor):
        return x._mat
    if isinstance(x, SparseCsrTensor):
        return x._mat.to_bcoo()
    raise TypeError(f"expected sparse tensor, got {type(x)}")


def _wrap_like(x, mat: jsparse.BCOO):
    if isinstance(x, SparseCsrTensor):
        return SparseCsrTensor(jsparse.BCSR.from_bcoo(mat))
    return SparseCooTensor(mat)
