"""paddle.sparse (reference python/paddle/sparse/__init__.py) — COO/CSR sparse
tensors on jax.experimental.sparse."""
from paddle_tpu.sparse.tensor import SparseCooTensor, SparseCsrTensor
from paddle_tpu.sparse.creation import sparse_coo_tensor, sparse_csr_tensor
from paddle_tpu.sparse.unary import (
    sin, tan, asin, atan, sinh, tanh, asinh, atanh, sqrt, square, log1p, abs,
    pow, cast, neg, deg2rad, rad2deg, expm1, coalesce, transpose, reshape, sum,
    isnan, slice, pca_lowrank,
)
from paddle_tpu.sparse.binary import (
    add, subtract, multiply, divide, matmul, mv, masked_matmul, addmm, mask_as,
    is_same_shape,
)
from paddle_tpu.sparse import nn

__all__ = [
    'sparse_coo_tensor', 'sparse_csr_tensor', 'sin', 'tan', 'asin', 'atan',
    'sinh', 'tanh', 'asinh', 'atanh', 'sqrt', 'square', 'log1p', 'abs', 'pow',
    'pca_lowrank', 'cast', 'neg', 'deg2rad', 'rad2deg', 'expm1', 'mv', 'matmul',
    'mask_as', 'masked_matmul', 'addmm', 'add', 'subtract', 'transpose', 'sum',
    'multiply', 'divide', 'coalesce', 'is_same_shape', 'reshape', 'isnan', 'slice',
]


def _patch_dense_methods():
    """paddle Tensor.to_sparse_coo()/to_sparse_csr() (reference
    python/paddle/tensor/to_string.py method patch)."""
    import jax.numpy as jnp
    from jax.experimental import sparse as jsparse

    from paddle_tpu.tensor.tensor import Tensor

    def to_sparse_coo(self, sparse_dim=None):
        n_sparse = sparse_dim if sparse_dim is not None else self.ndim
        mat = jsparse.BCOO.fromdense(self.data, n_dense=self.ndim - n_sparse)
        return SparseCooTensor(mat)

    def to_sparse_csr(self):
        return SparseCooTensor(jsparse.BCOO.fromdense(self.data)).to_sparse_csr()

    Tensor.to_sparse_coo = to_sparse_coo
    Tensor.to_sparse_csr = to_sparse_csr
    Tensor.is_sparse = lambda self: False
    Tensor.is_sparse_coo = lambda self: False
    Tensor.is_sparse_csr = lambda self: False


_patch_dense_methods()
