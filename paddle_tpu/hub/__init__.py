"""paddle.hub parity (reference: python/paddle/hapi/hub.py — list/help/load from a
github/gitee/local repo's hubconf.py).  Zero-egress: only ``source='local'`` works;
remote sources raise with instructions.
"""
from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]

_HUBCONF = "hubconf.py"


def _load_hubconf(repo_dir: str):
    path = os.path.join(repo_dir, _HUBCONF)
    if not os.path.isfile(path):
        raise FileNotFoundError(f"no {_HUBCONF} in {repo_dir}")
    spec = importlib.util.spec_from_file_location("paddle_tpu_hubconf", path)
    module = importlib.util.module_from_spec(spec)
    sys.path.insert(0, repo_dir)
    try:
        spec.loader.exec_module(module)
    finally:
        sys.path.pop(0)
    return module


def _check_source(source: str):
    if source != "local":
        raise RuntimeError(
            f"hub source '{source}' requires network access, which is disabled; "
            f"clone the repo and use source='local'."
        )


def list(repo_dir, source="github", force_reload=False):
    _check_source(source)
    module = _load_hubconf(repo_dir)
    return [
        name for name in dir(module)
        if callable(getattr(module, name)) and not name.startswith("_")
    ]


def help(repo_dir, model, source="github", force_reload=False):
    _check_source(source)
    module = _load_hubconf(repo_dir)
    if not hasattr(module, model):
        raise ValueError(f"model {model} not found in {repo_dir}")
    return getattr(module, model).__doc__


def load(repo_dir, model, source="github", force_reload=False, **kwargs):
    _check_source(source)
    module = _load_hubconf(repo_dir)
    if not hasattr(module, model):
        raise ValueError(f"model {model} not found in {repo_dir}")
    return getattr(module, model)(**kwargs)
