"""Datasets (python/paddle/io/dataloader/dataset.py parity)."""
from __future__ import annotations

import bisect

import numpy as np

__all__ = [
    "Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
    "ChainDataset", "ConcatDataset", "Subset", "random_split",
]


class Dataset:
    """Map-style dataset: implement __getitem__ and __len__."""

    def __getitem__(self, idx):
        raise NotImplementedError(
            f"'{self.__class__.__name__}' not implement in function '__getitem__'"
        )

    def __len__(self):
        raise NotImplementedError(
            f"'{self.__class__.__name__}' not implement in function '__len__'"
        )


class IterableDataset(Dataset):
    """Iterable-style dataset: implement __iter__."""

    def __iter__(self):
        raise NotImplementedError(
            f"'{self.__class__.__name__}' not implement in function '__iter__'"
        )

    def __getitem__(self, idx):
        raise TypeError("IterableDataset does not support __getitem__")

    def __len__(self):
        # TypeError (not RuntimeError): builtins like list() probe __len__ via
        # length_hint, which only tolerates TypeError
        raise TypeError("IterableDataset does not support __len__")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        lens = {t.shape[0] for t in tensors}
        assert len(lens) == 1, "tensors must have the same first-dim size"
        self.tensors = tensors

    def __getitem__(self, index):
        return tuple(t[index] for t in self.tensors)

    def __len__(self):
        return int(self.tensors[0].shape[0])


class ComposeDataset(Dataset):
    """Zip several map-style datasets, concatenating their fields."""

    def __init__(self, datasets):
        self.datasets = list(datasets)
        assert self.datasets, "datasets must not be empty"
        n = len(self.datasets[0])
        for d in self.datasets:
            assert len(d) == n, "lengths of datasets must be the same"

    def __len__(self):
        return len(self.datasets[0])

    def __getitem__(self, idx):
        sample = []
        for d in self.datasets:
            item = d[idx]
            if isinstance(item, (tuple, list)):
                sample.extend(item)
            else:
                sample.append(item)
        return tuple(sample)


class ChainDataset(IterableDataset):
    """Chain several iterable-style datasets."""

    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        assert self.datasets, "datasets should not be an empty iterable"
        self.cumulative_sizes = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx = len(self) + idx
        di = bisect.bisect_right(self.cumulative_sizes, idx)
        start = 0 if di == 0 else self.cumulative_sizes[di - 1]
        return self.datasets[di][idx - start]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    """paddle.io.random_split — lengths may be absolute or fractions summing to 1."""
    n = len(dataset)
    if all(0.0 < l < 1.0 for l in lengths) and abs(sum(lengths) - 1.0) < 1e-6:
        sizes = [int(np.floor(n * l)) for l in lengths]
        for i in range(n - sum(sizes)):
            sizes[i % len(sizes)] += 1
        lengths = sizes
    assert sum(lengths) == n, (
        "Sum of input lengths does not equal the length of the input dataset!"
    )
    perm = np.random.permutation(n).tolist()
    out, off = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[off:off + l]))
        off += l
    return out
