"""DataLoader (python/paddle/io/reader.py:262 parity).

TPU-native worker model: the reference forks worker *processes*
(io/dataloader/worker.py) because CPython+CUDA tolerates fork; the TPU/JAX
runtime does not (forking after backend init deadlocks the PJRT client), so
``num_workers > 0`` defaults to a prefetching *thread* pool feeding a bounded
queue — same overlap (host decode vs device step), no fork hazard.  True
``use_process_workers=True`` upgrades to real subprocess workers streaming
batches through per-worker native shared-memory rings (mirrors
the reference's Dataset/data_feed path).
"""
from __future__ import annotations

import itertools
import queue
import threading

import numpy as np

from paddle_tpu.io.dataset import Dataset, IterableDataset
from paddle_tpu.io.sampler import BatchSampler

__all__ = ["DataLoader", "default_collate_fn", "get_worker_info"]

_worker_info = threading.local()


class WorkerInfo:
    def __init__(self, id, num_workers, dataset):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


def get_worker_info():
    return getattr(_worker_info, "info", None)


def default_collate_fn(batch):
    """Stack samples into batched Tensors (reference: collate.py default_collate_fn)."""
    from paddle_tpu.tensor.tensor import Tensor

    sample = batch[0]
    if isinstance(sample, Tensor):
        import jax.numpy as jnp

        return Tensor(jnp.stack([s.data for s in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, np.integer)):
        return Tensor(np.asarray(batch, dtype=np.int64))
    if isinstance(sample, (float, np.floating)):
        return Tensor(np.asarray(batch, dtype=np.float32))
    if isinstance(sample, (str, bytes)):
        return list(batch)
    if isinstance(sample, dict):
        return {k: default_collate_fn([s[k] for s in batch]) for k in sample}
    if isinstance(sample, (tuple, list)):
        return type(sample)(default_collate_fn(list(f)) for f in zip(*batch))
    raise TypeError(f"batch data can not be a type of {type(sample)}")


def _tree_to_numpy(obj):
    """Tensors → ndarrays for cross-process pickling (namedtuple-safe)."""
    import jax as _jax
    import numpy as _np

    from paddle_tpu.tensor.tensor import Tensor as _T

    return _jax.tree_util.tree_map(
        lambda o: _np.asarray(o.numpy()) if isinstance(o, _T) else o, obj,
        is_leaf=lambda o: isinstance(o, _T),
    )


def _tree_to_tensor(obj):
    import jax as _jax
    import numpy as _np

    from paddle_tpu.tensor.tensor import Tensor as _T

    return _jax.tree_util.tree_map(
        lambda o: _T(o) if isinstance(o, _np.ndarray) else o, obj,
    )


def _numpy_default_collate(samples):
    """default_collate_fn's numpy twin for subprocess workers: stacks with
    numpy only, so workers never materialize jax arrays."""
    import numpy as _np

    first = samples[0]
    if isinstance(first, (list, tuple)):
        return type(first)(_numpy_default_collate([s[i] for s in samples])
                           for i in range(len(first)))
    if isinstance(first, dict):
        return {k: _numpy_default_collate([s[k] for s in samples]) for k in first}
    return _np.stack([_np.asarray(s) for s in samples])


class _NumpyCollate:
    """Picklable wrapper: run the user's collate in the worker, ship numpy."""

    def __init__(self, collate_fn):
        self._collate = collate_fn

    def __call__(self, samples):
        return _tree_to_numpy(self._collate(samples))


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False,
                 drop_last=False, collate_fn=None, num_workers=0,
                 use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False, use_process_workers=False):
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = max(int(num_workers), 0)
        self.prefetch_factor = max(int(prefetch_factor), 1)
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self.use_process_workers = use_process_workers
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = batch_sampler.batch_size
        else:
            if batch_size is None:
                self.batch_sampler = None
                self.batch_size = None
            else:
                self.batch_sampler = BatchSampler(
                    dataset, shuffle=shuffle, batch_size=batch_size,
                    drop_last=drop_last,
                )
                self.batch_size = batch_size

    def __len__(self):
        if self._iterable:
            raise TypeError("IterableDataset has no len()")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    # ------------------------------------------------------------------ iter
    def _index_batches(self):
        if self.batch_sampler is not None:
            yield from self.batch_sampler
        else:  # batch_size=None: sample-at-a-time
            yield from ([i] for i in range(len(self.dataset)))

    def _make_batch(self, indices):
        samples = [self.dataset[i] for i in indices]
        if self.batch_sampler is None and self.batch_size is None:
            return samples[0]
        return self.collate_fn(samples)

    def _iter_iterable(self):
        it = iter(self.dataset)
        if self.batch_size is None:
            yield from it
            return
        while True:
            chunk = list(itertools.islice(it, self.batch_size))
            if not chunk:
                return
            if len(chunk) < self.batch_size and self.drop_last:
                return
            yield self.collate_fn(chunk)

    def __iter__(self):
        if self.num_workers == 0:
            if self._iterable:
                yield from self._iter_iterable()
            else:
                for idx in self._index_batches():
                    yield self._make_batch(idx)
            return
        if self.use_process_workers:
            if self._iterable:
                raise ValueError(
                    "use_process_workers=True does not support IterableDataset "
                    "(the stream cannot be sharded by index); use map-style "
                    "datasets or thread workers"
                )
            yield from self._iter_process_workers()
            return
        yield from self._iter_prefetch()

    # --------------------------------------------- process workers (shm ring)
    _epoch_counter = itertools.count()

    def _iter_process_workers(self):
        """Real worker subprocesses streaming batches through native
        shared-memory rings (reference python/paddle/io/dataloader/worker.py +
        data_feed.cc blocking queues).

        One ring per worker; worker w pushes batches w, w+nw, ... in order, so
        the parent reads batch b straight from ring b % nw — sampler order with
        no reorder buffer, and ring capacity gives per-worker backpressure."""
        import os
        import pickle
        import subprocess

        from paddle_tpu.core.native import ShmRing
        from paddle_tpu.io.process_worker import spawn_workers

        batches = list(self._index_batches())
        if not batches:
            return
        nw = min(self.num_workers, len(batches))
        prefix = f"/pdl_{os.getpid()}_{id(self)}_{next(DataLoader._epoch_counter)}"
        # workers collate straight to numpy (no per-worker jax arrays); the
        # default collate gets a numpy-native twin
        collate = (_numpy_default_collate if self.collate_fn is default_collate_fn
                   else _NumpyCollate(self.collate_fn))
        rings = []
        procs, payload_path = [], None
        poll_ms = 1000
        deadline = self.timeout if self.timeout and self.timeout > 0 else None
        try:
            rings = [ShmRing(f"{prefix}_w{w}", capacity=(64 << 20) // nw, create=True)
                     for w in range(nw)]
            procs, payload_path = spawn_workers(
                self.dataset, batches, collate, nw, prefix,
                worker_init_fn=self.worker_init_fn,
            )
            for bi in range(len(batches)):
                w = bi % nw
                waited = 0.0
                exited_at = None
                while True:
                    try:
                        raw = rings[w].pop(timeout_ms=poll_ms)
                        break
                    except TimeoutError:
                        waited += poll_ms / 1000.0
                        rc = procs[w].poll()
                        if rc is not None and rc != 0:
                            raise RuntimeError(
                                f"DataLoader worker {w} died with exit code {rc}"
                            )
                        if rc == 0:
                            # exited cleanly without this batch (e.g. sys.exit
                            # in user code): allow one grace poll for in-flight
                            # data, then fail instead of spinning forever
                            if exited_at is None:
                                exited_at = waited
                            elif waited - exited_at >= 2 * poll_ms / 1000.0:
                                raise RuntimeError(
                                    f"DataLoader worker {w} exited without "
                                    f"producing batch {bi}"
                                )
                        if deadline is not None and waited >= deadline:
                            raise TimeoutError(
                                f"DataLoader batch {bi} not produced within "
                                f"timeout={self.timeout}s"
                            )
                msg = pickle.loads(raw)
                tag = msg[0]
                if tag == "__error__":
                    raise RuntimeError(f"DataLoader worker failed:\n{msg[1]}")
                if tag == "__done__":
                    raise RuntimeError(
                        f"DataLoader worker {w} finished early (expected batch {bi})"
                    )
                yield _tree_to_tensor(msg[1])
        finally:
            for p in procs:
                p.terminate()
            for p in procs:
                try:
                    p.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait(timeout=5)
            for r in rings:
                r.destroy()
            if payload_path is not None:
                try:
                    os.unlink(payload_path)
                except OSError:
                    pass

    def _iter_prefetch(self):
        """Bounded-queue prefetch with worker threads (order-preserving)."""
        if self._iterable:
            # single producer preserves stream order
            q: queue.Queue = queue.Queue(self.num_workers * self.prefetch_factor)
            stop = object()

            def produce():
                _worker_info.info = WorkerInfo(0, 1, self.dataset)
                if self.worker_init_fn:
                    self.worker_init_fn(0)
                try:
                    for b in self._iter_iterable():
                        q.put(b)
                finally:
                    q.put(stop)

            t = threading.Thread(target=produce, daemon=True)
            t.start()
            while True:
                item = q.get()
                if item is stop:
                    return
                yield item
            return

        batches = list(self._index_batches())
        results: dict[int, object] = {}
        lock = threading.Lock()
        cond = threading.Condition(lock)
        counter = itertools.count()
        max_ahead = self.num_workers * self.prefetch_factor
        next_emit = [0]

        def worker(wid):
            _worker_info.info = WorkerInfo(wid, self.num_workers, self.dataset)
            if self.worker_init_fn:
                self.worker_init_fn(wid)
            while True:
                i = next(counter)
                if i >= len(batches):
                    return
                with cond:
                    while i - next_emit[0] >= max_ahead:
                        cond.wait(0.1)
                out = self._make_batch(batches[i])
                with cond:
                    results[i] = out
                    cond.notify_all()

        threads = [
            threading.Thread(target=worker, args=(w,), daemon=True)
            for w in range(self.num_workers)
        ]
        for t in threads:
            t.start()
        for i in range(len(batches)):
            with cond:
                while i not in results:
                    cond.wait(0.1)
                out = results.pop(i)
                next_emit[0] = i + 1
                cond.notify_all()
            yield out

    @staticmethod
    def from_generator(*a, **k):  # pragma: no cover - legacy static-graph API
        raise NotImplementedError(
            "DataLoader.from_generator is a legacy fluid API; iterate a "
            "Dataset instead"
        )
