"""paddle.io namespace (python/paddle/io parity, SURVEY.md §2.10 Data IO)."""
from paddle_tpu.io.dataset import (  # noqa: F401
    ChainDataset, ComposeDataset, ConcatDataset, Dataset, IterableDataset,
    Subset, TensorDataset, random_split,
)
from paddle_tpu.io.reader import (  # noqa: F401
    DataLoader, default_collate_fn, get_worker_info,
)
from paddle_tpu.io.sampler import (  # noqa: F401
    BatchSampler, DistributedBatchSampler, RandomSampler, Sampler,
    SequenceSampler, SubsetRandomSampler, WeightedRandomSampler,
)
