"""Samplers and batch samplers (python/paddle/io/dataloader/sampler.py,
batch_sampler.py parity)."""
from __future__ import annotations

import numpy as np

__all__ = [
    "Sampler", "SequenceSampler", "RandomSampler", "WeightedRandomSampler",
    "BatchSampler", "DistributedBatchSampler", "SubsetRandomSampler",
]


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            yield from np.random.randint(0, n, self.num_samples).tolist()
        else:
            perm = np.random.permutation(n).tolist()
            yield from perm[: self.num_samples]

    def __len__(self):
        return self.num_samples


class SubsetRandomSampler(Sampler):
    def __init__(self, indices):
        super().__init__(indices)
        self.indices = list(indices)

    def __iter__(self):
        for i in np.random.permutation(len(self.indices)):
            yield self.indices[i]

    def __len__(self):
        return len(self.indices)


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        if not replacement and num_samples > len(weights):
            raise ValueError(
                "num_samples should be less than or equal to the length of "
                "weights when replacement is False"
            )
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(
            len(self.weights), self.num_samples, replace=self.replacement, p=p
        )
        yield from idx.tolist()

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1,
                 drop_last=False):
        assert (dataset is None) != (sampler is None), (
            "either dataset or sampler should be set"
        )
        self.sampler = sampler or (
            RandomSampler(dataset) if shuffle else SequenceSampler(dataset)
        )
        self.batch_size = batch_size
        self.drop_last = drop_last
        self.shuffle = shuffle

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Rank-sharded batch sampler (python/paddle/io/dataloader/batch_sampler.py
    DistributedBatchSampler): pads to even shards, supports set_epoch."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from paddle_tpu.distributed import parallel_env as _env

        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas or _env.get_world_size()
        self.local_rank = rank if rank is not None else _env.get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(np.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = np.arange(n).tolist()
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            rng.shuffle(indices)
        indices += indices[: self.total_size - n]  # pad
        indices = indices[self.local_rank : self.total_size : self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch
