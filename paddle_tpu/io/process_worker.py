"""Multiprocess DataLoader workers over the native shared-memory ring.

Reference: python/paddle/io/dataloader/worker.py — worker *processes* pull
index batches and push samples through queues; the C++ side moves data through
blocking queues (paddle/fluid/framework/data_feed.cc).  Here each worker is a
real subprocess (not fork: safe with an initialized runtime) that receives the
pickled dataset once, builds its share of the batches, and streams pickled
(batch_index, batch) records through one core.native.ShmRing — a single shm
copy instead of a pickle pipe per sample.

Worker protocol (records in the ring):
    pickle((batch_idx:int, payload:bytes)) — a finished batch
    pickle(("__done__", worker_id))        — worker drained its share
    pickle(("__error__", traceback_str))   — worker crashed
"""
from __future__ import annotations

import os
import pickle
import subprocess
import sys
import tempfile
import traceback


def spawn_workers(dataset, batches, collate_fn, num_workers, ring_prefix,
                  worker_init_fn=None, seed=None):
    """Serialize the job once, launch ``num_workers`` subprocesses.

    One ring per worker (``{ring_prefix}_w{i}``): each worker pushes its share
    of batches *in its own order*, so the parent reads batch ``b`` directly
    from ring ``b % num_workers`` — no reorder buffer, and a slow consumer
    back-pressures exactly the worker that is ahead (bounded memory)."""
    payload = {
        "dataset": dataset,
        "batches": batches,
        "collate_fn": collate_fn,
        "num_workers": num_workers,
        "worker_init_fn": worker_init_fn,
        "seed": seed,
    }
    fd, path = tempfile.mkstemp(suffix=".pdl")
    try:
        with os.fdopen(fd, "wb") as f:
            # frame 1: plain sys.path (always unpicklable-safe) so the worker
            # can resolve user modules before touching frame 2
            pickle.dump(list(sys.path), f)
            pickle.dump(payload, f)
    except (pickle.PicklingError, AttributeError, TypeError) as e:
        os.unlink(path)
        raise ValueError(
            "use_process_workers=True requires the dataset/collate_fn/"
            "worker_init_fn to be picklable by import path (defined in an "
            "importable module, not __main__ or a REPL); use thread workers "
            f"(use_process_workers=False) otherwise. Pickle error: {e}"
        ) from e
    procs = []
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"  # workers do host-side IO, never touch the TPU
    for wid in range(num_workers):
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.io.process_worker",
             path, f"{ring_prefix}_w{wid}", str(wid)],
            env=env,
        ))
    return procs, path


def _worker_main(payload_path, ring_name, worker_id):
    # adopt the parent's module search path BEFORE unpickling user classes
    with open(payload_path, "rb") as f:
        parent_path = pickle.load(f)
        for entry in reversed(parent_path):
            if entry not in sys.path:
                sys.path.insert(0, entry)
        job_blob = f.read()

    from paddle_tpu.core.native import ShmRing

    ring = ShmRing(ring_name, create=False)
    try:
        job = pickle.loads(job_blob)
        dataset = job["dataset"]
        collate = job["collate_fn"]
        nw = job["num_workers"]
        # populate get_worker_info() for per-worker dataset sharding logic
        from paddle_tpu.io.reader import WorkerInfo, _worker_info

        _worker_info.info = WorkerInfo(worker_id, nw, dataset)
        if job.get("worker_init_fn"):
            job["worker_init_fn"](worker_id)
        if job.get("seed") is not None:
            import numpy as np

            np.random.seed(job["seed"] + worker_id)
        for bi, indices in enumerate(job["batches"]):
            if bi % nw != worker_id:
                continue
            samples = [dataset[i] for i in indices]
            batch = collate(samples)
            ring.push(pickle.dumps((bi, batch), protocol=pickle.HIGHEST_PROTOCOL))
        ring.push(pickle.dumps(("__done__", worker_id)))
    except Exception:
        try:
            ring.push(pickle.dumps(("__error__", traceback.format_exc())))
        except Exception:
            pass
        raise
    # NOTE: no ring.close() — the ring is shared by all workers; closing it
    # here would cut off peers still streaming.  The "__done__" record is the
    # per-worker end-of-stream signal; the parent destroys the ring.


if __name__ == "__main__":
    _worker_main(sys.argv[1], sys.argv[2], int(sys.argv[3]))
