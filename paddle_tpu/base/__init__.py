"""paddle.base compatibility layer (reference: python/paddle/base/ — the legacy
"fluid" namespace many downstream repos still import).  Thin aliases onto the real
implementations; no separate machinery.
"""
from __future__ import annotations

from paddle_tpu.base import core  # noqa: F401
from paddle_tpu.core.device import (  # noqa: F401
    CPUPlace, CUDAPinnedPlace, CUDAPlace, CustomPlace, TPUPlace, XPUPlace,
    is_compiled_with_cuda, is_compiled_with_xpu,
)
from paddle_tpu.static.program import (  # noqa: F401
    Executor, Program, Scope, Variable, default_main_program,
    default_startup_program, global_scope, name_scope, program_guard, scope_guard,
)

__all__ = [
    "core", "Executor", "Program", "Scope", "Variable",
    "default_main_program", "default_startup_program", "global_scope",
    "program_guard", "scope_guard", "name_scope",
    "CPUPlace", "CUDAPlace", "CUDAPinnedPlace", "XPUPlace", "TPUPlace",
    "CustomPlace", "dygraph", "framework", "in_dygraph_mode",
]


def in_dygraph_mode() -> bool:
    import paddle_tpu

    return paddle_tpu.in_dynamic_mode()


class _DygraphShim:
    """paddle.base.dygraph — guard/no_grad aliases."""

    @staticmethod
    def guard(place=None):
        import contextlib

        @contextlib.contextmanager
        def _noop():
            yield

        return _noop()

    from paddle_tpu.autograd.engine import no_grad  # noqa: F401


dygraph = _DygraphShim


class _FrameworkShim:
    from paddle_tpu.core.dtype import convert_dtype  # noqa: F401

    @staticmethod
    def in_dygraph_mode():
        return in_dygraph_mode()


framework = _FrameworkShim
