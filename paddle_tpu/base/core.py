"""paddle.base.core compatibility shim.

The reference's ``core`` is the pybind11 extension module ``libpaddle``
(paddle/fluid/pybind/pybind.cc).  Here the native core is jax/XLA plus the
paddle_tpu.native C ABI host; this shim exposes the handful of ``core.*`` symbols
downstream code touches directly.
"""
from __future__ import annotations

import jax

from paddle_tpu.core.device import (  # noqa: F401
    CPUPlace, CUDAPinnedPlace, CUDAPlace, CustomPlace, Place, TPUPlace, XPUPlace,
    get_all_custom_device_type, get_all_device_type,
)
from paddle_tpu.core import dtype as _dtype


class VarDesc:
    """Legacy VarDesc.VarType dtype enum facade (reference: framework.proto)."""

    class VarType:
        FP16 = _dtype.float16
        BF16 = _dtype.bfloat16
        FP32 = _dtype.float32
        FP64 = _dtype.float64
        INT8 = _dtype.int8
        INT16 = _dtype.int16
        INT32 = _dtype.int32
        INT64 = _dtype.int64
        UINT8 = _dtype.uint8
        BOOL = _dtype.bool_
        COMPLEX64 = _dtype.complex64
        COMPLEX128 = _dtype.complex128


def is_compiled_with_cuda() -> bool:
    return any(d.platform == "gpu" for d in jax.devices())


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_custom_device(name: str) -> bool:
    from paddle_tpu.core.device import is_compiled_with_custom_device as _f

    return _f(name)


def get_custom_device_count(name: str) -> int:
    return sum(1 for d in jax.devices() if d.platform == name)


class eager:
    """core.eager namespace: Tensor is the eager tensor type."""

    from paddle_tpu.tensor.tensor import Tensor  # noqa: F401


def _get_all_register_op_kernels(*a, **k):  # pragma: no cover - parity shim
    return {}
