"""Incremental-decode attention over a preallocated KV cache (TPU-native).

Reference parity: the phi fused ``masked_multihead_attention`` decoding op
(paddle/phi/kernels/fusion/gpu/masked_multihead_attention_kernel.cu) — one
fused append-new-kv + attend-over-cache step per generated token.

TPU-first design choices:

* **Static shapes.**  The cache is preallocated once and every decode step
  runs the SAME compiled program regardless of the current length — position
  masking (``k_idx <= cur_len``) replaces dynamic slicing.  Two cache
  geometries share that property: the DENSE layout ``[B, Lmax, Hkv, D]``
  (one contiguous row span per slot) and the PAGED layout (a global block
  pool ``[N, C, Hkv, D]`` indirected through a per-slot ``[B, Lmax/C]``
  block table — ``init_kv_pool``).  The block table is a TRACED int32
  operand, so appending a block mid-stream or remapping a slot to shared
  prefix blocks changes only operand VALUES, never shapes: zero retraces.
* **Length-adaptive chunked reads.**  Decode is HBM-bandwidth-bound (a GEMV
  per head against the cache), so KV bytes ARE the step time — and a masked
  full-length read pays ``Lmax`` bytes for a request at context 200 in an
  ``Lmax=4096`` engine: 20× the traffic it needs.  ``chunk_size`` switches
  the attention read to an online-softmax (flash-style running max /
  denominator) ``lax.while_loop`` over ``[C]``-sized cache chunks whose trip
  count is ``ceil((max(live lengths) + T) / C)`` computed ON DEVICE — the
  compiled program is still traced exactly once (the trip count is a traced
  scalar, not a shape), but fully-masked tail chunks are never read, so HBM
  traffic per step is proportional to the longest LIVE context in the
  batch, not ``Lmax``.  Retired serving slots (parked at offset ``lmax`` by
  ``masked_lengths``) are excluded from the trip-count max, so one parked
  slot never forces full-length reads.  ``chunk_size=None`` (default) keeps
  the single fused full-length read — still optimal when contexts sit near
  ``Lmax`` or the cache is small.
* **int8 cache, float math.**  ``dtype="int8"`` in ``init_kv_cache`` /
  ``init_kv_pool`` stores KV quantized (symmetric absmax over ``D``, one
  float16 scale per (position, head) row in a parallel pytree leaf) —
  quantized ON APPEND inside the same cache scatter, dequantized INSIDE
  the chunked while_loop right after each chunk read, so only int8 bytes
  (+ 2 scale bytes per row) cross HBM per chunk: ~0.53× the traffic of a
  bf16 cache.  The scale array shares every piece of the index machinery —
  ``mode="drop"`` parking, ``mode="clip"`` paged gathers, the block-table
  indirection — because its indices are the data indices minus the
  trailing ``D`` axis.  Attention math is unchanged f32.
* **Paged block indirection rides the chunked loop.**  With a
  ``block_table`` the while_loop body gathers logical chunk ``i`` of each
  row from physical pool block ``table[b, i]`` instead of slicing a dense
  row — the SAME online-softmax recurrence over the SAME ``[B, C]`` tiles
  in the same order, so a paged read is bitwise the dense chunked read of
  equal ``chunk_size`` at f32 (the serving engine's paged-vs-dense parity
  matrix pins this).  Appends route through the same table: logical
  position ``l`` lands in pool block ``table[b, l // C]`` row ``l % C``,
  and any position past the slot's mapped capacity (or a table sentinel
  ``>= N``) is routed past the pool so the scatter DROPS it — the
  write-drop parking invariant survives paging unchanged.
* **GQA-native.**  kv heads are consumed directly (``[B, Hkv, G, ...]``
  einsums) — no ``repeat`` materialization, KV reads are 1/G of expanded
  heads.
* **Per-batch lengths.**  ``lengths [B]`` supports ragged batches (the
  reference's ``sequence_lengths``); appends use a vmapped
  ``dynamic_update_slice`` (lowers to one scatter).
* **Head-sharding safe.**  Under tensor-parallel serving
  (serving/sharding.py) the cache is sharded along the ``Hkv`` axis —
  axis 2 in BOTH geometries (dense ``[B, Lmax, Hkv, D]`` and the paged
  pool ``[N, C, Hkv, D]``), so ``kv_cache_pspec`` covers either one
  unchanged — and these reads partition cleanly: the chunked
  online-softmax running max/denominator reduce over the per-head chunk
  axis, never across heads; the trip count reduces over the (replicated)
  ``lengths``; and the paged block-table gather indexes only the
  unsharded pool axis 0 with a replicated table — so GSPMD runs the
  identical program per shard on ``Hkv/N`` heads with zero cross-chip
  collectives inside the attention read.  Keep it that way: any future
  reduction ACROSS the head axis (head-mixing, cross-head norm) breaks
  the partition and must be hoisted out of this module.
* Differentiability is not a goal (decode is inference); everything here is
  plain jnp under jit.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["init_kv_cache", "init_kv_pool", "decode_attention",
           "masked_lengths", "slot_prefill_attention"]

_NEG_INF = -1e30

# the supported cache storage dtypes — anything else is a loud ValueError,
# not a silent jnp.zeros coercion (a typo like "bfloat" used to surface as
# an opaque dtype error deep inside the first decode step)
_KV_DTYPES = ("float32", "float16", "bfloat16", "int8")
_Q8_MAX = 127.0
# int8 caches store a per-(position, head) float16 absmax scale alongside
# the quantized values.  float16 (not float32) keeps the analytic byte
# ratio vs a bf16 cache at (D + 2) / (2 D) — e.g. 0.53 at D=32 — instead
# of (D + 4) / (2 D); the scale magnitude is an activation absmax / 127,
# comfortably inside f16 range, and all arithmetic upcasts to f32 anyway.
_Q8_SCALE_DTYPE = jnp.float16


def _canon_dtype(dtype, where, supported, what, hint=""):
    """THE dtype-validation helper: canonicalize ``dtype`` against a
    supported-name set or raise a loud ValueError naming the set.

    ``init_kv_cache`` / ``init_kv_pool`` / the engine's ``kv_dtype`` knob
    share it via ``_canon_kv_dtype``, and the weight-quantization knob
    (models/llama_decode.py ``_canon_weight_dtype``) rides the same body —
    one canonical validation path instead of per-knob copies, so every
    storage-dtype typo fails the same way: at construction, with the
    supported set spelled out, never as an opaque dtype error deep inside
    the first compiled step."""
    try:
        name = jnp.dtype(dtype).name
    except TypeError:
        name = None
    if name not in supported:
        raise ValueError(
            f"{where}: unsupported {what} dtype {dtype!r} — supported: "
            f"{', '.join(supported)}.{hint}")
    return name


def _canon_kv_dtype(dtype, where):
    """Validate a cache dtype against the supported set -> canonical name."""
    return _canon_dtype(
        dtype, where, _KV_DTYPES, "KV cache",
        hint="  'int8' selects the quantized cache "
        "(per-(position, head) float16 scales stored in a parallel "
        "pytree leaf, quantize-on-append / dequant-in-loop).")


def _kv_data(cache):
    """Storage leaf of a cache operand: int8 caches are (data, scale)."""
    return cache[0] if isinstance(cache, tuple) else cache


def _q8_quantize(x):
    """Symmetric absmax int8 quantization over the trailing (D) axis.

    Returns (q int8 [..., D], scale f16 [...]): one scale per (position,
    head) row — the granularity that rides the cache scatter for free
    (same indices, one fewer trailing axis).  The divisor is the
    f16-ROUNDED scale, so dequantization with the stored scale reproduces
    each element to within scale/2 (+ one f16 ulp): the round-trip bound
    the unit test pins.
    """
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = (amax / _Q8_MAX).astype(_Q8_SCALE_DTYPE)
    inv = 1.0 / jnp.maximum(scale.astype(jnp.float32), 1e-8)
    q = jnp.clip(jnp.round(xf * inv[..., None]), -_Q8_MAX, _Q8_MAX)
    return q.astype(jnp.int8), scale


def _q8_dequant(q, scale):
    """Inverse of ``_q8_quantize``: f32 values from int8 data + f16 scale."""
    return q.astype(jnp.float32) * scale.astype(jnp.float32)[..., None]


def init_kv_cache(batch, max_len, num_kv_heads, head_dim, dtype="bfloat16"):
    """Preallocate a (k, v) cache pair [B, Lmax, Hkv, D].

    ``dtype="int8"`` selects the quantized cache: each of k/v becomes a
    ``(data int8 [B, Lmax, Hkv, D], scale f16 [B, Lmax, Hkv])`` pair —
    a nested pytree leaf that rides the same donated-cache plumbing, so
    the compiled serving programs specialize once on the structure and
    never retrace.
    """
    dtype = _canon_kv_dtype(dtype, "init_kv_cache")
    shape = (batch, max_len, num_kv_heads, head_dim)
    if dtype == "int8":
        def leaf():
            return (jnp.zeros(shape, jnp.int8),
                    jnp.zeros(shape[:-1], _Q8_SCALE_DTYPE))
        return leaf(), leaf()
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def init_kv_pool(num_blocks, block, num_kv_heads, head_dim,
                 dtype="bfloat16"):
    """Preallocate a paged (k, v) pool pair [N, C, Hkv, D].

    A slot's cache is no longer a contiguous ``[Lmax]`` row: it is the
    chain of pool blocks its ``[Lmax/C]`` block-table row names, appended
    lazily as the context grows and shareable across slots (refcounted
    prefix reuse — serving/kv_cache.py owns that bookkeeping).  The head
    axis sits at index 2 exactly like the dense cache, so the TP
    head-sharding spec applies to either geometry unchanged.

    ``dtype="int8"`` quantizes the pool: each of k/v becomes a
    ``(data int8 [N, C, Hkv, D], scale f16 [N, C, Hkv])`` pair.  The
    scale pool shares the block-table indirection — scales for logical
    chunk ``i`` live in scale block ``table[b, i]`` — so prefix sharing,
    sentinel routing, and LRU eviction all see ONE block id."""
    dtype = _canon_kv_dtype(dtype, "init_kv_pool")
    shape = (num_blocks, block, num_kv_heads, head_dim)
    if dtype == "int8":
        def leaf():
            return (jnp.zeros(shape, jnp.int8),
                    jnp.zeros(shape[:-1], _Q8_SCALE_DTYPE))
        return leaf(), leaf()
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def masked_lengths(lengths, live, lmax):
    """Per-slot write gating for continuous-batching serving.

    A serving engine runs ONE compiled step at fixed batch B while slots
    retire and are re-admitted independently.  Slots where ``live`` is
    False get offset ``lmax``: every ``_append`` index lands past the
    cache capacity so the scatter DROPS the write (mode="drop"), and the
    slot's cache/length state survives the step byte-for-byte untouched.
    Its attention output is garbage — the scheduler ignores it.

    Admission reuses the same trick with ``lengths = 0``: a prefill over
    the full batch writes ONLY the admitted slots (everyone else drops),
    so a retired slot is recycled without a reshape, a cache copy, or a
    recompile — the static-shape admission constraint on TPU.
    """
    return jnp.where(live, lengths.astype(jnp.int32), jnp.int32(lmax))


def _append(cache, new, lengths, layout, block_table=None):
    """Write ``new [B, T, Hkv, D]`` into the cache at per-batch offsets
    ``lengths [B]`` (indexed scatter — no reallocation).
    ``layout``: "blhd" cache [B, Lmax, Hkv, D] or "bhld" cache
    [B, Hkv, Lmax, D] (the reference's cache_kv layout).  With
    ``block_table [B, W]`` the cache is a paged pool [N, C, Hkv, D]
    ("blhd" only): logical position ``l`` of row ``b`` scatters into pool
    block ``table[b, l // C]`` at block row ``l % C``.

    Writes past the preallocated capacity are DROPPED (scatter
    mode="drop"), never clamped: a dynamic_update_slice would silently
    clamp the offset and overwrite the most recent valid entries (review
    r5).  The paged path preserves that contract by routing any logical
    position past the table's ``W*C`` span — and any sentinel table entry
    ``>= N`` (an unmapped chunk) — past the pool's block axis, so parked
    slots (offset ``lmax``) still drop every write.  Callers must still
    bound their decode loops by Lmax - prompt_len — an overflowing step
    simply does not extend the cache.

    An int8 ``(data, scale)`` cache quantizes ``new`` HERE — inside the
    append, not in the caller — and scatters data and scales with the SAME
    index math (the scale array is the data array minus the trailing ``D``
    axis), so drop/parking semantics hold for both leaves ("blhd" only)."""
    if isinstance(cache, tuple):
        if layout != "blhd":
            raise ValueError(
                "_append: int8 KV caches support only the blhd layout")
        data, scale = cache
        qn, sn = _q8_quantize(new)
        return (_append(data, qn, lengths, layout, block_table),
                _append(scale, sn, lengths, layout, block_table))
    lengths = lengths.astype(jnp.int32)
    if block_table is not None:
        n_blocks, c = cache.shape[0], cache.shape[1]
        b, t = new.shape[0], new.shape[1]
        w = block_table.shape[1]
        l = lengths[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]
        blk = jnp.take_along_axis(
            block_table.astype(jnp.int32),
            jnp.clip(l // c, 0, w - 1), axis=1)                     # [B, T]
        # invalid positions (past the W*C logical span — parked slots land
        # here) and sentinel entries route past the pool: scatter drops
        phys = jnp.where((l < w * c) & (blk < n_blocks), blk,
                         jnp.int32(n_blocks))
        return cache.at[phys.reshape(-1), (l % c).reshape(-1)].set(
            new.reshape(b * t, *new.shape[2:]).astype(cache.dtype),
            mode="drop")

    def one(c, n, off):
        # n is [T, Hkv, D] per batch entry in either cache layout
        idx = off + jnp.arange(n.shape[0], dtype=jnp.int32)
        if layout == "blhd":
            return c.at[idx].set(n.astype(c.dtype), mode="drop")
        return c.at[:, idx].set(jnp.swapaxes(n, 0, 1).astype(c.dtype),
                                mode="drop")

    return jax.vmap(one)(cache, new, lengths)


def _attend_full(qg, k_cache, v_cache, lengths, q_pos, scale, layout,
                 attn_bias):
    """Single fused masked read over the whole [Lmax] cache."""
    b, hkv, g, t, d = qg.shape
    if isinstance(k_cache, tuple):
        if layout != "blhd":
            raise ValueError(
                "_attend_full: int8 KV caches support only the blhd layout")
        # full-read fallback: dequantize the whole cache (the chunked path
        # is where the bytes win lives; this keeps chunk_size=None correct)
        k_cache = _q8_dequant(*k_cache)
        v_cache = _q8_dequant(*v_cache)
    lmax = k_cache.shape[1] if layout == "blhd" else k_cache.shape[2]
    k_eq = "blkd" if layout == "blhd" else "bkld"
    s = jnp.einsum(
        f"bkgtd,{k_eq}->bkgtl", qg,
        k_cache.astype(jnp.float32), preferred_element_type=jnp.float32,
    ) * scale
    if attn_bias is not None:
        bias = jnp.asarray(attn_bias, jnp.float32)
        bias = jnp.broadcast_to(bias, (b, 1, t, lmax))
        s = s + bias[:, :, None, :, :]
    k_idx = jnp.arange(lmax, dtype=jnp.int32)
    live = k_idx[None, None, :] <= q_pos[:, :, None]                    # [B,T,L]
    s = jnp.where(live[:, None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum(
        f"bkgtl,{k_eq}->bkgtd", p, v_cache.astype(jnp.float32),
        preferred_element_type=jnp.float32)


def _attend_chunked(qg, k_cache, v_cache, lengths, q_pos, scale, layout,
                    attn_bias, chunk, block_table=None):
    """Online-softmax ``lax.while_loop`` over [C]-sized cache chunks.

    Flash-style running (max, denominator, accumulator) carry; exact (not
    approximate) — the recurrence rescales previous partial sums by
    ``exp(m_old - m_new)`` so the result equals the full-read softmax up to
    float reassociation.  The trip count is a TRACED scalar
    ``ceil((max(live lengths) + T) / C)``: same compiled program every step
    (no retraces), but chunks past the longest live context are never
    read — HBM traffic tracks the batch's real context, not Lmax.  Slots
    parked by ``masked_lengths`` (offset >= lmax) are excluded from the
    trip-count max; their rows compute garbage (ignored by the scheduler)
    over whatever chunks DO run, which keeps every row's softmax finite.
    ``lmax % C != 0`` is handled by clamping the tail chunk's start to
    ``lmax - C`` and masking the re-read overlap out of the tail pass.

    With ``block_table [B, W]`` the caches are a paged pool
    ``[N, C, Hkv, D]`` (``C == chunk``, "blhd" only): iteration ``i``
    gathers each row's chunk from pool block ``table[b, i]`` instead of
    slicing a dense row, and the logical span is ``W * C``.  Sentinel /
    stale table entries only ever name chunks past a row's live length
    (the gather CLIPS OOB indices into the pool — never the NaN-filling
    default), so the causal mask discards whatever they gather — same
    guarantee the dense path gives chunks past ``lengths[b]``.

    int8 ``(data, scale)`` caches dequantize HERE, inside the loop body,
    immediately after each chunk slice/gather — so a step moves int8
    bytes (plus 2 scale bytes per (position, head)) across HBM and the
    f32 values exist only as a [B, C] working tile.  The scale chunk uses
    the SAME start offset / block index as the data chunk (paged: the
    same ``mode="clip"`` gather), so sentinel and tail semantics are
    shared by construction.
    """
    b, hkv, g, t, d = qg.shape
    c = int(chunk)
    quant = isinstance(k_cache, tuple)
    if quant and layout != "blhd":
        raise ValueError(
            "_attend_chunked: int8 KV caches support only the blhd layout")
    k_data = _kv_data(k_cache)
    if block_table is not None:
        if layout != "blhd":
            raise ValueError(
                "paged _attend_chunked supports only the blhd layout")
        if k_data.shape[1] != c:
            raise ValueError(
                f"paged _attend_chunked: chunk ({c}) must equal the pool "
                f"block size ({k_data.shape[1]})")
        block_table = block_table.astype(jnp.int32)
        lmax = block_table.shape[1] * c
    else:
        lmax = k_data.shape[1] if layout == "blhd" else k_data.shape[2]
    n_chunks = -(-lmax // c)
    bias = None
    if attn_bias is not None:
        bias = jnp.broadcast_to(jnp.asarray(attn_bias, jnp.float32),
                                (b, 1, t, lmax))
    # highest live position + 1 this step: parked slots (>= lmax) excluded
    eff = jnp.where(lengths < lmax, lengths, 0)
    trip = jnp.clip((jnp.max(eff) + t + c - 1) // c, 1, n_chunks)
    z = jnp.int32(0)

    def body(carry):
        i, m, l, acc = carry
        start = jnp.minimum(i * c, lmax - c)  # clamped tail start
        if block_table is not None:
            idx = jax.lax.dynamic_slice_in_dim(block_table, i, 1,
                                               axis=1)[:, 0]        # [B]
            # mode="clip", NOT the default "fill": fill gathers NaN for a
            # sentinel/unmapped entry, and the masked softmax weight times
            # NaN is NaN — clipping reads an arbitrary REAL block whose
            # rows the causal mask zeroes exactly like dense garbage rows

            def read(cache):
                if isinstance(cache, tuple):
                    db = jnp.take(cache[0], idx, axis=0, mode="clip")
                    sb = jnp.take(cache[1], idx, axis=0, mode="clip")
                    return _q8_dequant(db, sb)
                return jnp.take(cache, idx, axis=0, mode="clip")

            kb, vb = read(k_cache), read(v_cache)
            kb, vb = jnp.swapaxes(kb, 1, 2), jnp.swapaxes(vb, 1, 2)
        elif layout == "blhd":
            def read(cache):
                if isinstance(cache, tuple):
                    db = jax.lax.dynamic_slice(cache[0], (z, start, z, z),
                                               (b, c, hkv, d))
                    sb = jax.lax.dynamic_slice(cache[1], (z, start, z),
                                               (b, c, hkv))
                    return _q8_dequant(db, sb)
                return jax.lax.dynamic_slice(cache, (z, start, z, z),
                                             (b, c, hkv, d))

            kb, vb = read(k_cache), read(v_cache)
            kb, vb = jnp.swapaxes(kb, 1, 2), jnp.swapaxes(vb, 1, 2)
        else:
            kb = jax.lax.dynamic_slice(k_cache, (z, z, start, z),
                                       (b, hkv, c, d))
            vb = jax.lax.dynamic_slice(v_cache, (z, z, start, z),
                                       (b, hkv, c, d))
        s = jnp.einsum(
            "bkgtd,bkcd->bkgtc", qg, kb.astype(jnp.float32),
            preferred_element_type=jnp.float32) * scale
        if bias is not None:
            bb = jax.lax.dynamic_slice(bias, (z, z, z, start), (b, 1, t, c))
            s = s + bb[:, :, None, :, :]
        k_idx = start + jnp.arange(c, dtype=jnp.int32)            # [C] global
        # causal AND not already processed (the clamped tail re-reads
        # [start, i*c) — those positions belong to the previous chunk)
        live = (k_idx[None, None, :] <= q_pos[:, :, None]) \
            & (k_idx >= i * c)[None, None, :]                     # [B,T,C]
        s = jnp.where(live[:, None, None], s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        # explicit zero on masked lanes: a fully-masked row in an executed
        # chunk has s == m_new == _NEG_INF and exp(s - m_new) == 1 — the
        # classic online-softmax pollution bug
        p = jnp.where(live[:, None, None], jnp.exp(s - m_new[..., None]), 0.0)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1)
        acc = acc * corr[..., None] + jnp.einsum(
            "bkgtc,bkcd->bkgtd", p, vb.astype(jnp.float32),
            preferred_element_type=jnp.float32)
        return i + jnp.int32(1), m_new, l, acc

    m0 = jnp.full((b, hkv, g, t), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, hkv, g, t), jnp.float32)
    acc0 = jnp.zeros((b, hkv, g, t, d), jnp.float32)
    _, _, l, acc = jax.lax.while_loop(
        lambda carry: carry[0] < trip, body, (z, m0, l0, acc0))
    # chunk 0 runs unconditionally and position 0 is causally visible to
    # every query (q_pos >= 0), so l > 0 for any FINITE attn_bias — but a
    # bias of -inf over every visible position of a row zeroes its whole
    # denominator.  Guard the division so that row comes back 0 (finite
    # garbage, like the full path's softmax over all-masked scores) instead
    # of NaN.
    return acc / jnp.maximum(l, 1e-30)[..., None]


def _attend_dispatch(qg, k_cache, v_cache, lengths, q_pos, scale, layout,
                     attn_bias, chunk_size, lmax, block_table, attn_impl,
                     where):
    """Select the attention-read implementation for one attend.

    ``attn_impl`` (static): ``None`` / ``"reference"`` keep the existing
    dispatch — chunked ``lax.while_loop`` or fused full read — BITWISE
    unchanged; ``"pallas"`` selects the fused Pallas kernel
    (ops/paged_attention_pallas.py) when the geometry supports it and
    falls back to the reference path with a once-per-process log when it
    does not (a silent downgrade would ship while_loop speed under the
    fused flag)."""
    if attn_impl not in (None, "reference", "pallas"):
        raise ValueError(
            f"{where}: unknown attn_impl {attn_impl!r} — supported: "
            "'reference' (the lax.while_loop chunked read, the default), "
            "'pallas' (the fused paged-attention kernel, reference "
            "fallback on unsupported geometry)")
    if attn_impl == "pallas":
        from paddle_tpu.ops.paged_attention_pallas import (
            fused_decode_attention, fused_decode_supported, warn_fallback,
        )
        reason = fused_decode_supported(layout, attn_bias, chunk_size, lmax)
        if reason is None:
            return fused_decode_attention(
                qg, k_cache, v_cache, lengths, scale, int(chunk_size),
                block_table=block_table)
        warn_fallback(where, reason)
    if block_table is not None:
        return _attend_chunked(qg, k_cache, v_cache, lengths, q_pos, scale,
                               layout, attn_bias, int(chunk_size),
                               block_table)
    if chunk_size is not None and int(chunk_size) < lmax:
        return _attend_chunked(qg, k_cache, v_cache, lengths, q_pos, scale,
                               layout, attn_bias, int(chunk_size))
    return _attend_full(qg, k_cache, v_cache, lengths, q_pos, scale,
                        layout, attn_bias)


@functools.partial(jax.jit,
                   static_argnames=("scale", "layout", "chunk_size",
                                    "attn_impl"))
def decode_attention(q, k_new, v_new, k_cache, v_cache, lengths, scale=None,
                     layout="blhd", attn_bias=None, chunk_size=None,
                     block_table=None, attn_impl=None):
    """One decode step: append new kv, attend causally over the cache.

    q [B, T, H, D] (T = tokens this step, usually 1); k_new/v_new
    [B, T, Hkv, D]; k_cache/v_cache per ``layout`` ("blhd"
    [B, Lmax, Hkv, D] — the model projection order — or "bhld"
    [B, Hkv, Lmax, D] — the reference cache_kv order); lengths [B] — number
    of valid cache positions BEFORE this step.  ``attn_bias`` (optional,
    broadcastable to [B, 1, T, Lmax] fp) is added to the scores (the
    reference's src_mask).  ``chunk_size`` (static) selects the
    length-adaptive chunked read (see the module docstring): HBM traffic
    proportional to the longest live context instead of Lmax, allclose-
    identical to the full read; ``None`` (or >= Lmax) keeps the single
    fused full-length pass.  Returns (out [B, T, H, D], k_cache',
    v_cache', lengths + T).

    ``block_table [B, W]`` (traced int32) switches to the PAGED geometry:
    the caches are a global pool ``[N, C, Hkv, D]`` (``init_kv_pool``),
    appends and reads indirect through the table, and the logical span is
    ``W * C``.  Requires ``layout="blhd"`` and
    ``chunk_size == C`` (the chunked loop IS the paged read — see the
    module docstring); the paged read is bitwise the dense chunked read
    of the same chunk size at f32.

    Query token t (global position lengths+t) attends to cache positions
    <= lengths+t: bottom-right-aligned causality, same convention as the
    flash kernels' cached prefill.

    ``attn_impl`` (static): ``None``/``"reference"`` keep the existing
    read paths bitwise unchanged; ``"pallas"`` fuses gather + dequant +
    online softmax into one VMEM residency per KV chunk
    (ops/paged_attention_pallas.py) with reference fallback on
    unsupported geometry (logged once per process).
    """
    b, t, h, d = q.shape
    hkv = k_new.shape[2]
    k_data = _kv_data(k_cache)
    if isinstance(k_cache, tuple) and layout != "blhd":
        raise ValueError(
            "decode_attention: int8 KV caches support only layout='blhd'")
    if block_table is not None:
        if layout != "blhd":
            raise ValueError(
                "decode_attention: paged caches support only layout='blhd'")
        if chunk_size is None or int(chunk_size) != k_data.shape[1]:
            raise ValueError(
                f"decode_attention: paged caches require chunk_size == pool "
                f"block size ({k_data.shape[1]}), got {chunk_size}")
        lmax = block_table.shape[1] * k_data.shape[1]
    else:
        lmax = k_data.shape[1] if layout == "blhd" else k_data.shape[2]
    if hkv <= 0 or h % hkv:
        raise ValueError(
            f"decode_attention: query heads ({h}) must be an integer "
            f"multiple of kv heads ({hkv})")
    g = h // hkv
    scale = float(scale if scale is not None else 1.0 / (d ** 0.5))
    lengths = lengths.astype(jnp.int32)

    k_cache = _append(k_cache, k_new, lengths, layout, block_table)
    v_cache = _append(v_cache, v_new, lengths, layout, block_table)

    qg = q.reshape(b, t, hkv, g, d).transpose(0, 2, 3, 1, 4) \
        .astype(jnp.float32)                                # [B,Hkv,G,T,D]
    q_pos = lengths[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]  # [B,T]
    out = _attend_dispatch(qg, k_cache, v_cache, lengths, q_pos, scale,
                           layout, attn_bias, chunk_size, lmax, block_table,
                           attn_impl, "decode_attention")
    out = out.transpose(0, 3, 1, 2, 4).reshape(b, t, h, d).astype(q.dtype)
    return out, k_cache, v_cache, lengths + t


def _prefill_dispatch(q, k_new, v_new, k_cache, v_cache, slot, offset,
                      scale, chunk_size, lmax, block_table, prefill_impl,
                      where):
    """Select the prefill implementation for one admission chunk.

    ``prefill_impl`` (static): ``None`` / ``"reference"`` keep the
    existing scatter + chunked-read path BITWISE unchanged (return
    ``None`` so the caller runs it); ``"pallas"`` selects the fused
    attention + quantize-on-append kernel
    (ops/prefill_attention_pallas.py) when the geometry supports it and
    falls back with a once-per-process (call-site, reason) log when it
    does not — a prefill downgrade is keyed separately from any decode
    downgrade, so neither silences the other."""
    if prefill_impl not in (None, "reference", "pallas"):
        raise ValueError(
            f"{where}: unknown prefill_impl {prefill_impl!r} — supported: "
            "'reference' (scatter + chunked read, the default), 'pallas' "
            "(the fused prefill-attention + KV-append kernel, reference "
            "fallback on unsupported geometry)")
    if prefill_impl != "pallas":
        return None
    from paddle_tpu.ops.prefill_attention_pallas import (
        fused_prefill_attention, fused_prefill_supported,
    )
    from paddle_tpu.ops.paged_attention_pallas import warn_fallback
    t = q.shape[1]
    reason = fused_prefill_supported(chunk_size, lmax,
                                     t, block_table is not None)
    if reason is None:
        return fused_prefill_attention(
            q, k_new, v_new, k_cache, v_cache, slot, offset, scale,
            int(chunk_size), block_table=block_table)
    warn_fallback(where, f"prefill: {reason}", knob="prefill_impl")
    return None


def slot_prefill_attention(q, k_new, v_new, k_cache, v_cache, slot, offset,
                           scale=None, chunk_size=None, block_table=None,
                           attn_impl=None, prefill_impl=None):
    """Chunked-prefill attention for ONE slot of the batch cache.

    The serving engine's chunked admission path processes a prompt in
    fixed-size ``[1, P]`` pieces against the SLOT'S rows of the shared
    ``[B, Lmax]`` batch cache — not against a fresh per-bucket mini cache —
    so one compiled program covers every prompt length.  ``slot`` and
    ``offset`` are TRACED scalars (the device-carried write cursor): the
    chunk's k/v rows are scattered into cache row ``slot`` at positions
    ``offset + i`` (rows past capacity DROP, never clamp — same contract as
    ``_append``), and the chunk's queries attend causally over the slot's
    written prefix: query i (global position ``offset + i``) sees every
    previously written row ``< offset`` plus the intra-chunk causal prefix
    ``<= offset + i`` — exactly the monolithic prefill's mask restricted to
    this chunk's query rows, so chaining the chunks reproduces the
    monolithic forward.  Tail-chunk pad rows land in the cache as garbage
    at positions ``>= prompt_len`` — causally invisible to every real
    query and overwritten by decode appends, the same invariant the
    monolithic bucket-pad path relies on.

    ``chunk_size`` selects the length-adaptive chunked read over the
    slot's row (trip count tracks ``offset + P``, not ``Lmax``); ``None``
    keeps the fused full-length read.  Only the ``blhd`` layout (the
    model projection order the serving path uses) is supported.

    ``block_table [B, W]`` (traced int32) switches to the PAGED geometry:
    the caches are a pool ``[N, C, Hkv, D]`` and the chunk's rows scatter
    and read through the SLOT'S table row (gathered by the traced
    ``slot``), so no dense per-slot view is materialized.  Requires
    ``chunk_size == C``, like ``decode_attention``.

    ``prefill_impl`` (static): ``None``/``"reference"`` keep the
    scatter + chunked-read path bitwise unchanged; ``"pallas"`` fuses
    the chunk's attention WITH its quantize-on-append into one Pallas
    kernel (ops/prefill_attention_pallas.py) when the geometry supports
    it, reference fallback (logged once per process per reason)
    otherwise.  ``attn_impl`` keeps selecting the cache-READ kernel on
    the reference path.

    q [1, P, H, D]; k_new/v_new [1, P, Hkv, D]; caches [B, Lmax, Hkv, D].
    Returns (out [1, P, H, D], k_cache', v_cache').
    """
    b, t, h, d = q.shape
    if b != 1:
        raise ValueError(
            f"slot_prefill_attention: chunk batch must be 1 (got {b})")
    hkv = k_new.shape[2]
    lmax = _kv_data(k_cache).shape[1]
    if hkv <= 0 or h % hkv:
        raise ValueError(
            f"slot_prefill_attention: query heads ({h}) must be an integer "
            f"multiple of kv heads ({hkv})")
    g = h // hkv
    scale = float(scale if scale is not None else 1.0 / (d ** 0.5))
    slot = slot.astype(jnp.int32) if hasattr(slot, "astype") \
        else jnp.int32(slot)
    offset = offset.astype(jnp.int32) if hasattr(offset, "astype") \
        else jnp.int32(offset)

    if block_table is not None:
        blk = _kv_data(k_cache).shape[1]
        if chunk_size is None or int(chunk_size) != blk:
            raise ValueError(
                f"slot_prefill_attention: paged caches require "
                f"chunk_size == kv_block (the pool block size): got "
                f"chunk_size={chunk_size!r} with kv_block={blk} — the "
                "chunked loop IS the paged read, so the read chunk and "
                "the pool block must coincide")
        w = block_table.shape[1]
        # the slot's [1, W] table row (slot < B: no clamping)
        trow = jax.lax.dynamic_slice(
            block_table.astype(jnp.int32), (slot, jnp.int32(0)), (1, w))
        fused = _prefill_dispatch(
            q, k_new, v_new, k_cache, v_cache, slot, offset, scale,
            int(chunk_size), w * blk, trow, prefill_impl,
            "slot_prefill_attention")
        if fused is not None:
            return fused
        k_cache = _append(k_cache, k_new, offset[None], "blhd", trow)
        v_cache = _append(v_cache, v_new, offset[None], "blhd", trow)
        qg = q.reshape(1, t, hkv, g, d).transpose(0, 2, 3, 1, 4) \
            .astype(jnp.float32)
        q_pos = offset[None, None] + jnp.arange(t, dtype=jnp.int32)[None, :]
        out = _attend_dispatch(qg, k_cache, v_cache, offset[None], q_pos,
                               scale, "blhd", None, int(chunk_size),
                               w * blk, trow, attn_impl,
                               "slot_prefill_attention")
        out = out.transpose(0, 3, 1, 2, 4).reshape(1, t, h, d) \
            .astype(q.dtype)
        return out, k_cache, v_cache

    fused = _prefill_dispatch(
        q, k_new, v_new, k_cache, v_cache, slot, offset, scale,
        chunk_size, lmax, None, prefill_impl, "slot_prefill_attention")
    if fused is not None:
        return fused

    # scatter the chunk's rows into the slot (drop past capacity); int8
    # caches quantize the chunk here and scatter data + scales at the
    # same (slot, row) indices
    rows = offset + jnp.arange(t, dtype=jnp.int32)
    batch_idx = jnp.full((t,), slot, jnp.int32)

    def scatter(cache, new):
        if isinstance(cache, tuple):
            qn, sn = _q8_quantize(new[0])
            return (cache[0].at[batch_idx, rows].set(qn, mode="drop"),
                    cache[1].at[batch_idx, rows].set(sn, mode="drop"))
        return cache.at[batch_idx, rows].set(
            new[0].astype(cache.dtype), mode="drop")

    k_cache = scatter(k_cache, k_new)
    v_cache = scatter(v_cache, v_new)

    # the slot's [1, Lmax] view (slot < B: no dynamic_slice clamping)
    def slot_view(cache):
        if isinstance(cache, tuple):
            return (jax.lax.dynamic_slice(
                        cache[0], (slot, jnp.int32(0), jnp.int32(0),
                                   jnp.int32(0)), (1, lmax, hkv, d)),
                    jax.lax.dynamic_slice(
                        cache[1], (slot, jnp.int32(0), jnp.int32(0)),
                        (1, lmax, hkv)))
        return jax.lax.dynamic_slice(
            cache, (slot, jnp.int32(0), jnp.int32(0), jnp.int32(0)),
            (1, lmax, hkv, d))

    ks = slot_view(k_cache)
    vs = slot_view(v_cache)

    qg = q.reshape(1, t, hkv, g, d).transpose(0, 2, 3, 1, 4) \
        .astype(jnp.float32)                                # [1,Hkv,G,T,D]
    q_pos = offset[None, None] + jnp.arange(t, dtype=jnp.int32)[None, :]
    lengths = offset[None]                                  # [1]
    out = _attend_dispatch(qg, ks, vs, lengths, q_pos, scale, "blhd", None,
                           chunk_size, lmax, None, attn_impl,
                           "slot_prefill_attention")
    out = out.transpose(0, 3, 1, 2, 4).reshape(1, t, h, d).astype(q.dtype)
    return out, k_cache, v_cache
