"""Incremental-decode attention over a preallocated KV cache (TPU-native).

Reference parity: the phi fused ``masked_multihead_attention`` decoding op
(paddle/phi/kernels/fusion/gpu/masked_multihead_attention_kernel.cu) — one
fused append-new-kv + attend-over-cache step per generated token.

TPU-first design choices:

* **Static shapes.**  The cache is preallocated at ``[B, Lmax, Hkv, D]`` and
  every decode step runs the SAME compiled program regardless of the current
  length — position masking (``k_idx <= cur_len``) replaces dynamic slicing.
  The reference's CUDA kernel reads exactly ``cur_len`` keys; on TPU a
  masked full-length read is one fused bandwidth-bound pass with no
  recompilation, which is what wins on XLA (SURVEY §3: jit traces once).
* **GQA-native.**  kv heads are consumed directly (``[B, Hkv, G, ...]``
  einsums) — no ``repeat`` materialization, KV reads are 1/G of expanded
  heads.  Decode is HBM-bandwidth-bound (a GEMV per head against the cache),
  so KV bytes ARE the step time.
* **Per-batch lengths.**  ``lengths [B]`` supports ragged batches (the
  reference's ``sequence_lengths``); appends use a vmapped
  ``dynamic_update_slice`` (lowers to one scatter).
* Differentiability is not a goal (decode is inference); everything here is
  plain jnp under jit.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["init_kv_cache", "decode_attention", "masked_lengths"]

_NEG_INF = -1e30


def init_kv_cache(batch, max_len, num_kv_heads, head_dim, dtype="bfloat16"):
    """Preallocate a (k, v) cache pair [B, Lmax, Hkv, D]."""
    shape = (batch, max_len, num_kv_heads, head_dim)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def masked_lengths(lengths, live, lmax):
    """Per-slot write gating for continuous-batching serving.

    A serving engine runs ONE compiled step at fixed batch B while slots
    retire and are re-admitted independently.  Slots where ``live`` is
    False get offset ``lmax``: every ``_append`` index lands past the
    cache capacity so the scatter DROPS the write (mode="drop"), and the
    slot's cache/length state survives the step byte-for-byte untouched.
    Its attention output is garbage — the scheduler ignores it.

    Admission reuses the same trick with ``lengths = 0``: a prefill over
    the full batch writes ONLY the admitted slots (everyone else drops),
    so a retired slot is recycled without a reshape, a cache copy, or a
    recompile — the static-shape admission constraint on TPU.
    """
    return jnp.where(live, lengths.astype(jnp.int32), jnp.int32(lmax))


def _append(cache, new, lengths, layout):
    """Write ``new [B, T, Hkv, D]`` into the cache at per-batch offsets
    ``lengths [B]`` (vmapped indexed scatter — no reallocation).
    ``layout``: "blhd" cache [B, Lmax, Hkv, D] or "bhld" cache
    [B, Hkv, Lmax, D] (the reference's cache_kv layout).

    Writes past the preallocated capacity are DROPPED (scatter
    mode="drop"), never clamped: a dynamic_update_slice would silently
    clamp the offset and overwrite the most recent valid entries (review
    r5).  Callers must still bound their decode loops by Lmax - prompt_len
    — an overflowing step simply does not extend the cache."""

    def one(c, n, off):
        # n is [T, Hkv, D] per batch entry in either cache layout
        idx = off + jnp.arange(n.shape[0], dtype=jnp.int32)
        if layout == "blhd":
            return c.at[idx].set(n.astype(c.dtype), mode="drop")
        return c.at[:, idx].set(jnp.swapaxes(n, 0, 1).astype(c.dtype),
                                mode="drop")

    return jax.vmap(one)(cache, new, lengths.astype(jnp.int32))


@functools.partial(jax.jit, static_argnames=("scale", "layout"))
def decode_attention(q, k_new, v_new, k_cache, v_cache, lengths, scale=None,
                     layout="blhd", attn_bias=None):
    """One decode step: append new kv, attend causally over the cache.

    q [B, T, H, D] (T = tokens this step, usually 1); k_new/v_new
    [B, T, Hkv, D]; k_cache/v_cache per ``layout`` ("blhd"
    [B, Lmax, Hkv, D] — the model projection order — or "bhld"
    [B, Hkv, Lmax, D] — the reference cache_kv order); lengths [B] — number
    of valid cache positions BEFORE this step.  ``attn_bias`` (optional,
    broadcastable to [B, 1, T, Lmax] fp) is added to the scores (the
    reference's src_mask).  Returns (out [B, T, H, D], k_cache', v_cache',
    lengths + T).

    Query token t (global position lengths+t) attends to cache positions
    <= lengths+t: bottom-right-aligned causality, same convention as the
    flash kernels' cached prefill.
    """
    b, t, h, d = q.shape
    hkv = k_new.shape[2]
    lmax = k_cache.shape[1] if layout == "blhd" else k_cache.shape[2]
    if hkv <= 0 or h % hkv:
        raise ValueError(
            f"decode_attention: query heads ({h}) must be an integer "
            f"multiple of kv heads ({hkv})")
    g = h // hkv
    scale = float(scale if scale is not None else 1.0 / (d ** 0.5))
    lengths = lengths.astype(jnp.int32)

    k_cache = _append(k_cache, k_new, lengths, layout)
    v_cache = _append(v_cache, v_new, lengths, layout)
    k_eq = "blkd" if layout == "blhd" else "bkld"

    # [B, Hkv, G, T, D] x cache -> [B, Hkv, G, T, Lmax]
    qg = q.reshape(b, t, hkv, g, d).transpose(0, 2, 3, 1, 4)
    s = jnp.einsum(
        f"bkgtd,{k_eq}->bkgtl", qg.astype(jnp.float32),
        k_cache.astype(jnp.float32), preferred_element_type=jnp.float32,
    ) * scale
    if attn_bias is not None:
        bias = jnp.asarray(attn_bias, jnp.float32)
        bias = jnp.broadcast_to(bias, (b, 1, t, lmax))
        s = s + bias[:, :, None, :, :]
    k_idx = jnp.arange(lmax, dtype=jnp.int32)
    q_pos = lengths[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]  # [B,T]
    live = k_idx[None, None, :] <= q_pos[:, :, None]                    # [B,T,L]
    s = jnp.where(live[:, None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum(
        f"bkgtl,{k_eq}->bkgtd", p, v_cache.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    out = out.transpose(0, 3, 1, 2, 4).reshape(b, t, h, d).astype(q.dtype)
    return out, k_cache, v_cache, lengths + t
