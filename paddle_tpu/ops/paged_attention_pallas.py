"""Fused Pallas paged-attention kernel for the decode hot path.

The reference chunked decode read (ops/decode_attention.py:_attend_chunked)
is a ``lax.while_loop`` of gather -> dequant -> online-softmax stages that
XLA schedules as separate HBM round-trips: each chunk's int8 block is
gathered to HBM-resident f32, re-read by the score einsum, and the partial
softmax state bounces through registers between loop-carried arrays.  This
module fuses the whole read into ONE Pallas kernel per (batch row, kv
head), vLLM-PagedAttention-style:

* **Single VMEM residency per KV chunk.**  Grid ``(B, Hkv, n_chunks)``
  with the chunk axis minor: each program receives one ``[C, D]`` K tile
  and one V tile straight from HBM into VMEM, dequantizes int8 in-place
  (the f32 values never exist in HBM), scores against the resident
  ``[G*T, D]`` query tile and folds the result into the flash-style
  running (max, denominator, accumulator) carried in VMEM scratch across
  the chunk sweep — exactly the streamed layout of
  ops/flash_attention.py's ``_fwd_kernel_streamed``.
* **The block-table gather IS the index map.**  Paged mode prefetches the
  ``[B, W]`` block table as a scalar operand
  (``pltpu.PrefetchScalarGridSpec``): logical chunk ``i`` of row ``b``
  loads pool block ``clip(table[b, i], 0, N-1)`` directly — no gathered
  copy of the chunk is ever materialized.  The clip reproduces the
  reference's ``mode="clip"`` semantics: a sentinel (``>= N``) or stale
  entry reads an arbitrary REAL block whose rows the causal mask zeroes,
  never a NaN-filling OOB default.
* **Reference-exact masking.**  Per row, chunk ``i`` is live for key
  position ``k_idx <= q_pos`` with masked lanes explicitly zeroed after
  the exp (``p = where(live, exp(s - m_new), 0)``) — the same
  fully-masked-chunk pollution guard as the reference.  Slots parked by
  ``masked_lengths`` (offset ``>= lmax``) pass the causal test everywhere
  and come back as finite garbage the scheduler ignores, exactly like the
  reference rows.
* **Per-row adaptive compute.**  ``lengths`` rides the scalar prefetch
  too: a chunk past ``ceil((eff + T) / C)`` for its row (``eff = 0`` for
  parked slots — the reference's trip-count exclusion) skips its compute
  entirely via ``pl.when``, so MXU work tracks each row's real context.
* **CPU = interpret mode.**  ``interpret`` defaults to
  ``jax.default_backend() != "tpu"`` so the parity suite runs the same
  kernel logic on the virtual-device CPU platform; the flag is never the
  literal ``True`` in product code (tpu-lint PTL012 polices exactly that
  — interpret mode silently ships a ~100x slower kernel).

Geometry the kernel does NOT cover falls back to the bitwise reference
path: ``fused_decode_supported`` returns the reason and ``warn_fallback``
logs it once per process per (call-site, reason) — a silent fallback
would ship while_loop speed under an ``attn_impl="pallas"`` flag, and a
shared key would let a decode downgrade silence a later prefill one.
"""
from __future__ import annotations

import functools
import logging

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["fused_decode_attention", "fused_decode_supported",
           "fused_supported", "warn_fallback"]

_NEG_INF = -1e30

_LOG = logging.getLogger(__name__)
# once-per-process fallback log: (where, reason) pairs already warned.
# Serving dispatches thousands of steps through one traced program — the
# fallback decision happens at trace time, but a per-trace log line would
# still spam every warmup; dedup makes the downgrade loud exactly once.
_warned = set()


def fused_decode_supported(layout, attn_bias, chunk_size, lmax):
    """Geometry gate for the fused DECODE kernel: ``None`` when
    supported, else a human-readable reason string (the fallback log
    line).  The prefill kernel has its own gate —
    ops/prefill_attention_pallas.py ``fused_prefill_supported`` — with
    prefill-specific reasons, so a decode downgrade and a prefill
    downgrade are distinct ``warn_fallback`` keys and neither silences
    the other.

    The kernel covers the serving hot path — ``blhd`` caches (dense or
    paged), no additive bias, a chunked read whose chunk divides the
    logical span (uniform Pallas blocks; the reference's clamped-tail
    re-read has no block-uniform equivalent).  Everything else is the
    reference ``lax.while_loop``'s job.
    """
    if layout != "blhd":
        return f"layout {layout!r} (only 'blhd' is fused)"
    if attn_bias is not None:
        return "attn_bias is not fused"
    if chunk_size is None:
        return "chunk_size=None selects the single full-length read"
    if int(chunk_size) > lmax or lmax % int(chunk_size):
        return (f"chunk_size ({int(chunk_size)}) must divide the cache "
                f"span ({lmax}) for uniform kernel blocks")
    return None


#: Back-compat alias (pre-split name); the decode gate is the one this
#: module owns.
fused_supported = fused_decode_supported


def warn_fallback(where, reason, knob="attn_impl"):
    """Log the fused->reference downgrade once per process per
    (call-site, reason) key: a prefill fallback at one call site is
    never silenced by an earlier decode fallback at another."""
    key = (where, reason)
    if key not in _warned:
        _warned.add(key)
        _LOG.warning(
            "%s: %s='pallas' requested but unsupported — %s; "
            "falling back to the reference path (bitwise the "
            "%s=None path, logged once per process)",
            where, knob, reason, knob)


def _fused_kernel(*refs, chunk, lmax, t, group, scale, quant, paged):
    """One (batch row, kv head, chunk) step of the fused online softmax.

    refs (scalar-prefetch first, per PrefetchScalarGridSpec): lengths
    [B] (+ the [B, W] block table when paged, consumed by the index maps
    only), then q [1, 1, G*T, D], k/v chunk tiles [1, C, 1, D] (+ their
    [1, C, 1] f16 scale tiles when quant), the output block
    [1, 1, G*T, D], and VMEM scratch acc [G*T, D] / m, l [8, G*T]
    (sublane-replicated running state, the flash_attention idiom).
    """
    if paged:
        len_ref, _tbl_ref, *refs = refs
    else:
        len_ref, *refs = refs
    if quant:
        (q_ref, k_ref, ks_ref, v_ref, vs_ref, o_ref,
         acc_ref, m_ref, l_ref) = refs
    else:
        q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref = refs
    b = pl.program_id(0)
    i = pl.program_id(2)
    n_chunks = pl.num_programs(2)
    rows = group * t

    @pl.when(i == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    length = len_ref[b]
    # the reference trip count, per ROW instead of per batch: parked slots
    # (offset >= lmax) contribute eff = 0, so chunks past a row's live
    # span skip their MXU work (chunk 0 always runs: eff + t >= 1)
    eff = jnp.where(length < lmax, length, 0)
    work = i * chunk < eff + t

    @pl.when(work)
    def _compute():
        q = q_ref[0, 0]                                     # [G*T, D] f32
        k = k_ref[0, :, 0, :].astype(jnp.float32)           # [C, D]
        v = v_ref[0, :, 0, :].astype(jnp.float32)
        if quant:
            # int8 dequant in VMEM: the f32 chunk never touches HBM
            k = k * ks_ref[0, :, 0].astype(jnp.float32)[:, None]
            v = v * vs_ref[0, :, 0].astype(jnp.float32)[:, None]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale     # [G*T, C]
        # row r of the [G, T] query tile is step token r % t
        q_pos = length + jax.lax.broadcasted_iota(
            jnp.int32, (group, t), 1).reshape(rows)
        k_idx = i * chunk + jax.lax.broadcasted_iota(
            jnp.int32, (rows, chunk), 1)
        live = k_idx <= q_pos[:, None]
        s = jnp.where(live, s, _NEG_INF)
        m = m_ref[0]
        l = l_ref[0]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        # explicit zero on masked lanes — the online-softmax pollution
        # guard the reference carries (a fully-masked row has
        # s == m_new == _NEG_INF and exp(0) == 1 otherwise)
        p = jnp.where(live, jnp.exp(s - m_new[:, None]), 0.0)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_ref[...] = acc_ref[...] * corr[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(i == n_chunks - 1)
    def _fin():
        l_safe = jnp.maximum(l_ref[0], 1e-30)
        o_ref[0, 0] = (acc_ref[...] / l_safe[:, None]).astype(o_ref.dtype)


def fused_decode_attention(qg, k_cache, v_cache, lengths, scale, chunk,
                           block_table=None, interpret=None):
    """Fused drop-in for the reference ``_attend_chunked`` (blhd, no bias).

    qg ``[B, Hkv, G, T, D]`` f32 queries (the reference's grouped layout);
    caches dense ``[B, Lmax, Hkv, D]`` or — with ``block_table [B, W]`` —
    a paged pool ``[N, C, Hkv, D]``; int8 caches are ``(data, scale)``
    pairs dequantized in-kernel.  ``lengths [B]`` are the PRE-append
    lengths (parked slots at ``>= lmax``).  Returns ``[B, Hkv, G, T, D]``
    f32 — same contract as the reference read, numerically equal up to
    dot-product reassociation (the parity matrix pins the drift budget).
    ``interpret=None`` resolves to ``jax.default_backend() != "tpu"``.
    """
    from jax.experimental.pallas import tpu as pltpu

    b, hkv, g, t, d = qg.shape
    c = int(chunk)
    quant = isinstance(k_cache, tuple)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    paged = block_table is not None
    if paged:
        n_chunks = int(block_table.shape[1])
        lmax = n_chunks * c
        n_blocks = int((k_cache[0] if quant else k_cache).shape[0])
    else:
        lmax = int((k_cache[0] if quant else k_cache).shape[1])
        n_chunks = lmax // c
    gt = g * t
    q2 = qg.reshape(b, hkv, gt, d).astype(jnp.float32)
    lengths = lengths.astype(jnp.int32)

    # index maps receive (b, h, i, *scalar_refs); constant dims use
    # ``i * 0`` so the index dtype stays i32 under jax_enable_x64 (the
    # flash_attention.py Mosaic idiom)
    if paged:
        scalars = (lengths, block_table.astype(jnp.int32))

        def blk(tbl, bi, ci):
            # the reference gather's mode="clip": sentinel/stale entries
            # read a real pool block, the causal mask discards its rows
            return jnp.clip(tbl[bi, ci], 0, n_blocks - 1)

        q_idx = lambda bi, hi, ci, ln, tb: (bi, hi, ci * 0, ci * 0)
        k_idx = lambda bi, hi, ci, ln, tb: (blk(tb, bi, ci), ci * 0, hi,
                                            ci * 0)
        s_idx = lambda bi, hi, ci, ln, tb: (blk(tb, bi, ci), ci * 0, hi)
    else:
        scalars = (lengths,)
        q_idx = lambda bi, hi, ci, ln: (bi, hi, ci * 0, ci * 0)
        k_idx = lambda bi, hi, ci, ln: (bi, ci, hi, ci * 0)
        s_idx = lambda bi, hi, ci, ln: (bi, ci, hi)

    kv_spec = pl.BlockSpec((1, c, 1, d), k_idx)
    sc_spec = pl.BlockSpec((1, c, 1), s_idx)
    in_specs = [pl.BlockSpec((1, 1, gt, d), q_idx)]
    args = [q2]
    if quant:
        in_specs += [kv_spec, sc_spec, kv_spec, sc_spec]
        args += [k_cache[0], k_cache[1], v_cache[0], v_cache[1]]
    else:
        in_specs += [kv_spec, kv_spec]
        args += [k_cache, v_cache]

    out = pl.pallas_call(
        functools.partial(
            _fused_kernel, chunk=c, lmax=lmax, t=t, group=g,
            scale=float(scale), quant=quant, paged=paged),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=len(scalars),
            grid=(b, hkv, n_chunks),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, 1, gt, d), q_idx),
            scratch_shapes=[
                pltpu.VMEM((gt, d), jnp.float32),
                pltpu.VMEM((8, gt), jnp.float32),
                pltpu.VMEM((8, gt), jnp.float32),
            ]),
        out_shape=jax.ShapeDtypeStruct((b, hkv, gt, d), jnp.float32),
        interpret=interpret,
    )(*scalars, *args)
    return out.reshape(b, hkv, g, t, d)
