"""Flash attention for TPU: Pallas forward kernel + blockwise-differentiable fallback.

Reference parity: python/paddle/nn/functional/flash_attention.py over
third_party/flashattn (CUDA).  TPU-native design:

* ``_flash_fwd_pallas`` — an online-softmax Pallas kernel tiled for the MXU
  (q blocks in VMEM, k/v streamed block-by-block, fp32 accumulators).  Used as
  the forward fast path on TPU.
* ``blockwise_attention`` — the same math as a ``lax.scan`` over key/value
  blocks in pure jnp.  It is differentiable, memory-efficient (never
  materializes the [Lq, Lk] score matrix), works on any backend, and is the
  building block ring attention rotates over the mesh (ops/ring_attention.py).
* ``_flash_bwd_pallas`` — the standard two-pass flash backward as Pallas
  kernels (dk/dv pass over k blocks, dq pass over q blocks) consuming the
  forward's log-sum-exp rows; fp32 accumulation, no [Lq, Lk] tensor in HBM.
* ``flash_attention_blhd`` — custom_vjp wrapper: Pallas forward, Pallas
  backward.

Layout is Paddle's flash-attention layout [batch, seq, heads, head_dim].
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30


# --------------------------------------------------------------------------- pallas fwd
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int,
                causal: bool, scale: float):
    """One (batch*head, q-block) program: online softmax over k blocks.

    q_ref [1, block_q, D]; k_ref/v_ref [1, Lk, D]; o_ref [1, block_q, D];
    lse_ref [1, 8, block_q] — log-sum-exp rows, replicated across the 8
    sublanes so the stats tensor tiles legally on TPU; consumed by backward.
    """
    block_q = q_ref.shape[1]
    head_dim = q_ref.shape[2]
    lk = k_ref.shape[1]
    num_k_blocks = lk // block_k
    qi = pl.program_id(1)

    q = q_ref[0]  # [block_q, D]

    def body(kb, carry):
        acc, m, l = carry
        k = k_ref[0, pl.ds(kb * block_k, block_k), :]  # [block_k, D]
        v = v_ref[0, pl.ds(kb * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [block_q, block_k] fp32
        if causal:
            q_idx = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_idx = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_idx >= k_idx, s, jnp.float32(_NEG_INF))
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return acc_new, m_new, l_new

    init = (
        jnp.zeros((block_q, head_dim), jnp.float32),
        jnp.full((block_q,), _NEG_INF, jnp.float32),
        jnp.zeros((block_q,), jnp.float32),
    )
    # static trip count over ALL k blocks, fully-masked ones included
    # (exp(-inf)=0 keeps the result identical).  Causal block-skipping was
    # measured on v5e (L=2048, block 512) both as lax.cond-per-tile and as
    # all-i32 dynamic fori bounds: 12.7ms/13.2ms vs 12.1ms static-unrolled —
    # the skip costs more than the masked tiles; keep static + unroll.
    acc, m, l = jax.lax.fori_loop(jnp.int32(0), jnp.int32(num_k_blocks), body,
                                  init, unroll=num_k_blocks <= 8)
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[0] = jnp.broadcast_to(m + jnp.log(l_safe), (8, block_q))


def _pick_block(n: int, preferred: int, kind: str = "") -> int:
    """Largest power-of-two-ish divisor of ``n`` at most ``preferred``.

    When ``kind`` is given ("q"/"k"), PADDLE_TPU_FLASH_BLOCK[_Q|_K] overrides
    ``preferred`` for perf sweeps (bench_sweep.jsonl).  NOTE: the enclosing
    kernels are jax.jit'd, so the env is read at TRACE time — sweep in
    separate processes (as bench_sweep does), not by mutating os.environ
    between calls.  Callers passing explicit blocking (kind="") are never
    overridden."""
    if kind:
        import os
        import warnings

        env = (os.environ.get(f"PADDLE_TPU_FLASH_BLOCK_{kind.upper()}")
               or os.environ.get("PADDLE_TPU_FLASH_BLOCK"))
        if env:
            try:
                v = int(env)
            except ValueError:
                v = 0
            if v >= 8:
                preferred = v
            else:
                warnings.warn(
                    f"ignoring invalid flash block override {env!r} "
                    "(need an integer >= 8)", stacklevel=2)
    b = min(preferred, n)
    while n % b:
        b //= 2
    b = max(b, 1)
    if kind and b != min(preferred, n):
        import warnings

        warnings.warn(
            f"flash block_{kind} {preferred} does not divide L={n}; "
            f"using {b}", stacklevel=2)
    return b


@functools.partial(jax.jit, static_argnames=("causal", "scale", "interpret"))
def _flash_fwd_pallas(q, k, v, causal=False, scale=None, interpret=False):
    """[B, L, H, D] in/out; also returns lse [B*H, 8, Lq] (sublane-replicated
    fp32 log-sum-exp rows) for the backward kernels."""
    b, lq, h, d = q.shape
    lk = k.shape[1]
    scale = float(scale if scale is not None else 1.0 / (d ** 0.5))
    # -> [B*H, L, D]
    qh = jnp.swapaxes(q, 1, 2).reshape(b * h, lq, d)
    kh = jnp.swapaxes(k, 1, 2).reshape(b * h, lk, d)
    vh = jnp.swapaxes(v, 1, 2).reshape(b * h, lk, d)
    # sweep-chosen defaults (v5e, L=2048): k blocks 1024 beat 512 by ~1.2%
    # MFU; 256 loses 16% and full-L k overflows VMEM (bench_sweep.jsonl)
    block_q = _pick_block(lq, 512, "q")
    block_k = _pick_block(lk, 1024, "k")
    grid = (b * h, lq // block_q)
    out, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel, block_k=block_k, causal=causal, scale=scale
        ),
        grid=grid,
        # index maps use `i * 0` (not the literal 0) so the constant inherits the
        # i32 index dtype — a literal traces as i64 under jax_enable_x64 and
        # Mosaic rejects the mixed-width index tuple
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, i * 0)),
            pl.BlockSpec((1, lk, d), lambda bh, i: (bh, i * 0, i * 0)),
            pl.BlockSpec((1, lk, d), lambda bh, i: (bh, i * 0, i * 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, i * 0)),
            pl.BlockSpec((1, 8, block_q), lambda bh, i: (bh, i * 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, lq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, 8, lq), jnp.float32),
        ],
        interpret=interpret,
    )(qh, kh, vh)
    return jnp.swapaxes(out.reshape(b, h, lq, d), 1, 2), lse


# --------------------------------------------------------------------------- pallas bwd
# Standard flash-attention backward (the public two-pass formulation): with the
# forward's log-sum-exp rows the softmax is reconstructed per tile as
# p = exp(s - lse), then
#   dv = pᵀ·do,  dp = do·vᵀ,  ds = p ∘ (dp - delta) · scale,
#   dk = dsᵀ·q,  dq = Σ ds·k,      delta = rowsum(do ∘ o).
# Pass 1 (grid over k blocks) accumulates dk/dv with q/do streamed; pass 2
# (grid over q blocks) accumulates dq with k/v streamed.  All accumulation in
# fp32; no [Lq, Lk] tensor ever hits HBM — this replaces the recompute-vjp
# fallback whose stacked fp32 temps dominated the train-step footprint.


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, block_q: int, causal: bool,
                    scale: float):
    """One (batch*head, k-block) program: dk/dv for this k block.

    q_ref/do_ref [1, Lq, D]; k_ref/v_ref [1, block_k, D];
    lse_ref/delta_ref [1, 8, Lq] (sublane-replicated rows);
    dk_ref/dv_ref [1, block_k, D].
    """
    block_k = k_ref.shape[1]
    head_dim = k_ref.shape[2]
    lq = q_ref.shape[1]
    num_q_blocks = lq // block_q
    ki = pl.program_id(1)

    k = k_ref[0]  # [block_k, D]
    v = v_ref[0]

    def body(qb, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qb * block_q, block_q), :]       # [block_q, D]
        do = do_ref[0, pl.ds(qb * block_q, block_q), :]
        lse = lse_ref[0, 0, pl.ds(qb * block_q, block_q)]   # [block_q]
        delta = delta_ref[0, 0, pl.ds(qb * block_q, block_q)]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale                                           # [block_q, block_k]
        if causal:
            q_idx = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_idx = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_idx >= k_idx, s, jnp.float32(_NEG_INF))
        p = jnp.exp(s - lse[:, None])                       # [block_q, block_k]
        dv_new = dv + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )                                                   # [block_q, block_k]
        ds = p * (dp - delta[:, None]) * scale
        dk_new = dk + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return dk_new, dv_new

    init = (
        jnp.zeros((block_k, head_dim), jnp.float32),
        jnp.zeros((block_k, head_dim), jnp.float32),
    )
    dk, dv = jax.lax.fori_loop(jnp.int32(0), jnp.int32(num_q_blocks), body,
                               init, unroll=num_q_blocks <= 8)
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
                   block_k: int, causal: bool, scale: float):
    """One (batch*head, q-block) program: dq for this q block.

    q_ref/do_ref/dq_ref [1, block_q, D]; k_ref/v_ref [1, Lk, D];
    lse_ref/delta_ref [1, 8, block_q] (sublane-replicated rows).
    """
    block_q = q_ref.shape[1]
    head_dim = q_ref.shape[2]
    lk = k_ref.shape[1]
    num_k_blocks = lk // block_k
    qi = pl.program_id(1)

    q = q_ref[0]
    do = do_ref[0]
    lse = lse_ref[0, 0]
    delta = delta_ref[0, 0]

    def body(kb, dq):
        k = k_ref[0, pl.ds(kb * block_k, block_k), :]
        v = v_ref[0, pl.ds(kb * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if causal:
            q_idx = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_idx = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_idx >= k_idx, s, jnp.float32(_NEG_INF))
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta[:, None]) * scale
        return dq + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    dq = jax.lax.fori_loop(
        jnp.int32(0), jnp.int32(num_k_blocks), body,
        jnp.zeros((block_q, head_dim), jnp.float32), unroll=num_k_blocks <= 8
    )
    dq_ref[0] = dq.astype(dq_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "scale", "interpret"))
def _flash_bwd_pallas(q, k, v, out, lse, do, causal=False, scale=None,
                      interpret=False):
    """[B, L, H, D] in/out; lse [B*H, 8, Lq] from the forward kernel."""
    b, lq, h, d = q.shape
    lk = k.shape[1]
    scale = float(scale if scale is not None else 1.0 / (d ** 0.5))
    qh = jnp.swapaxes(q, 1, 2).reshape(b * h, lq, d)
    kh = jnp.swapaxes(k, 1, 2).reshape(b * h, lk, d)
    vh = jnp.swapaxes(v, 1, 2).reshape(b * h, lk, d)
    oh = jnp.swapaxes(out, 1, 2).reshape(b * h, lq, d)
    doh = jnp.swapaxes(do, 1, 2).reshape(b * h, lq, d)
    # delta = rowsum(do ∘ o): one cheap elementwise pass, fused by XLA;
    # replicated over 8 sublanes to match the lse tiling
    delta = jnp.sum(doh.astype(jnp.float32) * oh.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[:, None, :], (b * h, 8, lq))
    # sweep-chosen defaults (v5e, L=2048): k blocks 1024 beat 512 by ~1.2%
    # MFU; 256 loses 16% and full-L k overflows VMEM (bench_sweep.jsonl)
    block_q = _pick_block(lq, 512, "q")
    block_k = _pick_block(lk, 1024, "k")

    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, block_q=block_q, causal=causal, scale=scale
        ),
        grid=(b * h, lk // block_k),
        in_specs=[
            pl.BlockSpec((1, lq, d), lambda bh, i: (bh, i * 0, i * 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, i: (bh, i, i * 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, i: (bh, i, i * 0)),
            pl.BlockSpec((1, lq, d), lambda bh, i: (bh, i * 0, i * 0)),
            pl.BlockSpec((1, 8, lq), lambda bh, i: (bh, i * 0, i * 0)),
            pl.BlockSpec((1, 8, lq), lambda bh, i: (bh, i * 0, i * 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bh, i: (bh, i, i * 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, i: (bh, i, i * 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, lk, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, lk, d), v.dtype),
        ],
        interpret=interpret,
    )(qh, kh, vh, doh, lse, delta)

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, block_k=block_k, causal=causal, scale=scale
        ),
        grid=(b * h, lq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, i * 0)),
            pl.BlockSpec((1, lk, d), lambda bh, i: (bh, i * 0, i * 0)),
            pl.BlockSpec((1, lk, d), lambda bh, i: (bh, i * 0, i * 0)),
            pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, i * 0)),
            pl.BlockSpec((1, 8, block_q), lambda bh, i: (bh, i * 0, i)),
            pl.BlockSpec((1, 8, block_q), lambda bh, i: (bh, i * 0, i)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, i * 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, lq, d), q.dtype),
        interpret=interpret,
    )(qh, kh, vh, doh, lse, delta)

    unflat = lambda x, l: jnp.swapaxes(x.reshape(b, h, l, d), 1, 2)
    return unflat(dq, lq), unflat(dk, lk), unflat(dv, lk)


# ------------------------------------------------------------------- blockwise (jnp)
def blockwise_attention(q, k, v, causal=False, scale=None, block_k=512,
                        q_offset=0, k_offset=0, carry_in=None,
                        return_carry=False, q_segments=None, k_segments=None):
    """Memory-efficient attention as a scan over k/v blocks ([B, L, H, D]).

    ``q_offset``/``k_offset`` shift query/key positions to their global indices
    (ring attention passes each rotating shard's offset); ``carry_in``/
    ``return_carry`` expose the online-softmax state (acc, m, l) so callers can
    stitch multiple k/v shards together.  ``q_segments``/``k_segments``
    ([B, Lq] / [B, Lk] int arrays) restrict attention to same-segment pairs —
    the varlen/packed-sequence masking (flash_attn_unpadded, padding masks):
    tokens never attend across segment boundaries, and rows whose segment id
    is negative (padding) produce zeros.
    """
    b, lq, h, d = q.shape
    lk = k.shape[1]
    scale = float(scale if scale is not None else 1.0 / (d ** 0.5))
    block_k = _pick_block(lk, block_k)
    nblocks = lk // block_k
    qt = jnp.swapaxes(q, 1, 2).astype(jnp.float32) * scale  # [B, H, Lq, D]
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    kb = kt.reshape(b, h, nblocks, block_k, d)
    vb = vt.reshape(b, h, nblocks, block_k, d)
    q_idx = q_offset + jnp.arange(lq)

    kseg_b = (None if k_segments is None
              else jnp.asarray(k_segments).reshape(b, nblocks, block_k))
    qseg = None if q_segments is None else jnp.asarray(q_segments)

    def step(carry, blk):
        acc, m, l = carry
        kblk, vblk, kb_idx, kseg = blk
        s = jnp.einsum(
            "bhqd,bhkd->bhqk", qt, kblk.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        if causal:
            k_idx = k_offset + kb_idx * block_k + jnp.arange(block_k)
            mask = q_idx[:, None] >= k_idx[None, :]
            s = jnp.where(mask[None, None], s, _NEG_INF)
        if kseg is not None:
            seg_mask = qseg[:, :, None] == kseg[:, None, :]  # [B, Lq, block_k]
            s = jnp.where(seg_mask[:, None], s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vblk.astype(jnp.float32)
        )
        return (acc_new, m_new, l_new), None

    if carry_in is None:
        carry = (
            jnp.zeros((b, h, lq, d), jnp.float32),
            jnp.full((b, h, lq), _NEG_INF, jnp.float32),
            jnp.zeros((b, h, lq), jnp.float32),
        )
    else:
        carry = carry_in
    blocks = (
        jnp.moveaxis(kb, 2, 0),  # [nblocks, B, H, block_k, D]
        jnp.moveaxis(vb, 2, 0),
        jnp.arange(nblocks),
        None if kseg_b is None else jnp.moveaxis(kseg_b, 1, 0),
    )
    carry, _ = jax.lax.scan(step, carry, blocks)
    if return_carry:
        return carry
    acc, m, l = carry
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    if qseg is not None:
        out = jnp.where((qseg >= 0)[:, None, :, None], out, 0.0)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


# --------------------------------------------------------------------- public entry
def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def available(q_shape) -> bool:
    """Whether the Pallas fast path handles this shape (else XLA composition)."""
    if len(q_shape) != 4:
        return False
    _, l, _, d = q_shape
    # lane dim wants 128-multiples; tiny shapes aren't worth a kernel launch
    return _on_tpu() and d in (64, 128, 256) and l >= 128 and l % 128 == 0


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention_blhd(q, k, v, causal=False, scale=None):
    """Flash attention, [batch, seq, heads, head_dim]."""
    return _flash_fwd_pallas(q, k, v, causal=causal, scale=scale)[0]


def _fa_fwd(q, k, v, causal, scale):
    out, lse = _flash_fwd_pallas(q, k, v, causal=causal, scale=scale)
    return out, (q, k, v, out, lse)


def _fa_bwd(causal, scale, res, g):
    q, k, v, out, lse = res
    return _flash_bwd_pallas(q, k, v, out, lse, g, causal=causal, scale=scale)


flash_attention_blhd.defvjp(_fa_fwd, _fa_bwd)
