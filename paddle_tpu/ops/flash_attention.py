"""Flash attention for TPU: GQA-native Pallas kernels + blockwise fallback.

Reference parity: python/paddle/nn/functional/flash_attention.py over
third_party/flashattn (CUDA), including its native num_heads_k != num_heads
(GQA/MQA) support.  TPU-native design:

* **Packed layout, zero layout churn.**  The kernels consume the projection
  outputs DIRECTLY: q ``[B, L, H*D]``, k/v ``[B, L, Hkv*D]``.  BlockSpec index
  maps slice heads out of the packed minor dimension — the
  ``[B,L,H,D] -> [B*H,L,D]`` swapaxes/reshape round-trip of the r3 kernels
  (a real HBM transpose on every call, VERDICT r3 weak #2) is gone entirely.
* **GQA-native grid.**  Grid is ``(batch, kv_head, block)``; one program
  holds the q block of ALL ``G = H/Hkv`` query heads sharing one kv head and
  streams that kv head's K/V once.  KV HBM traffic is 1/G of the r3 kernel,
  which materialized ``jnp.repeat``-ed K/V (VERDICT r3 missing #2).
* ``_fwd_kernel`` — online-softmax forward, fp32 accumulators, MXU-shaped
  ``[block_q*G, block_k]`` score tiles.
* ``_bwd_dkv_kernel`` / ``_bwd_dq_kernel`` — the standard two-pass flash
  backward consuming the forward's log-sum-exp rows; fp32 accumulation, no
  ``[Lq, Lk]`` tensor in HBM.
* ``blockwise_attention`` — same math as a ``lax.scan`` in pure jnp:
  differentiable on any backend, and the building block ring attention
  rotates over the mesh (ops/ring_attention.py).

Row packing: within a q block, rows are ordered position-major / head-minor
(row ``r`` = position ``r // G``, group head ``r % G``), which is exactly the
memory order of a ``[block_q, G*D]`` tile — the reshape inside the kernel is
free.  Log-sum-exp/delta rows are carried ``[B, Hkv, 8, Lq*G]``
sublane-replicated so the stats tensors tile legally on TPU.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30


def validate_gqa(h: int, hkv: int, name: str = "attention") -> int:
    """Shared GQA head-grouping contract check (one place; the grouping
    convention itself lives in ``repeat_kv``).  Returns the group size."""
    if hkv <= 0 or h % hkv:
        raise ValueError(
            f"{name}: query heads ({h}) must be an integer multiple of "
            f"kv heads ({hkv})")
    return h // hkv


def _reject_causal_lq_gt_lk(lq: int, lk: int, causal: bool, name: str):
    """Causal with Lq > Lk has rows with NO live keys under the bottom-right
    aligned mask; the finite -1e30 mask sentinel makes those rows degenerate
    to uniform attention and their lse poisons the backward.  Fail loudly —
    the dense fallback owns that shape (ADVICE r4 + review r5)."""
    if causal and lq > lk:
        raise ValueError(
            f"{name}: causal attention requires Lq <= Lk (got Lq={lq}, "
            f"Lk={lk}); rows before the cached prefix would have no live "
            "keys. Use the dense fallback for this shape.")


def signed_sin(sin):
    """Fold rot_half's sign into the sin table once: concat(-sin_half,
    sin_half).  THE one source of the sign convention — _rot_tile consumes
    its output; ops/fused_rope.py imports both so the standalone and
    in-kernel rotations cannot drift apart."""
    d2 = sin.shape[-1] // 2
    return jnp.concatenate([-sin[..., :d2], sin[..., d2:]], axis=-1)


def _rot_tile(x, c, s):
    """Half-split rotary rotation of a [rows, d] tile: x*c + swap(x)*s,
    swap = concat(x[d/2:], x[:d/2]); ``s`` is the SIGNED sin table
    (signed_sin) so the swap is a plain lane concat.  The inverse rotation
    is the same call with ``-s`` (R^T = R(-θ)) — shared with
    ops/fused_rope.py, here applied on tiles already resident in VMEM."""
    d2 = x.shape[-1] // 2
    swapped = jnp.concatenate([x[:, d2:], x[:, :d2]], axis=1)
    return x * c + swapped * s


# --------------------------------------------------------------------------- pallas fwd
def _fwd_kernel(*refs, block_k: int, causal: bool, scale: float, group: int,
                head_dim: int, q_offset: int, segmented: bool = False,
                hp: int = 1, rope: bool = False):
    """One (batch, kv-head-block, q-block) program: online softmax over k
    blocks, for ``hp`` kv heads per program (unrolled in-kernel loop).

    q_ref [1, block_q, hp*G*D] (the G query heads of each of this program's
    hp kv heads, packed); k_ref/v_ref [1, Lk, hp*D];
    o_ref [1, block_q, hp*G*D]; lse_ref [1, hp, 8, block_q*G] — log-sum-exp
    rows (position-major, group-head-minor), replicated across the 8
    sublanes so the stats tensor tiles legally on TPU; consumed by backward.

    ``hp`` > 1 exists for SMALL head_dims (BERT-shaped MHA, d=64): with one
    kv head per program, g*d = 64 is an illegal minor tile AND per-program
    work is so small that program launch overhead dominates (measured 8
    TF/s at B=64 L=512 H=12 D=64 — slower than XLA dense once the backward
    is included).  Packing hp kv heads per program makes the minor dim
    hp*g*d a 128-multiple and amortizes the launch cost, while still
    consuming the projection layout with zero transposes.

    ``segmented``: two extra i32 inputs qseg_ref [1, 1, 8, block_q*G] (row
    order) and kseg_ref [1, 1, 8, Lk]; attention is restricted to
    same-segment (q, k) pairs — the padding/varlen mask.  A live row whose
    leading k blocks are fully out-of-segment self-corrects: when its first
    live key arrives, alpha = exp(-1e30 - m_live) = 0 wipes the garbage
    acc/l.  Rows with NO live key anywhere (padding, qseg < 0) are zeroed by
    the caller; self-attention guarantees every non-padding row matches its
    own position.
    """
    q_ref, k_ref, v_ref = refs[:3]
    i = 3
    if rope:
        # rope tables (packed hp==1 path only): q tables blocked like q
        # ([1, block_q, G*D], g-tiled minor), k tables like k ([1, Lk, D]);
        # sin pre-signed by the wrapper
        qcos_ref, qsin_ref, kcos_ref, ksin_ref = refs[i:i + 4]
        i += 4
    if segmented:
        qseg_ref, kseg_ref = refs[i:i + 2]
        i += 2
    o_ref, lse_ref = refs[i:i + 2]
    # 4-D refs = head-major bhld layout ([1, hp, L, D]); 3-D = packed
    block_q = q_ref.shape[2] if q_ref.ndim == 4 else q_ref.shape[1]
    rows = block_q * group
    lk = k_ref.shape[2] if k_ref.ndim == 4 else k_ref.shape[1]
    num_k_blocks = lk // block_k
    qi = pl.program_id(2)
    gd = group * head_dim

    qseg = qseg_ref[0, 0, 0] if segmented else None  # [rows] i32
    # hp > 1 refs are HEAD-MAJOR 4-D ([1, hp, L, D]): per-head tiles are
    # [L, D] with d the full minor dim — lane-aligned at any d.  (Lane
    # slices at j*d offsets inside a packed [L, hp*d] block measured 2x
    # slower: 64-lane slices off 128-alignment force Mosaic shuffles.)
    bhld = q_ref.ndim == 4

    for j in range(hp):
        if bhld:
            q = q_ref[0, j]  # [block_q, D] (g == 1 when hp > 1)
        else:
            # [block_q, G*D] -> [block_q*G, D]: contiguous, free
            q = q_ref[0, :, j * gd:(j + 1) * gd].reshape(rows, head_dim)
        if rope:
            q = _rot_tile(q, _rope_q_tile(qcos_ref, block_q, group, head_dim),
                          _rope_q_tile(qsin_ref, block_q, group, head_dim))

        def make_body(masked, q=q, j=j):
            def body(kb, carry):
                acc, m, l = carry
                if bhld:
                    k = k_ref[0, j, pl.ds(kb * block_k, block_k), :]
                    v = v_ref[0, j, pl.ds(kb * block_k, block_k), :]
                else:
                    k = k_ref[0, pl.ds(kb * block_k, block_k),
                              j * head_dim:(j + 1) * head_dim]  # [block_k, D]
                    v = v_ref[0, pl.ds(kb * block_k, block_k),
                              j * head_dim:(j + 1) * head_dim]
                if rope:
                    k = _rot_tile(
                        k, kcos_ref[0, pl.ds(kb * block_k, block_k), :],
                        ksin_ref[0, pl.ds(kb * block_k, block_k), :])
                s = jax.lax.dot_general(
                    q, k, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32
                ) * scale  # [rows, block_k] fp32
                if segmented:
                    kseg = kseg_ref[0, 0, 0, pl.ds(kb * block_k, block_k)]
                    s = jnp.where(qseg[:, None] == kseg[None, :], s,
                                  jnp.float32(_NEG_INF))
                if masked:
                    # row r is query position q_offset + qi*block_q + r//G —
                    # the offset (Lk-Lq) bottom-right-aligns the mask for
                    # cached/chunked prefill, matching the dense fallback's
                    # tril(kl-ql).  Position index built as a 3D iota
                    # reshaped (pos-major, head-minor) — integer division on
                    # i32 promotes to i64 under x64 and recurses Mosaic's
                    # convert lowering.
                    q_idx = q_offset + qi * block_q + jax.lax.broadcasted_iota(
                        jnp.int32, (block_q, group, block_k), 0
                    ).reshape(rows, block_k)
                    k_idx = kb * block_k + jax.lax.broadcasted_iota(
                        jnp.int32, (rows, block_k), 1
                    )
                    s = jnp.where(q_idx >= k_idx, s, jnp.float32(_NEG_INF))
                m_new = jnp.maximum(m, jnp.max(s, axis=-1))
                p = jnp.exp(s - m_new[:, None])
                alpha = jnp.exp(m - m_new)
                l_new = l * alpha + jnp.sum(p, axis=-1)
                acc_new = acc * alpha[:, None] + jax.lax.dot_general(
                    p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                return acc_new, m_new, l_new
            return body

        init = (
            jnp.zeros((rows, head_dim), jnp.float32),
            jnp.full((rows,), _NEG_INF, jnp.float32),
            jnp.zeros((rows,), jnp.float32),
        )
        if causal:
            # two-phase causal sweep (the r4 profile put the flash kernels
            # at 490ms of an 1830ms step with half their tiles fully
            # masked):
            #   [0, lo)  — k blocks fully BELOW the diagonal: no mask compute
            #   [lo, hi) — the diagonal band: masked
            #   [hi, ..) — fully above: skipped entirely
            # All-i32 dynamic fori bounds (a bare python int would promote
            # to i64 under x64 and recurse Mosaic's lowering).  Bounds clamp
            # to >= 0 as pure defense: with Lq > Lk the q_offset is negative
            # and floor division would otherwise produce negative k-block
            # indices whose clamped dynamic slices re-read block 0 (ADVICE
            # r4).  The shape itself is rejected at the entry points (dead
            # rows are NOT well-defined here: masked scores equal the finite
            # m init, so a dead row in a live block degenerates to uniform
            # attention).
            q_min = jnp.int32(q_offset) + qi * jnp.int32(block_q)
            lo = jnp.maximum(q_min // jnp.int32(block_k), jnp.int32(0))
            hi = jnp.maximum(
                (q_min + jnp.int32(block_q + block_k - 1))
                // jnp.int32(block_k), jnp.int32(0))
            carry = jax.lax.fori_loop(jnp.int32(0), lo, make_body(False),
                                      init)
            acc, m, l = jax.lax.fori_loop(lo, hi, make_body(True), carry)
        else:
            acc, m, l = jax.lax.fori_loop(
                jnp.int32(0), jnp.int32(num_k_blocks), make_body(False),
                init, unroll=num_k_blocks <= 8)
        l_safe = jnp.maximum(l, 1e-30)
        if bhld:
            o_ref[0, j] = (acc / l_safe[:, None]).astype(o_ref.dtype)
        else:
            o_ref[0, :, j * gd:(j + 1) * gd] = (
                acc / l_safe[:, None]).reshape(block_q, gd
                                               ).astype(o_ref.dtype)
        lse_ref[0, j] = jnp.broadcast_to(m + jnp.log(l_safe), (8, rows))


# ------------------------------------------------------------- streamed fwd
# Long-context variants: the resident kernels above hold the FULL K/V in
# VMEM per program (fast at 2k: one HBM fetch per q-block program), which
# overflows the 16MB scoped budget past ~12k tokens at d=128.  The streamed
# kernels move the k loop into the innermost GRID dimension: k/v arrive as
# [block_k] tiles, the online-softmax state lives in VMEM scratch across
# the k sweep (q/o blocks have k-independent index maps, so they stay
# resident), and outputs are written on the last k step.  Same math, same
# lse layout — the backward's dkv kernel already streams and works at any
# L.  hp == 1 only (the long-context target is the GQA d=128 family).


def _fwd_kernel_streamed(*refs, causal: bool, scale: float, group: int,
                         head_dim: int, q_offset: int,
                         segmented: bool = False):
    """Grid (b, kv_head, q_block, k_block); scratch carries (acc, m, l)."""
    if segmented:
        (q_ref, k_ref, v_ref, qseg_ref, kseg_ref, o_ref, lse_ref,
         acc_ref, m_ref, l_ref) = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref = refs
    block_q = q_ref.shape[1]
    rows = block_q * group
    block_k = k_ref.shape[1]
    qi = pl.program_id(2)
    kb = pl.program_id(3)
    nkb = pl.num_programs(3)

    @pl.when(kb == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    if causal:
        # block classes relative to the bottom-right-aligned diagonal
        live = (qi + 1) * block_q + q_offset > kb * block_k
        full = q_offset + qi * block_q >= (kb + 1) * block_k
    else:
        live, full = True, True

    def compute(masked):
        q = q_ref[0].reshape(rows, head_dim)
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if segmented:
            qseg = qseg_ref[0, 0, 0]
            kseg = kseg_ref[0, 0, 0]
            s = jnp.where(qseg[:, None] == kseg[None, :], s,
                          jnp.float32(_NEG_INF))
        if masked:
            q_idx = q_offset + qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, group, block_k), 0
            ).reshape(rows, block_k)
            k_idx = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (rows, block_k), 1)
            s = jnp.where(q_idx >= k_idx, s, jnp.float32(_NEG_INF))
        m = m_ref[0]  # [rows] row 0 of the (8, rows) sublane-replicated state
        l = l_ref[0]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_ref[...] = acc_ref[...] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    if causal:
        @pl.when(full)
        def _full():
            compute(False)

        @pl.when(live & jnp.logical_not(full))
        def _band():
            compute(True)
    else:
        compute(False)

    @pl.when(kb == nkb - 1)
    def _fin():
        l_safe = jnp.maximum(l_ref[0], 1e-30)
        o_ref[0] = (acc_ref[...] / l_safe[:, None]).reshape(
            block_q, group * head_dim).astype(o_ref.dtype)
        lse_ref[0, 0] = jnp.broadcast_to(
            m_ref[0] + jnp.log(l_safe), (8, rows))


def _bwd_dq_kernel_streamed(*refs, causal: bool, scale: float, group: int,
                            head_dim: int, q_offset: int,
                            segmented: bool = False):
    """Grid (b, kv_head, q_block, k_block); dq accumulates in scratch."""
    if segmented:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qseg_ref,
         kseg_ref, dq_ref, dqacc_ref) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
         dqacc_ref) = refs
    block_q = q_ref.shape[1]
    rows = block_q * group
    block_k = k_ref.shape[1]
    qi = pl.program_id(2)
    kb = pl.program_id(3)
    nkb = pl.num_programs(3)

    @pl.when(kb == 0)
    def _init():
        dqacc_ref[...] = jnp.zeros_like(dqacc_ref)

    if causal:
        live = (qi + 1) * block_q + q_offset > kb * block_k
        full = q_offset + qi * block_q >= (kb + 1) * block_k
    else:
        live, full = True, True

    def compute(masked):
        q = q_ref[0].reshape(rows, head_dim)
        do = do_ref[0].reshape(rows, head_dim)
        lse = lse_ref[0, 0, 0]
        delta = delta_ref[0, 0, 0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if segmented:
            qseg = qseg_ref[0, 0, 0]
            kseg = kseg_ref[0, 0, 0]
            s = jnp.where(qseg[:, None] == kseg[None, :], s,
                          jnp.float32(_NEG_INF))
        if masked:
            q_idx = q_offset + qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, group, block_k), 0
            ).reshape(rows, block_k)
            k_idx = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (rows, block_k), 1)
            s = jnp.where(q_idx >= k_idx, s, jnp.float32(_NEG_INF))
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        dqacc_ref[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        @pl.when(full)
        def _full():
            compute(False)

        @pl.when(live & jnp.logical_not(full))
        def _band():
            compute(True)
    else:
        compute(False)

    @pl.when(kb == nkb - 1)
    def _fin():
        dq_ref[0] = dqacc_ref[...].reshape(
            block_q, group * head_dim).astype(dq_ref.dtype)


def _stream_kv(lk: int, hp: int, d: int) -> bool:
    """True when full-K/V VMEM residency would blow the scoped budget: the
    resident kernels hold k+v (double-buffered) = 8*lk*hp*d bytes; past
    ~12MB the streamed grid variants take over (measured: 16k at d=128
    fails at 17.1M against the 16M limit)."""
    return 8 * lk * hp * d > 12 * 1024 * 1024


def _pick_block(n: int, preferred: int, kind: str = "") -> int:
    """Largest power-of-two-ish divisor of ``n`` at most ``preferred``.

    When ``kind`` is given ("q"/"k"), PADDLE_TPU_FLASH_BLOCK[_Q|_K] overrides
    ``preferred`` for perf sweeps (bench_sweep.jsonl).  NOTE: the enclosing
    kernels are jax.jit'd, so the env is read at TRACE time — sweep in
    separate processes (as bench_sweep does), not by mutating os.environ
    between calls.  Callers passing explicit blocking (kind="") are never
    overridden."""
    if kind:
        import os
        import warnings

        env = (os.environ.get(f"PADDLE_TPU_FLASH_BLOCK_{kind.upper()}")
               or os.environ.get("PADDLE_TPU_FLASH_BLOCK"))
        if env:
            try:
                v = int(env)
            except ValueError:
                v = 0
            if v >= 8:
                preferred = v
            else:
                warnings.warn(
                    f"ignoring invalid flash block override {env!r} "
                    "(need an integer >= 8)", stacklevel=2)
    b = min(preferred, n)
    while n % b:
        b //= 2
    b = max(b, 1)
    if kind and b != min(preferred, n):
        import warnings

        warnings.warn(
            f"flash block_{kind} {preferred} does not divide L={n}; "
            f"using {b}", stacklevel=2)
    return b


def _row_blocks(lq: int, group: int, target: int = 1024):
    """block_q for a G-grouped kernel.  r4 full-bench sweep (v5e, GQA4
    B16 L2048 D128, causal block-skip kernels): q256/k512 is the optimum —
    MFU 0.570 vs 0.549 @ q64-128/k1024, 0.554 @ q64/k512, 0.540 @ q512/k256
    (q >= 512 with k512 overflows the 16M scoped vmem).  Expressed as a
    1024-row target with block_q capped at 256; block_k default 512 at the
    call sites."""
    block_q = _pick_block(lq, max(8, min(256, target // group)), "q")
    return block_q


def _heads_per_program(hkv: int, g: int, d: int, lk: int) -> int:
    """kv heads per kernel program.  1 when the single-head minor dim g*d is
    already a legal (128-multiple) tile — the GQA/llama case, packed
    layout.  For small head dims (BERT-shaped MHA, d=64) any hp > 1
    switches the wrappers to the HEAD-MAJOR [B, H, L, D] layout, where each
    per-head tile is [L, D] with d the full minor dim — legal at any hp, so
    the divisor search below only has to respect the vmem budget for the
    resident k+v blocks; the unrolled in-kernel head loop amortizes program
    launch overhead (the per-head fold measured slower than XLA dense on
    the backward).  Returns 0 when no packing fits (callers fall back to
    the XLA path)."""
    if (g * d) % 128 == 0:
        return 1
    if g != 1:
        return 0  # GQA with a sub-128 minor: no head-major packing either
    import os

    env = os.environ.get("PADDLE_TPU_FLASH_HP")  # perf-sweep override
    if env:
        try:
            v = int(env)
        except ValueError:
            v = 0
        # v >= 2 only: hp == 1 would select the packed layout whose
        # sub-128 minor tile is exactly what this path exists to avoid.
        # The vmem budget still applies — an oversized override would
        # abort the sweep with a Mosaic OOM instead of recording a point.
        if (v >= 2 and hkv % v == 0
                and 2 * lk * v * d * 2 <= 4 * 1024 * 1024):
            return v
    for hp in range(hkv, 1, -1):
        if hkv % hp:
            continue
        if 2 * lk * hp * d * 2 <= 4 * 1024 * 1024:  # k+v bf16 <= 4MB
            return hp
    return 0


def _seg_rows(segments, g):
    """[B, L] i32 segment ids -> [B, 1, 8, L*G] in the kernels' row order
    (position-major, group-head-minor), sublane-replicated for TPU tiling."""
    s = jnp.asarray(segments, jnp.int32)
    if g > 1:
        s = jnp.repeat(s, g, axis=1)
    return jnp.broadcast_to(s[:, None, None, :],
                            (s.shape[0], 1, 8, s.shape[1]))


@functools.partial(
    jax.jit, static_argnames=("num_heads", "num_kv_heads", "causal", "scale",
                              "interpret"))
def _flash_fwd_pallas(q, k, v, num_heads, num_kv_heads, causal=False,
                      scale=None, interpret=False, q_segments=None,
                      k_segments=None, rope_tables=None):
    """q [B, Lq, H*D], k/v [B, Lk, Hkv*D] — the projection layout, consumed
    without any transpose.  Returns (out [B, Lq, H*D],
    lse [B, Hkv, 8, Lq*G]).  Optional q_segments/k_segments [B, Lq]/[B, Lk]
    i32 restrict attention to same-segment pairs (padding/varlen); rows with
    a negative segment id are zeroed.  ``rope_tables`` = (qcos, qsin, kcos,
    ksin) pre-tiled signed tables (flash_attention_packed_rope): q/k rotate
    IN-KERNEL on tiles already in VMEM — the standalone rope pass and its
    HBM round-trip disappear.  Resident packed (hp==1) path only."""
    b, lq, hd_packed = q.shape
    lk = k.shape[1]
    _reject_causal_lq_gt_lk(lq, lk, causal, "flash_attention")
    d = hd_packed // num_heads
    g = validate_gqa(num_heads, num_kv_heads, "flash_attention")
    scale = float(scale if scale is not None else 1.0 / (d ** 0.5))
    block_q = _row_blocks(lq, g)
    block_k = _pick_block(lk, 512, "k")
    hp = _heads_per_program(num_kv_heads, g, d, lk)
    if hp == 0:
        raise ValueError(
            f"flash_attention: no legal TPU tiling for head_dim={d}, "
            f"kv_heads={num_kv_heads} (minor dim not a 128-multiple); "
            "use blockwise_attention or the dense path")
    rope = rope_tables is not None
    if rope and (hp != 1 or _stream_kv(lk, hp, d)):
        raise ValueError(
            "rope_tables: in-kernel rotation is only wired for the resident "
            "packed (hp==1) kernels — gate with rope_fusable()")
    segmented = q_segments is not None
    if hp == 1 and _stream_kv(lk, hp, d):
        # long-context: stream k/v via the grid (full residency would blow
        # scoped vmem); scratch carries the online-softmax state
        from jax.experimental.pallas import tpu as pltpu

        rows = block_q * g
        in_specs = [
            pl.BlockSpec((1, block_q, g * d),
                         lambda bi, ci, i, kb: (bi, i, ci)),
            pl.BlockSpec((1, block_k, d), lambda bi, ci, i, kb: (bi, kb, ci)),
            pl.BlockSpec((1, block_k, d), lambda bi, ci, i, kb: (bi, kb, ci)),
        ]
        args = [q, k, v]
        if segmented:
            in_specs += [
                pl.BlockSpec((1, 1, 8, block_q * g),
                             lambda bi, ci, i, kb: (bi, i * 0, i * 0, i)),
                pl.BlockSpec((1, 1, 8, block_k),
                             lambda bi, ci, i, kb: (bi, i * 0, i * 0, kb)),
            ]
            args += [_seg_rows(q_segments, g), _seg_rows(k_segments, 1)]
        out, lse = pl.pallas_call(
            functools.partial(
                _fwd_kernel_streamed, causal=causal, scale=scale, group=g,
                head_dim=d, q_offset=lk - lq, segmented=segmented),
            grid=(b, num_kv_heads, lq // block_q, lk // block_k),
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((1, block_q, g * d),
                             lambda bi, ci, i, kb: (bi, i, ci)),
                pl.BlockSpec((1, 1, 8, block_q * g),
                             lambda bi, ci, i, kb: (bi, ci, i * 0, i)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((b, lq, num_heads * d), q.dtype),
                jax.ShapeDtypeStruct((b, num_kv_heads, 8, lq * g),
                                     jnp.float32),
            ],
            scratch_shapes=[
                pltpu.VMEM((rows, d), jnp.float32),
                pltpu.VMEM((8, rows), jnp.float32),
                pltpu.VMEM((8, rows), jnp.float32),
            ],
            interpret=interpret,
        )(*args)
        if segmented:
            out = jnp.where(
                (jnp.asarray(q_segments, jnp.int32) >= 0)[:, :, None],
                out, 0)
        return out, lse
    grid = (b, num_kv_heads // hp, lq // block_q)
    bhld = hp > 1
    # index maps use `i * 0` (not the literal 0) so the constant inherits the
    # i32 index dtype — a literal traces as i64 under jax_enable_x64 and
    # Mosaic rejects the mixed-width index tuple
    if bhld:
        # head-major layout for multi-head programs (g == 1): per-head
        # tiles [L, D] keep d the full minor dim — lane-aligned at any d
        args = [
            jnp.swapaxes(q.reshape(b, lq, num_heads, d), 1, 2),
            jnp.swapaxes(k.reshape(b, lk, num_kv_heads, d), 1, 2),
            jnp.swapaxes(v.reshape(b, lk, num_kv_heads, d), 1, 2),
        ]
        in_specs = [
            pl.BlockSpec((1, hp, block_q, d),
                         lambda bi, ci, i: (bi, ci, i, i * 0)),
            pl.BlockSpec((1, hp, lk, d),
                         lambda bi, ci, i: (bi, ci, i * 0, i * 0)),
            pl.BlockSpec((1, hp, lk, d),
                         lambda bi, ci, i: (bi, ci, i * 0, i * 0)),
        ]
        out_spec0 = pl.BlockSpec((1, hp, block_q, d),
                                 lambda bi, ci, i: (bi, ci, i, i * 0))
        out_shape0 = jax.ShapeDtypeStruct((b, num_heads, lq, d), q.dtype)
    else:
        args = [q, k, v]
        in_specs = [
            pl.BlockSpec((1, block_q, hp * g * d),
                         lambda bi, ci, i: (bi, i, ci)),
            pl.BlockSpec((1, lk, hp * d), lambda bi, ci, i: (bi, i * 0, ci)),
            pl.BlockSpec((1, lk, hp * d), lambda bi, ci, i: (bi, i * 0, ci)),
        ]
        out_spec0 = pl.BlockSpec((1, block_q, hp * g * d),
                                 lambda bi, ci, i: (bi, i, ci))
        out_shape0 = jax.ShapeDtypeStruct((b, lq, num_heads * d), q.dtype)
    if rope:
        in_specs += [
            pl.BlockSpec((1, block_q, d),
                         lambda bi, ci, i: (i * 0, i, i * 0)),
            pl.BlockSpec((1, block_q, d),
                         lambda bi, ci, i: (i * 0, i, i * 0)),
            pl.BlockSpec((1, lk, d), lambda bi, ci, i: (i * 0, i * 0, i * 0)),
            pl.BlockSpec((1, lk, d), lambda bi, ci, i: (i * 0, i * 0, i * 0)),
        ]
        args += list(rope_tables)
    if segmented:
        in_specs += [
            pl.BlockSpec((1, 1, 8, block_q * g),
                         lambda bi, ci, i: (bi, i * 0, i * 0, i)),
            pl.BlockSpec((1, 1, 8, lk),
                         lambda bi, ci, i: (bi, i * 0, i * 0, i * 0)),
        ]
        args += [_seg_rows(q_segments, g), _seg_rows(k_segments, 1)]
    out, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel, block_k=block_k, causal=causal, scale=scale,
            group=g, head_dim=d, q_offset=lk - lq, segmented=segmented,
            hp=hp, rope=rope,
        ),
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            out_spec0,
            pl.BlockSpec((1, hp, 8, block_q * g),
                         lambda bi, ci, i: (bi, ci, i * 0, i)),
        ],
        out_shape=[
            out_shape0,
            jax.ShapeDtypeStruct((b, num_kv_heads, 8, lq * g), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    if bhld:
        out = jnp.swapaxes(out, 1, 2).reshape(b, lq, num_heads * d)
    if segmented:
        # padding rows (negative segment id) emit zeros — the q_segments
        # convention shared with blockwise_attention
        out = jnp.where(
            (jnp.asarray(q_segments, jnp.int32) >= 0)[:, :, None], out, 0)
    return out, lse


# --------------------------------------------------------------------------- pallas bwd
# Standard flash-attention backward (the public two-pass formulation): with the
# forward's log-sum-exp rows the softmax is reconstructed per tile as
# p = exp(s - lse), then
#   dv = pᵀ·do,  dp = do·vᵀ,  ds = p ∘ (dp - delta) · scale,
#   dk = dsᵀ·q,  dq = Σ ds·k,      delta = rowsum(do ∘ o).
# Pass 1 (grid over k blocks) accumulates dk/dv with q/do streamed; pass 2
# (grid over q blocks) accumulates dq with k/v streamed.  All accumulation in
# fp32; no [Lq, Lk] tensor ever hits HBM.  dk/dv for one kv head gather the
# contributions of its G query heads inside one program — no repeat, no
# cross-program reduction.


def _delta_kernel(do_ref, o_ref, delta_ref, *, group: int, head_dim: int):
    """delta block for one (batch, kv-head, q-block) program: the packed
    [1, bl, G*D] do/o tiles reduce over d into [1, 1, 8, bl*G]
    sublane-replicated rows — the exact operand layout of the bwd kernels."""
    bl = do_ref.shape[1]
    rows = bl * group
    x = do_ref[0].astype(jnp.float32).reshape(rows, head_dim)
    y = o_ref[0].astype(jnp.float32).reshape(rows, head_dim)
    s = jnp.sum(x * y, axis=1)
    delta_ref[0, 0] = jnp.broadcast_to(s[None, :], (8, rows))


def _delta_pallas(do, out, num_kv_heads, g, d, interpret=False):
    """rowsum(do ∘ o) per (position, head) in the bwd kernels' consumer
    layout [B, Hkv, 8, Lq*G] f32 (sublane-replicated like lse)."""
    b, lq, _ = do.shape
    bl = _row_blocks(lq, g)
    if (bl * g) % 128:
        bl = lq  # full-dim minor block: legal at any size
    return pl.pallas_call(
        functools.partial(_delta_kernel, group=g, head_dim=d),
        grid=(b, num_kv_heads, lq // bl),
        in_specs=[
            pl.BlockSpec((1, bl, g * d), lambda bi, ci, i: (bi, i, ci)),
            pl.BlockSpec((1, bl, g * d), lambda bi, ci, i: (bi, i, ci)),
        ],
        out_specs=pl.BlockSpec((1, 1, 8, bl * g),
                               lambda bi, ci, i: (bi, ci, i * 0, i)),
        out_shape=jax.ShapeDtypeStruct(
            (b, num_kv_heads, 8, lq * g), jnp.float32),
        interpret=interpret,
    )(do, out)


def _bwd_dkv_kernel(*refs, causal: bool, scale: float, group: int,
                    head_dim: int, q_offset: int, segmented: bool = False,
                    hp: int = 1, rope: bool = False):
    """One (batch, kv-head-block, k-block, q-block) program: this q block's
    contribution to dk/dv of this k block, for hp kv heads (unrolled loop —
    see _fwd_kernel).

    q blocks are streamed by the GRID's innermost dim (not an in-kernel loop
    over a resident full-Lq block — 2 x 2MB x double-buffering of q/do blew
    the 16M scoped-vmem budget inside the full train step); the dk/dv output
    blocks have q-independent index maps, so Pallas keeps them resident in
    VMEM across the q sweep and writes back once (fp32, cast by the caller).

    q_ref/do_ref [1, block_q, hp*G*D]; k_ref/v_ref [1, block_k, hp*D];
    lse_ref/delta_ref [1, hp, 8, block_q*G]; dk_ref/dv_ref
    [1, block_k, hp*D] f32.  ``segmented`` adds qseg_ref
    [1, 1, 8, block_q*G] / kseg_ref [1, 1, 8, block_k] after delta_ref; the
    caller zeroes padding rows of ``do`` so dead-row lse garbage cannot
    contaminate dk/dv.
    """
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref = refs[:6]
    i = 6
    if rope:
        qcos_ref, qsin_ref, kcos_ref, ksin_ref = refs[i:i + 4]
        i += 4
    if segmented:
        qseg_ref, kseg_ref = refs[i:i + 2]
        i += 2
    dk_ref, dv_ref = refs[i:i + 2]
    block_k = k_ref.shape[2] if k_ref.ndim == 4 else k_ref.shape[1]
    block_q = q_ref.shape[2] if q_ref.ndim == 4 else q_ref.shape[1]
    rows = block_q * group
    gd = group * head_dim
    ki = pl.program_id(2)
    qb = pl.program_id(3)

    @pl.when(qb == 0)
    def _init():
        dk_ref[...] = jnp.zeros_like(dk_ref)
        dv_ref[...] = jnp.zeros_like(dv_ref)

    # causal tile classes (real scf.if on the scalar core, unlike lax.cond's
    # predication): fully above the diagonal -> skip all compute; fully
    # below -> compute without the mask (saves the iota/compare VPU work);
    # diagonal band -> masked compute.
    if causal:
        live = (qb + 1) * block_q + q_offset > ki * block_k
        full = q_offset + qb * block_q >= (ki + 1) * block_k
    else:
        live, full = True, True

    bhld = q_ref.ndim == 4  # head-major multi-head layout (see _fwd_kernel)

    def compute(masked):
        for j in range(hp):
            ds_ = slice(j * head_dim, (j + 1) * head_dim)
            gs = slice(j * gd, (j + 1) * gd)
            if bhld:
                k = k_ref[0, j]  # [block_k, D]
                v = v_ref[0, j]
                q = q_ref[0, j]  # [block_q, D] (g == 1)
                do = do_ref[0, j]
            else:
                k = k_ref[0, :, ds_]  # [block_k, D]
                v = v_ref[0, :, ds_]
                q = q_ref[0, :, gs].reshape(rows, head_dim)
                do = do_ref[0, :, gs].reshape(rows, head_dim)
            if rope:
                # recompute rotated q/k from the raw residuals (hp == 1)
                q = _rot_tile(
                    q, _rope_q_tile(qcos_ref, block_q, group, head_dim),
                    _rope_q_tile(qsin_ref, block_q, group, head_dim))
                k = _rot_tile(k, kcos_ref[0], ksin_ref[0])
            lse = lse_ref[0, j, 0]                         # [rows]
            delta = delta_ref[0, j, 0]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32
            ) * scale                                      # [rows, block_k]
            if segmented:
                qseg = qseg_ref[0, 0, 0]                   # [rows]
                kseg = kseg_ref[0, 0, 0]                   # [block_k]
                s = jnp.where(qseg[:, None] == kseg[None, :], s,
                              jnp.float32(_NEG_INF))
            if masked:
                q_idx = q_offset + qb * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, group, block_k), 0
                ).reshape(rows, block_k)
                k_idx = ki * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (rows, block_k), 1
                )
                s = jnp.where(q_idx >= k_idx, s, jnp.float32(_NEG_INF))
            p = jnp.exp(s - lse[:, None])                  # [rows, block_k]
            dv_upd = jax.lax.dot_general(
                p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            dp = jax.lax.dot_general(
                do, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )                                              # [rows, block_k]
            ds = p * (dp - delta[:, None]) * scale
            dk_upd = jax.lax.dot_general(
                ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            if bhld:
                dv_ref[0, j] += dv_upd
                dk_ref[0, j] += dk_upd
            else:
                dv_ref[0, :, ds_] += dv_upd
                dk_ref[0, :, ds_] += dk_upd

    if causal:
        @pl.when(full)
        def _full():
            compute(False)

        @pl.when(live & jnp.logical_not(full))
        def _diag():
            compute(True)
    else:
        compute(False)

    if rope:
        # dk accumulated in ROTATED space across the q sweep; the raw-space
        # cotangent is R^T dk̂ = rotation with -sin, applied once at the
        # final q step on the resident f32 accumulator
        @pl.when(qb == pl.num_programs(3) - 1)
        def _unrotate_dk():
            dk_ref[0] = _rot_tile(
                dk_ref[0], kcos_ref[0].astype(jnp.float32),
                -ksin_ref[0].astype(jnp.float32))


def _bwd_dq_kernel(*refs, block_k: int, causal: bool, scale: float,
                   group: int, head_dim: int, q_offset: int,
                   segmented: bool = False, hp: int = 1, rope: bool = False):
    """One (batch, kv-head-block, q-block) program: dq for this q block,
    for hp kv heads (unrolled loop — see _fwd_kernel).

    q_ref/do_ref/dq_ref [1, block_q, hp*G*D]; k_ref/v_ref [1, Lk, hp*D];
    lse_ref/delta_ref [1, hp, 8, block_q*G].  ``segmented`` adds qseg_ref
    [1, 1, 8, block_q*G] / kseg_ref [1, 1, 8, Lk] after delta_ref.
    """
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref = refs[:6]
    i = 6
    if rope:
        qcos_ref, qsin_ref, kcos_ref, ksin_ref = refs[i:i + 4]
        i += 4
    if segmented:
        qseg_ref, kseg_ref = refs[i:i + 2]
        i += 2
    dq_ref = refs[i]
    block_q = q_ref.shape[2] if q_ref.ndim == 4 else q_ref.shape[1]
    rows = block_q * group
    gd = group * head_dim
    lk = k_ref.shape[2] if k_ref.ndim == 4 else k_ref.shape[1]
    num_k_blocks = lk // block_k
    qi = pl.program_id(2)
    qseg = qseg_ref[0, 0, 0] if segmented else None
    bhld = q_ref.ndim == 4  # head-major multi-head layout (see _fwd_kernel)

    for j in range(hp):
        gs = slice(j * gd, (j + 1) * gd)
        ds_ = slice(j * head_dim, (j + 1) * head_dim)
        if bhld:
            q = q_ref[0, j]
            do = do_ref[0, j]
        else:
            q = q_ref[0, :, gs].reshape(rows, head_dim)
            do = do_ref[0, :, gs].reshape(rows, head_dim)
        if rope:
            q = _rot_tile(q, _rope_q_tile(qcos_ref, block_q, group, head_dim),
                          _rope_q_tile(qsin_ref, block_q, group, head_dim))
        lse = lse_ref[0, j, 0]
        delta = delta_ref[0, j, 0]

        def make_body(masked, q=q, do=do, lse=lse, delta=delta, ds_=ds_,
                      j=j):
            def body(kb, dq):
                if bhld:
                    k = k_ref[0, j, pl.ds(kb * block_k, block_k), :]
                    v = v_ref[0, j, pl.ds(kb * block_k, block_k), :]
                else:
                    k = k_ref[0, pl.ds(kb * block_k, block_k), ds_]
                    v = v_ref[0, pl.ds(kb * block_k, block_k), ds_]
                if rope:
                    k = _rot_tile(
                        k, kcos_ref[0, pl.ds(kb * block_k, block_k), :],
                        ksin_ref[0, pl.ds(kb * block_k, block_k), :])
                s = jax.lax.dot_general(
                    q, k, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32
                ) * scale
                if segmented:
                    kseg = kseg_ref[0, 0, 0, pl.ds(kb * block_k, block_k)]
                    s = jnp.where(qseg[:, None] == kseg[None, :], s,
                                  jnp.float32(_NEG_INF))
                if masked:
                    q_idx = (q_offset + qi * block_q
                             + jax.lax.broadcasted_iota(
                                 jnp.int32, (block_q, group, block_k), 0
                             ).reshape(rows, block_k))
                    k_idx = kb * block_k + jax.lax.broadcasted_iota(
                        jnp.int32, (rows, block_k), 1
                    )
                    s = jnp.where(q_idx >= k_idx, s, jnp.float32(_NEG_INF))
                p = jnp.exp(s - lse[:, None])
                dp = jax.lax.dot_general(
                    do, v, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32
                )
                ds = p * (dp - delta[:, None]) * scale
                return dq + jax.lax.dot_general(
                    ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
            return body

        dq0 = jnp.zeros((rows, head_dim), jnp.float32)
        if causal:
            # two-phase: mask-free full blocks, masked diagonal band, skip
            # the rest (all-i32 dynamic bounds, clamped >= 0 — see
            # _fwd_kernel)
            q_min = jnp.int32(q_offset) + qi * jnp.int32(block_q)
            lo = jnp.maximum(q_min // jnp.int32(block_k), jnp.int32(0))
            hi = jnp.maximum(
                (q_min + jnp.int32(block_q + block_k - 1))
                // jnp.int32(block_k), jnp.int32(0))
            dq = jax.lax.fori_loop(jnp.int32(0), lo, make_body(False), dq0)
            dq = jax.lax.fori_loop(lo, hi, make_body(True), dq)
        else:
            dq = jax.lax.fori_loop(jnp.int32(0), jnp.int32(num_k_blocks),
                                   make_body(False), dq0,
                                   unroll=num_k_blocks <= 8)
        if rope:
            # dq accumulated in rotated space; raw-space cotangent = R^T dq̂
            dq = _rot_tile(
                dq,
                _rope_q_tile(qcos_ref, block_q, group,
                             head_dim).astype(jnp.float32),
                -_rope_q_tile(qsin_ref, block_q, group,
                              head_dim).astype(jnp.float32))
        if bhld:
            dq_ref[0, j] = dq.astype(dq_ref.dtype)
        else:
            dq_ref[0, :, gs] = dq.reshape(block_q, gd).astype(dq_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("num_heads", "num_kv_heads", "causal", "scale",
                              "interpret"))
def _flash_bwd_pallas(q, k, v, out, lse, do, num_heads, num_kv_heads,
                      causal=False, scale=None, interpret=False,
                      q_segments=None, k_segments=None, rope_tables=None):
    """Packed layout in/out; lse [B, Hkv, 8, Lq*G] from the forward kernel.
    With ``rope_tables``, q/k arrive RAW: the kernels re-rotate them on
    load and the returned dq/dk are raw-space cotangents (inverse rotation
    applied in-kernel before the store)."""
    b, lq, _ = q.shape
    lk = k.shape[1]
    _reject_causal_lq_gt_lk(lq, lk, causal, "flash_attention backward")
    d = (q.shape[2]) // num_heads
    g = validate_gqa(num_heads, num_kv_heads, "flash_attention backward")
    scale = float(scale if scale is not None else 1.0 / (d ** 0.5))
    segmented = q_segments is not None
    if segmented:
        # padding rows carry garbage lse (their p reconstructs nonzero);
        # zeroing their do kills every dk/dv/dq contribution in one pass
        do = jnp.where(
            (jnp.asarray(q_segments, jnp.int32) >= 0)[:, :, None], do, 0)
    # delta = rowsum(do ∘ o) per (position, head), f32-accumulated, in the
    # bwd kernels' [B, Hkv, 8, Lq*G] row layout.  A dedicated Pallas pass
    # when the packed tile is legal: the XLA einsum formulation converted
    # do/o to f32 [B,L,H,D], layout-copied the 268MB intermediate
    # ({3,1,2,0}→{3,2,1,0}), and ran a separate reduce — ~40 ms/step at
    # the r5 bench shapes; the kernel reads the packed bf16 operands once
    # and writes delta directly in the consumer layout.
    if (g * d) % 128 == 0 and lq % 8 == 0:
        delta = _delta_pallas(do, out, num_kv_heads, g, d,
                              interpret=interpret)
    else:
        # small-head (hp>1 / BERT-shaped) fallback: einsum contraction
        # whose converts fuse into the reduce pass
        delta = jnp.einsum(
            "blhd,blhd->blh",
            do.reshape(b, lq, num_heads, d),
            out.reshape(b, lq, num_heads, d),
            preferred_element_type=jnp.float32)
        delta = delta.reshape(b, lq, num_kv_heads, g).transpose(0, 2, 1, 3)
        delta = jnp.broadcast_to(
            delta.reshape(b, num_kv_heads, 1, lq * g), lse.shape)
    block_q = _row_blocks(lq, g)
    block_k = _pick_block(lk, 512, "k")

    # q blocks stream via the innermost GRID dim; dk/dv blocks (index maps
    # q-independent) stay resident in VMEM across the q sweep and accumulate
    # in fp32, written back once and cast below
    hp = _heads_per_program(num_kv_heads, g, d, lk)
    if hp == 0:
        raise ValueError(
            f"flash_attention backward: no legal TPU tiling for head_dim="
            f"{d}, kv_heads={num_kv_heads}")
    bhld = hp > 1  # layout decision: head-major whenever multi-head programs
    if bhld:
        # the backward holds dk/dv f32 resident PLUS streamed k/v/q/do per
        # head — heavier than the forward.  In the head-major layout any hp
        # tiles legally (d is the full minor dim, even hp=1), so shrink hp
        # until the scoped-vmem estimate fits (hp=12 measured 21.4M > the
        # 16M limit on v5e; the 2x factor matches the compiler's
        # double-buffered accounting).
        block_q_est = _row_blocks(lq, g)
        while hp > 1:
            est = 2 * hp * (4 * lk * d * 6 + 4 * block_q_est * d * 2)
            if est <= 14 * 1024 * 1024 and num_kv_heads % hp == 0:
                break
            hp -= 1
    if bhld:
        # head-major layout for multi-head programs (see _flash_fwd_pallas)
        q_in = jnp.swapaxes(q.reshape(b, lq, num_heads, d), 1, 2)
        k_in = jnp.swapaxes(k.reshape(b, lk, num_kv_heads, d), 1, 2)
        v_in = jnp.swapaxes(v.reshape(b, lk, num_kv_heads, d), 1, 2)
        do_in = jnp.swapaxes(do.reshape(b, lq, num_heads, d), 1, 2)
        dkv_specs = [
            pl.BlockSpec((1, hp, block_q, d),
                         lambda bi, ci, i, qb: (bi, ci, qb, i * 0)),
            pl.BlockSpec((1, hp, block_k, d),
                         lambda bi, ci, i, qb: (bi, ci, i, i * 0)),
            pl.BlockSpec((1, hp, block_k, d),
                         lambda bi, ci, i, qb: (bi, ci, i, i * 0)),
            pl.BlockSpec((1, hp, block_q, d),
                         lambda bi, ci, i, qb: (bi, ci, qb, i * 0)),
            pl.BlockSpec((1, hp, 8, block_q * g),
                         lambda bi, ci, i, qb: (bi, ci, i * 0, qb)),
            pl.BlockSpec((1, hp, 8, block_q * g),
                         lambda bi, ci, i, qb: (bi, ci, i * 0, qb)),
        ]
        dkv_args = [q_in, k_in, v_in, do_in, lse, delta]
        dkv_out_specs = [
            pl.BlockSpec((1, hp, block_k, d),
                         lambda bi, ci, i, qb: (bi, ci, i, i * 0)),
            pl.BlockSpec((1, hp, block_k, d),
                         lambda bi, ci, i, qb: (bi, ci, i, i * 0)),
        ]
        dkv_out_shape = [
            jax.ShapeDtypeStruct((b, num_kv_heads, lk, d), jnp.float32),
            jax.ShapeDtypeStruct((b, num_kv_heads, lk, d), jnp.float32),
        ]
    else:
        dkv_specs = [
            pl.BlockSpec((1, block_q, hp * g * d),
                         lambda bi, ci, i, qb: (bi, qb, ci)),
            pl.BlockSpec((1, block_k, hp * d),
                         lambda bi, ci, i, qb: (bi, i, ci)),
            pl.BlockSpec((1, block_k, hp * d),
                         lambda bi, ci, i, qb: (bi, i, ci)),
            pl.BlockSpec((1, block_q, hp * g * d),
                         lambda bi, ci, i, qb: (bi, qb, ci)),
            pl.BlockSpec((1, hp, 8, block_q * g),
                         lambda bi, ci, i, qb: (bi, ci, i * 0, qb)),
            pl.BlockSpec((1, hp, 8, block_q * g),
                         lambda bi, ci, i, qb: (bi, ci, i * 0, qb)),
        ]
        dkv_args = [q, k, v, do, lse, delta]
        dkv_out_specs = [
            pl.BlockSpec((1, block_k, hp * d),
                         lambda bi, ci, i, qb: (bi, i, ci)),
            pl.BlockSpec((1, block_k, hp * d),
                         lambda bi, ci, i, qb: (bi, i, ci)),
        ]
        dkv_out_shape = [
            jax.ShapeDtypeStruct(k.shape, jnp.float32),
            jax.ShapeDtypeStruct(v.shape, jnp.float32),
        ]
    rope = rope_tables is not None
    if rope:
        if hp != 1 or bhld or (hp == 1 and _stream_kv(lk, hp, d)):
            raise ValueError(
                "rope_tables: in-kernel rotation is only wired for the "
                "resident packed (hp==1) kernels")
        dkv_specs += [
            pl.BlockSpec((1, block_q, d),
                         lambda bi, ci, i, qb: (i * 0, qb, i * 0)),
            pl.BlockSpec((1, block_q, d),
                         lambda bi, ci, i, qb: (i * 0, qb, i * 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda bi, ci, i, qb: (i * 0, i, i * 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda bi, ci, i, qb: (i * 0, i, i * 0)),
        ]
        dkv_args += list(rope_tables)
    if segmented:
        qseg_rows = _seg_rows(q_segments, g)
        kseg_rows = _seg_rows(k_segments, 1)
        dkv_specs += [
            pl.BlockSpec((1, 1, 8, block_q * g),
                         lambda bi, ci, i, qb: (bi, i * 0, i * 0, qb)),
            pl.BlockSpec((1, 1, 8, block_k),
                         lambda bi, ci, i, qb: (bi, i * 0, i * 0, i)),
        ]
        dkv_args += [qseg_rows, kseg_rows]
    dk32, dv32 = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, causal=causal, scale=scale,
            group=g, head_dim=d, q_offset=lk - lq, segmented=segmented,
            hp=hp, rope=rope,
        ),
        grid=(b, num_kv_heads // hp, lk // block_k, lq // block_q),
        in_specs=dkv_specs,
        out_specs=dkv_out_specs,
        out_shape=dkv_out_shape,
        interpret=interpret,
    )(*dkv_args)
    if bhld:
        dk32 = jnp.swapaxes(dk32, 1, 2).reshape(b, lk, num_kv_heads * d)
        dv32 = jnp.swapaxes(dv32, 1, 2).reshape(b, lk, num_kv_heads * d)
    dk = dk32.astype(k.dtype)
    dv = dv32.astype(v.dtype)

    if hp == 1 and _stream_kv(lk, hp, d):
        # long-context dq: stream k/v via the grid, accumulate in scratch
        from jax.experimental.pallas import tpu as pltpu

        rows = block_q * g
        dq_specs = [
            pl.BlockSpec((1, block_q, g * d),
                         lambda bi, ci, i, kb: (bi, i, ci)),
            pl.BlockSpec((1, block_k, d), lambda bi, ci, i, kb: (bi, kb, ci)),
            pl.BlockSpec((1, block_k, d), lambda bi, ci, i, kb: (bi, kb, ci)),
            pl.BlockSpec((1, block_q, g * d),
                         lambda bi, ci, i, kb: (bi, i, ci)),
            pl.BlockSpec((1, 1, 8, block_q * g),
                         lambda bi, ci, i, kb: (bi, ci, i * 0, i)),
            pl.BlockSpec((1, 1, 8, block_q * g),
                         lambda bi, ci, i, kb: (bi, ci, i * 0, i)),
        ]
        dq_args = [q, k, v, do, lse, delta]
        if segmented:
            dq_specs += [
                pl.BlockSpec((1, 1, 8, block_q * g),
                             lambda bi, ci, i, kb: (bi, i * 0, i * 0, i)),
                pl.BlockSpec((1, 1, 8, block_k),
                             lambda bi, ci, i, kb: (bi, i * 0, i * 0, kb)),
            ]
            dq_args += [qseg_rows, kseg_rows]
        dq = pl.pallas_call(
            functools.partial(
                _bwd_dq_kernel_streamed, causal=causal, scale=scale,
                group=g, head_dim=d, q_offset=lk - lq, segmented=segmented),
            grid=(b, num_kv_heads, lq // block_q, lk // block_k),
            in_specs=dq_specs,
            out_specs=pl.BlockSpec((1, block_q, g * d),
                                   lambda bi, ci, i, kb: (bi, i, ci)),
            out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
            scratch_shapes=[pltpu.VMEM((rows, d), jnp.float32)],
            interpret=interpret,
        )(*dq_args)
        return dq, dk, dv
    if bhld:
        dq_specs = [
            pl.BlockSpec((1, hp, block_q, d),
                         lambda bi, ci, i: (bi, ci, i, i * 0)),
            pl.BlockSpec((1, hp, lk, d),
                         lambda bi, ci, i: (bi, ci, i * 0, i * 0)),
            pl.BlockSpec((1, hp, lk, d),
                         lambda bi, ci, i: (bi, ci, i * 0, i * 0)),
            pl.BlockSpec((1, hp, block_q, d),
                         lambda bi, ci, i: (bi, ci, i, i * 0)),
            pl.BlockSpec((1, hp, 8, block_q * g),
                         lambda bi, ci, i: (bi, ci, i * 0, i)),
            pl.BlockSpec((1, hp, 8, block_q * g),
                         lambda bi, ci, i: (bi, ci, i * 0, i)),
        ]
        dq_args = [q_in, k_in, v_in, do_in, lse, delta]
        dq_out_spec = pl.BlockSpec((1, hp, block_q, d),
                                   lambda bi, ci, i: (bi, ci, i, i * 0))
        dq_out_shape = jax.ShapeDtypeStruct((b, num_heads, lq, d), q.dtype)
    else:
        dq_specs = [
            pl.BlockSpec((1, block_q, hp * g * d),
                         lambda bi, ci, i: (bi, i, ci)),
            pl.BlockSpec((1, lk, hp * d), lambda bi, ci, i: (bi, i * 0, ci)),
            pl.BlockSpec((1, lk, hp * d), lambda bi, ci, i: (bi, i * 0, ci)),
            pl.BlockSpec((1, block_q, hp * g * d),
                         lambda bi, ci, i: (bi, i, ci)),
            pl.BlockSpec((1, hp, 8, block_q * g),
                         lambda bi, ci, i: (bi, ci, i * 0, i)),
            pl.BlockSpec((1, hp, 8, block_q * g),
                         lambda bi, ci, i: (bi, ci, i * 0, i)),
        ]
        dq_args = [q, k, v, do, lse, delta]
        dq_out_spec = pl.BlockSpec((1, block_q, hp * g * d),
                                   lambda bi, ci, i: (bi, i, ci))
        dq_out_shape = jax.ShapeDtypeStruct(q.shape, q.dtype)
    if rope:
        dq_specs += [
            pl.BlockSpec((1, block_q, d),
                         lambda bi, ci, i: (i * 0, i, i * 0)),
            pl.BlockSpec((1, block_q, d),
                         lambda bi, ci, i: (i * 0, i, i * 0)),
            pl.BlockSpec((1, lk, d), lambda bi, ci, i: (i * 0, i * 0, i * 0)),
            pl.BlockSpec((1, lk, d), lambda bi, ci, i: (i * 0, i * 0, i * 0)),
        ]
        dq_args += list(rope_tables)
    if segmented:
        dq_specs += [
            pl.BlockSpec((1, 1, 8, block_q * g),
                         lambda bi, ci, i: (bi, i * 0, i * 0, i)),
            pl.BlockSpec((1, 1, 8, lk),
                         lambda bi, ci, i: (bi, i * 0, i * 0, i * 0)),
        ]
        dq_args += [qseg_rows, kseg_rows]
    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, block_k=block_k, causal=causal, scale=scale,
            group=g, head_dim=d, q_offset=lk - lq, segmented=segmented,
            hp=hp, rope=rope,
        ),
        grid=(b, num_kv_heads // hp, lq // block_q),
        in_specs=dq_specs,
        out_specs=dq_out_spec,
        out_shape=dq_out_shape,
        interpret=interpret,
    )(*dq_args)
    if bhld:
        dq = jnp.swapaxes(dq, 1, 2).reshape(b, lq, num_heads * d)
    return dq, dk, dv


# --------------------------------------------------------------- packed entry
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention_packed(q, k, v, num_heads, num_kv_heads, causal=False,
                           scale=None, interpret=False):
    """GQA flash attention in the projection layout: q [B, L, H*D],
    k/v [B, L, Hkv*D] -> [B, L, H*D].  H % Hkv == 0."""
    return _flash_fwd_pallas(q, k, v, num_heads, num_kv_heads, causal=causal,
                             scale=scale, interpret=interpret)[0]


def _fap_fwd(q, k, v, num_heads, num_kv_heads, causal, scale, interpret):
    out, lse = _flash_fwd_pallas(q, k, v, num_heads, num_kv_heads,
                                 causal=causal, scale=scale,
                                 interpret=interpret)
    return out, (q, k, v, out, lse)


def _fap_bwd(num_heads, num_kv_heads, causal, scale, interpret, res, g):
    q, k, v, out, lse = res
    return _flash_bwd_pallas(q, k, v, out, lse, g, num_heads, num_kv_heads,
                             causal=causal, scale=scale, interpret=interpret)


flash_attention_packed.defvjp(_fap_fwd, _fap_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def flash_attention_packed_segmented(q, k, v, q_segments, k_segments,
                                     num_heads, num_kv_heads, causal=False,
                                     scale=None, interpret=False):
    """Segment-masked (padding/varlen) GQA flash attention, projection
    layout.  q_segments [B, Lq] / k_segments [B, Lk] i32: attention is
    restricted to equal-segment pairs; negative q segments are padding rows
    (zero output, zero grads).  Reference parity:
    python/paddle/nn/functional/flash_attention.py flash_attn_unpadded /
    the padding-mask path of scaled_dot_product_attention."""
    return _flash_fwd_pallas(q, k, v, num_heads, num_kv_heads, causal=causal,
                             scale=scale, interpret=interpret,
                             q_segments=q_segments, k_segments=k_segments)[0]


def _faps_fwd(q, k, v, q_segments, k_segments, num_heads, num_kv_heads,
              causal, scale, interpret):
    out, lse = _flash_fwd_pallas(q, k, v, num_heads, num_kv_heads,
                                 causal=causal, scale=scale,
                                 interpret=interpret, q_segments=q_segments,
                                 k_segments=k_segments)
    return out, (q, k, v, q_segments, k_segments, out, lse)


def _faps_bwd(num_heads, num_kv_heads, causal, scale, interpret, res, g):
    import numpy as _np

    q, k, v, q_segments, k_segments, out, lse = res
    dq, dk, dv = _flash_bwd_pallas(
        q, k, v, out, lse, g, num_heads, num_kv_heads, causal=causal,
        scale=scale, interpret=interpret, q_segments=q_segments,
        k_segments=k_segments)
    f0 = lambda x: _np.zeros(x.shape, dtype=jax.dtypes.float0)
    return dq, dk, dv, f0(q_segments), f0(k_segments)


flash_attention_packed_segmented.defvjp(_faps_fwd, _faps_bwd)


# ----------------------------------------------------- fused-rope packed entry
def _rope_kernel_tables(cos, sin, g, lq, lk, dtype):
    """Raw [Lk, D] tables -> kernel operands: q tables [1, Lq, D] (aligned
    to the LAST lq positions — cached-prefill bottom-right convention), k
    tables [1, Lk, D]; sin pre-signed (signed_sin) so the in-kernel swap
    is a plain lane concat.  The per-group broadcast happens IN-KERNEL
    (_rope_q_tile) — a g-tiled [Lq, G*D] operand would stream g× the
    table bytes through every program (review r5)."""
    cos = cos.astype(dtype)
    sin_s = signed_sin(sin).astype(dtype)
    return cos[lk - lq:][None], sin_s[lk - lq:][None], cos[None], sin_s[None]


def _rope_q_tile(t_ref, block_q, group, d):
    """[1, block_q, D] table block -> the packed q tile's row order
    ([block_q*G, D], position-major group-minor) via in-VMEM broadcast —
    the same pattern as ops/fused_rope.py's kernel."""
    t = t_ref[0]
    return jnp.broadcast_to(t[:, None, :], (block_q, group, d)
                            ).reshape(block_q * group, d)


def rope_fusable(q_shape, k_shape, num_heads, num_kv_heads) -> bool:
    """Gate for flash_attention_packed_rope: TPU, resident packed (hp==1)
    kernels, lane-aligned head dim.  Everything else applies rope outside
    (ops/fused_rope.py standalone kernel or the jnp chain)."""
    if not _on_tpu():
        return False
    b, lq, qd = q_shape
    lk = k_shape[1]
    d = qd // num_heads
    if d * num_heads != qd or d % 128:
        return False
    g = num_heads // num_kv_heads
    if g * num_kv_heads != num_heads or (g * d) % 128:
        return False
    # rope adds resident kcos+ksin ([Lk, D] each, double-buffered) to the
    # kernels' k/v residency — budget them like an extra k+v pair so a
    # shape that barely fit WITHOUT rope doesn't blow scoped vmem with it
    # (review r5): 8*(lk*d + lk*d) bytes vs the 12MB streaming threshold
    if _stream_kv(2 * lk, 1, d):      # long-context streamed kernels
        return False
    return lq % 128 == 0 and lk % 128 == 0


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def flash_attention_packed_rope(q, k, v, cos, sin, num_heads, num_kv_heads,
                                causal=False, scale=None, interpret=False):
    """GQA flash attention with rotary embedding FUSED INTO the kernels:
    q/k arrive RAW in the projection layout and rotate on tiles already in
    VMEM — the standalone rope pass (read+rotate+write of q and k per
    layer, plus its backward) disappears from the step.  cos/sin are the
    standard half-duplicated tables [Lk, D] (positions are the caller's —
    slice for cached prefill); they are treated as positional constants
    (zero cotangent), matching the reference fused kernel
    (paddle/phi/kernels/fusion/gpu/fused_rope_kernel.cu) whose tables are
    not differentiable either.  Gate with ``rope_fusable``."""
    b, lq, _ = q.shape
    lk = k.shape[1]
    g = num_heads // num_kv_heads
    tables = _rope_kernel_tables(cos, sin, g, lq, lk, q.dtype)
    return _flash_fwd_pallas(q, k, v, num_heads, num_kv_heads, causal=causal,
                             scale=scale, interpret=interpret,
                             rope_tables=tables)[0]


def _fapr_fwd(q, k, v, cos, sin, num_heads, num_kv_heads, causal, scale,
              interpret):
    b, lq, _ = q.shape
    lk = k.shape[1]
    g = num_heads // num_kv_heads
    tables = _rope_kernel_tables(cos, sin, g, lq, lk, q.dtype)
    out, lse = _flash_fwd_pallas(q, k, v, num_heads, num_kv_heads,
                                 causal=causal, scale=scale,
                                 interpret=interpret, rope_tables=tables)
    return out, (q, k, v, out, lse, cos, sin)


def _fapr_bwd(num_heads, num_kv_heads, causal, scale, interpret, res, gct):
    q, k, v, out, lse, cos, sin = res
    b, lq, _ = q.shape
    lk = k.shape[1]
    g = num_heads // num_kv_heads
    tables = _rope_kernel_tables(cos, sin, g, lq, lk, q.dtype)
    dq, dk, dv = _flash_bwd_pallas(
        q, k, v, out, lse, gct, num_heads, num_kv_heads, causal=causal,
        scale=scale, interpret=interpret, rope_tables=tables)
    return dq, dk, dv, jnp.zeros_like(cos), jnp.zeros_like(sin)


flash_attention_packed_rope.defvjp(_fapr_fwd, _fapr_bwd)


# ------------------------------------------------------------------- blockwise (jnp)
def blockwise_attention(q, k, v, causal=False, scale=None, block_k=512,
                        q_offset=0, k_offset=0, carry_in=None,
                        return_carry=False, q_segments=None, k_segments=None):
    """Memory-efficient attention as a scan over k/v blocks ([B, L, H, D]).

    ``q_offset``/``k_offset`` shift query/key positions to their global indices
    (ring attention passes each rotating shard's offset); ``carry_in``/
    ``return_carry`` expose the online-softmax state (acc, m, l) so callers can
    stitch multiple k/v shards together.  ``q_segments``/``k_segments``
    ([B, Lq] / [B, Lk] int arrays) restrict attention to same-segment pairs —
    and k/v may carry fewer (kv) heads than q (GQA/MQA, consumed natively) —
    the varlen/packed-sequence masking (flash_attn_unpadded, padding masks):
    tokens never attend across segment boundaries, and rows whose segment id
    is negative (padding) produce zeros.
    """
    b, lq, h, d = q.shape
    lk = k.shape[1]
    hkv = k.shape[2]
    g = validate_gqa(h, hkv, "blockwise_attention")
    # GQA: kv heads consumed natively (no repeat; a ring
    # rotation of GQA k/v moves 1/g the ICI bytes of expanded heads)
    scale = float(scale if scale is not None else 1.0 / (d ** 0.5))
    block_k = _pick_block(lk, block_k)
    nblocks = lk // block_k
    qt = jnp.swapaxes(q, 1, 2).astype(jnp.float32) * scale  # [B, H, Lq, D]
    qt5 = qt.reshape(b, hkv, g, lq, d)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    kb = kt.reshape(b, hkv, nblocks, block_k, d)
    vb = vt.reshape(b, hkv, nblocks, block_k, d)
    q_idx = q_offset + jnp.arange(lq)

    kseg_b = (None if k_segments is None
              else jnp.asarray(k_segments).reshape(b, nblocks, block_k))
    qseg = None if q_segments is None else jnp.asarray(q_segments)

    def step(carry, blk):
        acc, m, l = carry
        kblk, vblk, kb_idx, kseg = blk
        s = jnp.einsum(
            "bkgqd,bkcd->bkgqc", qt5, kblk.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        ).reshape(b, h, lq, block_k)
        if causal:
            k_idx = k_offset + kb_idx * block_k + jnp.arange(block_k)
            mask = q_idx[:, None] >= k_idx[None, :]
            s = jnp.where(mask[None, None], s, _NEG_INF)
        if kseg is not None:
            seg_mask = qseg[:, :, None] == kseg[:, None, :]  # [B, Lq, block_k]
            s = jnp.where(seg_mask[:, None], s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bkgqc,bkcd->bkgqd", p.reshape(b, hkv, g, lq, block_k),
            vblk.astype(jnp.float32)
        ).reshape(b, h, lq, d)
        return (acc_new, m_new, l_new), None

    if carry_in is None:
        # derive the init from qt (0*qt) so its type matches the scan body's
        # outputs under shard_map (a plain zeros constant is unvarying over
        # the manual axes and trips the carry-type check)
        carry = (
            jnp.zeros_like(qt),
            jnp.full((b, h, lq), _NEG_INF, jnp.float32) + 0 * qt[..., 0],
            0 * qt[..., 0],
        )
    else:
        carry = carry_in
    blocks = (
        jnp.moveaxis(kb, 2, 0),  # [nblocks, B, H, block_k, D]
        jnp.moveaxis(vb, 2, 0),
        jnp.arange(nblocks),
        None if kseg_b is None else jnp.moveaxis(kseg_b, 1, 0),
    )
    carry, _ = jax.lax.scan(step, carry, blocks)
    if return_carry:
        return carry
    acc, m, l = carry
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    if qseg is not None:
        out = jnp.where((qseg >= 0)[:, None, :, None], out, 0.0)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


# --------------------------------------------------------------------- public entry
def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def available(q_shape, k_shape=None, causal=False) -> bool:
    """Whether the Pallas fast path handles this shape (else XLA composition).

    ``k_shape`` (optional, [B, Lk, Hkv, D]) enables the GQA check: query
    heads must be an integer multiple of kv heads.  ``causal`` with Lq > Lk
    is rejected: the first Lq-Lk query rows have NO live keys under the
    bottom-right-aligned mask and the backward's lse reconstruction is
    undefined for empty rows — the dense fallback owns that shape
    (ADVICE r4)."""
    if len(q_shape) != 4:
        return False
    _, l, h, d = q_shape
    hkv = h
    if k_shape is not None:
        hkv = k_shape[2]
        if hkv <= 0 or h % hkv or k_shape[1] % 128:
            return False
        if causal and q_shape[1] > k_shape[1]:
            return False
    # packed-layout q blocks slice (H/Hkv)*D lanes out of H*D: the minor
    # dim must be a 128-multiple — or a multi-head program block must make
    # it one (BERT-shaped d=64 MHA packs hp kv heads per program; see
    # _heads_per_program).  The lk used in the vmem guard is k_shape's when
    # given, else l (self-attention).
    lk = k_shape[1] if k_shape is not None else l
    if _heads_per_program(hkv, h // hkv, d, lk) == 0:
        return False
    return _on_tpu() and d in (64, 128, 256) and l >= 128 and l % 128 == 0


def flash_attention_blhd(q, k, v, causal=False, scale=None, q_segments=None,
                         k_segments=None, interpret=False):
    """Flash attention, [batch, seq, heads, head_dim]; k/v may carry fewer
    (kv) heads than q (GQA/MQA).  Thin packing wrapper over
    ``flash_attention_packed`` — the [B,L,H,D] <-> [B,L,H*D] reshapes are
    contiguous, i.e. free.  Optional q_segments/k_segments [B, Lq]/[B, Lk]
    route through the segment-masked kernels (padding/varlen masks).
    Small head dims (BERT-base d=64 MHA) are handled by multi-head program
    blocks (_heads_per_program) in a head-major layout — that path DOES
    transpose q/k/v (and the backward's do/dq/dk/dv) to [B, H, L, D],
    trading those copies for legal tiling + amortized program launches;
    measured net faster than both the per-head fold and XLA dense at BERT
    bench shapes."""
    b, lq, h, d = q.shape
    lk = k.shape[1]
    hkv = k.shape[2]
    validate_gqa(h, hkv, "flash_attention_blhd")
    qp = q.reshape(b, lq, h * d)
    kp = k.reshape(b, lk, hkv * d)
    vp = v.reshape(b, lk, hkv * d)
    if q_segments is None:
        out = flash_attention_packed(qp, kp, vp, h, hkv, causal, scale,
                                     interpret)
    else:
        out = flash_attention_packed_segmented(
            qp, kp, vp, q_segments, k_segments, h, hkv, causal, scale,
            interpret)
    return out.reshape(b, lq, h, d)


def repeat_kv(k, v, rep: int):
    """Expand GQA kv heads to the full query-head count ([B, L, Hkv, D] ->
    [B, L, Hkv*rep, D]).  ONE source of truth for the kv-head -> query-head
    grouping convention (query head j reads kv head j // rep — consecutive
    blocks of `rep`), which must match the packed kernels' BlockSpec head
    slicing above.  Only paths that cannot consume kv heads natively (dense
    fallback, ring attention rotation) should call this."""
    if rep == 1:
        return k, v
    return jnp.repeat(k, rep, axis=2), jnp.repeat(v, rep, axis=2)
