"""Flash attention for TPU: GQA-native Pallas kernels + blockwise fallback.

Reference parity: python/paddle/nn/functional/flash_attention.py over
third_party/flashattn (CUDA), including its native num_heads_k != num_heads
(GQA/MQA) support.  TPU-native design:

* **Packed layout, zero layout churn.**  The kernels consume the projection
  outputs DIRECTLY: q ``[B, L, H*D]``, k/v ``[B, L, Hkv*D]``.  BlockSpec index
  maps slice heads out of the packed minor dimension — the
  ``[B,L,H,D] -> [B*H,L,D]`` swapaxes/reshape round-trip of the r3 kernels
  (a real HBM transpose on every call, VERDICT r3 weak #2) is gone entirely.
* **GQA-native grid.**  Grid is ``(batch, kv_head, block)``; one program
  holds the q block of ALL ``G = H/Hkv`` query heads sharing one kv head and
  streams that kv head's K/V once.  KV HBM traffic is 1/G of the r3 kernel,
  which materialized ``jnp.repeat``-ed K/V (VERDICT r3 missing #2).
* ``_fwd_kernel`` — online-softmax forward, fp32 accumulators, MXU-shaped
  ``[block_q*G, block_k]`` score tiles.
* ``_bwd_dkv_kernel`` / ``_bwd_dq_kernel`` — the standard two-pass flash
  backward consuming the forward's log-sum-exp rows; fp32 accumulation, no
  ``[Lq, Lk]`` tensor in HBM.
* ``blockwise_attention`` — same math as a ``lax.scan`` in pure jnp:
  differentiable on any backend, and the building block ring attention
  rotates over the mesh (ops/ring_attention.py).

Row packing: within a q block, rows are ordered position-major / head-minor
(row ``r`` = position ``r // G``, group head ``r % G``), which is exactly the
memory order of a ``[block_q, G*D]`` tile — the reshape inside the kernel is
free.  Log-sum-exp/delta rows are carried ``[B, Hkv, 8, Lq*G]``
sublane-replicated so the stats tensors tile legally on TPU.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30


def validate_gqa(h: int, hkv: int, name: str = "attention") -> int:
    """Shared GQA head-grouping contract check (one place; the grouping
    convention itself lives in ``repeat_kv``).  Returns the group size."""
    if hkv <= 0 or h % hkv:
        raise ValueError(
            f"{name}: query heads ({h}) must be an integer multiple of "
            f"kv heads ({hkv})")
    return h // hkv


def _reject_causal_lq_gt_lk(lq: int, lk: int, causal: bool, name: str):
    """Causal with Lq > Lk has rows with NO live keys under the bottom-right
    aligned mask; the finite -1e30 mask sentinel makes those rows degenerate
    to uniform attention and their lse poisons the backward.  Fail loudly —
    the dense fallback owns that shape (ADVICE r4 + review r5)."""
    if causal and lq > lk:
        raise ValueError(
            f"{name}: causal attention requires Lq <= Lk (got Lq={lq}, "
            f"Lk={lk}); rows before the cached prefix would have no live "
            "keys. Use the dense fallback for this shape.")


# --------------------------------------------------------------------------- pallas fwd
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int,
                causal: bool, scale: float, group: int, head_dim: int,
                q_offset: int):
    """One (batch, kv-head, q-block) program: online softmax over k blocks.

    q_ref [1, block_q, G*D] (this kv head's G query heads, packed);
    k_ref/v_ref [1, Lk, D]; o_ref [1, block_q, G*D];
    lse_ref [1, 1, 8, block_q*G] — log-sum-exp rows (position-major,
    group-head-minor), replicated across the 8 sublanes so the stats tensor
    tiles legally on TPU; consumed by backward.
    """
    block_q = q_ref.shape[1]
    rows = block_q * group
    lk = k_ref.shape[1]
    num_k_blocks = lk // block_k
    qi = pl.program_id(2)

    # [block_q, G*D] -> [block_q*G, D]: contiguous, free
    q = q_ref[0].reshape(rows, head_dim)

    def make_body(masked):
        def body(kb, carry):
            acc, m, l = carry
            k = k_ref[0, pl.ds(kb * block_k, block_k), :]  # [block_k, D]
            v = v_ref[0, pl.ds(kb * block_k, block_k), :]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32
            ) * scale  # [rows, block_k] fp32
            if masked:
                # row r is query position q_offset + qi*block_q + r//G — the
                # offset (Lk-Lq) bottom-right-aligns the mask for cached/
                # chunked prefill, matching the dense fallback's tril(kl-ql).
                # Position index built as a 3D iota reshaped (pos-major,
                # head-minor) — integer division on i32 promotes to i64 under
                # x64 and recurses Mosaic's convert lowering.
                q_idx = q_offset + qi * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, group, block_k), 0
                ).reshape(rows, block_k)
                k_idx = kb * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (rows, block_k), 1
                )
                s = jnp.where(q_idx >= k_idx, s, jnp.float32(_NEG_INF))
            m_new = jnp.maximum(m, jnp.max(s, axis=-1))
            p = jnp.exp(s - m_new[:, None])
            alpha = jnp.exp(m - m_new)
            l_new = l * alpha + jnp.sum(p, axis=-1)
            acc_new = acc * alpha[:, None] + jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            return acc_new, m_new, l_new
        return body

    init = (
        jnp.zeros((rows, head_dim), jnp.float32),
        jnp.full((rows,), _NEG_INF, jnp.float32),
        jnp.zeros((rows,), jnp.float32),
    )
    if causal:
        # two-phase causal sweep (the r4 profile put the flash kernels at
        # 490ms of an 1830ms step with half their tiles fully masked):
        #   [0, lo)  — k blocks fully BELOW the diagonal: no mask compute
        #   [lo, hi) — the diagonal band: masked
        #   [hi, ..) — fully above: skipped entirely
        # All-i32 dynamic fori bounds (a bare python int would promote to
        # i64 under x64 and recurse Mosaic's lowering).  Bounds clamp to
        # >= 0 as pure defense: with Lq > Lk the q_offset is negative and
        # floor division would otherwise produce negative k-block indices
        # whose clamped dynamic slices re-read block 0 (ADVICE r4).  The
        # shape itself is rejected at the entry points (dead rows are NOT
        # well-defined here: masked scores equal the finite m init, so a
        # dead row in a live block degenerates to uniform attention).
        q_min = jnp.int32(q_offset) + qi * jnp.int32(block_q)
        lo = jnp.maximum(q_min // jnp.int32(block_k), jnp.int32(0))
        hi = jnp.maximum(
            (q_min + jnp.int32(block_q + block_k - 1)) // jnp.int32(block_k),
            jnp.int32(0))
        carry = jax.lax.fori_loop(jnp.int32(0), lo, make_body(False), init)
        acc, m, l = jax.lax.fori_loop(lo, hi, make_body(True), carry)
    else:
        acc, m, l = jax.lax.fori_loop(jnp.int32(0), jnp.int32(num_k_blocks),
                                      make_body(False), init,
                                      unroll=num_k_blocks <= 8)
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe[:, None]).reshape(block_q, group * head_dim
                                               ).astype(o_ref.dtype)
    lse_ref[0, 0] = jnp.broadcast_to(m + jnp.log(l_safe), (8, rows))


def _pick_block(n: int, preferred: int, kind: str = "") -> int:
    """Largest power-of-two-ish divisor of ``n`` at most ``preferred``.

    When ``kind`` is given ("q"/"k"), PADDLE_TPU_FLASH_BLOCK[_Q|_K] overrides
    ``preferred`` for perf sweeps (bench_sweep.jsonl).  NOTE: the enclosing
    kernels are jax.jit'd, so the env is read at TRACE time — sweep in
    separate processes (as bench_sweep does), not by mutating os.environ
    between calls.  Callers passing explicit blocking (kind="") are never
    overridden."""
    if kind:
        import os
        import warnings

        env = (os.environ.get(f"PADDLE_TPU_FLASH_BLOCK_{kind.upper()}")
               or os.environ.get("PADDLE_TPU_FLASH_BLOCK"))
        if env:
            try:
                v = int(env)
            except ValueError:
                v = 0
            if v >= 8:
                preferred = v
            else:
                warnings.warn(
                    f"ignoring invalid flash block override {env!r} "
                    "(need an integer >= 8)", stacklevel=2)
    b = min(preferred, n)
    while n % b:
        b //= 2
    b = max(b, 1)
    if kind and b != min(preferred, n):
        import warnings

        warnings.warn(
            f"flash block_{kind} {preferred} does not divide L={n}; "
            f"using {b}", stacklevel=2)
    return b


def _row_blocks(lq: int, group: int, target: int = 1024):
    """block_q for a G-grouped kernel.  r4 full-bench sweep (v5e, GQA4
    B16 L2048 D128, causal block-skip kernels): q256/k512 is the optimum —
    MFU 0.570 vs 0.549 @ q64-128/k1024, 0.554 @ q64/k512, 0.540 @ q512/k256
    (q >= 512 with k512 overflows the 16M scoped vmem).  Expressed as a
    1024-row target with block_q capped at 256; block_k default 512 at the
    call sites."""
    block_q = _pick_block(lq, max(8, min(256, target // group)), "q")
    return block_q


@functools.partial(
    jax.jit, static_argnames=("num_heads", "num_kv_heads", "causal", "scale",
                              "interpret"))
def _flash_fwd_pallas(q, k, v, num_heads, num_kv_heads, causal=False,
                      scale=None, interpret=False):
    """q [B, Lq, H*D], k/v [B, Lk, Hkv*D] — the projection layout, consumed
    without any transpose.  Returns (out [B, Lq, H*D],
    lse [B, Hkv, 8, Lq*G])."""
    b, lq, hd_packed = q.shape
    lk = k.shape[1]
    _reject_causal_lq_gt_lk(lq, lk, causal, "flash_attention")
    d = hd_packed // num_heads
    g = validate_gqa(num_heads, num_kv_heads, "flash_attention")
    scale = float(scale if scale is not None else 1.0 / (d ** 0.5))
    block_q = _row_blocks(lq, g)
    block_k = _pick_block(lk, 512, "k")
    grid = (b, num_kv_heads, lq // block_q)
    # index maps use `i * 0` (not the literal 0) so the constant inherits the
    # i32 index dtype — a literal traces as i64 under jax_enable_x64 and
    # Mosaic rejects the mixed-width index tuple
    out, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel, block_k=block_k, causal=causal, scale=scale,
            group=g, head_dim=d, q_offset=lk - lq,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, g * d), lambda bi, ci, i: (bi, i, ci)),
            pl.BlockSpec((1, lk, d), lambda bi, ci, i: (bi, i * 0, ci)),
            pl.BlockSpec((1, lk, d), lambda bi, ci, i: (bi, i * 0, ci)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, g * d), lambda bi, ci, i: (bi, i, ci)),
            pl.BlockSpec((1, 1, 8, block_q * g),
                         lambda bi, ci, i: (bi, ci, i * 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, lq, num_heads * d), q.dtype),
            jax.ShapeDtypeStruct((b, num_kv_heads, 8, lq * g), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse


# --------------------------------------------------------------------------- pallas bwd
# Standard flash-attention backward (the public two-pass formulation): with the
# forward's log-sum-exp rows the softmax is reconstructed per tile as
# p = exp(s - lse), then
#   dv = pᵀ·do,  dp = do·vᵀ,  ds = p ∘ (dp - delta) · scale,
#   dk = dsᵀ·q,  dq = Σ ds·k,      delta = rowsum(do ∘ o).
# Pass 1 (grid over k blocks) accumulates dk/dv with q/do streamed; pass 2
# (grid over q blocks) accumulates dq with k/v streamed.  All accumulation in
# fp32; no [Lq, Lk] tensor ever hits HBM.  dk/dv for one kv head gather the
# contributions of its G query heads inside one program — no repeat, no
# cross-program reduction.


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, causal: bool,
                    scale: float, group: int, head_dim: int, q_offset: int):
    """One (batch, kv-head, k-block, q-block) program: this q block's
    contribution to dk/dv of this k block.

    q blocks are streamed by the GRID's innermost dim (not an in-kernel loop
    over a resident full-Lq block — 2 x 2MB x double-buffering of q/do blew
    the 16M scoped-vmem budget inside the full train step); the dk/dv output
    blocks have q-independent index maps, so Pallas keeps them resident in
    VMEM across the q sweep and writes back once (fp32, cast by the caller).

    q_ref/do_ref [1, block_q, G*D]; k_ref/v_ref [1, block_k, D];
    lse_ref/delta_ref [1, 1, 8, block_q*G]; dk_ref/dv_ref [1, block_k, D] f32.
    """
    block_k = k_ref.shape[1]
    block_q = q_ref.shape[1]
    rows = block_q * group
    ki = pl.program_id(2)
    qb = pl.program_id(3)

    @pl.when(qb == 0)
    def _init():
        dk_ref[...] = jnp.zeros_like(dk_ref)
        dv_ref[...] = jnp.zeros_like(dv_ref)

    # causal tile classes (real scf.if on the scalar core, unlike lax.cond's
    # predication): fully above the diagonal -> skip all compute; fully
    # below -> compute without the mask (saves the iota/compare VPU work);
    # diagonal band -> masked compute.
    if causal:
        live = (qb + 1) * block_q + q_offset > ki * block_k
        full = q_offset + qb * block_q >= (ki + 1) * block_k
    else:
        live, full = True, True

    def compute(masked):
        k = k_ref[0]  # [block_k, D]
        v = v_ref[0]
        q = q_ref[0].reshape(rows, head_dim)
        do = do_ref[0].reshape(rows, head_dim)
        lse = lse_ref[0, 0, 0]                             # [rows]
        delta = delta_ref[0, 0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale                                          # [rows, block_k]
        if masked:
            q_idx = q_offset + qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, group, block_k), 0
            ).reshape(rows, block_k)
            k_idx = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (rows, block_k), 1
            )
            s = jnp.where(q_idx >= k_idx, s, jnp.float32(_NEG_INF))
        p = jnp.exp(s - lse[:, None])                      # [rows, block_k]
        dv_ref[0] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                  # [rows, block_k]
        ds = p * (dp - delta[:, None]) * scale
        dk_ref[0] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        @pl.when(full)
        def _full():
            compute(False)

        @pl.when(live & jnp.logical_not(full))
        def _diag():
            compute(True)
    else:
        compute(False)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
                   block_k: int, causal: bool, scale: float, group: int,
                   head_dim: int, q_offset: int):
    """One (batch, kv-head, q-block) program: dq for this q block.

    q_ref/do_ref/dq_ref [1, block_q, G*D]; k_ref/v_ref [1, Lk, D];
    lse_ref/delta_ref [1, 1, 8, block_q*G].
    """
    block_q = q_ref.shape[1]
    rows = block_q * group
    lk = k_ref.shape[1]
    num_k_blocks = lk // block_k
    qi = pl.program_id(2)

    q = q_ref[0].reshape(rows, head_dim)
    do = do_ref[0].reshape(rows, head_dim)
    lse = lse_ref[0, 0, 0]
    delta = delta_ref[0, 0, 0]

    def make_body(masked):
        def body(kb, dq):
            k = k_ref[0, pl.ds(kb * block_k, block_k), :]
            v = v_ref[0, pl.ds(kb * block_k, block_k), :]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32
            ) * scale
            if masked:
                q_idx = q_offset + qi * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, group, block_k), 0
                ).reshape(rows, block_k)
                k_idx = kb * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (rows, block_k), 1
                )
                s = jnp.where(q_idx >= k_idx, s, jnp.float32(_NEG_INF))
            p = jnp.exp(s - lse[:, None])
            dp = jax.lax.dot_general(
                do, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32
            )
            ds = p * (dp - delta[:, None]) * scale
            return dq + jax.lax.dot_general(
                ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        return body

    dq0 = jnp.zeros((rows, head_dim), jnp.float32)
    if causal:
        # two-phase: mask-free full blocks, masked diagonal band, skip the
        # rest (all-i32 dynamic bounds, clamped >= 0 — see _fwd_kernel)
        q_min = jnp.int32(q_offset) + qi * jnp.int32(block_q)
        lo = jnp.maximum(q_min // jnp.int32(block_k), jnp.int32(0))
        hi = jnp.maximum(
            (q_min + jnp.int32(block_q + block_k - 1)) // jnp.int32(block_k),
            jnp.int32(0))
        dq = jax.lax.fori_loop(jnp.int32(0), lo, make_body(False), dq0)
        dq = jax.lax.fori_loop(lo, hi, make_body(True), dq)
    else:
        dq = jax.lax.fori_loop(jnp.int32(0), jnp.int32(num_k_blocks),
                               make_body(False), dq0,
                               unroll=num_k_blocks <= 8)
    dq_ref[0] = dq.reshape(block_q, group * head_dim).astype(dq_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("num_heads", "num_kv_heads", "causal", "scale",
                              "interpret"))
def _flash_bwd_pallas(q, k, v, out, lse, do, num_heads, num_kv_heads,
                      causal=False, scale=None, interpret=False):
    """Packed layout in/out; lse [B, Hkv, 8, Lq*G] from the forward kernel."""
    b, lq, _ = q.shape
    lk = k.shape[1]
    _reject_causal_lq_gt_lk(lq, lk, causal, "flash_attention backward")
    d = (q.shape[2]) // num_heads
    g = validate_gqa(num_heads, num_kv_heads, "flash_attention backward")
    scale = float(scale if scale is not None else 1.0 / (d ** 0.5))
    # delta = rowsum(do ∘ o) per (position, head): one cheap elementwise pass
    # fused by XLA; regrouped to the kernels' (kv-head, pos*G+g) row order and
    # replicated over 8 sublanes to match the lse tiling
    delta = jnp.sum(
        do.astype(jnp.float32).reshape(b, lq, num_heads, d)
        * out.astype(jnp.float32).reshape(b, lq, num_heads, d), axis=-1)
    delta = delta.reshape(b, lq, num_kv_heads, g).transpose(0, 2, 1, 3)
    delta = jnp.broadcast_to(
        delta.reshape(b, num_kv_heads, 1, lq * g), lse.shape)
    block_q = _row_blocks(lq, g)
    block_k = _pick_block(lk, 512, "k")

    # q blocks stream via the innermost GRID dim; dk/dv blocks (index maps
    # q-independent) stay resident in VMEM across the q sweep and accumulate
    # in fp32, written back once and cast below
    dk32, dv32 = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, causal=causal, scale=scale,
            group=g, head_dim=d, q_offset=lk - lq,
        ),
        grid=(b, num_kv_heads, lk // block_k, lq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, g * d),
                         lambda bi, ci, i, qb: (bi, qb, ci)),
            pl.BlockSpec((1, block_k, d), lambda bi, ci, i, qb: (bi, i, ci)),
            pl.BlockSpec((1, block_k, d), lambda bi, ci, i, qb: (bi, i, ci)),
            pl.BlockSpec((1, block_q, g * d),
                         lambda bi, ci, i, qb: (bi, qb, ci)),
            pl.BlockSpec((1, 1, 8, block_q * g),
                         lambda bi, ci, i, qb: (bi, ci, i * 0, qb)),
            pl.BlockSpec((1, 1, 8, block_q * g),
                         lambda bi, ci, i, qb: (bi, ci, i * 0, qb)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bi, ci, i, qb: (bi, i, ci)),
            pl.BlockSpec((1, block_k, d), lambda bi, ci, i, qb: (bi, i, ci)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, jnp.float32),
            jax.ShapeDtypeStruct(v.shape, jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    dk = dk32.astype(k.dtype)
    dv = dv32.astype(v.dtype)

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, block_k=block_k, causal=causal, scale=scale,
            group=g, head_dim=d, q_offset=lk - lq,
        ),
        grid=(b, num_kv_heads, lq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, g * d), lambda bi, ci, i: (bi, i, ci)),
            pl.BlockSpec((1, lk, d), lambda bi, ci, i: (bi, i * 0, ci)),
            pl.BlockSpec((1, lk, d), lambda bi, ci, i: (bi, i * 0, ci)),
            pl.BlockSpec((1, block_q, g * d), lambda bi, ci, i: (bi, i, ci)),
            pl.BlockSpec((1, 1, 8, block_q * g),
                         lambda bi, ci, i: (bi, ci, i * 0, i)),
            pl.BlockSpec((1, 1, 8, block_q * g),
                         lambda bi, ci, i: (bi, ci, i * 0, i)),
        ],
        out_specs=pl.BlockSpec((1, block_q, g * d),
                               lambda bi, ci, i: (bi, i, ci)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# --------------------------------------------------------------- packed entry
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention_packed(q, k, v, num_heads, num_kv_heads, causal=False,
                           scale=None):
    """GQA flash attention in the projection layout: q [B, L, H*D],
    k/v [B, L, Hkv*D] -> [B, L, H*D].  H % Hkv == 0."""
    return _flash_fwd_pallas(q, k, v, num_heads, num_kv_heads, causal=causal,
                             scale=scale)[0]


def _fap_fwd(q, k, v, num_heads, num_kv_heads, causal, scale):
    out, lse = _flash_fwd_pallas(q, k, v, num_heads, num_kv_heads,
                                 causal=causal, scale=scale)
    return out, (q, k, v, out, lse)


def _fap_bwd(num_heads, num_kv_heads, causal, scale, res, g):
    q, k, v, out, lse = res
    return _flash_bwd_pallas(q, k, v, out, lse, g, num_heads, num_kv_heads,
                             causal=causal, scale=scale)


flash_attention_packed.defvjp(_fap_fwd, _fap_bwd)


# ------------------------------------------------------------------- blockwise (jnp)
def blockwise_attention(q, k, v, causal=False, scale=None, block_k=512,
                        q_offset=0, k_offset=0, carry_in=None,
                        return_carry=False, q_segments=None, k_segments=None):
    """Memory-efficient attention as a scan over k/v blocks ([B, L, H, D]).

    ``q_offset``/``k_offset`` shift query/key positions to their global indices
    (ring attention passes each rotating shard's offset); ``carry_in``/
    ``return_carry`` expose the online-softmax state (acc, m, l) so callers can
    stitch multiple k/v shards together.  ``q_segments``/``k_segments``
    ([B, Lq] / [B, Lk] int arrays) restrict attention to same-segment pairs —
    and k/v may carry fewer (kv) heads than q (GQA/MQA, consumed natively) —
    the varlen/packed-sequence masking (flash_attn_unpadded, padding masks):
    tokens never attend across segment boundaries, and rows whose segment id
    is negative (padding) produce zeros.
    """
    b, lq, h, d = q.shape
    lk = k.shape[1]
    hkv = k.shape[2]
    g = validate_gqa(h, hkv, "blockwise_attention")
    # GQA: kv heads consumed natively (no repeat; a ring
    # rotation of GQA k/v moves 1/g the ICI bytes of expanded heads)
    scale = float(scale if scale is not None else 1.0 / (d ** 0.5))
    block_k = _pick_block(lk, block_k)
    nblocks = lk // block_k
    qt = jnp.swapaxes(q, 1, 2).astype(jnp.float32) * scale  # [B, H, Lq, D]
    qt5 = qt.reshape(b, hkv, g, lq, d)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    kb = kt.reshape(b, hkv, nblocks, block_k, d)
    vb = vt.reshape(b, hkv, nblocks, block_k, d)
    q_idx = q_offset + jnp.arange(lq)

    kseg_b = (None if k_segments is None
              else jnp.asarray(k_segments).reshape(b, nblocks, block_k))
    qseg = None if q_segments is None else jnp.asarray(q_segments)

    def step(carry, blk):
        acc, m, l = carry
        kblk, vblk, kb_idx, kseg = blk
        s = jnp.einsum(
            "bkgqd,bkcd->bkgqc", qt5, kblk.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        ).reshape(b, h, lq, block_k)
        if causal:
            k_idx = k_offset + kb_idx * block_k + jnp.arange(block_k)
            mask = q_idx[:, None] >= k_idx[None, :]
            s = jnp.where(mask[None, None], s, _NEG_INF)
        if kseg is not None:
            seg_mask = qseg[:, :, None] == kseg[:, None, :]  # [B, Lq, block_k]
            s = jnp.where(seg_mask[:, None], s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bkgqc,bkcd->bkgqd", p.reshape(b, hkv, g, lq, block_k),
            vblk.astype(jnp.float32)
        ).reshape(b, h, lq, d)
        return (acc_new, m_new, l_new), None

    if carry_in is None:
        # derive the init from qt (0*qt) so its type matches the scan body's
        # outputs under shard_map (a plain zeros constant is unvarying over
        # the manual axes and trips the carry-type check)
        carry = (
            jnp.zeros_like(qt),
            jnp.full((b, h, lq), _NEG_INF, jnp.float32) + 0 * qt[..., 0],
            0 * qt[..., 0],
        )
    else:
        carry = carry_in
    blocks = (
        jnp.moveaxis(kb, 2, 0),  # [nblocks, B, H, block_k, D]
        jnp.moveaxis(vb, 2, 0),
        jnp.arange(nblocks),
        None if kseg_b is None else jnp.moveaxis(kseg_b, 1, 0),
    )
    carry, _ = jax.lax.scan(step, carry, blocks)
    if return_carry:
        return carry
    acc, m, l = carry
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    if qseg is not None:
        out = jnp.where((qseg >= 0)[:, None, :, None], out, 0.0)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


# --------------------------------------------------------------------- public entry
def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def available(q_shape, k_shape=None, causal=False) -> bool:
    """Whether the Pallas fast path handles this shape (else XLA composition).

    ``k_shape`` (optional, [B, Lk, Hkv, D]) enables the GQA check: query
    heads must be an integer multiple of kv heads.  ``causal`` with Lq > Lk
    is rejected: the first Lq-Lk query rows have NO live keys under the
    bottom-right-aligned mask and the backward's lse reconstruction is
    undefined for empty rows — the dense fallback owns that shape
    (ADVICE r4)."""
    if len(q_shape) != 4:
        return False
    _, l, h, d = q_shape
    hkv = h
    if k_shape is not None:
        hkv = k_shape[2]
        if hkv <= 0 or h % hkv or k_shape[1] % 128:
            return False
        if causal and q_shape[1] > k_shape[1]:
            return False
    # packed-layout q blocks slice (H/Hkv)*D lanes out of H*D: the minor dim
    # must be a 128-multiple (d=64 MHA, e.g. BERT-base, takes the XLA path)
    if (h // hkv) * d % 128:
        return False
    return _on_tpu() and d in (64, 128, 256) and l >= 128 and l % 128 == 0


def flash_attention_blhd(q, k, v, causal=False, scale=None):
    """Flash attention, [batch, seq, heads, head_dim]; k/v may carry fewer
    (kv) heads than q (GQA/MQA).  Thin packing wrapper over
    ``flash_attention_packed`` — the [B,L,H,D] <-> [B,L,H*D] reshapes are
    contiguous, i.e. free."""
    b, lq, h, d = q.shape
    hkv = k.shape[2]
    out = flash_attention_packed(
        q.reshape(b, lq, h * d),
        k.reshape(b, k.shape[1], hkv * d),
        v.reshape(b, v.shape[1], hkv * d),
        h, hkv, causal, scale,
    )
    return out.reshape(b, lq, h, d)


def repeat_kv(k, v, rep: int):
    """Expand GQA kv heads to the full query-head count ([B, L, Hkv, D] ->
    [B, L, Hkv*rep, D]).  ONE source of truth for the kv-head -> query-head
    grouping convention (query head j reads kv head j // rep — consecutive
    blocks of `rep`), which must match the packed kernels' BlockSpec head
    slicing above.  Only paths that cannot consume kv heads natively (dense
    fallback, ring attention rotation) should call this."""
    if rep == 1:
        return k, v
    return jnp.repeat(k, rep, axis=2), jnp.repeat(v, rep, axis=2)
