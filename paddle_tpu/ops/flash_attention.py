"""Flash attention for TPU: Pallas forward kernel + blockwise-differentiable fallback.

Reference parity: python/paddle/nn/functional/flash_attention.py over
third_party/flashattn (CUDA).  TPU-native design:

* ``_flash_fwd_pallas`` — an online-softmax Pallas kernel tiled for the MXU
  (q blocks in VMEM, k/v streamed block-by-block, fp32 accumulators).  Used as
  the forward fast path on TPU.
* ``blockwise_attention`` — the same math as a ``lax.scan`` over key/value
  blocks in pure jnp.  It is differentiable, memory-efficient (never
  materializes the [Lq, Lk] score matrix), works on any backend, and is the
  building block ring attention rotates over the mesh (ops/ring_attention.py).
* ``flash_attention_blhd`` — custom_vjp wrapper: Pallas forward, backward via
  the vjp of ``blockwise_attention`` (recompute — the flashattn backward
  strategy, traded for FLOPs exactly as jax.checkpoint would).

Layout is Paddle's flash-attention layout [batch, seq, heads, head_dim].
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30


# --------------------------------------------------------------------------- pallas fwd
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool,
                scale: float):
    """One (batch*head, q-block) program: online softmax over k blocks.

    q_ref [1, block_q, D]; k_ref/v_ref [1, Lk, D]; o_ref [1, block_q, D].
    """
    block_q = q_ref.shape[1]
    head_dim = q_ref.shape[2]
    lk = k_ref.shape[1]
    num_k_blocks = lk // block_k
    qi = pl.program_id(1)

    q = q_ref[0]  # [block_q, D]

    def body(kb, carry):
        acc, m, l = carry
        k = k_ref[0, pl.ds(kb * block_k, block_k), :]  # [block_k, D]
        v = v_ref[0, pl.ds(kb * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [block_q, block_k] fp32
        if causal:
            q_idx = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_idx = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_idx >= k_idx, s, jnp.float32(_NEG_INF))
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return acc_new, m_new, l_new

    init = (
        jnp.zeros((block_q, head_dim), jnp.float32),
        jnp.full((block_q,), _NEG_INF, jnp.float32),
        jnp.zeros((block_q,), jnp.float32),
    )
    # static trip count: a dynamic (causal-skip) bound trips a Mosaic
    # while-lowering recursion under x64; fully-masked blocks contribute
    # exp(-inf)=0 so the result is identical
    acc, m, l = jax.lax.fori_loop(jnp.int32(0), jnp.int32(num_k_blocks), body,
                                  init, unroll=num_k_blocks <= 8)
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


def _pick_block(n: int, preferred: int) -> int:
    b = min(preferred, n)
    while n % b:
        b //= 2
    return max(b, 1)


@functools.partial(jax.jit, static_argnames=("causal", "scale", "interpret"))
def _flash_fwd_pallas(q, k, v, causal=False, scale=None, interpret=False):
    """[B, L, H, D] in/out.  Grid: (B*H_kv-expanded, q blocks)."""
    b, lq, h, d = q.shape
    lk = k.shape[1]
    scale = float(scale if scale is not None else 1.0 / (d ** 0.5))
    # -> [B*H, L, D]
    qh = jnp.swapaxes(q, 1, 2).reshape(b * h, lq, d)
    kh = jnp.swapaxes(k, 1, 2).reshape(b * h, lk, d)
    vh = jnp.swapaxes(v, 1, 2).reshape(b * h, lk, d)
    block_q = _pick_block(lq, 512)
    block_k = _pick_block(lk, 512)
    grid = (b * h, lq // block_q)
    out = pl.pallas_call(
        functools.partial(
            _fwd_kernel, block_k=block_k, causal=causal, scale=scale
        ),
        grid=grid,
        # index maps use `i * 0` (not the literal 0) so the constant inherits the
        # i32 index dtype — a literal traces as i64 under jax_enable_x64 and
        # Mosaic rejects the mixed-width index tuple
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, i * 0)),
            pl.BlockSpec((1, lk, d), lambda bh, i: (bh, i * 0, i * 0)),
            pl.BlockSpec((1, lk, d), lambda bh, i: (bh, i * 0, i * 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, i * 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, lq, d), q.dtype),
        interpret=interpret,
    )(qh, kh, vh)
    return jnp.swapaxes(out.reshape(b, h, lq, d), 1, 2)


# ------------------------------------------------------------------- blockwise (jnp)
def blockwise_attention(q, k, v, causal=False, scale=None, block_k=512,
                        q_offset=0, k_offset=0, carry_in=None,
                        return_carry=False):
    """Memory-efficient attention as a scan over k/v blocks ([B, L, H, D]).

    ``q_offset``/``k_offset`` shift query/key positions to their global indices
    (ring attention passes each rotating shard's offset); ``carry_in``/
    ``return_carry`` expose the online-softmax state (acc, m, l) so callers can
    stitch multiple k/v shards together.
    """
    b, lq, h, d = q.shape
    lk = k.shape[1]
    scale = float(scale if scale is not None else 1.0 / (d ** 0.5))
    block_k = _pick_block(lk, block_k)
    nblocks = lk // block_k
    qt = jnp.swapaxes(q, 1, 2).astype(jnp.float32) * scale  # [B, H, Lq, D]
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    kb = kt.reshape(b, h, nblocks, block_k, d)
    vb = vt.reshape(b, h, nblocks, block_k, d)
    q_idx = q_offset + jnp.arange(lq)

    def step(carry, blk):
        acc, m, l = carry
        kblk, vblk, kb_idx = blk
        s = jnp.einsum(
            "bhqd,bhkd->bhqk", qt, kblk.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        if causal:
            k_idx = k_offset + kb_idx * block_k + jnp.arange(block_k)
            mask = q_idx[:, None] >= k_idx[None, :]
            s = jnp.where(mask[None, None], s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vblk.astype(jnp.float32)
        )
        return (acc_new, m_new, l_new), None

    if carry_in is None:
        carry = (
            jnp.zeros((b, h, lq, d), jnp.float32),
            jnp.full((b, h, lq), _NEG_INF, jnp.float32),
            jnp.zeros((b, h, lq), jnp.float32),
        )
    else:
        carry = carry_in
    blocks = (
        jnp.moveaxis(kb, 2, 0),  # [nblocks, B, H, block_k, D]
        jnp.moveaxis(vb, 2, 0),
        jnp.arange(nblocks),
    )
    carry, _ = jax.lax.scan(step, carry, blocks)
    if return_carry:
        return carry
    acc, m, l = carry
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


# --------------------------------------------------------------------- public entry
def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def available(q_shape) -> bool:
    """Whether the Pallas fast path handles this shape (else XLA composition)."""
    if len(q_shape) != 4:
        return False
    _, l, _, d = q_shape
    # lane dim wants 128-multiples; tiny shapes aren't worth a kernel launch
    return _on_tpu() and d in (64, 128, 256) and l >= 128 and l % 128 == 0


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention_blhd(q, k, v, causal=False, scale=None):
    """Flash attention, [batch, seq, heads, head_dim]."""
    return _flash_fwd_pallas(q, k, v, causal=causal, scale=scale)


def _fa_fwd(q, k, v, causal, scale):
    return _flash_fwd_pallas(q, k, v, causal=causal, scale=scale), (q, k, v)


def _fa_bwd(causal, scale, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: blockwise_attention(q_, k_, v_, causal=causal,
                                               scale=scale), q, k, v
    )
    return vjp(g)


flash_attention_blhd.defvjp(_fa_fwd, _fa_bwd)
